"""Train CLI — the reference's ``src/train.py`` entrypoint surface
(SURVEY.md §2.2 "Train CLI", §3.1) re-expressed over typed configs:
pick a preset (the five driver configs, BASELINE.json:7-11), override fields
from flags, create a numbered run dir, train.

Examples
--------
  python -m gansformer_tpu.cli.train --preset clevr64-simplex --total-kimg 10
  python -m gansformer_tpu.cli.train --preset ffhq256-duplex \\
      --data-path /data/ffhq-tfrecords --batch-size 64 --resume
"""

from __future__ import annotations

import argparse
import dataclasses
import os

from gansformer_tpu.core.config import (
    DataConfig, ExperimentConfig, MeshConfig, ModelConfig, TrainConfig,
    get_preset, PRESETS)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="GANsformer-TPU training")
    p.add_argument("--preset", default="clevr64-simplex", choices=sorted(PRESETS))
    p.add_argument("--config", default=None,
                   help="JSON config file (e.g. a run dir's config.json); "
                        "overrides --preset, flags still apply on top")
    p.add_argument("--results-dir", default="results")
    p.add_argument("--desc", default=None, help="run dir description suffix")
    p.add_argument("--resume", action="store_true",
                   help="resume from latest checkpoint in --resume-dir")
    p.add_argument("--resume-dir", default=None,
                   help="run dir to resume (default: a fresh run dir)")
    p.add_argument("--run-dir", default=None,
                   help="pin the run dir explicitly (no numbered-dir "
                        "allocation) — the supervisor's contract: "
                        "gansformer-supervise passes the same dir on "
                        "every restart; with --resume the run continues "
                        "if checkpoints exist, else starts fresh in "
                        "place")
    # model overrides (reference flags: --g-arch, --components-num, ...)
    p.add_argument("--attention", choices=["none", "simplex", "duplex"])
    p.add_argument("--components", type=int, help="k latent components")
    p.add_argument("--resolution", type=int)
    p.add_argument("--dtype", choices=["float32", "bfloat16"])
    # training overrides
    p.add_argument("--batch-size", type=int)
    p.add_argument("--total-kimg", type=int)
    # Tri-state like the other model flags: None inherits the loaded
    # config.  'pallas' = the fused blockwise kernels with backward
    # kernels + second-order derivative rule (ops/pallas_attention.py) —
    # training-grade since ISSUE 9.  On TPU the request is resolved
    # through the native smoke check (fwd AND bwd kernels) before any
    # step program compiles; a failed check falls back to 'xla' with the
    # reason printed, matching the config rule's wording.
    p.add_argument("--attention-backend", default=None,
                   choices=("xla", "pallas"),
                   help="attention compute backend for the train step "
                        "programs ('pallas' = fused differentiable "
                        "kernels; on TPU a failed native smoke check "
                        "falls back to xla with the reason printed; "
                        "default: inherit the loaded config)")
    # Same tri-state discipline for the modulated-conv/upfirdn family
    # (ISSUE 14): 'pallas' = the fused modconv/upfirdn kernel family
    # (ops/pallas_modconv.py) with hand-written backward kernels —
    # training-grade to second order, resolved through its own native
    # smoke check on TPU before any step program compiles.
    p.add_argument("--conv-backend", default=None,
                   choices=("xla", "pallas"),
                   help="modulated-conv/upfirdn compute backend for the "
                        "train step programs ('pallas' = fused "
                        "modulate→conv→demodulate / polyphase up-conv / "
                        "upfirdn kernels; on TPU a failed native smoke "
                        "check falls back to xla with the reason "
                        "printed; default: inherit the loaded config)")
    p.add_argument("--g-lr", type=float)
    p.add_argument("--d-lr", type=float)
    p.add_argument("--r1-gamma", type=float)
    p.add_argument("--seed", type=int)
    # MFU levers (ISSUE 5): prepared, flag-gated step-time variants — the
    # A/B battery (scripts/ab_levers.py) prices them; these flags arm them
    # for a real run once a measured Δms justifies it (PERF.md §1d).
    p.add_argument("--pl-batch-shrink", type=int, default=None,
                   help="path-length probe batch divisor (reference "
                        "default 2; 1 = full-batch probe, 4 = prepared "
                        "cheaper variant)")
    p.add_argument("--r1-batch-shrink", type=int, default=None,
                   help="compute R1 on the first batch/N reals (unbiased "
                        "slice estimator, lazy-reg weight unchanged); "
                        "default 1 = off")
    p.add_argument("--attn-fused-kv", action="store_const", const=True,
                   dest="attn_fused_kv", default=None,
                   help="fuse each attention direction's K/V projections "
                        "into one matmul (exact math, different param "
                        "tree; default off)")
    p.add_argument("--no-attn-fused-kv", action="store_const", const=False,
                   dest="attn_fused_kv",
                   help="disable the fused K/V projection (overrides a "
                        "loaded config that enabled it)")
    p.add_argument("--fused-cycle", action="store_const", const=True,
                   dest="fused_cycle", default=None,
                   help="dispatch one jitted program per full lazy-reg "
                        "cycle (d_reg_interval iterations) instead of two "
                        "per iteration")
    p.add_argument("--no-fused-cycle", action="store_const", const=False,
                   dest="fused_cycle",
                   help="disable the fused cycle (overrides a loaded "
                        "config that enabled it)")
    # Overlap layer (ISSUE 2) — tri-state: None inherits the loaded
    # config; both default ON via the config dataclasses.  The off
    # switches are the synchronous parity/debug fallbacks.
    p.add_argument("--device-prefetch", action="store_const", const=True,
                   dest="device_prefetch", default=None,
                   help="keep a background-thread ring of batches already "
                        "in device memory (default on; h2d leaves the hot "
                        "loop)")
    p.add_argument("--no-device-prefetch", action="store_const", const=False,
                   dest="device_prefetch",
                   help="synchronous host->device transfer on the loop "
                        "thread (parity fallback)")
    p.add_argument("--async-checkpoint", action="store_const", const=True,
                   dest="async_checkpoint", default=None,
                   help="checkpoint/snapshot writeback on a background "
                        "writer thread (default on; the loop only pays "
                        "dispatch cost)")
    p.add_argument("--no-async-checkpoint", action="store_const",
                   const=False, dest="async_checkpoint",
                   help="synchronous checkpoint/snapshot writes on the "
                        "loop thread (parity fallback)")
    p.add_argument("--selfcheck", action="store_true",
                   help="run graftlint (AST rules + structural jaxpr "
                        "trace — including the graftnum fp32-island / "
                        "accumulation / stability audit — + the "
                        "PartitionSpec-contract check on the four train "
                        "steps) before training; writes "
                        "<run_dir>/graftlint.json and aborts on NEW "
                        "findings — catch a dtype leak, a bf16 island "
                        "breach, or a mis-partitioned step before it "
                        "burns accelerator hours")
    p.add_argument("--debug-nans", action="store_true",
                   help="enable jax_debug_nans + per-tick finite checks")
    p.add_argument("--profile-dir", default=None,
                   help="jax.profiler trace of tick 1 → this dir "
                        "(TensorBoard profile plugin)")
    p.add_argument("--device-time-ticks", type=int, default=None,
                   help="device-truth sampling cadence: every N ticks, "
                        "trace one full tick with jax.profiler and fold "
                        "device/* gauges (device-time MFU, per-program "
                        "device ms, wall-vs-device divergence) into "
                        "telemetry.prom.  0 = off (use 0 for unattended "
                        "relayed-TPU runs — a killed trace can wedge the "
                        "tunnel); default 8")
    # data overrides
    p.add_argument("--data-path", default=None)
    p.add_argument("--data-source",
                   choices=["synthetic", "npz", "tfrecord", "folder"])
    p.add_argument("--mirror-augment", action="store_true")
    # Data-plane fault tolerance (ISSUE 15, docs/data.md): the corruption
    # budget, the transient-read retry count, and the producer-progress
    # stall watchdog.  Past the budget the run exits typed
    # (EXIT_DATA_CORRUPT) and the supervisor gives up instead of
    # crash-looping on a static defect.
    p.add_argument("--max-corrupt-frac", type=float, default=None,
                   help="quarantined/total record fraction above which "
                        "the run fails typed as data-corrupt "
                        "(non-retryable; default 0.01)")
    p.add_argument("--io-retries", type=int, default=None,
                   help="bounded-backoff retries for transient record "
                        "read errors (default 3)")
    p.add_argument("--stall-after-s", type=float, default=None,
                   help="data-stall watchdog: seconds of zero producer "
                        "progress before the loop fails typed as "
                        "data-stalled (0 = off; default 120)")
    # mesh / multi-host (replaces reference --num-gpus)
    p.add_argument("--mesh-data", type=int, default=None,
                   help="data-axis size; -1 = all devices "
                        "(default: from --config, else -1)")
    p.add_argument("--mesh-model", type=int, default=None,
                   help="model-axis size (sequence/context parallelism "
                        "shards attention grids over this axis; "
                        "default: from --config, else 1)")
    # Tri-state (ADVICE r3): default None inherits the loaded config — a
    # resumed sequence-parallel run keeps its layout, and --no-sequence-
    # parallel can turn it OFF (symmetric with the --mesh-model override).
    p.add_argument("--sequence-parallel", action="store_const", const=True,
                   dest="sequence_parallel", default=None,
                   help="shard every attention block's H*W grid axis over "
                        "the model mesh axis (needs --mesh-model > 1)")
    p.add_argument("--no-sequence-parallel", action="store_const", const=False,
                   dest="sequence_parallel",
                   help="disable sequence parallelism (overrides a loaded "
                        "config that enabled it)")
    # Tri-state like the other layout flags: None inherits the loaded
    # config (a resumed --fsdp run keeps its layout without re-passing).
    p.add_argument("--fsdp", action="store_const", const=True,
                   dest="fsdp", default=None,
                   help="shard optimizer-state leaves over the data mesh "
                        "axis (ZeRO-1; params/EMA stay replicated — no "
                        "parameter gather in compute).  Needs a data "
                        "axis > 1; validation explains misuse in words")
    p.add_argument("--no-fsdp", action="store_const", const=False,
                   dest="fsdp",
                   help="replicate optimizer state (overrides a loaded "
                        "config that enabled fsdp)")
    p.add_argument("--coordinator", default=None,
                   help="host:port for jax.distributed.initialize")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    return p


def config_from_args(args) -> ExperimentConfig:
    if getattr(args, "config", None):
        with open(args.config) as f:
            cfg = ExperimentConfig.from_json(f.read())
    else:
        cfg = get_preset(args.preset)

    def override(obj, **kv):
        kv = {k: v for k, v in kv.items() if v is not None}
        return dataclasses.replace(obj, **kv) if kv else obj

    model = override(cfg.model, attention=args.attention,
                     components=args.components, resolution=args.resolution,
                     dtype=args.dtype)
    sp = getattr(args, "sequence_parallel", None)
    if sp is not None:            # tri-state: None inherits the config
        model = dataclasses.replace(model, sequence_parallel=sp)
    fkv = getattr(args, "attn_fused_kv", None)
    if fkv is not None:           # tri-state: None inherits the config
        model = dataclasses.replace(model, attn_fused_kv=fkv)
    ab = getattr(args, "attention_backend", None)
    if ab is not None:            # tri-state: None inherits the config
        model = dataclasses.replace(model, attention_backend=ab)
    cb = getattr(args, "conv_backend", None)
    if cb is not None:            # tri-state: None inherits the config
        model = dataclasses.replace(model, conv_backend=cb)
    train = override(cfg.train, batch_size=args.batch_size,
                     total_kimg=args.total_kimg, g_lr=args.g_lr,
                     d_lr=args.d_lr, r1_gamma=args.r1_gamma, seed=args.seed,
                     pl_batch_shrink=getattr(args, "pl_batch_shrink", None),
                     r1_batch_shrink=getattr(args, "r1_batch_shrink", None),
                     device_time_ticks=getattr(args, "device_time_ticks",
                                               None))
    fc = getattr(args, "fused_cycle", None)
    if fc is not None:                # tri-state: None inherits the config
        train = dataclasses.replace(train, fused_cycle=fc)
    ac = getattr(args, "async_checkpoint", None)
    if ac is not None:                # tri-state: None inherits the config
        train = dataclasses.replace(train, async_checkpoint=ac)
    if args.debug_nans:
        train = dataclasses.replace(train, debug_nans=True)
    if args.profile_dir:
        train = dataclasses.replace(train, profile_dir=args.profile_dir)
    data = override(cfg.data, path=args.data_path, source=args.data_source,
                    resolution=args.resolution,
                    max_corrupt_frac=getattr(args, "max_corrupt_frac", None),
                    io_retries=getattr(args, "io_retries", None),
                    stall_after_s=getattr(args, "stall_after_s", None))
    if args.mirror_augment:
        data = dataclasses.replace(data, mirror_augment=True)
    dp = getattr(args, "device_prefetch", None)
    if dp is not None:                # tri-state: None inherits the config
        data = dataclasses.replace(data, device_prefetch=dp)
    # Mesh flags default to the loaded config's mesh (so `--resume` of a
    # sequence-parallel run keeps its layout without re-passing flags);
    # validate() enforces mesh/model consistency with one clear message.
    mesh = MeshConfig(
        data=args.mesh_data if args.mesh_data is not None else cfg.mesh.data,
        model=(getattr(args, "mesh_model", None)
               if getattr(args, "mesh_model", None) is not None
               else cfg.mesh.model),
        fsdp=(getattr(args, "fsdp", None)
              if getattr(args, "fsdp", None) is not None
              else cfg.mesh.fsdp),
        coordinator_address=args.coordinator or cfg.mesh.coordinator_address,
        num_processes=(args.num_processes if args.num_processes is not None
                       else cfg.mesh.num_processes),
        process_id=(args.process_id if args.process_id is not None
                    else cfg.mesh.process_id))
    return ExperimentConfig(name=cfg.name, model=model, train=train,
                            data=data, mesh=mesh).validate()


def _latest_run_dir(results_dir: str):
    """Most recent numbered run dir (the reference's results/ convention)."""
    from gansformer_tpu.utils.logging import list_run_dirs

    runs = list_run_dirs(results_dir)
    return runs[-1] if runs else None


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)

    from gansformer_tpu.parallel.mesh import init_distributed
    from gansformer_tpu.train.loop import train
    from gansformer_tpu.utils.logging import (
        RunLogger, create_run_dir, list_run_dirs, next_run_id)

    run_dir = None
    if args.run_dir:
        # Pinned run dir (the supervisor's restart contract): --resume
        # here means "continue if there is anything to continue" — a
        # child that crashed before its first checkpoint restarts fresh
        # in the same dir instead of erroring.
        run_dir = args.run_dir
        os.makedirs(run_dir, exist_ok=True)
        if args.resume and not os.path.isdir(
                os.path.join(run_dir, "checkpoints")):
            args.resume = False
    elif args.resume:
        run_dir = args.resume_dir or _latest_run_dir(args.results_dir)
        if run_dir is None or not os.path.isdir(
                os.path.join(run_dir, "checkpoints")):
            raise SystemExit(
                f"--resume: no run dir with checkpoints found "
                f"(looked in {args.resume_dir or args.results_dir}); "
                f"pass --resume-dir explicitly")
    if args.resume and not args.config:
        # Resume continues the RUN'S config (flags still override on top);
        # falling back to the preset would silently train a different model
        # into the old run dir.
        saved = os.path.join(run_dir, "config.json")
        if os.path.exists(saved):
            args.config = saved
    cfg = config_from_args(args)
    init_distributed(cfg.mesh)

    import jax

    from gansformer_tpu.utils.hostenv import enable_compile_cache

    enable_compile_cache()   # warm second-order compiles across invocations

    def _resolve_pallas(cfg, field, resolver):
        """The smoke-check-and-fall-back discipline (ADVICE r3), on the
        TRAINING entry point: resolve before any step program compiles,
        so a Mosaic regression costs one tiny compile + a clear message
        instead of a failed multi-minute second-order compile.  The
        resolved backend lands in the saved config.json — a resumed run
        re-resolves from its own record, never from a stale request.
        Shared by attention_backend (ISSUE 9) and conv_backend
        (ISSUE 14)."""
        if getattr(cfg.model, field) != "pallas":
            return cfg
        import sys as _sys

        resolved = resolver("pallas")
        if jax.process_count() > 1:
            # Every host must land on the SAME backend: the smoke check
            # runs per-process, and a host-local failure (transient
            # compile-cache corruption, flaky Mosaic lowering) would
            # otherwise leave this host compiling xla step programs while
            # its peers compile pallas ones — the job then hangs at the
            # first collective instead of failing cleanly.  AND-reduce
            # the verdict, same discipline as the run-id / selfcheck
            # broadcasts below.
            from jax.experimental import multihost_utils
            import numpy as np

            oks = multihost_utils.process_allgather(
                np.int32(resolved == "pallas"))
            if int(np.min(oks)) == 0:
                resolved = "xla"
        if resolved != "pallas":
            flag = "--" + field.replace("_", "-")
            print(f"[train] {flag} pallas requested but the native TPU "
                  f"smoke check failed on at least one host (reason on "
                  f"its stderr); training continues on {field}='xla'",
                  file=_sys.stderr)
            cfg = dataclasses.replace(cfg, model=dataclasses.replace(
                cfg.model, **{field: resolved}))
        return cfg

    if cfg.model.attention_backend == "pallas":
        from gansformer_tpu.ops.pallas_attention import resolve_backend

        cfg = _resolve_pallas(cfg, "attention_backend", resolve_backend)
    if cfg.model.conv_backend == "pallas":
        from gansformer_tpu.ops.pallas_modconv import resolve_conv_backend

        cfg = _resolve_pallas(cfg, "conv_backend", resolve_conv_backend)
    is_main = jax.process_index() == 0
    if run_dir is None:
        desc = args.desc or f"{cfg.name}-{cfg.model.attention}-k{cfg.model.components}"
        if jax.process_count() > 1:
            # All hosts must agree on the run dir; process 0 picks the id
            # and broadcasts it (a shared results dir would otherwise race).
            from jax.experimental import multihost_utils
            import numpy as np

            rid = multihost_utils.broadcast_one_to_all(
                np.int32(next_run_id(args.results_dir) if is_main else 0))
            run_dir = create_run_dir(args.results_dir, desc,
                                     run_id=int(rid), create=is_main)
        else:
            run_dir = create_run_dir(args.results_dir, desc)
    if not args.resume and is_main:
        with open(os.path.join(run_dir, "config.json"), "w") as f:
            f.write(cfg.to_json())
    logger = RunLogger(run_dir, active=is_main)
    logger.write(f"run dir: {run_dir}")
    if args.resume:
        # Elastic restart (ROADMAP item 5): the devices this resume sees
        # may not be the devices the run was checkpointed on — validate/
        # rewrite the saved mesh config instead of crashing in make_mesh
        # or the loop's divisibility check.  restore() returns layout-
        # agnostic arrays and the loop re-places them through
        # state_shardings/fsdp_spec, so the config is the only piece
        # that needs fixing.
        from gansformer_tpu.supervise.elastic import resolve_elastic_mesh

        cfg, notes = resolve_elastic_mesh(cfg, len(jax.devices()))
        if notes and is_main:
            from gansformer_tpu.supervise import events

            for n in notes:
                logger.write(n)
            events.append_event(run_dir, "elastic", notes=notes,
                                n_devices=len(jax.devices()))
    if args.selfcheck:
        # Pre-flight: the whole analysis stack (AST rules + jaxpr trace
        # rules) in one pass, machine-readable artifact in the run dir.
        # New findings abort BEFORE any accelerator time is spent.
        # Process 0 runs the check; the verdict is broadcast so every
        # process aborts together instead of peers hanging in train()'s
        # first collective against a dead coordinator.
        n_new = 0
        if is_main:
            from gansformer_tpu.analysis.cli import run_selfcheck

            try:
                n_new = run_selfcheck(run_dir)
                logger.write(f"selfcheck: {n_new} new finding(s) "
                             f"({os.path.join(run_dir, 'graftlint.json')})")
            except Exception as e:
                # a crashed selfcheck must still reach the broadcast
                # below — otherwise the peers block in the collective
                # against a dead coordinator instead of aborting
                logger.write(f"selfcheck crashed: {type(e).__name__}: "
                             f"{str(e)[:300]}")
                n_new = -1
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            import numpy as np

            n_new = int(multihost_utils.broadcast_one_to_all(
                np.int32(n_new)))
        if n_new:
            raise SystemExit(
                "--selfcheck: the check itself crashed (see log.txt)"
                if n_new < 0 else
                f"--selfcheck: {n_new} new graftlint finding(s); see "
                f"{os.path.join(run_dir, 'graftlint.json')} — fix, "
                f"suppress with a justification, or baseline, then rerun")
    from gansformer_tpu.data.errors import DataCorrupt, DataStalled
    from gansformer_tpu.supervise.events import (
        EXIT_DATA_CORRUPT, EXIT_DATA_STALLED, EXIT_PREEMPTED,
        PreemptionExit)

    try:
        train(cfg, run_dir, resume=args.resume, logger=logger)
    except PreemptionExit as e:
        # Graceful preemption (SIGTERM → final checkpoint): the DISTINCT
        # exit code is the supervisor's classification signal — this was
        # an orderly hand-back of the device, not a crash.
        logger.write(f"preempted cleanly at step {e.step}; "
                     f"exit code {EXIT_PREEMPTED}")
        raise SystemExit(EXIT_PREEMPTED)
    except DataCorrupt as e:
        # Corruption budget exhausted — a STATIC data defect.  The
        # distinct exit code makes the supervisor classify this as
        # non-retryable (cause 'data-corrupt') and give up instead of
        # burning its restart budget on a crash loop (ISSUE 15).
        logger.write(f"data corrupt (budget exhausted): {e}; "
                     f"exit code {EXIT_DATA_CORRUPT}")
        raise SystemExit(EXIT_DATA_CORRUPT)
    except DataStalled as e:
        # Input pipeline stalled past its watchdog — classified and fast
        # (well inside the supervisor's heartbeat-staleness SIGKILL);
        # possibly transient, so the supervisor still retries it.
        logger.write(f"data stalled: {e}; exit code {EXIT_DATA_STALLED}")
        raise SystemExit(EXIT_DATA_STALLED)


if __name__ == "__main__":
    main()
