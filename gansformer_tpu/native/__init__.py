"""Native host-ops loader — the reference's ``custom_ops.get_plugin`` role
(SURVEY.md §2.1 "Runtime kernel compiler": nvcc at first use, cached by
source hash, loaded into the process).  Here: ``g++ -O3 -shared`` at first
use, cached by source hash under ``~/.cache``-style dir inside the repo,
loaded via ctypes.  Device compute stays with XLA; this covers the host
data path (TFRecord scan/parse, CRC32C) that feeds the chips.

Every entry point degrades gracefully: if no C++ toolchain is available
the callers keep their pure-Python fallbacks.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "host_ops.cpp")
_CACHE = os.path.join(_DIR, "_build")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> Optional[str]:
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = os.path.join(_CACHE, f"host_ops-{tag}.so")
    if os.path.exists(out):
        return out
    os.makedirs(_CACHE, exist_ok=True)
    # atomic: build to a temp name, rename into place (concurrent procs)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_CACHE)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
             "-o", tmp, _SRC],
            check=True, capture_output=True, text=True, timeout=120)
        os.replace(tmp, out)
        return out
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """The compiled host-ops library, or None (callers use Python paths)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("GANSFORMER_TPU_NO_NATIVE") == "1":
        return None
    path = _compile()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.gft_crc32c.restype = ctypes.c_uint32
    lib.gft_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.gft_scan_records.restype = ctypes.c_int64
    lib.gft_scan_records.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int,
        ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_size_t)]
    lib.gft_parse_example.restype = ctypes.c_int
    lib.gft_parse_example.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
    _lib = lib
    return _lib


def crc32c(data: bytes) -> Optional[int]:
    lib = get_lib()
    if lib is None:
        return None
    return int(lib.gft_crc32c(data, len(data)))


def scan_records(buf: bytes, verify_crc: bool = False):
    """(offsets, lengths, consumed) for every COMPLETE TFRecord payload in
    ``buf``, or None if the native lib is unavailable.

    ``consumed`` is the byte count covered by complete records — a partial
    record at the tail is left unconsumed so callers can stream a file in
    chunks.  Raises ValueError on a CRC mismatch (verify_crc)."""
    import numpy as np

    lib = get_lib()
    if lib is None:
        return None
    cap = max(16, len(buf) // 16)          # record overhead is 16 bytes
    offs = np.empty(cap, np.int64)
    lens = np.empty(cap, np.int64)
    consumed = ctypes.c_size_t()
    err_pos = ctypes.c_size_t()
    n = lib.gft_scan_records(
        buf, len(buf),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        cap, int(verify_crc), ctypes.byref(consumed), ctypes.byref(err_pos))
    if n < 0:
        raise ValueError(
            f"corrupt TFRecord: CRC mismatch at byte {err_pos.value}")
    return offs[:n], lens[:n], consumed.value


def parse_example(payload: bytes):
    """(shape tuple, data_offset, data_length) — spans within ``payload``
    for one reference-schema Example; None if the native lib is
    unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    shape = (ctypes.c_int64 * 4)()
    ndim = ctypes.c_int32()
    d_off = ctypes.c_int64()
    d_len = ctypes.c_int64()
    rc = lib.gft_parse_example(
        payload, len(payload), shape, ctypes.byref(ndim),
        ctypes.byref(d_off), ctypes.byref(d_len))
    if rc != 0:
        raise ValueError(f"malformed Example record (native rc={rc})")
    return (tuple(shape[i] for i in range(ndim.value)),
            d_off.value, d_len.value)
