// Native host-side data-path kernels (C++), loaded via ctypes.
//
// Role: the reference's native layer is CUDA compute kernels compiled by
// nvcc at first use (src/dnnlib/tflib/custom_ops.py, SURVEY.md §2.1).  On
// TPU the *compute* kernels belong to XLA — what remains native-worthy is
// the host data path that feeds the chips: TFRecord frame scanning,
// tf.train.Example proto walking, and CRC32C checksums.  These are the
// pure-Python hot spots of data/dataset.py + data/tfrecord_writer.py; this
// translation unit replaces them with -O3 C++ behind a stable C ABI
// (gansformer_tpu/native/__init__.py compiles + caches it g++-at-first-use,
// mirroring the reference's nvcc-at-first-use design).
//
// ABI: plain C functions, int64/size_t/uint8* only — no C++ types cross
// the boundary, so ctypes needs no struct mirroring.

#include <cstdint>
#include <cstddef>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli, reflected 0x82F63B78) — slicing-by-8.
// ---------------------------------------------------------------------------

static uint32_t kCrcTable[8][256];
static bool crc_init_done = false;

static void crc_init() {
    for (int i = 0; i < 256; ++i) {
        uint32_t c = (uint32_t)i;
        for (int k = 0; k < 8; ++k)
            c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
        kCrcTable[0][i] = c;
    }
    for (int t = 1; t < 8; ++t)
        for (int i = 0; i < 256; ++i)
            kCrcTable[t][i] = (kCrcTable[t - 1][i] >> 8) ^
                              kCrcTable[0][kCrcTable[t - 1][i] & 0xFF];
    crc_init_done = true;
}

uint32_t gft_crc32c(const uint8_t* buf, size_t len) {
    if (!crc_init_done) crc_init();
    uint32_t crc = 0xFFFFFFFFu;
    while (len >= 8) {
        uint64_t word;
        std::memcpy(&word, buf, 8);          // little-endian hosts only
        word ^= crc;
        crc = kCrcTable[7][word & 0xFF] ^
              kCrcTable[6][(word >> 8) & 0xFF] ^
              kCrcTable[5][(word >> 16) & 0xFF] ^
              kCrcTable[4][(word >> 24) & 0xFF] ^
              kCrcTable[3][(word >> 32) & 0xFF] ^
              kCrcTable[2][(word >> 40) & 0xFF] ^
              kCrcTable[1][(word >> 48) & 0xFF] ^
              kCrcTable[0][(word >> 56) & 0xFF];
        buf += 8;
        len -= 8;
    }
    while (len--) crc = (crc >> 8) ^ kCrcTable[0][(crc ^ *buf++) & 0xFF];
    return crc ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// TFRecord frame scan: u64 length, u32 masked-crc(len), payload, u32
// masked-crc(payload).  Fills (offset, length) pairs for every COMPLETE
// record in the buffer; *consumed reports the bytes covered by complete
// records so callers can stream the file in chunks (the next chunk starts
// at consumed).  verify_crc != 0 additionally checks both checksums (the
// pure-Python reader skips them; native is fast enough to verify).
//
// All bounds checks are subtraction-form — a hostile/corrupt u64 length
// field must not overflow `pos + rec_len` (that wrap previously caused an
// infinite loop / OOB read).
//
// Returns record count (>= 0; a partial record at the tail is NOT an
// error — it just isn't consumed), or -1 with *err_pos = byte offset on a
// CRC mismatch.
// ---------------------------------------------------------------------------

static inline uint32_t masked(uint32_t crc) {
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8u);
}

int64_t gft_scan_records(const uint8_t* buf, size_t len,
                         int64_t* offs, int64_t* lens, int64_t cap,
                         int verify_crc, size_t* consumed,
                         size_t* err_pos) {
    size_t pos = 0;
    int64_t n = 0;
    *consumed = 0;
    *err_pos = 0;
    while (len - pos >= 12) {
        uint64_t rec_len;
        std::memcpy(&rec_len, buf + pos, 8);
        // need rec_len + 4 more bytes after the 12-byte header; overflow-safe
        size_t avail = len - pos - 12;
        if (rec_len > avail || avail - rec_len < 4) break;  // partial tail
        if (verify_crc) {
            uint32_t want;
            std::memcpy(&want, buf + pos + 8, 4);
            if (masked(gft_crc32c(buf + pos, 8)) != want) {
                *err_pos = pos;
                return -1;
            }
            std::memcpy(&want, buf + pos + 12 + rec_len, 4);
            if (masked(gft_crc32c(buf + pos + 12, rec_len)) != want) {
                *err_pos = pos;
                return -1;
            }
        }
        if (n < cap) {
            offs[n] = (int64_t)(pos + 12);
            lens[n] = (int64_t)rec_len;
        }
        ++n;
        pos += 12 + (size_t)rec_len + 4;
        *consumed = pos;
    }
    return n;
}

// ---------------------------------------------------------------------------
// tf.train.Example walk for the reference schema {shape: int64[..],
// data: bytes} (proto field numbers cited at data/dataset.py:185-195).
// Fills shape (up to 4 dims) and the data span; returns 0 on success,
// negative error codes otherwise.
// ---------------------------------------------------------------------------

static int read_varint(const uint8_t* buf, size_t len, size_t* pos,
                       uint64_t* out) {
    uint64_t result = 0;
    int shift = 0;
    while (*pos < len && shift < 64) {
        uint8_t b = buf[(*pos)++];
        result |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) { *out = result; return 0; }
        shift += 7;
    }
    return -1;
}

// Walk one message level; returns 0 and the value span for `field`
// with wire type 2, scanning from *pos to end.
struct Span { size_t off; size_t len; };

static int find_fields(const uint8_t* buf, size_t off, size_t end,
                       int want_field, Span* out, int out_cap) {
    size_t pos = off;
    int found = 0;
    while (pos < end) {
        uint64_t tag, tmp;
        if (read_varint(buf, end, &pos, &tag)) return -2;
        int field = (int)(tag >> 3), wt = (int)(tag & 7);
        switch (wt) {
            case 0:
                if (read_varint(buf, end, &pos, &tmp)) return -2;
                break;
            case 2: {
                uint64_t ln;
                if (read_varint(buf, end, &pos, &ln)) return -2;
                if (ln > end - pos) return -2;     // overflow-safe bound
                if (field == want_field && found < out_cap) {
                    out[found].off = pos;
                    out[found].len = (size_t)ln;
                }
                if (field == want_field) ++found;
                pos += ln;
                break;
            }
            case 5: pos += 4; break;
            case 1: pos += 8; break;
            default: return -3;
        }
        if (pos > end) return -2;
    }
    return found;
}

int gft_parse_example(const uint8_t* buf, size_t len,
                      int64_t* shape, int32_t* ndim,
                      int64_t* data_off, int64_t* data_len) {
    Span features;
    int n = find_fields(buf, 0, len, 1, &features, 1);   // Example.features
    if (n < 1) return -10;
    Span entries[64];
    int n_ent = find_fields(buf, features.off, features.off + features.len,
                            1, entries, 64);             // feature map entries
    if (n_ent < 0) return -11;
    if (n_ent > 64) n_ent = 64;
    *ndim = 0;
    *data_off = -1;
    bool have_shape = false;
    for (int i = 0; i < n_ent; ++i) {
        Span key, val;
        if (find_fields(buf, entries[i].off, entries[i].off + entries[i].len,
                        1, &key, 1) < 1) continue;
        if (find_fields(buf, entries[i].off, entries[i].off + entries[i].len,
                        2, &val, 1) < 1) continue;
        if (key.len == 5 && !std::memcmp(buf + key.off, "shape", 5)) {
            Span lst;                                    // Feature.int64_list
            if (find_fields(buf, val.off, val.off + val.len, 3, &lst, 1) < 1)
                return -12;
            // int64_list.value: repeated varint (packed or not)
            size_t pos = lst.off, end = lst.off + lst.len;
            while (pos < end && *ndim < 4) {
                uint64_t tag;
                if (read_varint(buf, end, &pos, &tag)) return -12;
                int wt = (int)(tag & 7);
                if (wt == 0) {
                    uint64_t v;
                    if (read_varint(buf, end, &pos, &v)) return -12;
                    shape[(*ndim)++] = (int64_t)v;
                } else if (wt == 2) {                    // packed
                    uint64_t ln;
                    if (read_varint(buf, end, &pos, &ln)) return -12;
                    size_t pend = pos + ln;
                    while (pos < pend && *ndim < 4) {
                        uint64_t v;
                        if (read_varint(buf, pend, &pos, &v)) return -12;
                        shape[(*ndim)++] = (int64_t)v;
                    }
                } else return -12;
            }
            have_shape = true;
        } else if (key.len == 4 && !std::memcmp(buf + key.off, "data", 4)) {
            Span lst;                                    // Feature.bytes_list
            if (find_fields(buf, val.off, val.off + val.len, 1, &lst, 1) < 1)
                return -13;
            Span bytes;                                  // bytes_list.value
            if (find_fields(buf, lst.off, lst.off + lst.len, 1, &bytes, 1) < 1)
                return -13;
            *data_off = (int64_t)bytes.off;
            *data_len = (int64_t)bytes.len;
        }
    }
    if (!have_shape || *data_off < 0) return -14;
    return 0;
}

}  // extern "C"
