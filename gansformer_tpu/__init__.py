"""gansformer_tpu — a TPU-native (JAX/XLA) GANsformer framework.

A from-scratch re-design of the capability surface of
GiorgiaAuroraAdorni/gansformer-reproducibility-challenge (StyleGAN2-based
Generative Adversarial Transformers, TF1/CUDA lineage) for TPU hardware:

- ``ops``      — the compute primitives that replace the reference's custom
                 CUDA kernels (upfirdn2d, fused_bias_act, modulated conv,
                 bipartite attention), expressed as XLA-fusable jnp/lax
                 composites XLA fuses on its own (profiling showed no need for hand-written kernels).
- ``models``   — Flax generator (mapping + attention-augmented synthesis) and
                 discriminator.
- ``losses``   — non-saturating logistic GAN loss, R1, path-length reg.
- ``train``    — two-timescale G/D training engine with lazy regularization,
                 EMA generator, orbax checkpointing.
- ``parallel`` — device mesh / sharding layer (the NCCL all-reduce of the
                 reference becomes XLA collectives over ICI/DCN).
- ``data``     — record IO + dataset pipeline.
- ``metrics``  — on-device FID / Inception Score evaluator.
- ``cli``      — train / generate / evaluate entrypoints.

(Subpackages land incrementally; see the repo README for current status.)

Reference lineage is documented per-module via ``src/<path>`` citations into
the upstream layout reconstructed in /root/repo/SURVEY.md.
"""

__version__ = "0.1.0"
