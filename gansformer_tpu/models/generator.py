"""Generator = mapping + synthesis, with truncation support.

Reference: ``G_GANsformer`` + the EMA clone ``Gs`` and truncation trick
(SURVEY.md §2.3).  Unlike the reference — where truncation lives inside the
pickled Network via a ``w_avg`` variable — the w statistics here are part of
the train state (``w_avg`` EMA of mapping outputs), passed in explicitly at
sampling time.  That keeps the module pure and jit-friendly.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from gansformer_tpu.core.config import ModelConfig
from gansformer_tpu.models.mapping import MappingNetwork
from gansformer_tpu.models.synthesis import SynthesisNetwork


class Generator(nn.Module):
    cfg: ModelConfig

    def setup(self):
        cfg = self.cfg
        self.mapping = MappingNetwork(
            w_dim=cfg.w_dim, hidden_dim=cfg.mapping_dim,
            num_layers=cfg.mapping_layers, lrmul=cfg.mapping_lrmul,
            label_dim=cfg.label_dim)
        self.synthesis = SynthesisNetwork(cfg)

    def __call__(self, z: jax.Array, noise_mode: str = "random",
                 truncation_psi: float = 1.0,
                 w_avg: Optional[jax.Array] = None,
                 label: Optional[jax.Array] = None) -> jax.Array:
        """z: [N, num_ws, latent_dim] → images [N, R, R, C]."""
        ws = self.mapping(z, label)
        if truncation_psi != 1.0:
            assert w_avg is not None, "truncation needs the w_avg EMA"
            ws = w_avg[None, None, :] + truncation_psi * (ws - w_avg[None, None, :])
        return self.synthesis(ws, noise_mode=noise_mode)

    def map(self, z: jax.Array,
            label: Optional[jax.Array] = None) -> jax.Array:
        return self.mapping(z, label)

    def synthesize(self, ws: jax.Array, noise_mode: str = "random") -> jax.Array:
        return self.synthesis(ws, noise_mode=noise_mode)
