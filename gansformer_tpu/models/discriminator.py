"""Discriminator: StyleGAN2 residual D with optional bipartite attention.

Reference: D_GANsformer in ``src/training/network.py`` (SURVEY.md §2.3):
fromRGB at full resolution, residual blocks {conv 3×3, blur-pool down conv
3×3, 1×1 skip-down, sum/√2}, minibatch-stddev at 4×4, dense head → logit.
GANsformer optionally inserts bipartite attention with ``d_components``
learned query vectors that aggregate region statistics from the grid.
"""

from __future__ import annotations

import functools
import math

import flax.linen as nn
import jax
import jax.numpy as jnp

from gansformer_tpu.core.config import ModelConfig
from gansformer_tpu.models.attention import BipartiteAttention
from gansformer_tpu.models.layers import EqualConv, EqualDense, minibatch_stddev


class Discriminator(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, img: jax.Array,
                 label: "jax.Array | None" = None) -> jax.Array:
        """img: [N, R, R, C] (+ label [N, label_dim]) → logits [N, 1]."""
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        f = cfg.blur_filter
        x = img.astype(dtype)
        n = x.shape[0]
        # conv_backend routes the blur-pool/decimated-skip FIR legs of
        # every residual block through the fused upfirdn kernel
        # (ISSUE 14); the dense convs stay plain MXU contractions.
        Conv = functools.partial(EqualConv, conv_backend=cfg.conv_backend)

        x = Conv(cfg.nf(cfg.resolution), kernel=1, act="lrelu",
                 dtype=dtype, name="from_rgb")(x)

        # D attention is independent of the generator's attention flag — it
        # only keys off d_attention + the attn resolution window.
        attn_res = (
            {r for r in cfg.block_resolutions
             if cfg.attn_start_res <= r <= cfg.attn_max_res}
            if cfg.d_attention else set())
        if cfg.d_attention:
            queries = self.param("d_queries", nn.initializers.normal(1.0),
                                 (1, cfg.d_components, cfg.w_dim), jnp.float32)
            y = jnp.broadcast_to(
                queries, (n, cfg.d_components, cfg.w_dim)).astype(dtype)

        # resolution → resolution/2 residual blocks, down to 4×4
        for res in reversed(cfg.block_resolutions[1:]):  # R, R/2, ..., 8
            nf_out = cfg.nf(res // 2)
            if res in attn_res:
                x, y = BipartiteAttention(
                    grid_dim=x.shape[-1], latent_dim=cfg.w_dim,
                    num_heads=cfg.num_heads, duplex=True,
                    integration=cfg.integration,
                    pos_encoding=cfg.pos_encoding,
                    grid_shard=cfg.sequence_parallel,
                    backend=cfg.attention_backend,
                    fused_kv=cfg.attn_fused_kv,
                    dtype=dtype, name=f"b{res}_attn")(x, y)
            t = Conv(x.shape[-1], act="lrelu", resample_filter=f,
                     dtype=dtype, name=f"b{res}_conv0")(x)
            t = Conv(nf_out, down=2, act="lrelu", resample_filter=f,
                     dtype=dtype, name=f"b{res}_conv1")(t)
            skip = Conv(nf_out, kernel=1, down=2, use_bias=False,
                        resample_filter=f, dtype=dtype,
                        name=f"b{res}_skip")(x)
            x = (t + skip) * (1.0 / math.sqrt(2.0))

        # 4×4 head
        x = minibatch_stddev(x, cfg.mbstd_group_size, cfg.mbstd_num_features)
        x = Conv(cfg.nf(4), act="lrelu", dtype=dtype, name="head_conv")(x)
        x = x.reshape(n, -1)
        x = EqualDense(cfg.nf(2), act="lrelu", dtype=dtype, name="head_fc")(x)
        if cfg.label_dim > 0:
            # Projection head: logit = ⟨features, embed(label)⟩ / √dim — the
            # conditional-D scheme of the StyleGAN2 lineage.
            if label is None:
                raise ValueError("conditional discriminator needs a label")
            cmap_dim = cfg.nf(2)
            feat = EqualDense(cmap_dim, dtype=jnp.float32, name="head_out")(
                x.astype(jnp.float32))
            cmap = EqualDense(cmap_dim, name="label_embed")(
                label.astype(jnp.float32))
            cmap = cmap * jax.lax.rsqrt(
                jnp.mean(jnp.square(cmap), axis=-1, keepdims=True) + 1e-8)
            return jnp.sum(feat * cmap, axis=-1, keepdims=True) / \
                jnp.sqrt(jnp.asarray(cmap_dim, jnp.float32))
        x = EqualDense(1, dtype=jnp.float32, name="head_out")(x.astype(jnp.float32))
        return x
