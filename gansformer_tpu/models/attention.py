"""Bipartite attention block — the GANsformer layer (SURVEY.md §2.3).

Connects the k latent components Y ∈ R^{N×k×D} with the image feature grid
X ∈ R^{N×n×C} (n = H·W).  Cost O(n·k): two batched einsums + a softmax over
the tiny k axis — an MXU-friendly workload that shards over the batch axis
with no attention-specific collectives.

Simplex: grid attends to latents (Q from X, K/V from Y); the attended result
updates the grid features region-wise ("attention-driven styling" instead of
StyleGAN2's single global style).

Duplex: the latents first update themselves from the grid — Y acts as
key-value "centroids" tracking soft assignments (a k-means-like step) — and
then the grid attends back to the refined latents.  ``kmeans_iters`` controls
how many centroid refinement rounds run per block.

Integration modes (reference's ``integration`` flag):
  'add'  : X += proj(attended)
  'mul'  : X  = norm(X) * (1 + a(attended))
  'both' : X  = norm(X) * (1 + a(attended)) + b(attended)
where norm is a non-affine instance norm over grid positions (the learned
scale/shift comes from the attention output itself — that is the point).
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from gansformer_tpu.models.layers import EqualDense
from gansformer_tpu.ops import multihead_attention, sinusoidal_grid_encoding
from gansformer_tpu.parallel.mesh import MODEL_AXIS


def _instance_norm(x: jax.Array, axis: int = 1, eps: float = 1e-8) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mu = x32.mean(axis=axis, keepdims=True)
    var = x32.var(axis=axis, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype)


class BipartiteAttention(nn.Module):
    grid_dim: int            # C — channels of the grid features at this block
    latent_dim: int          # D — width of the latent components
    num_heads: int = 1
    duplex: bool = False
    integration: str = "both"
    kmeans_iters: int = 1
    pos_encoding: str = "sinusoidal"   # 'sinusoidal' | 'learned' | 'none'
    dtype: jnp.dtype = jnp.float32
    # Sequence/context parallelism: shard the n = H·W grid axis over the
    # mesh's model axis via GSPMD constraints (batch stays on the data axis).
    # The duplex centroid softmax then spans shards; XLA inserts exactly the
    # pmax/psum collectives that ``ops.attention.sharded_multihead_attention``
    # writes by hand (tests hold the two to parity).  Requires an ambient
    # mesh (``jax.sharding.set_mesh``) when enabled.
    grid_shard: bool = False
    # 'xla' (jnp composite) or 'pallas' (fused blockwise kernels with
    # backward kernels + a second-order derivative rule — training-grade
    # since ISSUE 9; ops/pallas_attention.py).  The pallas path sows no
    # probability maps, so attention-overlay collection needs 'xla'.
    backend: str = "xla"
    # MFU lever (ModelConfig.attn_fused_kv, ISSUE 5): one K∥V projection
    # matmul per direction instead of two.  Exact math (concatenated
    # weight columns — EqualDense's 1/√fan_in scale depends only on the
    # shared input width); the duplex centroid phase then reads the
    # n = H·W grid once instead of twice.  Different param tree — the
    # variant owns its own checkpoints.
    fused_kv: bool = False

    def _attend(self, q, k, v):
        """(out, probs|None) via the configured backend."""
        if self.backend == "pallas":
            from gansformer_tpu.ops.pallas_attention import (
                multihead_attention_pallas)
            interpret = jax.default_backend() != "tpu"
            return multihead_attention_pallas(
                q, k, v, self.num_heads, interpret=interpret), None
        return multihead_attention(q, k, v, self.num_heads)

    def _constrain(self, t: jax.Array) -> jax.Array:
        """Pin a [N, n, ...] grid tensor's n axis to the model mesh axis.

        The batch dim stays UNCONSTRAINED: the main step batches are data-
        sharded, but the path-length phase synthesizes at batch//pl_shrink,
        which may not divide the data axis — GSPMD picks per-caller.

        No-op when no ambient mesh (or one without a model axis) is active:
        a checkpoint trained with sequence_parallel=True must still sample
        on a single chip from the plain generate/evaluate CLIs."""
        if not self.grid_shard:
            return t
        from jax.sharding import PartitionSpec as P
        mesh = None
        try:
            from jax.sharding import get_abstract_mesh
            mesh = get_abstract_mesh()
        except ImportError:
            pass
        if mesh is None or mesh.empty:
            # jax without set_mesh (0.4/0.5): the ambient mesh is whatever
            # `with Mesh:` installed (MeshEnv.activate's fallback), so an
            # empty ABSTRACT mesh must not silently disable grid sharding.
            try:
                from jax._src.mesh import thread_resources
            except ImportError:   # private symbol gone: treat as no mesh
                return t
            mesh = thread_resources.env.physical_mesh
        if mesh.empty or MODEL_AXIS not in mesh.axis_names:
            return t
        spec = P(P.UNCONSTRAINED, MODEL_AXIS, *([None] * (t.ndim - 2)))
        return jax.lax.with_sharding_constraint(t, spec)

    @nn.compact
    def __call__(self, x: jax.Array, y: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """x: [N,H,W,C] grid, y: [N,k,D] latents → (updated x, updated y)."""
        n, h, w, c = x.shape
        k = y.shape[1]
        att = self.grid_dim  # attention width
        assert att % self.num_heads == 0

        grid = self._constrain(x.reshape(n, h * w, c))

        # Positional encodings enter the grid's QUERIES/KEYS only (content
        # stream stays position-free, as values carry content).
        if self.pos_encoding == "sinusoidal":
            pe_dim = max(4, (att // 4) * 4)
            enc = jnp.asarray(sinusoidal_grid_encoding(h, w, pe_dim))
            pos = EqualDense(att, dtype=self.dtype, name="pos_proj")(
                enc.astype(self.dtype))[None]                      # [1,n,att]
        elif self.pos_encoding == "learned":
            pos = self.param("pos_emb", nn.initializers.normal(0.02),
                             (1, h * w, att), jnp.float32).astype(self.dtype)
        else:
            pos = jnp.zeros((1, 1, att), dtype=self.dtype)

        grid_qk = grid.astype(self.dtype)

        if self.duplex:
            # Centroid phase: latents query the grid and absorb what their
            # regions look like (soft k-means assignment + update).
            for it in range(self.kmeans_iters):
                q_y = EqualDense(att, dtype=self.dtype,
                                 name=f"dup{it}_q_y")(y.astype(self.dtype))
                if self.fused_kv:
                    # K∥V in one matmul over the grid (v_x's unfused input
                    # grid.astype(dtype) IS grid_qk); pos enters K only.
                    kv_x = EqualDense(att + self.latent_dim,
                                      dtype=self.dtype,
                                      name=f"dup{it}_kv_x")(grid_qk)
                    k_x = kv_x[..., :att] + pos
                    v_x = kv_x[..., att:]
                else:
                    k_x = EqualDense(att, dtype=self.dtype,
                                     name=f"dup{it}_k_x")(grid_qk) + pos
                    v_x = EqualDense(self.latent_dim, dtype=self.dtype,
                                     name=f"dup{it}_v_x")(
                                         grid.astype(self.dtype))
                upd, _ = self._attend(q_y, k_x, v_x)
                gate = EqualDense(self.latent_dim, dtype=self.dtype,
                                  name=f"dup{it}_gate")(upd)
                y = y + jax.nn.sigmoid(gate.astype(jnp.float32)).astype(y.dtype) \
                    * EqualDense(self.latent_dim, dtype=self.dtype,
                                 name=f"dup{it}_proj")(upd).astype(y.dtype)

        # Main phase: grid attends to (possibly refined) latents.
        q_x = EqualDense(att, dtype=self.dtype, name="q_x")(grid_qk) + pos
        if self.fused_kv:
            kv_y = EqualDense(2 * att, dtype=self.dtype,
                              name="kv_y")(y.astype(self.dtype))
            k_y, v_y = kv_y[..., :att], kv_y[..., att:]
        else:
            k_y = EqualDense(att, dtype=self.dtype,
                             name="k_y")(y.astype(self.dtype))
            v_y = EqualDense(att, dtype=self.dtype,
                             name="v_y")(y.astype(self.dtype))
        out, probs = self._attend(q_x, k_y, v_y)
        # Region-assignment maps [N, heads, n, k] — the GANsformer paper's
        # attention visualizations; collected only when callers apply with
        # mutable=['intermediates'] (zero cost otherwise).  The pallas
        # backend never materializes the maps (that is its point).
        if probs is not None:
            self.sow("intermediates", "attn_probs",
                     probs.reshape(n, self.num_heads, h, w, k))

        if self.integration == "add":
            grid = grid + EqualDense(c, dtype=self.dtype, name="o_proj")(out)
        else:
            scale = EqualDense(c, dtype=self.dtype, name="o_scale")(out)
            normed = _instance_norm(grid, axis=1)
            grid = normed * (1.0 + scale)
            if self.integration == "both":
                grid = grid + EqualDense(c, dtype=self.dtype, name="o_shift")(out)

        grid = self._constrain(grid)
        return grid.reshape(n, h, w, c).astype(x.dtype), y
