"""Mapping network: z → w, shared across the k latent components.

Reference: the 8-layer FC mapping of G_GANsformer (``src/training/network.py``
G_mapping; SURVEY.md §2.3) — lrelu MLP with 0.01 lr-multiplier, input
pixel-norm per component.  The same MLP maps every component (weight sharing),
so the Dense-on-last-axis broadcast over the component axis is the whole
implementation — no per-component loop.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from gansformer_tpu.models.layers import EqualDense


class MappingNetwork(nn.Module):
    w_dim: int = 512
    hidden_dim: int = 512
    num_layers: int = 8
    lrmul: float = 0.01
    # Conditional path (label_dim > 0): the label is embedded, pixel-normed,
    # and concatenated onto every component's latent before the MLP — the
    # lineage's conditional-mapping scheme (embed + concat, SURVEY.md §2.2).
    label_dim: int = 0

    @nn.compact
    def __call__(self, z: jax.Array,
                 label: "jax.Array | None" = None) -> jax.Array:
        """z: [N, num_ws, latent_dim] (+ label [N, label_dim]) →
        w: [N, num_ws, w_dim] (fp32)."""
        assert z.ndim == 3
        x = z.astype(jnp.float32)
        # per-component pixel norm
        x = x * jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True)
                              + 1e-8)
        if self.label_dim > 0:
            if label is None:
                raise ValueError("conditional mapping needs a label")
            y = EqualDense(x.shape[-1], name="label_embed")(
                label.astype(jnp.float32))
            y = y * jax.lax.rsqrt(
                jnp.mean(jnp.square(y), axis=-1, keepdims=True) + 1e-8)
            y = jnp.broadcast_to(y[:, None, :],
                                 (x.shape[0], x.shape[1], y.shape[-1]))
            x = jnp.concatenate([x, y], axis=-1)
        for i in range(self.num_layers - 1):
            x = EqualDense(self.hidden_dim, lrmul=self.lrmul, act="lrelu",
                           name=f"fc{i}")(x)
        x = EqualDense(self.w_dim, lrmul=self.lrmul, act="lrelu",
                       name=f"fc{self.num_layers - 1}")(x)
        return x
