"""Synthesis network: const 4×4 → modulated-conv blocks with bipartite
attention → RGB skip accumulation.

Reference: G_synthesis of ``src/training/network.py`` (SURVEY.md §2.3):
StyleGAN2 skeleton — learned constant input, per-resolution {up-conv, conv}
pairs with noise + fused lrelu, tRGB skip summation with FIR-upsampled
accumulation — augmented with simplex/duplex bipartite attention between the
k latent components and the grid at resolutions 4..attn_max_res.

Style routing (``cfg.style_mode``):
  'global'    — the dedicated *global* latent component drives every conv's
                modulation (StyleGAN2-style global statistics); the k
                components inject region-wise structure through the
                attention-block gating only.
  'attention' — after each attention block the refined latents are projected
                and added to the global style, so later convs are modulated
                by attention output — the ``modulated_conv2d(x, w_attn)``
                routing of SURVEY.md §3.2.
"""

from __future__ import annotations

import functools
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from gansformer_tpu.core.config import ModelConfig
from gansformer_tpu.models.attention import BipartiteAttention
from gansformer_tpu.models.layers import EqualDense, ModulatedConv
from gansformer_tpu.ops import upsample_2d


class SynthesisNetwork(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, ws: jax.Array, noise_mode: str = "random") -> jax.Array:
        """ws: [N, num_ws, w_dim] → images [N, R, R, C] in [-1, 1]-ish range."""
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        n = ws.shape[0]
        assert ws.shape[1] == cfg.num_ws

        # Global component drives conv styles; the k components feed attention.
        if cfg.use_global:
            w_global = ws[:, -1]
            y = ws[:, : cfg.components]
        else:
            w_global = ws.mean(axis=1)
            y = ws
        y = y.astype(dtype)

        attn_res = set(cfg.attn_resolutions())
        f = cfg.blur_filter
        assert cfg.style_mode in ("global", "attention"), cfg.style_mode

        const = self.param("const", nn.initializers.normal(1.0),
                           (1, 4, 4, cfg.nf(4)), jnp.float32)
        x = jnp.broadcast_to(const, (n, 4, 4, cfg.nf(4))).astype(dtype)

        # No per-block remat here, deliberately: measured to INCREASE the
        # second-order-grad workspace at ffhq1024 (PERF.md §2a).
        Attn = BipartiteAttention
        Conv = functools.partial(ModulatedConv,
                                 conv_backend=cfg.conv_backend)

        # Running conv style: starts at the global latent; in 'attention'
        # mode each attention block folds its refined latents in, so convs
        # downstream are modulated by attention output (w_attn, §3.2).
        w_style = w_global
        rgb: Optional[jax.Array] = None
        for res in cfg.block_resolutions:
            nf = cfg.nf(res)
            if res > 4:
                x = Conv(nf, up=2, resample_filter=f, dtype=dtype,
                         name=f"b{res}_conv_up")(x, w_style, noise_mode)
            x = Conv(nf, resample_filter=f, dtype=dtype,
                     name=f"b{res}_conv")(x, w_style, noise_mode)
            if res in attn_res:
                x, y = Attn(
                    grid_dim=nf, latent_dim=cfg.w_dim,
                    num_heads=cfg.num_heads,
                    duplex=(cfg.attention == "duplex"),
                    integration=cfg.integration,
                    kmeans_iters=cfg.kmeans_iters,
                    pos_encoding=cfg.pos_encoding,
                    grid_shard=cfg.sequence_parallel,
                    backend=cfg.attention_backend,
                    fused_kv=cfg.attn_fused_kv,
                    dtype=dtype, name=f"b{res}_attn")(x, y)
                if cfg.style_mode == "attention":
                    # ReZero-gated: scalar starts at 0 so styling begins
                    # exactly global and training grows the attention term.
                    w_attn = EqualDense(
                        cfg.w_dim, dtype=jnp.float32,
                        name=f"b{res}_wattn")(
                            y.mean(axis=1).astype(jnp.float32))
                    gate = self.param(f"b{res}_wattn_gate",
                                      nn.initializers.zeros, (), jnp.float32)
                    w_style = w_global + gate * w_attn
            # tRGB skip: modulated 1×1, no demod, linear act.
            t = Conv(cfg.img_channels, kernel=1, demodulate=False,
                     use_noise=False, act="linear", dtype=dtype,
                     name=f"b{res}_trgb")(x, w_style, noise_mode="none")
            rgb = (t if rgb is None
                   else upsample_2d(rgb, f, backend=cfg.conv_backend) + t)

        return rgb.astype(jnp.float32)
