"""Synthesis network: const 4×4 → modulated-conv blocks with bipartite
attention → RGB skip accumulation.

Reference: G_synthesis of ``src/training/network.py`` (SURVEY.md §2.3):
StyleGAN2 skeleton — learned constant input, per-resolution {up-conv, conv}
pairs with noise + fused lrelu, tRGB skip summation with FIR-upsampled
accumulation — augmented with simplex/duplex bipartite attention between the
k latent components and the grid at resolutions 4..attn_max_res.

Style routing: the dedicated *global* latent component drives every conv's
modulation (StyleGAN2-style global statistics); the k components inject
region-wise structure through the attention blocks.  This is the same split
of responsibilities the reference implements.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from gansformer_tpu.core.config import ModelConfig
from gansformer_tpu.models.attention import BipartiteAttention
from gansformer_tpu.models.layers import ModulatedConv
from gansformer_tpu.ops import upsample_2d


class SynthesisNetwork(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, ws: jax.Array, noise_mode: str = "random") -> jax.Array:
        """ws: [N, num_ws, w_dim] → images [N, R, R, C] in [-1, 1]-ish range."""
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        n = ws.shape[0]
        assert ws.shape[1] == cfg.num_ws

        # Global component drives conv styles; the k components feed attention.
        if cfg.use_global:
            w_global = ws[:, -1]
            y = ws[:, : cfg.components]
        else:
            w_global = ws.mean(axis=1)
            y = ws
        y = y.astype(dtype)

        attn_res = set(cfg.attn_resolutions())
        f = cfg.blur_filter

        const = self.param("const", nn.initializers.normal(1.0),
                           (1, 4, 4, cfg.nf(4)), jnp.float32)
        x = jnp.broadcast_to(const, (n, 4, 4, cfg.nf(4))).astype(dtype)

        rgb: Optional[jax.Array] = None
        for res in cfg.block_resolutions:
            nf = cfg.nf(res)
            if res > 4:
                x = ModulatedConv(nf, up=2, resample_filter=f, dtype=dtype,
                                  name=f"b{res}_conv_up")(x, w_global,
                                                          noise_mode=noise_mode)
            x = ModulatedConv(nf, resample_filter=f, dtype=dtype,
                              name=f"b{res}_conv")(x, w_global,
                                                   noise_mode=noise_mode)
            if res in attn_res:
                x, y = BipartiteAttention(
                    grid_dim=nf, latent_dim=cfg.w_dim,
                    num_heads=cfg.num_heads,
                    duplex=(cfg.attention == "duplex"),
                    integration=cfg.integration,
                    kmeans_iters=cfg.kmeans_iters,
                    pos_encoding=cfg.pos_encoding,
                    dtype=dtype, name=f"b{res}_attn")(x, y)
            # tRGB skip: modulated 1×1, no demod, linear act.
            t = ModulatedConv(cfg.img_channels, kernel=1, demodulate=False,
                              use_noise=False, act="linear", dtype=dtype,
                              name=f"b{res}_trgb")(x, w_global,
                                                   noise_mode="none")
            rgb = t if rgb is None else upsample_2d(rgb, f) + t

        return rgb.astype(jnp.float32)
