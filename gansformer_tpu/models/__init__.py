from gansformer_tpu.models.layers import EqualDense, EqualConv, minibatch_stddev
from gansformer_tpu.models.attention import BipartiteAttention
from gansformer_tpu.models.mapping import MappingNetwork
from gansformer_tpu.models.synthesis import SynthesisNetwork
from gansformer_tpu.models.discriminator import Discriminator
from gansformer_tpu.models.generator import Generator
