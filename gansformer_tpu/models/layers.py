"""Equalized-learning-rate layers + shared building blocks.

StyleGAN2's trick (reference ``src/training/network.py``: ``get_weight`` with
``he_std``/``lrmul`` runtime scaling, SURVEY.md §2.3): parameters are stored
at unit scale and multiplied by ``gain/sqrt(fan_in) * lrmul`` at use time so
Adam's per-parameter normalization sees identical gradient scales everywhere.
Params live in fp32; compute may be bf16 (``dtype``).
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gansformer_tpu.ops import (
    conv2d, fused_bias_act, modulated_conv2d, resolve_weight)


def matmul_precision(dtype) -> lax.Precision:
    """fp32 math runs at true fp32; bf16 rides the MXU natively."""
    return lax.Precision.HIGHEST if dtype == jnp.float32 else lax.Precision.DEFAULT


class EqualDense(nn.Module):
    features: int
    gain: float = 1.0
    lrmul: float = 1.0
    use_bias: bool = True
    bias_init: float = 0.0
    act: str = "linear"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        fan_in = x.shape[-1]
        # resolve_weight: int8w serving bundles store QuantizedWeight
        # leaves; dequant happens here, ahead of the lrmul/gain scaling.
        w = resolve_weight(
            self.param("w", nn.initializers.normal(stddev=1.0 / self.lrmul),
                       (fan_in, self.features), jnp.float32))
        coef = self.gain / math.sqrt(fan_in) * self.lrmul
        y = jnp.dot(x.astype(self.dtype), (w * coef).astype(self.dtype),
                    precision=matmul_precision(self.dtype))
        b = None
        if self.use_bias:
            b = self.param("b", nn.initializers.constant(self.bias_init),
                           (self.features,), jnp.float32) * self.lrmul
        return fused_bias_act(y, b, act=self.act)


class EqualConv(nn.Module):
    features: int
    kernel: int = 3
    up: int = 1
    down: int = 1
    gain: float = 1.0
    lrmul: float = 1.0
    use_bias: bool = True
    act: str = "linear"
    resample_filter: tuple = (1, 3, 3, 1)
    dtype: jnp.dtype = jnp.float32
    # 'xla' | 'pallas' (ModelConfig.conv_backend, ISSUE 14): 'pallas'
    # routes this layer's FIR resampling legs (blur-pool, decimated
    # skip) through the fused pad→FIR→resample kernel; the dense conv
    # itself stays a plain MXU contraction either way.
    conv_backend: str = "xla"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        fan_in = x.shape[-1] * self.kernel**2
        w = resolve_weight(
            self.param("w", nn.initializers.normal(stddev=1.0 / self.lrmul),
                       (self.kernel, self.kernel, x.shape[-1], self.features),
                       jnp.float32))
        coef = self.gain / math.sqrt(fan_in) * self.lrmul
        y = conv2d(x.astype(self.dtype), (w * coef).astype(self.dtype),
                   up=self.up, down=self.down,
                   resample_filter=self.resample_filter,
                   backend=self.conv_backend)
        b = None
        if self.use_bias:
            b = self.param("b", nn.initializers.zeros,
                           (self.features,), jnp.float32) * self.lrmul
        return fused_bias_act(y, b, act=self.act)


class ModulatedConv(nn.Module):
    """Style-modulated conv layer: affine(w_style) → modulated_conv2d → noise
    → fused bias+act.  The per-layer unit of the synthesis network
    (reference's ``layer()`` inside G_synthesis, SURVEY.md §2.3)."""

    features: int
    kernel: int = 3
    up: int = 1
    demodulate: bool = True
    use_noise: bool = True
    act: str = "lrelu"
    resample_filter: tuple = (1, 3, 3, 1)
    dtype: jnp.dtype = jnp.float32
    # 'xla' (jnp composite) or 'pallas' (the fused kernel family of
    # ops/pallas_modconv.py — modulate→conv→demodulate in one kernel,
    # polyphase up-conv + depth-to-space fused, blur + bias/act on the
    # fused upfirdn kernel; training-grade to second order, ISSUE 14).
    conv_backend: str = "xla"

    @nn.compact
    def __call__(self, x: jax.Array, w_style: jax.Array,
                 noise_mode: str = "random") -> jax.Array:
        cin = x.shape[-1]
        # Style affine "A": bias-init 1 so styles start at identity.
        styles = EqualDense(cin, bias_init=1.0, dtype=jnp.float32,
                            name="affine")(w_style)
        weight = resolve_weight(
            self.param("w", nn.initializers.normal(stddev=1.0),
                       (self.kernel, self.kernel, cin, self.features),
                       jnp.float32))
        coef = 1.0 / math.sqrt(cin * self.kernel**2)
        assert noise_mode in ("random", "none"), f"bad noise_mode {noise_mode!r}"
        add_noise = self.use_noise and noise_mode != "none"
        b = self.param("b", nn.initializers.zeros, (self.features,), jnp.float32)
        if self.conv_backend == "pallas":
            from gansformer_tpu.ops import modulated_conv2d_pallas

            # Noise sits between demod and bias/act, so the bias/act
            # epilogue fuses into the final kernel only on the
            # noise-free paths (tRGB always; everything at
            # noise_mode='none').
            y = modulated_conv2d_pallas(
                x.astype(self.dtype), (weight * coef).astype(self.dtype),
                styles, demodulate=self.demodulate, up=self.up,
                resample_filter=self.resample_filter,
                bias=None if add_noise else b,
                act=None if add_noise else self.act)
            if not add_noise:
                return y
        else:
            y = modulated_conv2d(x.astype(self.dtype),
                                 (weight * coef).astype(self.dtype),
                                 styles, demodulate=self.demodulate,
                                 up=self.up,
                                 resample_filter=self.resample_filter)
        if add_noise:
            strength = self.param("noise_strength", nn.initializers.zeros,
                                  (), jnp.float32)
            noise = jax.random.normal(self.make_rng("noise"),
                                      y.shape[:3] + (1,), dtype=self.dtype)
            y = y + noise * strength.astype(self.dtype)
        return fused_bias_act(y, b, act=self.act)


def minibatch_stddev(x: jax.Array, group_size: int = 4,
                     num_features: int = 1) -> jax.Array:
    """Append cross-sample stddev statistics as extra channels.

    Reference: minibatch-stddev layer in D (SURVEY.md §2.3).  Under a sharded
    batch axis the mean over N is handled by GSPMD (becomes a psum over the
    data mesh axis), exactly replacing the reference's in-graph per-tower
    behavior — but global, which is strictly better.
    """
    n, h, w, c = x.shape
    g = min(group_size, n)
    while n % g != 0:
        g -= 1
    f = num_features
    # groups of g CONSECUTIVE samples
    y = x.reshape(n // g, g, h, w, f, c // f).astype(jnp.float32)
    y = y - y.mean(axis=1, keepdims=True)
    y = jnp.sqrt(jnp.square(y).mean(axis=1) + 1e-8)   # [n/g, h, w, f, c/f]
    y = y.mean(axis=(1, 2, 4))                        # [n/g, f]
    y = jnp.repeat(y, g, axis=0).reshape(n, 1, 1, f)
    y = jnp.broadcast_to(y, (n, h, w, f)).astype(x.dtype)
    return jnp.concatenate([x, y], axis=-1)
