from gansformer_tpu.metrics.fid import (
    frechet_distance,
    compute_activation_stats,
    fid_from_features,
)
from gansformer_tpu.metrics.inception_score import inception_score
from gansformer_tpu.metrics.metric_base import MetricGroup, FIDMetric, ISMetric
