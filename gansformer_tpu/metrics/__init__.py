from gansformer_tpu.metrics.fid import (
    frechet_distance,
    compute_activation_stats,
    fid_from_features,
)
from gansformer_tpu.metrics.inception_score import inception_score
from gansformer_tpu.metrics.metric_base import (
    MetricGroup,
    FIDMetric,
    ISMetric,
    PPLMetric,
    PRMetric,
    parse_metric_names,
)
from gansformer_tpu.metrics.precision_recall import precision_recall
from gansformer_tpu.metrics.ppl import ppl_from_distances
