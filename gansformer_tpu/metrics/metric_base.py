"""Metric registry + real-statistics caching — reference ``metric_base.py``
(SURVEY.md §2.2, §3.3): metrics run per snapshot; Inception activations of
the real dataset are computed once and cached on disk keyed by dataset.
"""

from __future__ import annotations

import hashlib
import os
from typing import Callable, Dict, Iterator, List, Optional

import jax
import numpy as np

from gansformer_tpu.data.dataset import Dataset, normalize_images
from gansformer_tpu.obs import registry as telemetry
from gansformer_tpu.obs.spans import get_tracer, span
from gansformer_tpu.metrics.fid import compute_activation_stats, frechet_distance
from gansformer_tpu.metrics.inception import FeatureExtractor, make_extractor
from gansformer_tpu.metrics.inception_score import inception_score


# Keys in MetricGroup.run output that are boolean FLAGS, not metrics
# (VERDICT r5 weak #4 / item 7): consumers (train loop, evaluate CLI,
# learning-run harvester) must route these to flag-<name>.txt / log lines
# and never emit them as metric-<name>.txt series.
FLAG_KEYS = ("calibrated",)


class Metric:
    name: str = "metric"

    def run(self, sample_fn: Callable[[int], jax.Array], dataset: Dataset,
            extractor: FeatureExtractor, cache_dir: Optional[str],
            pair_fn: Optional[Callable] = None,
            sweep_cache: Optional[Dict] = None) -> Dict[str, float]:
        """pair_fn(n, t, seed, epsilon) → (img_a, img_b): the generator's
        PPL probe (train/steps.py ``ppl_pairs``); None for callers that
        only run image-level metrics.  sweep_cache: per-group memo dict so
        fid/is/pr share one 50k-fake sweep."""
        raise NotImplementedError


def _real_features(dataset: Dataset, extractor: FeatureExtractor,
                   num_images: int, batch_size: int,
                   cache: Optional[dict] = None) -> np.ndarray:
    """The ONE real-image feature sweep (FID stats + P&R share it);
    memoized per MetricGroup.run like the fake sweep.

    Multi-host (VERDICT r3 weak #3): each process reads a DISJOINT shard of
    the dataset and the extractor merges per-batch features globally, so
    every process sees identical features of ``num_images`` real images —
    instead of every host sweeping (and double-counting) the full set.
    """
    if cache is not None and ("real", num_images, batch_size) in cache:
        return cache[("real", num_images, batch_size)]
    pc = jax.process_count()
    # single-process stays byte-identical to the historical sweep (and
    # tolerates minimal dataset stubs without a shard kwarg)
    kw = {"shard": (jax.process_index(), pc)} if pc > 1 else {}
    local_bs = max(1, batch_size // pc)
    feats = []
    seen = 0
    for batch in dataset.batches(local_bs, seed=123, **kw):
        imgs = normalize_images(np.asarray(batch["image"], np.float32))
        f, _ = extractor(imgs)         # global features under multi-host
        take = min(len(f), num_images - seen)
        feats.append(np.asarray(f[:take]))
        seen += take
        if seen >= num_images:
            break
    out = np.concatenate(feats)
    if cache is not None:
        cache[("real", num_images, batch_size)] = out
    return out


def _real_stats(dataset: Dataset, extractor: FeatureExtractor,
                num_images: int, batch_size: int,
                cache_dir: Optional[str]):
    """(μ, Σ) of real-image features, disk-cached like the reference's
    per-dataset activation pickles."""
    key = None
    if cache_dir:
        # 'rand2' (not 'rand'): the r5 uncalibrated-extractor fix (He
        # rescale + probe standardization, inception.py) changes every
        # random-regime feature — a pre-fix cached μ/Σ must not be reused.
        tag = f"{dataset.cache_tag()}-{num_images}-" \
              f"{'cal' if extractor.calibrated else 'rand2'}"
        key = os.path.join(
            cache_dir, "real-stats-" +
            hashlib.md5(tag.encode()).hexdigest()[:16] + ".npz")
        if os.path.exists(key):
            z = np.load(key)
            return z["mu"], z["sigma"]
    mu, sigma = compute_activation_stats(
        _real_features(dataset, extractor, num_images, batch_size))
    if key:
        # EVERY process writes (they computed identical stats — enforced by
        # the extractor's cross-host calibration check): with per-host
        # run_dirs each host needs its own copy, and a process-0-only write
        # would desynchronize the `os.path.exists(key)` fast path above,
        # deadlocking the next COLLECTIVE sweep.  Unique tmp + atomic
        # replace keeps same-host processes from interleaving writes.
        os.makedirs(cache_dir, exist_ok=True)
        tmp = f"{key}.tmp{jax.process_index()}.npz"
        np.savez(tmp, mu=mu, sigma=sigma)
        os.replace(tmp, key)
    return mu, sigma


def _fake_features(sample_fn, extractor, num_images: int, batch_size: int,
                   cache: Optional[dict] = None):
    """50k-fake generation + extraction; memoized per MetricGroup.run so
    fid/is/pr in one group share a single sweep."""
    if cache is not None and ("fake", num_images, batch_size) in cache:
        return cache[("fake", num_images, batch_size)]
    feats, logits = [], []
    seen = 0
    while seen < num_images:
        imgs = sample_fn(batch_size)
        f, l = extractor(imgs)
        take = min(batch_size, num_images - seen)
        feats.append(np.asarray(f[:take]))
        logits.append(np.asarray(l[:take]))
        seen += take
    out = (np.concatenate(feats), np.concatenate(logits))
    if cache is not None:
        cache[("fake", num_images, batch_size)] = out
    return out


def _count_tag(n: int) -> str:
    return f"{n // 1000}k" if n >= 1000 and n % 1000 == 0 else str(n)


class FIDMetric(Metric):
    """FID@N — the north-star metric (BASELINE.json:2)."""

    def __init__(self, num_images: int = 50000, batch_size: int = 32):
        self.name = f"fid{_count_tag(num_images)}"
        self.num_images = num_images
        self.batch_size = batch_size

    def run(self, sample_fn, dataset, extractor, cache_dir, pair_fn=None,
            sweep_cache=None):
        mu_r, s_r = _real_stats(dataset, extractor,
                                min(self.num_images,
                                    dataset.num_images or self.num_images),
                                self.batch_size, cache_dir)
        feats, _ = _fake_features(sample_fn, extractor, self.num_images,
                                  self.batch_size, cache=sweep_cache)
        mu_f, s_f = compute_activation_stats(feats)
        # With random Inception weights the number is a valid two-sample
        # discrepancy but NOT comparable to published FID — say so in the
        # metric name itself so it can never be mistaken for the real thing.
        name = self.name if extractor.calibrated else f"{self.name}_uncal"
        return {name: frechet_distance(mu_r, s_r, mu_f, s_f)}


class ISMetric(Metric):
    def __init__(self, num_images: int = 50000, batch_size: int = 32,
                 splits: int = 10):
        self.name = f"is{_count_tag(num_images)}"
        self.num_images = num_images
        self.batch_size = batch_size
        self.splits = splits

    def run(self, sample_fn, dataset, extractor, cache_dir, pair_fn=None,
            sweep_cache=None):
        _, logits = _fake_features(sample_fn, extractor, self.num_images,
                                   self.batch_size, cache=sweep_cache)
        mean, std = inception_score(logits, self.splits)
        name = self.name if extractor.calibrated else f"{self.name}_uncal"
        return {f"{name}_mean": mean, f"{name}_std": std}


class PPLMetric(Metric):
    """Perceptual path length (reference perceptual_path_length.py) over the
    generator's w-space lerp probe — needs ``pair_fn`` (train/steps.py
    ``ppl_pairs``)."""

    def __init__(self, num_samples: int = 50000, batch_size: int = 32,
                 epsilon: float = 1e-4):
        self.name = f"ppl{_count_tag(num_samples)}_wfull"
        self.num_samples = num_samples
        self.batch_size = batch_size
        self.epsilon = epsilon

    def run(self, sample_fn, dataset, extractor, cache_dir, pair_fn=None,
            sweep_cache=None):
        if pair_fn is None:
            raise ValueError(
                "PPL needs the generator's pair probe; pass pair_fn "
                "(train/steps.py ppl_pairs) into MetricGroup.run")
        from gansformer_tpu.metrics.ppl import (
            ppl_from_distances, sample_ppl_distances)

        d = sample_ppl_distances(pair_fn, extractor, self.num_samples,
                                 self.batch_size, self.epsilon)
        name = self.name if extractor.calibrated else f"{self.name}_uncal"
        return {name: ppl_from_distances(d)}


class PRMetric(Metric):
    """Improved precision & recall (reference precision_recall.py)."""

    def __init__(self, num_images: int = 50000, batch_size: int = 32,
                 k: int = 3):
        self.name = f"pr{_count_tag(num_images)}_{k}"
        self.num_images = num_images
        self.batch_size = batch_size
        self.k = k

    def run(self, sample_fn, dataset, extractor, cache_dir, pair_fn=None,
            sweep_cache=None):
        from gansformer_tpu.metrics.precision_recall import precision_recall

        # P&R needs raw real FEATURES (not μ/Σ) — shares the single
        # real-image sweep helper; fakes come from the per-group cache.
        n_real = min(self.num_images,
                     dataset.num_images or self.num_images)
        feats_r = _real_features(dataset, extractor, n_real, self.batch_size,
                                 cache=sweep_cache)
        feats_f, _ = _fake_features(sample_fn, extractor, self.num_images,
                                    self.batch_size, cache=sweep_cache)
        p, r = precision_recall(feats_r, feats_f, k=self.k)
        name = self.name if extractor.calibrated else f"{self.name}_uncal"
        return {f"{name}_precision": p, f"{name}_recall": r}


class MetricGroup:
    """Run a set of metrics against one generator snapshot — the analog of
    the reference's ``MetricGroup.run(snapshot_pkl, dataset)``."""

    def __init__(self, metrics: List[Metric],
                 extractor: Optional[FeatureExtractor] = None,
                 cache_dir: Optional[str] = None):
        self.metrics = metrics
        self.extractor = extractor or make_extractor()
        self.cache_dir = cache_dir

    def run(self, sample_fn: Callable[[int], jax.Array],
            dataset: Dataset,
            pair_fn: Optional[Callable] = None) -> Dict[str, float]:
        out: Dict[str, float] = {}
        sweep_cache: Dict = {}   # fid/is/pr share one 50k-fake sweep
        for m in self.metrics:
            # Per-metric span (→ events.jsonl, nested under the loop's
            # `metric` phase) + a duration gauge, so a slow metric sweep
            # is attributable to the metric, not just "metrics".
            with span(f"metric/{m.name}") as sp:
                out.update(m.run(sample_fn, dataset, self.extractor,
                                 self.cache_dir, pair_fn=pair_fn,
                                 sweep_cache=sweep_cache))
            telemetry.gauge(f"metric/{m.name}/duration_s").set(sp.duration_s)
            telemetry.counter("metric/runs_total").inc()
        # sweeps also run OUTSIDE the train loop's flush points (evaluate
        # CLI, post-train experiment sweep): push the buffered span events
        # to events.jsonl now or they die with the process / next reset
        get_tracer().flush()
        out["calibrated"] = float(self.extractor.calibrated)
        return out


def parse_metric_names(names: str, num_images: Optional[int] = None,
                       batch_size: int = 32) -> List[Metric]:
    """'fid50k,is50k' → metric objects (reference CLI --metrics flag).

    An explicit ``num_images`` overrides the count encoded in the name —
    and the metric object renames itself accordingly, so a 1k-sample smoke
    FID is never logged as fid50k.
    """
    def parse_count(suffix: str) -> int:
        if not suffix:
            return 50000
        return (int(suffix[:-1]) * 1000 if suffix.endswith("k")
                else int(suffix))

    out: List[Metric] = []
    for n in filter(None, names.split(",")):
        if n.startswith("fid"):
            out.append(FIDMetric(num_images or parse_count(n[3:]), batch_size))
        elif n.startswith("is"):
            out.append(ISMetric(num_images or parse_count(n[2:]), batch_size))
        elif n.startswith("ppl"):
            out.append(PPLMetric(num_images or parse_count(n[3:]), batch_size))
        elif n.startswith("pr"):
            out.append(PRMetric(num_images or parse_count(n[2:]), batch_size))
        else:
            raise ValueError(f"unknown metric {n!r}")
    return out
