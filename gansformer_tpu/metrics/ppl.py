"""Perceptual Path Length — reference ``src/metrics/perceptual_path_length.py``
(SURVEY.md §2.2 "optional metrics"): mean perceptual distance between images
at w-space lerp positions t and t+ε, scaled by 1/ε², with the extreme tails
filtered out.

Deliberate deviation (recorded in SURVEY.md §7.4): the lineage measures
image distance with a VGG16 LPIPS network downloaded from NVIDIA; this
framework uses the Inception pool3 feature L2 of the shared FID extractor
instead — one backbone for every metric, no second weight download, and
the distance is still a deep perceptual metric.  Numbers are therefore not
directly comparable to published PPL (which is fine: PPL is used as a
*relative* smoothness diagnostic between checkpoints of the same run).
"""

from __future__ import annotations

import numpy as np


def ppl_from_distances(d: np.ndarray, lo_pct: float = 1.0,
                       hi_pct: float = 99.0) -> float:
    """Filtered mean (the lineage drops both 1% tails before averaging)."""
    d = np.asarray(d, np.float64)
    lo, hi = np.percentile(d, [lo_pct, hi_pct])
    mask = (d >= lo) & (d <= hi)
    return float(d[mask].mean()) if mask.any() else float(d.mean())


def sample_ppl_distances(pair_fn, extractor, num_samples: int,
                         batch_size: int, epsilon: float = 1e-4,
                         seed: int = 0) -> np.ndarray:
    """Drive the ``pair_fn(n, t, rng_seed, epsilon)`` probe (built over the
    generator by train/steps.py ``ppl_pairs``) and return per-sample
    ε-normalized squared feature distances."""
    rs = np.random.RandomState(seed)
    out = []
    seen = 0
    while seen < num_samples:
        # always full batches (constant jit shapes; divisible by any mesh
        # the caller shards over) — the surplus is trimmed at the end
        t = rs.rand(batch_size).astype(np.float32)   # sampling='full'
        img_a, img_b = pair_fn(batch_size, t, rs.randint(2**31), epsilon)
        fa, _ = extractor(img_a)
        fb, _ = extractor(img_b)
        diff = np.asarray(fa, np.float64) - np.asarray(fb, np.float64)
        out.append((diff ** 2).sum(axis=-1) / (epsilon ** 2))
        seen += batch_size
    return np.concatenate(out)[:num_samples]
