"""The one metric-sweep entry point shared by the evaluate CLI, the
experiment harness, and the in-loop metric hook's CLI-equivalent path.

Runs the reference's §3.3 flow (SURVEY.md): build the mesh, shard the
generator sweep and the Inception extractor over it, run the metric group.
Also owns the eval-mesh fallback: a checkpoint trained on a larger mesh
(e.g. ``--mesh-model 2`` sequence parallelism on a pod) must still
evaluate on whatever devices this host has — if the saved mesh doesn't
fit, fall back to an all-devices data-parallel mesh (the sequence-parallel
constraint is a layout hint and no-ops on a model axis of size 1).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax

from gansformer_tpu.core.config import ExperimentConfig, MeshConfig


def make_eval_mesh(cfg: ExperimentConfig):
    """The run's mesh if this host can build it, else all-devices DP."""
    from gansformer_tpu.parallel.mesh import make_mesh

    try:
        return make_mesh(cfg.mesh)
    except ValueError:
        return make_mesh(MeshConfig())


def run_metric_sweep(cfg: ExperimentConfig, state, run_dir: str,
                     metrics: str, *,
                     batch_size: Optional[int] = None,
                     num_images: Optional[int] = None,
                     truncation_psi: float = 1.0,
                     seed: int = 7,
                     inception_npz: Optional[str] = None,
                     cache_dir: Optional[str] = None) -> Dict[str, float]:
    """Metric names string → results dict (``{'fid50k_uncal': …}``).

    ``state`` is a host-side TrainState (restored or just trained); it is
    replicated over the eval mesh here.  Real-data Inception activations
    cache under ``<run_dir>/metric-cache`` unless overridden.
    """
    from gansformer_tpu.data.dataset import make_dataset
    from gansformer_tpu.metrics.inception import make_extractor
    from gansformer_tpu.metrics.metric_base import (
        MetricGroup, parse_metric_names)
    from gansformer_tpu.train.steps import (
        make_metric_samplers, make_train_steps)

    batch_size = batch_size or cfg.train.batch_size
    env = make_eval_mesh(cfg)
    fns = make_train_steps(cfg, env, batch_size=batch_size)
    dataset = make_dataset(cfg.data)
    # --num-images overrides the sample count *at construction* so the
    # metric name (and the metric-<name>.txt it lands in) stays honest.
    group = MetricGroup(
        parse_metric_names(metrics, batch_size=batch_size,
                           num_images=num_images),
        make_extractor(inception_npz, env=env),
        cache_dir=cache_dir or os.path.join(run_dir, "metric-cache"))
    # replicate params over the mesh; make_metric_samplers shards z/labels
    # so the generator half of the sweep is data-parallel too
    state = jax.device_put(state, env.replicated())
    sample_fn, pair_fn = make_metric_samplers(
        fns, state, cfg, env, dataset,
        truncation_psi=truncation_psi, seed=seed)
    # Ambient mesh for the sweep (ADVICE r3): without it the sequence-
    # parallel grid constraints in BipartiteAttention._constrain see an
    # empty abstract mesh and silently no-op — the saved model-axis layout
    # would idle during eval while the docstring promises it is honored.
    with env.activate():
        return group.run(sample_fn, dataset, pair_fn=pair_fn)
