"""Improved Precision & Recall — reference ``src/metrics/precision_recall.py``
(SURVEY.md §2.2 "optional metrics"), the kNN-manifold estimator of
Kynkäänniemi et al. 2019:

* each feature set defines a manifold = union of hyperspheres around every
  point with radius = distance to its k-th nearest neighbour (k=3);
* precision = fraction of fakes inside the REAL manifold;
* recall    = fraction of reals inside the FAKE manifold.

TPU-native design: distances are computed as blocked ``|a|²+|b|²-2ab``
matmul tiles under jit (MXU-friendly; the reference streams the same tiles
through a TF1 graph), so a 50k×50k sweep never materializes — peak memory
is one [row_block × N] tile.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k",))
def _kth_nn_tile(rows: jax.Array, self_idx: jax.Array, feats: jax.Array,
                 k: int) -> jax.Array:
    """k-th-NN squared distance for one row block vs the full set,
    excluding self-matches by index (self_idx traced → no per-block
    recompile)."""
    d2 = (jnp.sum(rows ** 2, -1)[:, None]
          + jnp.sum(feats ** 2, -1)[None, :]
          - 2.0 * rows @ feats.T)
    d2 = jnp.maximum(d2, 0.0)
    d2 = jnp.where(jnp.arange(feats.shape[0])[None, :] == self_idx[:, None],
                   jnp.inf, d2)
    neg_smallest, _ = jax.lax.top_k(-d2, k)      # k smallest distances
    return -neg_smallest[:, k - 1]


def _kth_nn_sq(feats_np: np.ndarray, k: int, block: int) -> np.ndarray:
    """Blocked k-th-NN radii: peak memory is one [block × N] tile, never
    the full N×N matrix (a 50k sweep would be 10 GB)."""
    feats = jnp.asarray(feats_np, jnp.float32)
    out = []
    for i in range(0, len(feats_np), block):
        rows = feats[i:i + block]
        idx = jnp.arange(i, i + rows.shape[0])
        out.append(np.asarray(_kth_nn_tile(rows, idx, feats, k)))
    return np.concatenate(out)


def _in_manifold(queries: np.ndarray, refs: np.ndarray,
                 ref_radii_sq: np.ndarray, block: int) -> np.ndarray:
    """query ∈ manifold(refs) ⇔ ∃j: ||q-r_j||² ≤ radius_j²."""
    refs_j = jnp.asarray(refs, jnp.float32)
    radii = jnp.asarray(ref_radii_sq, jnp.float32)
    hits = []
    for i in range(0, len(queries), block):
        q = jnp.asarray(queries[i:i + block], jnp.float32)
        d2 = (jnp.sum(q ** 2, -1)[:, None]
              + jnp.sum(refs_j ** 2, -1)[None, :]
              - 2.0 * q @ refs_j.T)
        hits.append(np.asarray(jnp.any(
            jnp.maximum(d2, 0.0) <= radii[None, :], axis=-1)))
    return np.concatenate(hits)


def precision_recall(real_feats: np.ndarray, fake_feats: np.ndarray,
                     k: int = 3, block: int = 4096) -> Tuple[float, float]:
    """Improved precision/recall between two feature sets."""
    real = np.asarray(real_feats, np.float32)
    fake = np.asarray(fake_feats, np.float32)
    real_r = _kth_nn_sq(real, k, block)
    fake_r = _kth_nn_sq(fake, k, block)
    precision = float(_in_manifold(fake, real, real_r, block).mean())
    recall = float(_in_manifold(real, fake, fake_r, block).mean())
    return precision, recall
