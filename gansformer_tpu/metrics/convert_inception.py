"""Convert public Inception-v3 checkpoints to the FID extractor's npz layout.

The reference's FID rests on NVIDIA's pickled TF1 Inception graph
(``src/metrics/frechet_inception_distance.py``; SURVEY.md §3.3).  This
framework loads weights from a flat ``{'path/to/param': array}`` npz
(``inception.load_params_npz``); this module produces that npz from either
of the two practical public sources:

* **Keras** — ``keras.applications.InceptionV3`` (same TF-slim architecture;
  ``weights='imagenet'`` when network access exists, or an already-downloaded
  ``.h5``).  Keras names layers positionally (``conv2d_42``), so pairing is
  by topological order: keras creates Conv2D/BatchNormalization layers in
  exactly the source-code order our Flax modules are called in; the golden
  test (``tests/test_inception_convert.py``) locks this pairing down by
  asserting forward parity against keras itself.
* **Torch** — a ``state_dict`` in torchvision naming (this covers
  pytorch-fid's ``pt_inception-2015-12-05`` export of the original TF1 FID
  graph, the checkpoint that makes FID numbers comparable to published
  values).  Mapping is structural (``Mixed_5b.branch1x1.conv.weight`` →
  ``Mixed_5b/b1x1/conv/kernel``), with OIHW→HWIO transposes.

Name-mapping summary (torch → ours):
  ``Conv2d_{1a_3x3,2a_3x3,2b_3x3,3b_1x1,4a_3x3}`` → ``Conv2d_{1a,2a,2b,3b,4a}``
  ``branch1x1`` → ``b1x1``; ``branch5x5_N`` → ``b5x5_N``;
  ``branch3x3dbl_N[ab]`` → ``b3x3dbl_N[ab]``; ``branch3x3[_N]`` → ``b3x3[_N]``;
  ``branch7x7_N`` → ``b7x7_N``; ``branch7x7dbl_N`` → ``b7x7dbl_N``;
  ``branch7x7x3_N`` → ``b7x7x3_N``; ``branch_pool`` → ``bpool``; ``fc`` → ``fc``
  per-conv: ``conv.weight``→``conv/kernel`` (HWIO), ``bn.bias``→``beta``,
  ``bn.running_mean``→``mean``, ``bn.running_var``→``var``.

CLI:
  python -m gansformer_tpu.metrics.convert_inception --keras imagenet -o w.npz
  python -m gansformer_tpu.metrics.convert_inception --keras path.h5 -o w.npz
  python -m gansformer_tpu.metrics.convert_inception --torch path.pt -o w.npz
"""

from __future__ import annotations

import argparse
from typing import Dict, List

import numpy as np


def ordered_convbn_paths() -> List[str]:
    """Our ConvBN module paths in call (= keras creation) order."""
    mixed_a = ["b1x1", "b5x5_1", "b5x5_2",
               "b3x3dbl_1", "b3x3dbl_2", "b3x3dbl_3", "bpool"]
    mixed_b = ["b3x3", "b3x3dbl_1", "b3x3dbl_2", "b3x3dbl_3"]
    mixed_c = ["b1x1", "b7x7_1", "b7x7_2", "b7x7_3",
               "b7x7dbl_1", "b7x7dbl_2", "b7x7dbl_3", "b7x7dbl_4",
               "b7x7dbl_5", "bpool"]
    mixed_d = ["b3x3_1", "b3x3_2",
               "b7x7x3_1", "b7x7x3_2", "b7x7x3_3", "b7x7x3_4"]
    mixed_e = ["b1x1", "b3x3_1", "b3x3_2a", "b3x3_2b",
               "b3x3dbl_1", "b3x3dbl_2", "b3x3dbl_3a", "b3x3dbl_3b", "bpool"]
    paths = [f"Conv2d_{n}" for n in ("1a", "2a", "2b", "3b", "4a")]
    for block, branches in [
        ("Mixed_5b", mixed_a), ("Mixed_5c", mixed_a), ("Mixed_5d", mixed_a),
        ("Mixed_6a", mixed_b),
        ("Mixed_6b", mixed_c), ("Mixed_6c", mixed_c),
        ("Mixed_6d", mixed_c), ("Mixed_6e", mixed_c),
        ("Mixed_7a", mixed_d),
        ("Mixed_7b", mixed_e), ("Mixed_7c", mixed_e),
    ]:
        paths += [f"{block}/{b}" for b in branches]
    return paths


def from_keras(model) -> Dict[str, np.ndarray]:
    """Keras InceptionV3 (include_top=True) → flat param dict."""
    import keras

    def _creation_index(layer) -> int:
        # keras auto-names ('conv2d_42') carry creation order; model.layers
        # itself is DEPTH-sorted (branches interleave), so sort it back.
        suffix = layer.name.rsplit("_", 1)[-1]
        return int(suffix) if suffix.isdigit() else 0

    convs = sorted((l for l in model.layers
                    if isinstance(l, keras.layers.Conv2D)),
                   key=_creation_index)
    bns = sorted((l for l in model.layers
                  if isinstance(l, keras.layers.BatchNormalization)),
                 key=_creation_index)
    dense = [l for l in model.layers if isinstance(l, keras.layers.Dense)]
    paths = ordered_convbn_paths()
    if not (len(convs) == len(bns) == len(paths)):
        raise ValueError(
            f"layer count mismatch: {len(convs)} convs, {len(bns)} BNs, "
            f"expected {len(paths)} — keras architecture drifted?")
    flat: Dict[str, np.ndarray] = {}
    for path, conv, bn in zip(paths, convs, bns):
        (kernel,) = conv.get_weights()          # HWIO already
        beta, mean, var = bn.get_weights()      # scale=False in InceptionV3
        flat[f"{path}/conv/kernel"] = np.asarray(kernel, np.float32)
        flat[f"{path}/beta"] = np.asarray(beta, np.float32)
        flat[f"{path}/mean"] = np.asarray(mean, np.float32)
        flat[f"{path}/var"] = np.asarray(var, np.float32)
    if len(dense) != 1:
        raise ValueError(f"expected 1 Dense head, found {len(dense)}")
    kernel, bias = dense[0].get_weights()
    flat["fc/kernel"] = np.asarray(kernel, np.float32)
    flat["fc/bias"] = np.asarray(bias, np.float32)
    return flat


_TORCH_CONV_RENAME = {
    "Conv2d_1a_3x3": "Conv2d_1a", "Conv2d_2a_3x3": "Conv2d_2a",
    "Conv2d_2b_3x3": "Conv2d_2b", "Conv2d_3b_1x1": "Conv2d_3b",
    "Conv2d_4a_3x3": "Conv2d_4a",
}


def _torch_path(module: str) -> str:
    """torchvision module path → our module path."""
    if module in _TORCH_CONV_RENAME:
        return _TORCH_CONV_RENAME[module]
    block, _, branch = module.partition(".")
    if not branch:
        raise KeyError(module)
    ours = ("bpool" if branch == "branch_pool"
            else branch.replace("branch", "b"))
    return f"{block}/{ours}"


def from_torch_state_dict(sd) -> Dict[str, np.ndarray]:
    """torchvision-named state_dict → flat param dict (OIHW→HWIO).

    torchvision's BasicConv2d uses affine BN (a per-channel scale γ our
    scale-free ConvBN lacks); since both use eps=1e-3 the fold is exact:
    γ·(conv(x)−μ)·rsqrt(σ²+eps)+β == ((γ·k)∗x − γμ)·rsqrt(σ²+eps)+β,
    i.e. scale the conv kernel's output channels and μ by γ.
    """
    flat: Dict[str, np.ndarray] = {}
    gammas: Dict[str, np.ndarray] = {}
    for key, value in sd.items():
        v = np.asarray(getattr(value, "numpy", lambda: value)(),
                       dtype=np.float32)
        if key.startswith("AuxLogits") or key.endswith("num_batches_tracked"):
            continue
        if key == "fc.weight":
            flat["fc/kernel"] = v.T
            continue
        if key == "fc.bias":
            flat["fc/bias"] = v
            continue
        module, leaf2, leaf1 = key.rsplit(".", 2)[0], *key.rsplit(".", 2)[1:]
        path = _torch_path(module)
        if leaf2 == "conv" and leaf1 == "weight":
            flat[f"{path}/conv/kernel"] = v.transpose(2, 3, 1, 0)
        elif leaf2 == "bn" and leaf1 == "bias":
            flat[f"{path}/beta"] = v
        elif leaf2 == "bn" and leaf1 == "running_mean":
            flat[f"{path}/mean"] = v
        elif leaf2 == "bn" and leaf1 == "running_var":
            flat[f"{path}/var"] = v
        elif leaf2 == "bn" and leaf1 == "weight":
            gammas[path] = v
        else:
            raise KeyError(f"unrecognized state_dict entry {key!r}")
    for path, gamma in gammas.items():
        flat[f"{path}/conv/kernel"] = flat[f"{path}/conv/kernel"] * gamma
        flat[f"{path}/mean"] = flat[f"{path}/mean"] * gamma
    return flat


def expected_keys() -> List[str]:
    keys = []
    for p in ordered_convbn_paths():
        keys += [f"{p}/conv/kernel", f"{p}/beta", f"{p}/mean", f"{p}/var"]
    return keys + ["fc/kernel", "fc/bias"]


def validate(flat: Dict[str, np.ndarray]) -> None:
    missing = sorted(set(expected_keys()) - set(flat))
    extra = sorted(set(flat) - set(expected_keys()))
    if missing or extra:
        raise ValueError(f"bad conversion: missing={missing[:5]}... "
                         f"extra={extra[:5]}...")


def save_npz(flat: Dict[str, np.ndarray], path: str) -> None:
    validate(flat)
    np.savez(path, **flat)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--keras", metavar="H5_OR_IMAGENET",
                     help="'imagenet' (downloads) or a keras .h5 weights file")
    src.add_argument("--torch", metavar="PT",
                     help="torch state_dict file in torchvision naming")
    ap.add_argument("-o", "--output", required=True, help="output .npz")
    args = ap.parse_args(argv)

    if args.keras:
        import keras

        weights = args.keras if args.keras == "imagenet" else None
        model = keras.applications.InceptionV3(
            weights=weights, classifier_activation=None)
        if weights is None:
            model.load_weights(args.keras)
        flat = from_keras(model)
    else:
        import torch

        obj = torch.load(args.torch, map_location="cpu",
                         weights_only=False)
        sd = obj.state_dict() if hasattr(obj, "state_dict") else obj
        flat = from_torch_state_dict(sd)
    save_npz(flat, args.output)
    print(f"wrote {len(flat)} arrays → {args.output}")


if __name__ == "__main__":
    main()
