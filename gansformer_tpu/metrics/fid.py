"""Fréchet Inception Distance — on-device statistics, host-side sqrtm.

Reference: ``src/metrics/frechet_inception_distance.py`` (SURVEY.md §2.2,
§3.3): Inception activations for 50k reals (cached) and 50k fakes, then
``d² = |μ₁-μ₂|² + Tr(Σ₁+Σ₂-2√(Σ₁Σ₂))`` via ``scipy.linalg.sqrtm`` — the
reason for the reference's scipy pin (Dockerfile:9, T0).

TPU split: μ/Σ accumulation is a pair of ``psum``-friendly reductions done
on device in fp64-free form (shifted sums for stability); the 2048×2048
matrix square root runs either on host via scipy or on device via
Newton–Schulz iteration (``sqrtm_newton_schulz``) — both provided, NS is the
default when scipy is absent.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def compute_activation_stats(feats: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """features [N, D] → (mu [D], sigma [D, D])."""
    feats = np.asarray(feats, dtype=np.float64)
    mu = feats.mean(axis=0)
    sigma = np.cov(feats, rowvar=False)
    return mu, sigma


def sqrtm_newton_schulz(a: jnp.ndarray, iters: int = 30) -> jnp.ndarray:
    """Matrix square root of a PSD matrix by Newton–Schulz iteration.

    Runs entirely on device (MXU matmuls), fp32 with a norm pre-scale.
    Accurate to ~1e-4 relative for well-conditioned covariance products —
    adequate for FID (differences of interest are >0.1).
    """
    a = a.astype(jnp.float32)
    n = a.shape[0]
    norm = jnp.sqrt(jnp.sum(a * a))
    y = a / norm
    z = jnp.eye(n, dtype=jnp.float32)
    eye3 = 3.0 * jnp.eye(n, dtype=jnp.float32)

    def body(_, yz):
        y, z = yz
        t = 0.5 * (eye3 - z @ y)
        return (y @ t, t @ z)

    y, z = jax.lax.fori_loop(0, iters, body, (y, z))
    return y * jnp.sqrt(norm)


def frechet_distance(mu1: np.ndarray, sigma1: np.ndarray,
                     mu2: np.ndarray, sigma2: np.ndarray,
                     method: str = "auto") -> float:
    """d²((μ₁,Σ₁), (μ₂,Σ₂)) — the FID formula."""
    mu1 = np.asarray(mu1, np.float64)
    mu2 = np.asarray(mu2, np.float64)
    sigma1 = np.asarray(sigma1, np.float64)
    sigma2 = np.asarray(sigma2, np.float64)
    diff = mu1 - mu2

    covmean = None
    if method in ("auto", "scipy"):
        try:
            import scipy.linalg

            covmean, _ = scipy.linalg.sqrtm(sigma1 @ sigma2, disp=False)
            covmean = np.real(covmean)
        except ImportError:
            if method == "scipy":
                raise
    if covmean is None:
        covmean = np.asarray(sqrtm_newton_schulz(jnp.asarray(sigma1 @ sigma2)),
                             np.float64)

    return float(diff @ diff + np.trace(sigma1) + np.trace(sigma2)
                 - 2.0 * np.trace(covmean))


def fid_from_features(real_feats: np.ndarray, fake_feats: np.ndarray,
                      method: str = "auto") -> float:
    mu_r, s_r = compute_activation_stats(real_feats)
    mu_f, s_f = compute_activation_stats(fake_feats)
    return frechet_distance(mu_r, s_r, mu_f, s_f, method=method)
