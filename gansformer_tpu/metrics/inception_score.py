"""Inception Score — exp(E_x[KL(p(y|x) || p(y))]) over generated images.

Reference: ``src/metrics/inception_score.py`` (SURVEY.md §2.2): softmax KL on
50k fake-image Inception logits, mean/std over 10 splits.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def inception_score(logits: np.ndarray, splits: int = 10) -> Tuple[float, float]:
    """logits [N, num_classes] → (mean IS, std IS over splits)."""
    logits = np.asarray(logits, np.float64)
    logits = logits - logits.max(axis=1, keepdims=True)
    probs = np.exp(logits)
    probs /= probs.sum(axis=1, keepdims=True)

    scores = []
    n = len(probs)
    for i in range(splits):
        part = probs[i * n // splits:(i + 1) * n // splits]
        if len(part) == 0:
            continue
        py = part.mean(axis=0, keepdims=True)
        kl = part * (np.log(part + 1e-16) - np.log(py + 1e-16))
        scores.append(np.exp(kl.sum(axis=1).mean()))
    return float(np.mean(scores)), float(np.std(scores))
