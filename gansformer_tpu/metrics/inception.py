"""Inception-v3 feature extractor in Flax — the FID/IS backbone, on device.

Reference: a pickled TF1 Inception graph downloaded from NVIDIA
(``src/metrics/frechet_inception_distance.py``; SURVEY.md §3.3).  Here the
architecture is implemented natively (BN-Inception-v3, pool3 features = 2048-d,
aux-free) and weights load from an ``.npz`` you convert once from any public
Inception-v3 checkpoint (``load_params_npz``).  With no weight file present we
fall back to a *deterministic randomly-initialized* network: FID computed with
random features is still a valid two-sample discrepancy (random-projection
FID correlates with true FID) and keeps the full pipeline exercisable in
airgapped CI — but numbers are NOT comparable to reference FID; callers get
a ``calibrated`` flag saying which regime they are in.

Numerics note (SURVEY.md §7.3 item 3): FID comparability hinges on resize
semantics; ``preprocess`` uses bilinear resize to 299² with antialiasing
matching TF's ``tf.image.resize(..., antialias=True)``.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def _avg_pool_tf(x):
    """3×3/1 SAME average pool with TF semantics: padded positions are
    EXCLUDED from the divisor (``count_include_pad=False``).  The reference's
    TF1 Inception graph — and pytorch-fid's patched torchvision port — both
    use this; including the padding shifts border features and breaks FID
    comparability."""
    return nn.avg_pool(x, (3, 3), (1, 1), "SAME", count_include_pad=False)


class ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False, name="conv")(x)
        # inference-only BN: scale=1 folded, running stats as params
        mean = self.param("mean", nn.initializers.zeros, (self.features,))
        var = self.param("var", nn.initializers.ones, (self.features,))
        beta = self.param("beta", nn.initializers.zeros, (self.features,))
        x = (x - mean) * jax.lax.rsqrt(var + 1e-3) + beta
        return nn.relu(x)


class InceptionA(nn.Module):
    pool_features: int

    @nn.compact
    def __call__(self, x):
        b1 = ConvBN(64, (1, 1), name="b1x1")(x)
        b5 = ConvBN(48, (1, 1), name="b5x5_1")(x)
        b5 = ConvBN(64, (5, 5), name="b5x5_2")(b5)
        b3 = ConvBN(64, (1, 1), name="b3x3dbl_1")(x)
        b3 = ConvBN(96, (3, 3), name="b3x3dbl_2")(b3)
        b3 = ConvBN(96, (3, 3), name="b3x3dbl_3")(b3)
        bp = _avg_pool_tf(x)
        bp = ConvBN(self.pool_features, (1, 1), name="bpool")(bp)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    @nn.compact
    def __call__(self, x):
        b3 = ConvBN(384, (3, 3), (2, 2), "VALID", name="b3x3")(x)
        bd = ConvBN(64, (1, 1), name="b3x3dbl_1")(x)
        bd = ConvBN(96, (3, 3), name="b3x3dbl_2")(bd)
        bd = ConvBN(96, (3, 3), (2, 2), "VALID", name="b3x3dbl_3")(bd)
        bp = nn.max_pool(x, (3, 3), (2, 2), "VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    c7: int

    @nn.compact
    def __call__(self, x):
        c7 = self.c7
        b1 = ConvBN(192, (1, 1), name="b1x1")(x)
        b7 = ConvBN(c7, (1, 1), name="b7x7_1")(x)
        b7 = ConvBN(c7, (1, 7), name="b7x7_2")(b7)
        b7 = ConvBN(192, (7, 1), name="b7x7_3")(b7)
        bd = ConvBN(c7, (1, 1), name="b7x7dbl_1")(x)
        bd = ConvBN(c7, (7, 1), name="b7x7dbl_2")(bd)
        bd = ConvBN(c7, (1, 7), name="b7x7dbl_3")(bd)
        bd = ConvBN(c7, (7, 1), name="b7x7dbl_4")(bd)
        bd = ConvBN(192, (1, 7), name="b7x7dbl_5")(bd)
        bp = _avg_pool_tf(x)
        bp = ConvBN(192, (1, 1), name="bpool")(bp)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    @nn.compact
    def __call__(self, x):
        b3 = ConvBN(192, (1, 1), name="b3x3_1")(x)
        b3 = ConvBN(320, (3, 3), (2, 2), "VALID", name="b3x3_2")(b3)
        b7 = ConvBN(192, (1, 1), name="b7x7x3_1")(x)
        b7 = ConvBN(192, (1, 7), name="b7x7x3_2")(b7)
        b7 = ConvBN(192, (7, 1), name="b7x7x3_3")(b7)
        b7 = ConvBN(192, (3, 3), (2, 2), "VALID", name="b7x7x3_4")(b7)
        bp = nn.max_pool(x, (3, 3), (2, 2), "VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    @nn.compact
    def __call__(self, x):
        b1 = ConvBN(320, (1, 1), name="b1x1")(x)
        b3 = ConvBN(384, (1, 1), name="b3x3_1")(x)
        b3 = jnp.concatenate([ConvBN(384, (1, 3), name="b3x3_2a")(b3),
                              ConvBN(384, (3, 1), name="b3x3_2b")(b3)], axis=-1)
        bd = ConvBN(448, (1, 1), name="b3x3dbl_1")(x)
        bd = ConvBN(384, (3, 3), name="b3x3dbl_2")(bd)
        bd = jnp.concatenate([ConvBN(384, (1, 3), name="b3x3dbl_3a")(bd),
                              ConvBN(384, (3, 1), name="b3x3dbl_3b")(bd)], axis=-1)
        bp = _avg_pool_tf(x)
        bp = ConvBN(192, (1, 1), name="bpool")(bp)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    """Returns (pool_features [N,2048], logits [N,1008])."""

    num_classes: int = 1008  # reference graph uses 1008-way output

    @nn.compact
    def __call__(self, x):
        x = ConvBN(32, (3, 3), (2, 2), "VALID", name="Conv2d_1a")(x)
        x = ConvBN(32, (3, 3), padding="VALID", name="Conv2d_2a")(x)
        x = ConvBN(64, (3, 3), name="Conv2d_2b")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), "VALID")
        x = ConvBN(80, (1, 1), padding="VALID", name="Conv2d_3b")(x)
        x = ConvBN(192, (3, 3), padding="VALID", name="Conv2d_4a")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), "VALID")
        x = InceptionA(32, name="Mixed_5b")(x)
        x = InceptionA(64, name="Mixed_5c")(x)
        x = InceptionA(64, name="Mixed_5d")(x)
        x = InceptionB(name="Mixed_6a")(x)
        x = InceptionC(128, name="Mixed_6b")(x)
        x = InceptionC(160, name="Mixed_6c")(x)
        x = InceptionC(160, name="Mixed_6d")(x)
        x = InceptionC(192, name="Mixed_6e")(x)
        x = InceptionD(name="Mixed_7a")(x)
        x = InceptionE(name="Mixed_7b")(x)
        x = InceptionE(name="Mixed_7c")(x)
        pool = jnp.mean(x, axis=(1, 2))                 # [N, 2048]
        logits = nn.Dense(self.num_classes, name="fc")(pool)
        return pool, logits


def preprocess(images: jax.Array) -> jax.Array:
    """[-1,1] float NHWC at any resolution → 299×299, stays in [-1,1]
    (the scaling the reference's Inception graph expects)."""
    x = jnp.clip(images, -1.0, 1.0)
    if x.shape[1] != 299 or x.shape[2] != 299:
        x = jax.image.resize(x, (x.shape[0], 299, 299, x.shape[3]),
                             method="bilinear", antialias=True)
    if x.shape[-1] == 1:
        x = jnp.repeat(x, 3, axis=-1)
    return x


class FeatureExtractor:
    """Jitted (features, logits) on [-1,1] images; batched sweep helper.

    Pass a ``MeshEnv`` to run the sweep data-parallel over the mesh
    (VERDICT r2 item 4): params are replicated, each batch is sharded on
    the ``data`` axis, and the 50k-image FID sweep scales with chips.
    Batches that don't divide the mesh are zero-padded and trimmed.
    """

    def __init__(self, params: Optional[Any] = None, seed: int = 0,
                 env: Optional[Any] = None):
        if params is None:
            self.net = InceptionV3()
            params = self.net.init(
                jax.random.PRNGKey(seed), jnp.zeros((1, 299, 299, 3)))["params"]
            # Uncalibrated regime fixes (r5 — FID_uncal measured ~1e-4 for
            # ANY pair of distributions, making 'FID fell' unobservable):
            #
            # 1. He-rescale every kernel (lecun init loses the ReLU's √2
            #    per layer; through ~100 convs the signal decayed to ~1e-4
            #    absolute scale, and FID scales QUADRATICALLY with feature
            #    scale).
            # 2. Standardize features/logits per-dim against a fixed
            #    multi-scale noise probe, so the random-projection FID
            #    lands in an O(1..1e3) readable range and IS_uncal's
            #    softmax sees O(1) logit spread.  Deterministic (seeded
            #    probe), dataset-independent, applied ONLY when
            #    uncalibrated; per-dim affine scaling preserves exactly
            #    the two-sample-discrepancy property the docstring claims.
            params = jax.tree_util.tree_map_with_path(
                lambda path, x: x * np.sqrt(2.0)
                if path[-1].key == "kernel" else x, params)
            self.calibrated = False
        else:
            # class count follows the checkpoint: 1008 for the reference's
            # TF1 graph, 1000 for torchvision/keras ImageNet weights.
            num_classes = int(np.shape(params["fc"]["kernel"])[-1])
            self.net = InceptionV3(num_classes=num_classes)
            self.calibrated = True
        self.env = env
        raw_apply = jax.jit(
            lambda p, x: self.net.apply({"params": p}, preprocess(x)))
        if not self.calibrated:
            # Scales are computed BEFORE the mesh device_put below, on the
            # process-local default device: mixing global-mesh params with
            # a local probe array (or reducing a non-fully-addressable
            # output eagerly) would crash every multi-host uncalibrated
            # sweep at construction.  Deterministic per seed, so every
            # process computes identical scales — the cross-host
            # calibration agreement check guards any drift.
            f_scale, l_scale = self._probe_scales(raw_apply, params, seed)
        if env is not None:
            params = jax.device_put(params, env.replicated())
        self.params = params
        if self.calibrated:
            self._apply = raw_apply
        else:
            self._apply = jax.jit(
                lambda p, x: tuple(
                    o * s for o, s in zip(raw_apply(p, x),
                                          (f_scale, l_scale))))

    # seed -> (f_scale, l_scale): the probe forward costs a full Inception
    # compile+run; it is a pure function of the seed, so pay it once per
    # process, not once per FeatureExtractor (CI builds several).
    _PROBE_MEMO: dict = {}

    @classmethod
    def _probe_scales(cls, raw_apply, params, seed: int):
        """Per-dim 1/std of features and logits over a fixed 16-image
        multi-scale noise probe (coarse 8² + mid 32² + fine 299² Gaussian
        pyramids) — spans low- and high-frequency content so no probe-dead
        feature dim gets a huge scale by accident; floored at 1e-3 of the
        per-tensor median std so genuinely dead dims stay quiet."""
        if seed in cls._PROBE_MEMO:
            return cls._PROBE_MEMO[seed]
        k = jax.random.PRNGKey(seed + 1)
        k1, k2, k3 = jax.random.split(k, 3)
        n = 16

        def up(key, r):
            z = jax.random.normal(key, (n, r, r, 3), jnp.float32)
            return jax.image.resize(z, (n, 299, 299, 3), "bilinear")

        probe = jnp.tanh(up(k1, 8) + 0.5 * up(k2, 32)
                         + 0.25 * jax.random.normal(
                             k3, (n, 299, 299, 3), jnp.float32))
        feats, logits = raw_apply(params, probe)

        def scale(t):
            s = jnp.std(t, axis=0)
            floor = 1e-3 * jnp.median(s) + 1e-20
            return 1.0 / jnp.maximum(s, floor)

        cls._PROBE_MEMO[seed] = (scale(feats), scale(logits))
        return cls._PROBE_MEMO[seed]

    def __call__(self, images: jax.Array):
        """(features, logits) for ``images``.

        Single-process (or no env): unchanged — device arrays in, device
        arrays out.  With ``process_count > 1`` (VERDICT r3 weak #3) the
        contract is: every process calls collectively, passing either the
        same GLOBAL sharded array (the fake sweep) or its own equally-sized
        host-local shard (the real sweep); the return value is the GLOBAL
        features/logits as host numpy, identical on every process.
        """
        if self.env is None:
            return self._apply(self.params, images)
        if jax.process_count() == 1:
            n, d = images.shape[0], self.env.data_size
            pad = (-n) % d
            if pad:
                images = jnp.concatenate(
                    [jnp.asarray(images),
                     jnp.zeros((pad,) + images.shape[1:], images.dtype)])
            images = jax.device_put(images, self.env.batch())
            f, l = self._apply(self.params, images)
            return (f[:n], l[:n]) if pad else (f, l)
        return self._call_multihost(images)

    def _call_multihost(self, images):
        from jax.experimental import multihost_utils

        if not getattr(self, "_mh_checked", False):
            # Calibration resolves per-host filesystem (weights npz /
            # torch-hub cache / network luck); running the COLLECTIVE
            # sweep with different weights per process would produce
            # garbage or a cross-host hang — fail with words instead.
            flags = np.asarray(multihost_utils.process_allgather(
                np.int32(self.calibrated)))
            if not (flags == flags.flat[0]).all():
                raise RuntimeError(
                    f"Inception calibration differs across processes "
                    f"(calibrated per process: {flags.tolist()}); "
                    f"distribute the same weights npz to every host, e.g. "
                    f"via GANSFORMER_TPU_INCEPTION_NPZ")
            self._mh_checked = True

        def gather(x):
            # global sharded jax.Array → full global numpy on every host
            return np.asarray(multihost_utils.process_allgather(x, tiled=True))

        if isinstance(images, jax.Array):
            # Fake path: a jax.Array is BY CONTRACT a global array here
            # (sample_fn/pair_fn build them via env.put_global); pad at the
            # logical end (an SPMD op every process executes), trim after.
            n, d = images.shape[0], self.env.data_size
            pad = (-n) % d
            if pad:
                images = jnp.concatenate(
                    [images,
                     jnp.zeros((pad,) + images.shape[1:], images.dtype)])
            f, l = self._apply(self.params, images)
            return gather(f)[:n], gather(l)[:n]
        # Real path: host-local shard, same n_local on every process (the
        # sweep iterates fixed-size sharded batches); pad each host block
        # to local-row divisibility, strip the interleaved pads after.
        images = np.asarray(images)
        n_local = images.shape[0]
        rows = self.env.local_data_rows
        pad = (-n_local) % rows
        if pad:
            images = np.concatenate(
                [images, np.zeros((pad,) + images.shape[1:], images.dtype)])
        garr = jax.make_array_from_process_local_data(
            self.env.batch(), images)
        f, l = self._apply(self.params, garr)
        f, l = gather(f), gather(l)
        if pad:
            pc = jax.process_count()
            per = n_local + pad

            def strip(x):
                return (x.reshape((pc, per) + x.shape[1:])[:, :n_local]
                        .reshape((pc * n_local,) + x.shape[1:]))

            f, l = strip(f), strip(l)
        return f, l

    def sweep(self, image_batches, max_images: int) -> Tuple[np.ndarray, np.ndarray]:
        """Iterate [-1,1]-float batches → stacked (features, logits)."""
        feats, logits = [], []
        seen = 0
        for batch in image_batches:
            f, l = self(batch)
            f, l = np.asarray(f), np.asarray(l)
            take = min(len(f), max_images - seen)
            feats.append(f[:take])
            logits.append(l[:take])
            seen += take
            if seen >= max_images:
                break
        return np.concatenate(feats), np.concatenate(logits)


def tree_from_flat(flat) -> dict:
    """{'a/b/c': array} → nested params dict."""
    tree: dict = {}
    for k, v in flat.items():
        node = tree
        parts = k.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(v)
    return tree


def load_params_npz(path: str):
    """Load a flat {'a/b/c': array} npz into the nested params dict."""
    return tree_from_flat(dict(np.load(path)))


_WEIGHTS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".weights")
_CAL_NPZ = os.path.join(_WEIGHTS_DIR, "inception-imagenet.npz")
_FETCH_OUTCOME = os.path.join(_WEIGHTS_DIR, "inception-fetch-outcome.json")


_FAILED_PROBES: dict = {}   # {source path: mtime} of failed conversions


def _npz_loads(path: str) -> bool:
    """A truncated npz from a killed converter must never be trusted."""
    try:
        with np.load(path) as z:
            return len(z.files) > 0
    except Exception:
        return False


def _local_checkpoint_candidates():
    """(kind, path) pairs of already-on-disk Inception checkpoints the
    converter can consume WITHOUT network access (VERDICT r3 item 5):
    an explicit env override, the torchvision/torch-hub download cache
    (inception_v3_google-*.pth / pytorch-fid's pt_inception-*.pth), and
    the keras download cache."""
    cands = []
    src = os.environ.get("GANSFORMER_TPU_INCEPTION_SRC")
    if src and os.path.exists(src):
        kind = "torch" if src.endswith((".pt", ".pth")) else "keras"
        cands.append((kind, src))
    home = os.path.expanduser("~")
    torch_home = os.environ.get(
        "TORCH_HOME", os.path.join(home, ".cache", "torch"))
    hub_ckpts = os.path.join(torch_home, "hub", "checkpoints")
    if os.path.isdir(hub_ckpts):
        for fn in sorted(os.listdir(hub_ckpts)):
            if "inception" in fn.lower() and fn.endswith((".pt", ".pth")):
                cands.append(("torch", os.path.join(hub_ckpts, fn)))
    keras_h5 = os.path.join(
        home, ".keras", "models",
        "inception_v3_weights_tf_dim_ordering_tf_kernels.h5")
    if os.path.exists(keras_h5):
        cands.append(("keras", keras_h5))
    return cands


def _run_converter(args, timeout: float):
    """convert_inception CLI in a subprocess (a hung download or a poison
    pickle can't stall/kill the caller); returns (returncode, stderr_tail)."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    try:
        proc = subprocess.run(
            [sys.executable, "-m",
             "gansformer_tpu.metrics.convert_inception", *args],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(_WEIGHTS_DIR))
        return proc.returncode, (proc.stderr or "")[-800:]
    except subprocess.TimeoutExpired:
        return -1, f"timed out after {timeout:.0f}s"
    except OSError as e:
        return -1, f"spawn failed: {e}"


def try_fetch_calibrated(timeout: float = 240.0) -> Optional[str]:
    """Obtain calibrated ImageNet Inception weights without user action:
    probe local checkpoint caches first (torchvision/keras/env override —
    airgapped machines often have one), then a one-shot keras download
    attempt (VERDICT r2 item 2), with the outcome recorded to
    ``.weights/inception-fetch-outcome.json`` either way.

    A NEW local checkpoint is noticed on any call (a user may drop one in
    later), but a candidate that already failed conversion is skipped by
    (path, mtime) — in-process and across processes via the outcome file —
    so a stale/corrupt cache file cannot re-cost a converter subprocess on
    every metric tick.  Only the NETWORK attempt is one-shot."""
    import json
    import sys

    try:
        if os.path.exists(_CAL_NPZ) and _npz_loads(_CAL_NPZ):
            return _CAL_NPZ
        os.makedirs(_WEIGHTS_DIR, exist_ok=True)
    except OSError:
        return None                      # read-only install: degrade quietly
    failed_probes = dict(_FAILED_PROBES)
    if os.path.exists(_FETCH_OUTCOME):
        try:
            with open(_FETCH_OUTCOME) as f:
                for p in json.load(f).get("local_probes", []):
                    if p.get("returncode") != 0 and "mtime" in p:
                        failed_probes[p["source"]] = p["mtime"]
        except (OSError, ValueError):
            pass
    outcome = {"attempted": True, "path": _CAL_NPZ, "local_probes": []}
    for kind, src in _local_checkpoint_candidates():
        try:
            mtime = os.path.getmtime(src)
        except OSError:
            continue
        if failed_probes.get(src) == mtime:
            continue                     # same bytes already failed once
        rc, err = _run_converter([f"--{kind}", src, "-o", _CAL_NPZ],
                                 timeout=timeout)
        probe = {"kind": kind, "source": src, "returncode": rc,
                 "mtime": mtime}
        if rc != 0:
            probe["stderr_tail"] = err[-300:]
            _FAILED_PROBES[src] = mtime
        outcome["local_probes"].append(probe)
        if rc == 0 and _npz_loads(_CAL_NPZ):
            outcome["result"] = "success"
            outcome["source"] = src
            try:
                with open(_FETCH_OUTCOME, "w") as f:
                    json.dump(outcome, f, indent=2)
            except OSError:
                pass
            return _CAL_NPZ
    if os.path.exists(_FETCH_OUTCOME):
        # network attempt already failed once; persist any NEW probe
        # failures so other processes skip them too
        if outcome["local_probes"]:
            try:
                with open(_FETCH_OUTCOME) as f:
                    prev = json.load(f)
                prev.setdefault("local_probes", []).extend(
                    outcome["local_probes"])
                with open(_FETCH_OUTCOME, "w") as f:
                    json.dump(prev, f, indent=2)
            except (OSError, ValueError):
                pass
        return None
    rc, err = _run_converter(["--keras", "imagenet", "-o", _CAL_NPZ],
                             timeout=timeout)
    outcome["returncode"] = rc
    outcome["stderr_tail"] = err
    ok = rc == 0 and _npz_loads(_CAL_NPZ)
    if not ok and os.path.exists(_CAL_NPZ):
        try:                             # drop a partial/corrupt download
            os.unlink(_CAL_NPZ)
        except OSError:
            pass
    outcome["result"] = "success" if ok else "failed"
    try:
        with open(_FETCH_OUTCOME, "w") as f:
            json.dump(outcome, f, indent=2)
    except OSError:
        pass
    if ok:
        return _CAL_NPZ
    print(f"[metrics] calibrated Inception weights unavailable "
          f"({outcome['stderr_tail'][-160:]!r}); using the deterministic "
          f"random extractor — FID/IS report as *_uncal",
          file=sys.stderr)
    return None


def make_extractor(weights_path: Optional[str] = None,
                   env: Optional[Any] = None) -> FeatureExtractor:
    """env: optional MeshEnv — shards the activation sweep over the mesh.

    Weight resolution order: explicit path → $GANSFORMER_TPU_INCEPTION_NPZ
    → previously fetched ``.weights/inception-imagenet.npz`` → a one-shot
    keras-download attempt (outcome recorded) → deterministic random
    weights (honest ``*_uncal`` metric naming)."""
    npz_path = weights_path or os.environ.get("GANSFORMER_TPU_INCEPTION_NPZ")
    if not (npz_path and os.path.exists(npz_path)):
        npz_path = try_fetch_calibrated()
    if npz_path and os.path.exists(npz_path):
        return FeatureExtractor(load_params_npz(npz_path), env=env)
    return FeatureExtractor(None, env=env)
