"""Counters / gauges / histograms — process-global metric registry.

Instruments are created-or-fetched by slash-path name
(``counter("data/starved_total")``); the same name always returns the
same instrument, so instrumentation sites don't coordinate.  The
registry exports two forms:

* ``snapshot()`` — plain nested dict, embedded as the ``telemetry``
  section of each ``stats.jsonl`` tick record (utils/logging.py).
* ``export_text()`` — Prometheus text exposition (names sanitized,
  ``data/wait_ms`` → ``data_wait_ms``), written atomically to
  ``telemetry.prom`` at every tick so a node-local scraper or a human
  ``cat`` sees current values mid-run.

Histograms keep count/sum/min/max (no buckets — the per-tick consumers
here want means and extremes, and bucket boundaries would be guesses).
"""

from __future__ import annotations

import os
import re
import threading
from typing import Dict, Optional

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def atomic_write_text(path: str, text: str) -> None:
    """Write-then-rename so a concurrent reader never sees a torn file;
    the tmp file is removed if the write itself fails."""
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def prom_name(name: str) -> str:
    """Slash-path instrument name → legal Prometheus metric name."""
    n = _NAME_RE.sub("_", name)
    if not n or not (n[0].isalpha() or n[0] in "_:"):
        n = "_" + n
    return n


def parse_prom_values(path: str) -> Dict[str, float]:
    """``telemetry.prom`` sample lines → {prom name: value} (last write
    wins).  Lives next to ``export_text`` so the ONE module that owns
    the format both writes and reads it — the doctor and the schema
    lint's family check are the consumers."""
    out: Dict[str, float] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            if len(parts) != 2:
                continue
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                continue
    return out


def parse_prom_exemplars(path: str) -> Dict[str, str]:
    """``# EXEMPLAR <sample_name> <label>`` comment lines →
    {sample name: label}.  The read half of the histogram exemplar
    channel (``Histogram.observe(v, exemplar=...)``): the requests CLI
    resolves ``serve_e2e_ms_max`` here to the request ID whose timeline
    explains the p99 outlier."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) == 4 and parts[0] == "#" and \
                    parts[1] == "EXEMPLAR":
                out[parts[2]] = parts[3]
    return out


class Counter:
    """Monotonic count.  ``inc()`` only — decrements are a gauge's job."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-write-wins instantaneous value (queue depth, peak bytes)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def max(self, v: float) -> None:
        """Keep the high-water mark (peak-memory style gauges)."""
        with self._lock:
            self._value = max(self._value, float(v))

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Streaming count/sum/min/max of observations.

    ``observe(v, exemplar=...)`` may attach an exemplar label (a request
    ID) to the observation; the histogram retains the exemplar of its
    CURRENT max, so a p99 outlier in ``serve/e2e_ms`` links straight to
    the request timeline that produced it (exported as a ``# EXEMPLAR``
    comment line in the prom text — comments are transparent to
    ``parse_prom_values`` and the schema lint, so the channel costs the
    readers nothing)."""

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.max_exemplar: Optional[str] = None

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            if self.max is None or v >= self.max:
                self.max = v
                if exemplar is not None:
                    self.max_exemplar = str(exemplar)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def _get(self, table: dict, others: tuple, name: str, cls):
        with self._lock:
            if name in table:
                return table[name]
            for other in others:
                if name in other:
                    raise TypeError(
                        f"telemetry name {name!r} already registered as a "
                        f"different instrument type")
            inst = cls(name, threading.Lock())
            table[name] = inst
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, (self._gauges, self._histograms),
                         name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, (self._counters, self._histograms),
                         name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(self._histograms, (self._counters, self._gauges),
                         name, Histogram)

    def reset(self) -> None:
        """Drop every instrument.  The train loop calls this at run start
        so telemetry.prom / stats.jsonl describe ONE run even when several
        train() calls share a process (experiment arms, tests)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()

    # -- exports -----------------------------------------------------------

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {
                "counters": {n: c.value for n, c in self._counters.items()},
                "gauges": {n: g.value for n, g in self._gauges.items()},
                "histograms": {
                    n: ({"count": h.count, "sum": h.sum, "mean": h.mean,
                         "min": h.min, "max": h.max}
                        | ({"max_exemplar": h.max_exemplar}
                           if h.max_exemplar is not None else {}))
                    for n, h in self._histograms.items()},
            }

    def export_text(self) -> str:
        """Prometheus text exposition format (one family per instrument;
        histograms as <name>_count/_sum/_min/_max).  Values use Python's
        shortest round-trip float repr — ``%g``-style 6-digit formatting
        would silently corrupt counters past ~1e6."""
        def fmt(v) -> str:
            return repr(float(v))

        lines = []
        with self._lock:
            for n, c in sorted(self._counters.items()):
                pn = prom_name(n)
                lines += [f"# TYPE {pn} counter", f"{pn} {fmt(c.value)}"]
            for n, g in sorted(self._gauges.items()):
                pn = prom_name(n)
                lines += [f"# TYPE {pn} gauge", f"{pn} {fmt(g.value)}"]
            for n, h in sorted(self._histograms.items()):
                pn = prom_name(n)
                lines.append(f"# TYPE {pn} summary")
                lines.append(f"{pn}_count {fmt(h.count)}")
                lines.append(f"{pn}_sum {fmt(h.sum)}")
                if h.count:
                    lines.append(f"{pn}_min {fmt(h.min)}")
                    lines.append(f"{pn}_max {fmt(h.max)}")
                    if h.max_exemplar is not None:
                        # comment channel: readers that don't know about
                        # exemplars (parse_prom_values, check_prom) skip
                        # '#' lines by contract
                        lines.append(f"# EXEMPLAR {pn}_max "
                                     f"{h.max_exemplar}")
        return "\n".join(lines) + "\n" if lines else ""

    def write_prom(self, path: str) -> None:
        """Atomic rewrite — a scraper never sees a torn file."""
        atomic_write_text(path, self.export_text())


_REGISTRY = Registry()


def get_registry() -> Registry:
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return _REGISTRY.histogram(name)
