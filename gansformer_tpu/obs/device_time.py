"""Periodic device-time sampler — the loop's "the device said so" layer
(ISSUE 8 tentpole a).

Wall-clock spans (obs/spans.py) say where the HOST loop's time went; the
r3 retraction proved they can lie about what the chip is doing.  This
sampler generalizes the loop's one-shot steady-state profiler window
(``TrainConfig.profile_dir``) into a flag-gated periodic probe: every
``every_ticks`` ticks it wraps ONE full tick window (boundary to
boundary — both endpoints are ``block_until_ready``-synced, so the wall
comparison is honest) in a ``jax.profiler`` trace to a temp dir, parses
it with ``utils/profparse.py`` (xplane, or the no-TensorFlow Chrome
fallback), folds the result into the telemetry registry, and deletes
the trace.  Gauges:

* ``device/busy_ms`` / ``device/span_ms`` / ``device/wall_ms`` — the
  sampled window's merged device-busy time, trace span, and host wall.
* ``device/wall_busy_ratio`` — busy/wall, THE wall-vs-device divergence
  gauge: ≈1 compute-bound, ≪1 host-bound, >1 means the wall clock is
  not covering device execution (the r3 failure mode).
* ``device/phase_ms/<program>`` — per-jitted-program attribution
  (``d_step``, ``g_step_pl``, ``cycle``, …; names come from the trace's
  ``PjitFunction``/``jit_*`` events).
* ``device/mfu`` — device-time MFU (FLOPs actually executed over busy
  seconds vs chip peak), beside the wall-clock ``timing/mfu`` stat.
* ``device/samples_total`` / ``device/sample_failed_total`` counters,
  ``device/unavailable`` (no parser could read the last trace) and
  ``device/sampler_off`` (the explicit profiling-is-off marker the
  telemetry schema lint requires) gauges.

Every profiler call is wrapped: a wedged or unavailable tracer costs
one failed sample, never training.  CAUTION for unattended tunnel runs:
a client killed mid-trace was observed (bench.py r4 note) to wedge the
relayed backend claim for subsequent processes — the battery's train
stage passes ``--device-time-ticks 0`` for exactly that reason.
"""

from __future__ import annotations

import shutil
import tempfile
import time
from typing import Optional

from gansformer_tpu.obs.registry import counter, gauge

# bound the device/phase_ms/* cardinality: keep the heaviest programs
_MAX_PROGRAMS = 12


class DeviceTimeSampler:
    def __init__(self, every_ticks: int = 0,
                 flops_per_it: Optional[float] = None,
                 peak_tflops: Optional[float] = None,
                 enabled: bool = True):
        self.every = int(every_ticks or 0)
        self.enabled = bool(enabled) and self.every > 0
        self.flops_per_it = flops_per_it
        self.peak_tflops = peak_tflops
        self._dir: Optional[str] = None
        self._t0 = 0.0
        # materialize the markers at construction (the loop builds the
        # sampler after the per-run registry reset) so the FIRST prom
        # write already answers "is device truth being sampled?"
        gauge("device/sampler_off").set(0.0 if self.enabled else 1.0)
        if self.enabled:
            counter("device/samples_total")
            self._warm()

    def _warm(self) -> None:
        """Pay the profiler's one-time per-process init (measured ~11 s
        on this container) HERE, at setup — outside any tick window —
        with a throwaway start/stop.  Without this the first sampled
        tick carries ~11 s of uncovered wall, which both breaks the
        phase-sum invariant (sum(timing/phase/*) ≈ sec_per_tick) and
        skews the first divergence ratio low.  Subsequent starts are
        ~0 s (verified); a failure here just means the first real
        sample pays the init instead."""
        import jax

        tdir = tempfile.mkdtemp(prefix="graft_devtime_warm_")
        try:
            jax.profiler.start_trace(tdir)
            jax.profiler.stop_trace()
        except Exception:
            pass
        finally:
            shutil.rmtree(tdir, ignore_errors=True)

    @property
    def sampling(self) -> bool:
        return self._dir is not None

    def maybe_start(self, tick: int) -> bool:
        """Start a trace at this tick boundary when the cadence says so
        (``tick % every == 1`` — the same "first steady-state window"
        alignment as the one-shot ``profile_dir`` trace; ``every == 1``
        fires at every boundary, hence the ``1 % every`` right-hand
        side).  The trace is stopped and folded by ``stop_and_fold`` at
        the NEXT boundary."""
        if not self.enabled or self.sampling \
                or tick % self.every != 1 % self.every:
            return False
        import jax

        tdir = tempfile.mkdtemp(prefix="graft_devtime_")
        try:
            jax.profiler.start_trace(tdir)
        except Exception:
            # tracer unavailable/already active: one failed sample,
            # never a dead run
            shutil.rmtree(tdir, ignore_errors=True)
            counter("device/sample_failed_total").inc()
            return False
        self._dir = tdir
        self._t0 = time.time()
        return True

    def stop_and_fold(self, wall_s: Optional[float] = None,
                      iters: Optional[float] = None) -> Optional[dict]:
        """Stop the active trace, parse it, fold the registry gauges,
        delete the trace dir.  ``wall_s`` is the sampled window's host
        wall (the caller's ``sec_per_tick`` — both endpoints synced);
        ``iters`` the training iterations the window ran (for device-time
        MFU).  Returns the ``device_time_report`` dict (with ``wall_s``
        added) or None when no trace was active / the stop failed."""
        if not self.sampling:
            return None
        import jax

        tdir, self._dir = self._dir, None
        wall = wall_s if wall_s is not None else time.time() - self._t0
        try:
            jax.profiler.stop_trace()
        except Exception:
            shutil.rmtree(tdir, ignore_errors=True)
            counter("device/sample_failed_total").inc()
            return None
        from gansformer_tpu.utils.profparse import device_time_report

        try:
            rep = device_time_report(tdir)
        finally:
            shutil.rmtree(tdir, ignore_errors=True)
        rep["wall_s"] = wall
        if rep.get("status") != "ok":
            counter("device/sample_failed_total").inc()
            gauge("device/unavailable").set(1.0)
            return rep
        counter("device/samples_total").inc()
        gauge("device/unavailable").set(0.0)
        busy = rep["busy_s"]
        gauge("device/busy_ms").set(busy * 1e3)
        gauge("device/span_ms").set(rep["span_s"] * 1e3)
        gauge("device/wall_ms").set(wall * 1e3)
        if wall > 0:
            gauge("device/wall_busy_ratio").set(busy / wall)
        progs = sorted(rep.get("program_busy_s", {}).items(),
                       key=lambda kv: -kv[1])[:_MAX_PROGRAMS]
        for name, s in progs:
            gauge(f"device/phase_ms/{name}").set(s * 1e3)
        if self.flops_per_it and self.peak_tflops and iters and busy > 0:
            rep["device_mfu"] = (self.flops_per_it * iters / busy
                                 / (self.peak_tflops * 1e12))
            gauge("device/mfu").set(rep["device_mfu"])
        return rep

    def close(self) -> None:
        """Discard an in-flight trace without folding (exception paths,
        end of run) so the process-global profiler is released."""
        if not self.sampling:
            return
        import jax

        tdir, self._dir = self._dir, None
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        shutil.rmtree(tdir, ignore_errors=True)
