"""SLO error budgets — declared objectives, burn rates, exhaustion.

An SLO here is a declared objective over served traffic: "99% of
fulfilled requests complete under 2000 ms", "99.9% of admitted requests
are fulfilled", "at most 1% of submissions are shed".  The error budget
is the allowance the target leaves open (a 99.9% target over 10k
requests budgets 10 bad ones); the **burn rate** is how fast the window
is spending it::

    burn_rate = (bad / total) / (1 - target)

Burn rate 1.0 means the window spends exactly its budget; 10 means the
budget is gone in a tenth of the window (the classic page-now
threshold).  ``exhausted`` (bad > budget in the evaluated window) is
what flips the doctor's ``slo`` section to FAIL.

Sources, in preference order:

* ``requests.jsonl`` — the per-request ledger the request tracer
  writes.  Row-level outcomes and latencies allow every objective to be
  evaluated EXACTLY over a rolling window (``window_s`` back from
  ``now`` by each row's wall-clock ``t_wall``).
* ``telemetry.prom`` — lifetime ``serve_*`` counters.  No per-request
  rows, so the window is "since service start", availability cannot
  see per-request latency, and the latency objective reports
  ``no_data``.  Still enough to compute shed/availability budgets on a
  run that disabled the ledger.

Jax-free (artifact readers only) — the doctor, the ``slo`` CLI
subcommand, and fleet-level rollups all run on machines with no
accelerator stack.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from gansformer_tpu.obs.reqtrace import read_requests

# The declared objective set.  ``kind`` picks the good/bad classifier;
# ``target`` is the good-fraction the budget is written against;
# ``threshold_ms`` parameterizes the latency classifier.  Callers may
# pass their own list to ``evaluate_slos`` — these are the defaults the
# doctor and the CLI grade against.
DEFAULT_OBJECTIVES: List[dict] = [
    {"name": "latency_p99", "kind": "latency", "target": 0.99,
     "threshold_ms": 2000.0,
     "description": "fulfilled requests complete under threshold_ms"},
    {"name": "availability", "kind": "availability", "target": 0.999,
     "description": "admitted requests are fulfilled "
                    "(failed/expired spend budget; client cancels don't)"},
    {"name": "shed_rate", "kind": "shed", "target": 0.99,
     "description": "submissions are admitted rather than shed"},
]


def _classify_ledger(rows: List[dict], obj: dict) -> Optional[dict]:
    """(good, bad, total) for one objective over ledger rows, or None
    when the objective can't see any qualifying traffic."""
    kind = obj["kind"]
    if kind == "latency":
        done = [r for r in rows if r.get("outcome") == "fulfilled"]
        if not done:
            return None
        thresh = float(obj.get("threshold_ms", 2000.0))
        good = sum(1 for r in done
                   if float(r.get("e2e_ms", 0.0)) <= thresh)
        return {"good": good, "bad": len(done) - good, "total": len(done)}
    if kind == "availability":
        admitted = [r for r in rows
                    if r.get("outcome") in ("fulfilled", "failed",
                                            "expired")]
        if not admitted:
            return None
        good = sum(1 for r in admitted if r["outcome"] == "fulfilled")
        return {"good": good, "bad": len(admitted) - good,
                "total": len(admitted)}
    if kind == "shed":
        submitted = [r for r in rows if r.get("outcome") != "cancelled"]
        if not submitted:
            return None
        bad = sum(1 for r in submitted if r["outcome"] == "shed")
        return {"good": len(submitted) - bad, "bad": bad,
                "total": len(submitted)}
    raise ValueError(f"unknown SLO kind {kind!r}")


def _classify_prom(vals: Dict[str, float], obj: dict) -> Optional[dict]:
    """Lifetime-counter approximation of one objective (see module
    docstring for what each fallback can and cannot see)."""
    kind = obj["kind"]
    requests = vals.get("serve_requests_total", 0.0)
    shed = vals.get("serve_shed_total", 0.0)
    expired = vals.get("serve_expired_total", 0.0)
    cancelled = vals.get("serve_cancelled_total", 0.0)
    if kind == "latency":
        return None               # counters carry no per-request latency
    if kind == "availability":
        # admitted minus client cancels; failures beyond expiry are not
        # separately countered, so expiry is the visible budget spend
        total = requests - cancelled
        if total <= 0:
            return None
        bad = min(expired, total)
        return {"good": total - bad, "bad": bad, "total": total}
    if kind == "shed":
        total = requests + shed
        if total <= 0:
            return None
        return {"good": requests, "bad": shed, "total": total}
    raise ValueError(f"unknown SLO kind {kind!r}")


def _budget(obj: dict, counts: Optional[dict], source: str,
            window_s: Optional[float]) -> dict:
    out = {"name": obj["name"], "kind": obj["kind"],
           "target": obj["target"],
           "description": obj.get("description", ""),
           "source": source, "window_s": window_s}
    if obj["kind"] == "latency":
        out["threshold_ms"] = float(obj.get("threshold_ms", 2000.0))
    if counts is None:
        out.update({"status": "no_data", "good": 0, "bad": 0, "total": 0,
                    "compliance": None, "budget_total": 0.0,
                    "budget_spent": 0.0, "budget_remaining": 0.0,
                    "burn_rate": 0.0, "exhausted": False})
        return out
    good, bad, total = counts["good"], counts["bad"], counts["total"]
    target = float(obj["target"])
    allowed = (1.0 - target) * total          # budgeted bad count
    bad_frac = bad / total
    burn = bad_frac / (1.0 - target) if target < 1.0 else (
        float("inf") if bad else 0.0)
    exhausted = bad > allowed
    out.update({
        "status": "exhausted" if exhausted else "ok",
        "good": good, "bad": bad, "total": total,
        "compliance": round(good / total, 6),
        "budget_total": round(allowed, 3),
        "budget_spent": float(bad),
        "budget_remaining": round(max(allowed - bad, 0.0), 3),
        "burn_rate": (round(burn, 4)
                      if burn != float("inf") else burn),
        "exhausted": exhausted,
    })
    return out


def evaluate_slos(run_dir: str,
                  objectives: Optional[List[dict]] = None,
                  window_s: float = 3600.0,
                  now: Optional[float] = None) -> dict:
    """Grade every objective over a run dir's artifacts.

    Prefers the ``requests.jsonl`` ledger (rolling ``window_s`` window
    ending at ``now``, by row ``t_wall``); falls back to lifetime
    ``telemetry.prom`` counters when no ledger rows qualify.  Never
    raises on missing/torn artifacts — objectives without data report
    ``status: no_data``.  Returns ``{source, window_s, rows, objectives,
    exhausted, worst_burn_rate}``; ``exhausted`` lists the objectives
    whose budget is spent (what the doctor FAILs on)."""
    objectives = DEFAULT_OBJECTIVES if objectives is None else objectives
    now = time.time() if now is None else now

    rows = read_requests(os.path.join(run_dir, "requests.jsonl"))
    windowed = [r for r in rows
                if isinstance(r.get("t_wall"), (int, float))
                and now - r["t_wall"] <= window_s]
    vals: Dict[str, float] = {}
    source = "ledger" if windowed else "prom"
    if not windowed:
        prom = os.path.join(run_dir, "telemetry.prom")
        if os.path.exists(prom):
            from gansformer_tpu.obs.registry import parse_prom_values
            try:
                vals = parse_prom_values(prom)
            except OSError:
                vals = {}
        if not vals:
            source = "none"

    graded = []
    for obj in objectives:
        if source == "ledger":
            counts = _classify_ledger(windowed, obj)
            graded.append(_budget(obj, counts, "ledger", window_s))
        elif source == "prom":
            counts = _classify_prom(vals, obj)
            graded.append(_budget(obj, counts, "prom", None))
        else:
            graded.append(_budget(obj, None, "none", None))
    exhausted = [o["name"] for o in graded if o["exhausted"]]
    burns = [o["burn_rate"] for o in graded
             if o["status"] not in ("no_data",)]
    return {"source": source, "window_s": window_s,
            "rows": len(windowed), "objectives": graded,
            "exhausted": exhausted,
            "worst_burn_rate": max(burns) if burns else 0.0}


def render_slos(report: dict) -> str:
    """Human rendering for the ``slo`` CLI subcommand."""
    lines = [f"source={report['source']} "
             f"window_s={report['window_s']:g} rows={report['rows']}"]
    for o in report["objectives"]:
        if o["status"] == "no_data":
            lines.append(f"  {o['name']:<14s} target={o['target']:g}  "
                         f"no data")
            continue
        lines.append(
            f"  {o['name']:<14s} target={o['target']:g}  "
            f"compliance={o['compliance']:.4f}  "
            f"bad={o['bad']}/{o['total']}  "
            f"budget={o['budget_spent']:g}/{o['budget_total']:g}  "
            f"burn={o['burn_rate']:g}  "
            f"{'EXHAUSTED' if o['exhausted'] else 'ok'}")
    return "\n".join(lines)
