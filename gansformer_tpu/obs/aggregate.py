"""Fleet telemetry aggregation — N processes' artifacts → one view.

A pod-scale run emits per-process artifacts (``telemetry.prom`` /
``telemetry-p<idx>.prom``, ``heartbeat-p*.json``,
``supervisor_events.jsonl``) but the questions that matter at fleet
scale are cross-process: which process is the straggler (step skew),
how wide is the device-MFU spread, did restarts cluster on one host
(restart asymmetry)?  ``aggregate_fleet`` folds everything into one
``fleet.json`` / ``fleet.prom`` pair with DECLARED per-family merge
semantics:

=============  ==========================================================
family         merge
=============  ==========================================================
counters       sum over reporting processes
gauges         max / min / spread (exported as ``<name>_max`` /
               ``<name>_min`` / ``<name>_spread``)
histograms     ``_count``/``_sum`` sum, ``_min`` min, ``_max`` max
heartbeats     roster + step skew via ``check_heartbeats`` (the SAME
               computation the doctor and the heartbeats CLI use — the
               two can never disagree on the straggler verdict)
supervisor     restart events counted per input (restart asymmetry =
               max − min across inputs)
=============  ==========================================================

Degradation contract (the satellite's edge cases): a missing process, a
stale heartbeat, conflicting gauge timestamps (per-process artifacts
written too far apart for gauges to describe one instant), or a
partially-written prom file degrade to a PARTIAL fleet view — the
``fleet/partial`` marker is set, the reasons are listed, and nothing
ever raises.  Jax-free: the aggregator runs on a coordinator node with
no accelerator stack.

Inputs: ONE shared run dir (heartbeat-p*.json roster; per-process proms
as ``telemetry-p<idx>.prom`` when present, else ``telemetry.prom``
attributed to process 0 — the single-writer layout the train loop
uses), or a LIST of per-process run dirs (each with its own
``telemetry.prom``).
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Dict, List, Optional, Tuple, Union

from gansformer_tpu.obs.heartbeat import check_heartbeats, read_heartbeats
from gansformer_tpu.obs.registry import atomic_write_text

_SUMMARY_SUFFIX = re.compile(r"_(count|sum|min|max)$")


def _parse_prom_typed(path: str) -> Tuple[Dict[str, str],
                                          Dict[str, float], List[str]]:
    """({family: type}, {sample name: value}, issues).  Never raises:
    unreadable files and torn lines become issues — the partial-view
    inputs this module exists to tolerate."""
    types: Dict[str, str] = {}
    values: Dict[str, float] = {}
    issues: List[str] = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        return types, values, [f"{path}: unreadable ({e})"]
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) == 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        parts = line.split()
        if len(parts) != 2:
            issues.append(f"{path}:{i}: torn line")
            continue
        try:
            values[parts[0]] = float(parts[1])
        except ValueError:
            issues.append(f"{path}:{i}: non-numeric value")
    return types, values, issues


def _family_of(name: str, types: Dict[str, str]) -> Tuple[str, str]:
    """(family base name, declared type) for one sample name; summary
    member suffixes resolve to their family."""
    if name in types:
        return name, types[name]
    base = _SUMMARY_SUFFIX.sub("", name)
    if base in types:
        return base, types[base]
    return name, "untyped"


def _discover_inputs(run_dirs) -> List[dict]:
    """Normalize the two input shapes into per-process descriptors:
    {idx, heartbeat (rec or None), prom_path (or None)}."""
    if isinstance(run_dirs, (str, os.PathLike)):
        run_dir = str(run_dirs)
        beats = read_heartbeats(run_dir)
        indices = sorted(beats) or [0]
        procs = []
        for idx in indices:
            prom = os.path.join(run_dir, f"telemetry-p{idx}.prom")
            if not os.path.exists(prom):
                # single-writer layout: process 0 owns telemetry.prom
                prom = (os.path.join(run_dir, "telemetry.prom")
                        if idx == 0 else None)
            procs.append({"idx": idx, "dir": run_dir,
                          "heartbeat": beats.get(idx),
                          "prom_path": prom})
        return procs
    procs = []
    for i, d in enumerate(run_dirs):
        d = str(d)
        beats = read_heartbeats(d)
        idx = sorted(beats)[0] if beats else i
        prom = os.path.join(d, "telemetry.prom")
        procs.append({"idx": idx, "dir": d,
                      "heartbeat": beats.get(idx),
                      "prom_path": prom if os.path.exists(prom)
                      else None})
    return procs


def _count_restarts(run_dir: str) -> Optional[int]:
    """Restart events in a dir's supervisor ledger (None when absent);
    torn lines skipped — the ledger's own readers do the same."""
    path = os.path.join(run_dir, "supervisor_events.jsonl")
    if not os.path.exists(path):
        return None
    n = 0
    try:
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("kind") == "restart":
                    n += 1
    except OSError:
        return None
    return n


def aggregate_fleet(run_dirs: Union[str, List[str]],
                    expected: Optional[int] = None,
                    max_age_s: Optional[float] = None,
                    now: Optional[float] = None,
                    gauge_skew_s: float = 300.0) -> dict:
    """Fold per-process artifacts into the fleet view (see module
    docstring for merge semantics and the degradation contract).

    ``expected`` declares the roster size (missing processes detected);
    ``max_age_s`` judges heartbeat staleness; ``gauge_skew_s`` bounds
    how far apart per-process heartbeat times may be before gauge
    merges are flagged as non-simultaneous (conflicting timestamps).
    Never raises on bad inputs — the return carries ``partial`` +
    ``partial_reasons`` instead."""
    now = time.time() if now is None else now
    procs = _discover_inputs(run_dirs)
    partial_reasons: List[str] = []

    # -- roster / heartbeats (the check_heartbeats verdict verbatim) --------
    single_dir = isinstance(run_dirs, (str, os.PathLike))
    hb_dir = str(run_dirs) if single_dir else None
    steps: Dict[int, int] = {}
    ages: Dict[int, float] = {}
    hb_times: List[float] = []
    for p in procs:
        rec = p["heartbeat"]
        if rec is not None:
            steps[p["idx"]] = int(rec.get("step", 0))
            ages[p["idx"]] = now - rec.get("time", 0.0)
            hb_times.append(rec.get("time", 0.0))
    if single_dir:
        hb = check_heartbeats(
            hb_dir, max_age_s=max_age_s if max_age_s is not None else 1e18,
            expected=list(range(expected)) if expected is not None
            else None, now=now)
        step_skew = hb["step_skew"]
        stale = hb["stale"]
        missing = hb["missing"]
    else:
        step_skew = (max(steps.values()) - min(steps.values())
                     if steps else 0)
        stale = sorted(idx for idx, age in ages.items()
                       if max_age_s is not None and age > max_age_s)
        missing = (sorted(set(range(expected)) - set(steps))
                   if expected is not None else [])
    reporting = sorted(steps)
    roster = sorted(set(reporting) | set(missing)
                    | set(range(expected or 0)))
    if not reporting:
        partial_reasons.append("no heartbeat reported by any process")
    for idx in missing:
        partial_reasons.append(f"process {idx} missing (no heartbeat)")
    for idx in stale:
        partial_reasons.append(
            f"process {idx} heartbeat stale ({ages[idx]:.0f}s old)")

    # -- per-process proms ---------------------------------------------------
    counters: Dict[str, float] = {}
    gauges: Dict[str, dict] = {}
    summaries: Dict[str, dict] = {}
    prom_reporting: List[int] = []
    for p in procs:
        path = p["prom_path"]
        p["prom"] = os.path.basename(path) if path else None
        p["prom_issues"] = 0
        if path is None:
            continue
        if not os.path.exists(path):
            partial_reasons.append(
                f"process {p['idx']}: prom file missing ({path})")
            continue
        types, values, issues = _parse_prom_typed(path)
        if issues:
            p["prom_issues"] = len(issues)
            partial_reasons.append(
                f"process {p['idx']}: partially-written prom "
                f"({len(issues)} unparsable line(s))")
        if not values:
            continue
        prom_reporting.append(p["idx"])
        for name, v in values.items():
            fam, kind = _family_of(name, types)
            if kind == "counter":
                counters[name] = counters.get(name, 0.0) + v
            elif kind == "summary":
                s = summaries.setdefault(fam, {})
                member = name[len(fam) + 1:] if name != fam else "value"
                if member in ("count", "sum"):
                    s[member] = s.get(member, 0.0) + v
                elif member == "min":
                    s[member] = min(s.get(member, v), v)
                elif member == "max":
                    s[member] = max(s.get(member, v), v)
            else:                       # gauge / untyped: spread stats
                g = gauges.setdefault(name, {"per_process": {}})
                g["per_process"][p["idx"]] = v
    for g in gauges.values():
        vs = list(g["per_process"].values())
        g["min"], g["max"] = min(vs), max(vs)
        g["spread"] = g["max"] - g["min"]
        g["per_process"] = {str(k): v
                            for k, v in sorted(g["per_process"].items())}

    # conflicting gauge timestamps: gauges merged from artifacts whose
    # heartbeat times straddle more than gauge_skew_s cannot describe
    # one instant — the spread numbers are flagged, not trusted
    gauge_ts_conflict = (len(prom_reporting) > 1 and len(hb_times) > 1
                         and max(hb_times) - min(hb_times) > gauge_skew_s)
    if gauge_ts_conflict:
        partial_reasons.append(
            "conflicting gauge timestamps: per-process artifacts span "
            f"{max(hb_times) - min(hb_times):.0f}s > {gauge_skew_s:.0f}s "
            "— merged gauges are not simultaneous")

    # -- restart asymmetry ---------------------------------------------------
    restart_dirs = sorted({p["dir"] for p in procs})
    restarts: Dict[str, int] = {}
    for d in restart_dirs:
        n = _count_restarts(d)
        if n is not None:
            restarts[d] = n
    restart_counts = list(restarts.values())
    restart_spread = (max(restart_counts) - min(restart_counts)
                      if len(restart_counts) > 1 else 0)

    mfu = gauges.get("device_mfu", {})
    return {
        "generated_at": now,
        "processes": {
            str(p["idx"]): {
                "step": steps.get(p["idx"]),
                "age_s": (round(ages[p["idx"]], 3)
                          if p["idx"] in ages else None),
                "heartbeat": p["heartbeat"] is not None,
                "prom": p["prom"],
                "prom_issues": p["prom_issues"],
            } for p in procs},
        "expected": expected, "roster": roster,
        "reporting": reporting, "missing": missing, "stale": stale,
        "prom_reporting": sorted(prom_reporting),
        "partial": bool(partial_reasons),
        "partial_reasons": partial_reasons,
        "steps": {str(k): v for k, v in sorted(steps.items())},
        "step_skew": step_skew,
        "heartbeat_age_max_s": (round(max(ages.values()), 3)
                                if ages else None),
        "gauge_ts_conflict": gauge_ts_conflict,
        "counters": dict(sorted(counters.items())),
        "gauges": dict(sorted(gauges.items())),
        "histograms": dict(sorted(summaries.items())),
        "mfu_spread": mfu.get("spread"),
        "mfu_per_process": mfu.get("per_process"),
        "restarts": restarts,
        "restarts_total": sum(restart_counts),
        "restart_spread": restart_spread,
    }


def fleet_prom_text(fleet: dict) -> str:
    """The fleet view as Prometheus text: the ``fleet_*`` meta family
    (partial marker first — the one value a reader must never miss),
    then merged counters, gauge spread triples, and summary families.
    Every sample is TYPE-declared so ``check_prom`` passes."""
    def fmt(v) -> str:
        return repr(float(v))

    lines = []

    def g(name: str, v) -> None:
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {fmt(v)}")

    g("fleet_partial", 1.0 if fleet["partial"] else 0.0)
    g("fleet_processes", len(fleet["roster"]))
    g("fleet_processes_reporting", len(fleet["reporting"]))
    g("fleet_processes_missing", len(fleet["missing"]))
    g("fleet_processes_stale", len(fleet["stale"]))
    g("fleet_step_skew", fleet["step_skew"])
    g("fleet_heartbeat_age_max_s", fleet["heartbeat_age_max_s"] or 0.0)
    g("fleet_gauge_ts_conflict",
      1.0 if fleet["gauge_ts_conflict"] else 0.0)
    g("fleet_restart_spread", fleet["restart_spread"])
    if fleet["mfu_spread"] is not None:
        g("fleet_mfu_spread", fleet["mfu_spread"])
    lines.append("# TYPE fleet_restarts_total counter")
    lines.append(f"fleet_restarts_total {fmt(fleet['restarts_total'])}")
    for name, v in fleet["counters"].items():
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {fmt(v)}")
    for name, gd in fleet["gauges"].items():
        for stat in ("max", "min", "spread"):
            lines.append(f"# TYPE {name}_{stat} gauge")
            lines.append(f"{name}_{stat} {fmt(gd[stat])}")
    for fam, s in fleet["histograms"].items():
        lines.append(f"# TYPE {fam} summary")
        for member in ("count", "sum", "min", "max"):
            if member in s:
                lines.append(f"{fam}_{member} {fmt(s[member])}")
    return "\n".join(lines) + "\n" if lines else ""


def write_fleet(fleet: dict, out_dir: str) -> Tuple[str, str]:
    """Write ``fleet.json`` + ``fleet.prom`` (atomic — a scraper never
    sees a torn fleet view); returns the two paths."""
    os.makedirs(out_dir, exist_ok=True)
    json_path = os.path.join(out_dir, "fleet.json")
    prom_path = os.path.join(out_dir, "fleet.prom")
    atomic_write_text(json_path,
                      json.dumps(fleet, indent=1, sort_keys=True) + "\n")
    atomic_write_text(prom_path, fleet_prom_text(fleet))
    return json_path, prom_path
