"""Per-process heartbeats — liveness files for multi-host runs.

A multi-host JAX run that loses one process doesn't crash: the
survivors block forever in the next collective.  The only cheap remedy
is out-of-band liveness: every process rewrites
``heartbeat-p<idx>.json`` (atomically) at each tick with its step,
kimg, wall time, and device-memory stats; ``check_heartbeats()`` reads
them all back and reports which peers are stale or missing, so an
external babysitter (or ``python -m gansformer_tpu.cli.telemetry
heartbeats <run_dir>``) can kill-and-restart the run instead of letting
it hang.  Heartbeats assume the run dir is shared (NFS/GCS-fuse) or
per-host probed — each file is self-describing either way.

Clocks are injectable (``time_fn`` / ``now``) so staleness tests run on
a fake clock rather than ``sleep()``.
"""

from __future__ import annotations

import glob
import json
import os
import re
import socket
import time
from typing import Callable, Dict, List, Optional

from gansformer_tpu.obs.registry import atomic_write_text, gauge

_HB_RE = re.compile(r"heartbeat-p(\d+)\.json$")


def device_memory_stats() -> Optional[dict]:
    """Summed ``memory_stats()`` over local devices, or None when the
    backend doesn't report (CPU) or jax isn't importable.  Also records
    the ``device/mem_peak_bytes`` gauge as a side effect."""
    try:
        import jax

        per_dev = [d.memory_stats() for d in jax.local_devices()]
    except Exception:
        return None
    per_dev = [s for s in per_dev if s]
    if not per_dev:
        return None
    out = {
        "bytes_in_use": sum(s.get("bytes_in_use", 0) for s in per_dev),
        "peak_bytes_in_use": sum(
            s.get("peak_bytes_in_use", 0) for s in per_dev),
        "num_devices": len(per_dev),
    }
    gauge("device/mem_peak_bytes").max(out["peak_bytes_in_use"])
    return out


def hbm_device_stats() -> Optional[dict]:
    """Max-over-LOCAL-devices HBM stats right now, or None when the
    backend reports no memory stats (CPU) or jax is unimportable.  Max,
    not sum: a straggler device OOMs first, so the per-device view is
    the one that answers "does FFHQ-1024 fit".  Pure read (no gauges) —
    shared by ``sample_hbm`` and bench.py's artifact snapshot so the
    two can never disagree on aggregation."""
    try:
        import jax

        per_dev = [d.memory_stats() or {} for d in jax.local_devices()]
    except Exception:
        return None
    per_dev = [s for s in per_dev if s]
    if not per_dev:
        return None
    return {
        "bytes_in_use": max(s.get("bytes_in_use", 0) for s in per_dev),
        "peak_bytes": max(s.get("peak_bytes_in_use", 0) for s in per_dev),
        "bytes_limit": max(s.get("bytes_limit", 0) for s in per_dev),
        "devices": len(per_dev),
    }


def sample_hbm() -> Optional[dict]:
    """Per-tick HBM gauges (ISSUE 8 tentpole b) from
    ``hbm_device_stats``:

    * ``hbm/bytes_in_use`` (gauge, current), ``hbm/peak_bytes``
      (high-water gauge), ``hbm/bytes_limit`` (when the backend reports
      it), ``hbm/devices`` (local devices that reported).
    * ``hbm/unavailable`` — 1.0 when the backend reports no memory
      stats (CPU) or jax is unimportable; the EXPLICIT marker the
      telemetry schema lint requires, so "no hbm numbers" can never be
      confused with "forgot to sample".

    Returns the sampled dict (embedded in the heartbeat record) or None
    when unavailable."""
    out = hbm_device_stats()
    if out is None:
        gauge("hbm/unavailable").set(1.0)
        return None
    gauge("hbm/unavailable").set(0.0)
    gauge("hbm/bytes_in_use").set(out["bytes_in_use"])
    gauge("hbm/peak_bytes").max(out["peak_bytes"])
    gauge("hbm/devices").set(out["devices"])
    if out["bytes_limit"]:
        gauge("hbm/bytes_limit").set(out["bytes_limit"])
    return out


def host_rss_peak_bytes() -> Optional[int]:
    """Peak resident set of this process (linux ru_maxrss is KiB)."""
    try:
        import resource

        kib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    except Exception:
        return None
    peak = int(kib) * 1024
    gauge("host/mem_peak_bytes").max(peak)
    return peak


class Heartbeat:
    """Writer for one process's ``heartbeat-p<idx>.json``."""

    def __init__(self, run_dir: str, process_index: int = 0,
                 time_fn: Callable[[], float] = time.time):
        self.run_dir = run_dir
        self.process_index = process_index
        self.path = os.path.join(run_dir,
                                 f"heartbeat-p{process_index}.json")
        self._time = time_fn
        # every process needs the dir to exist for ITS file, even when
        # process 0 hasn't finished creating the shared run dir yet
        os.makedirs(run_dir, exist_ok=True)

    def beat(self, step: int = 0, kimg: float = 0.0,
             extra: Optional[dict] = None) -> dict:
        rec = {
            "process": self.process_index,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "time": self._time(),
            "step": int(step),
            "kimg": float(kimg),
        }
        mem = device_memory_stats()
        if mem is not None:
            rec["device_memory"] = mem
        hbm = sample_hbm()
        if hbm is not None:
            rec["hbm"] = hbm
        rss = host_rss_peak_bytes()
        if rss is not None:
            rec["host_rss_peak_bytes"] = rss
        if extra:
            rec.update(extra)
        atomic_write_text(self.path, json.dumps(rec))
        return rec


def read_heartbeats(run_dir: str) -> Dict[int, dict]:
    """{process_index: record} for every readable heartbeat file."""
    out: Dict[int, dict] = {}
    for path in glob.glob(os.path.join(run_dir, "heartbeat-p*.json")):
        m = _HB_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                out[int(m.group(1))] = json.load(f)
        except (OSError, ValueError):
            continue  # mid-replace or torn file: next probe sees it
    return out


def check_heartbeats(run_dir: str, max_age_s: float = 300.0,
                     expected: Optional[List[int]] = None,
                     now: Optional[float] = None,
                     max_step_skew: Optional[int] = None) -> dict:
    """Staleness + straggler probe over a run dir's heartbeat files.

    Returns ``{"ok", "ages", "stale", "missing", "steps", "step_skew",
    "skew_exceeded"}`` where ``ages`` maps process index → seconds since
    its last beat, ``stale`` lists processes older than ``max_age_s``,
    ``missing`` lists expected indices with no file at all, and
    ``step_skew`` is the max inter-process step spread (``max(step) -
    min(step)`` — the straggler signal for a multihost run whose peers
    all still beat but one lags the collectives; ISSUE 8 satellite).
    ``skew_exceeded`` is True when ``max_step_skew`` is given and the
    spread is larger; ``ok`` is True iff nothing is stale, missing, or
    skew-exceeded.  ``expected=None`` checks only the processes that
    have ever written (missing detection needs the roster).
    """
    now = time.time() if now is None else now
    beats = read_heartbeats(run_dir)
    ages = {idx: now - rec.get("time", 0.0) for idx, rec in beats.items()}
    stale = sorted(idx for idx, age in ages.items() if age > max_age_s)
    missing = (sorted(set(expected) - set(beats))
               if expected is not None else [])
    steps = {idx: int(rec.get("step", 0)) for idx, rec in beats.items()}
    step_skew = (max(steps.values()) - min(steps.values())) if steps else 0
    skew_exceeded = (max_step_skew is not None
                     and step_skew > max_step_skew)
    return {"ok": not stale and not missing and not skew_exceeded,
            "ages": ages, "stale": stale, "missing": missing,
            "steps": steps, "step_skew": step_skew,
            "skew_exceeded": skew_exceeded}
