"""Telemetry subsystem — dependency-free observability primitives.

Three layers, each importable without jax/tensorflow so host-side tools
(data loaders, CLIs, tests) can instrument themselves for free:

* ``spans``    — nestable ``span("phase")`` context managers with
  thread-local stacks.  Per-phase wall time accumulates in a process
  tracer (drained at each tick into ``timing/phase/*`` stats) and every
  span is appended to ``events.jsonl`` in Chrome-trace event form.
* ``registry`` — process-global counters / gauges / histograms with a
  Prometheus-style text export (``telemetry.prom``, rewritten per tick).
* ``heartbeat`` — per-process ``heartbeat-p<idx>.json`` liveness files
  (now carrying per-device HBM stats via ``sample_hbm``) plus
  ``check_heartbeats()`` — staleness AND inter-process step skew — so a
  multi-host run can detect a dead or straggling peer instead of
  hanging forever in a collective.
* ``device_time`` — the periodic device-truth sampler (ISSUE 8): flag-
  gated ``jax.profiler`` windows parsed into ``device/*`` gauges
  (device-time MFU, per-program device ms, wall-vs-device divergence).
  The one layer that DOES import jax — lazily, inside methods.
* ``reqtrace`` — per-request lifecycle tracing for the serving plane
  (ISSUE 16): request IDs minted at submit, causal event timelines
  through the continuous-batching dispatcher, terminal outcomes with
  causes, a bounded ``requests.jsonl`` ledger, and Chrome async events
  merged into the same ``events.jsonl`` the spans write.
* ``aggregate`` — fleet telemetry aggregation: N processes' prom /
  heartbeat / supervisor artifacts folded into ``fleet.json`` /
  ``fleet.prom`` with declared merge semantics (counters sum, gauges
  spread, histograms merge) and a never-raise partial-view contract.
* ``slo`` — declared service objectives (latency / availability / shed)
  graded into error budgets and burn rates over rolling windows of the
  request ledger, with lifetime-counter fallback.

The train loop wires all of them (train/loop.py); the data pipeline,
checkpointing, and metric layers record into the registry directly.
``docs/observability.md`` describes the run-dir artifacts;
``gansformer-telemetry doctor <run_dir>`` cross-checks them in one
report.
"""

from gansformer_tpu.obs.aggregate import (  # noqa: F401
    aggregate_fleet, fleet_prom_text, write_fleet)
from gansformer_tpu.obs.device_time import DeviceTimeSampler  # noqa: F401
from gansformer_tpu.obs.heartbeat import (  # noqa: F401
    Heartbeat, check_heartbeats, device_memory_stats, read_heartbeats,
    sample_hbm)
from gansformer_tpu.obs.registry import (  # noqa: F401
    Registry, counter, gauge, get_registry, histogram)
from gansformer_tpu.obs.reqtrace import (  # noqa: F401
    ReqTracer, configure_reqtrace, get_reqtracer, read_requests,
    render_timeline)
from gansformer_tpu.obs.slo import (  # noqa: F401
    DEFAULT_OBJECTIVES, evaluate_slos, render_slos)
from gansformer_tpu.obs.spans import (  # noqa: F401
    Tracer, configure_tracer, get_tracer, span)

_COMPILE_LISTENER = {"installed": False}


def install_compile_listener() -> bool:
    """Count XLA compiles into ``compile/compiles_total`` (+ a duration
    histogram ``compile/compile_ms``) via jax.monitoring.  The listener
    registers once per process, but the instruments are re-materialized
    on every call — the loop calls this after its per-run
    ``Registry.reset()``, so even a fully-warm-cache run exports an
    explicit ``compile_compiles_total 0.0``.  Returns False (and stays
    silent) when jax or its monitoring events are unavailable —
    telemetry must never be a dependency.
    """
    try:
        from jax import monitoring
    except Exception:
        return False
    counter("compile/compiles_total")
    histogram("compile/compile_ms")
    if _COMPILE_LISTENER["installed"]:
        return True

    def _on_duration(event: str, duration: float, **kw) -> None:
        # one event per actual XLA compile — NOT the per-call jaxpr-trace
        # events, which fire on every cache hit too.  Instruments are
        # resolved per event (cheap dict lookup) so a per-run
        # Registry.reset() can't orphan them.
        if "backend_compile" in event:
            counter("compile/compiles_total").inc()
            histogram("compile/compile_ms").observe(duration * 1000.0)

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        return False
    _COMPILE_LISTENER["installed"] = True
    return True


class RetraceWatch:
    """Counts compiles past the warm-up boundary into
    ``compile/retraces_total`` (ISSUE 4 satellite).

    The trace-level ``retrace-hazard`` rule statically predicts "this
    entry point compiles exactly once"; this watch is the runtime
    cross-check: every XLA compile the ``install_compile_listener``
    stream sees AFTER ``arm()`` (the train loop arms at the first tick
    boundary, when all step variants have compiled) is by definition a
    retrace — equivalent work re-entering the compiler mid-run.  A
    nonzero ``compile/retraces_total`` in telemetry.prom is the
    production symptom the static rule exists to prevent; disagreement
    between the two is a bug report against either side.
    """

    def __init__(self):
        self._baseline = None

    def arm(self) -> None:
        """Freeze the warm-up compile count; later compiles are
        retraces.  Also materializes the counter so telemetry shows an
        explicit 0 from the first armed tick."""
        self._baseline = counter("compile/compiles_total").value
        counter("compile/retraces_total")

    def poll(self) -> float:
        """Fold new post-warm-up compiles into the counter; returns the
        running total.  Cheap — two registry lookups; call per tick."""
        if self._baseline is None:
            return 0.0
        seen = counter("compile/compiles_total").value - self._baseline
        c = counter("compile/retraces_total")
        if seen > c.value:
            c.inc(seen - c.value)
        return c.value
