"""Trace spans — per-phase wall-time accounting + Chrome-trace events.

``span("phase")`` is a nestable context manager.  Each thread keeps its
own span stack (thread-local), so the prefetch producer and the main
loop can both trace without cross-talk.  On exit a span contributes:

* **self time** — its duration minus the time spent in child spans on
  the same thread.  Self times of all phases partition covered wall
  time with no double counting, so ``sum(timing/phase/*) ≈
  sec_per_tick`` holds even with nesting (the acceptance property the
  loop-integration test asserts).
* **total time** — inclusive duration (what a human means by "time in
  the metric phase").
* a Chrome-trace complete event (``"ph": "X"``, microsecond ts/dur)
  buffered and appended to the tracer's ``events.jsonl`` sink.  Each
  line is one event object, so the file converts to a Chrome trace by
  wrapping the lines in ``{"traceEvents": [...]}`` —
  ``python -m gansformer_tpu.cli.telemetry trace <run_dir>`` does it.

The process-global tracer (``get_tracer()``/module-level ``span``) is
what production code uses; tests construct private ``Tracer`` instances
with fake clocks.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

# events buffered in memory before an incremental append to the sink
_FLUSH_EVERY = 512


class SpanHandle:
    """What ``span(...)`` yields: ``duration_s`` is filled at span exit."""

    __slots__ = ("name", "duration_s")

    def __init__(self, name: str):
        self.name = name
        self.duration_s = 0.0


class Tracer:
    """Accumulates per-phase wall time and emits Chrome-trace events.

    ``time_fn`` is the monotonic span clock (tests pass a fake);
    durations and the trace timeline both derive from it, so a
    monkeypatched clock produces a fully consistent trace.
    """

    def __init__(self, time_fn: Callable[[], float] = time.perf_counter):
        self._time = time_fn
        self._lock = threading.Lock()
        self._local = threading.local()
        self._self_s: Dict[str, float] = {}
        self._total_s: Dict[str, float] = {}
        self._count: Dict[str, int] = {}
        self._events: List[dict] = []
        self._sink_path: Optional[str] = None
        self._pid = 0
        self._origin = time_fn()

    # -- configuration -----------------------------------------------------

    def configure(self, events_path: Optional[str],
                  process_index: int = 0, truncate: bool = True) -> None:
        """Point the tracer at a run dir's ``events.jsonl`` (truncated:
        one trace per run).  ``events_path=None`` keeps accumulating
        totals but drops trace events (non-zero processes).

        ``truncate=False`` (resume) appends instead, preserving the
        crash-window events the aborted process flushed; the resumed
        process's ``ts`` restarts at 0, which Chrome-trace viewers
        render as overlapping tracks rather than an error."""
        with self._lock:
            self._flush_locked()
            self._sink_path = events_path
            self._pid = process_index
            self._origin = self._time()
            if events_path and (truncate or not os.path.exists(events_path)):
                parent = os.path.dirname(events_path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                open(events_path, "w").close()

    def reset(self) -> None:
        """Discard accumulated totals and buffered events (run start)."""
        with self._lock:
            self._self_s.clear()
            self._total_s.clear()
            self._count.clear()
            self._events.clear()

    # -- spans -------------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextmanager
    def span(self, name: str):
        """Yields a handle whose ``duration_s`` is set on exit, so call
        sites that also want the duration (e.g. for a gauge) read it from
        the span instead of re-timing the same region."""
        stack = self._stack()
        frame = [name, self._time(), 0.0]       # name, start, child time
        stack.append(frame)
        handle = SpanHandle(name)
        try:
            yield handle
        finally:
            end = self._time()
            stack.pop()
            dur = end - frame[1]
            handle.duration_s = dur
            self_s = max(dur - frame[2], 0.0)
            if stack:
                stack[-1][2] += dur
            with self._lock:
                self._self_s[name] = self._self_s.get(name, 0.0) + self_s
                self._total_s[name] = self._total_s.get(name, 0.0) + dur
                self._count[name] = self._count.get(name, 0) + 1
                if self._sink_path is not None:
                    self._events.append({
                        "name": name, "ph": "X",
                        "ts": round((frame[1] - self._origin) * 1e6, 3),
                        "dur": round(dur * 1e6, 3),
                        "pid": self._pid, "tid": threading.get_ident(),
                    })
                    if len(self._events) >= _FLUSH_EVERY:
                        self._flush_locked()

    # -- pre-formed events (request tracing) --------------------------------

    @property
    def process_index(self) -> int:
        return self._pid

    def ts_us(self, t: Optional[float] = None) -> float:
        """A ``time_fn`` timestamp (default: now) as microseconds on this
        tracer's trace timeline (clamped at 0 — an event recorded before
        ``configure()`` reset the origin lands at the timeline start
        rather than producing an illegal negative ts)."""
        t = self._time() if t is None else t
        return round(max(t - self._origin, 0.0) * 1e6, 3)

    def emit(self, event: dict) -> None:
        """Append ONE pre-formed Chrome-trace event (async request
        events, batch linkage spans) to the same buffered sink the span
        events ride — merged ordering, one ``events.jsonl``.  The caller
        owns the event shape; ``ts`` should come from ``ts_us`` so both
        families share a timeline.  Dropped (cheaply) while no sink is
        configured, matching the span-event policy for non-zero
        processes."""
        with self._lock:
            if self._sink_path is None:
                return
            self._events.append(event)
            if len(self._events) >= _FLUSH_EVERY:
                self._flush_locked()

    # -- draining / flushing -----------------------------------------------

    def drain(self) -> Dict[str, Dict[str, float]]:
        """{phase: {self_s, total_s, count}} accumulated since the last
        drain; resets the accumulators and flushes buffered events."""
        with self._lock:
            out = {n: {"self_s": self._self_s[n],
                       "total_s": self._total_s.get(n, 0.0),
                       "count": float(self._count.get(n, 0))}
                   for n in self._self_s}
            self._self_s, self._total_s, self._count = {}, {}, {}
            self._flush_locked()
        return out

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._events:
            return
        if self._sink_path is not None:
            with open(self._sink_path, "a") as f:
                for ev in self._events:
                    f.write(json.dumps(ev) + "\n")
        self._events.clear()


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def configure_tracer(events_path: Optional[str],
                     process_index: int = 0, truncate: bool = True) -> Tracer:
    _TRACER.configure(events_path, process_index, truncate=truncate)
    return _TRACER


def span(name: str):
    """``with span("data_wait"): ...`` on the process-global tracer."""
    return _TRACER.span(name)
