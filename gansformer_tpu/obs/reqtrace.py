"""Per-request distributed tracing — "what happened to request X?".

The serving floor (serve/service.py) answers aggregate questions
(p50/p99, shed rate) through the registry; this module answers the
per-request one.  Every ``Ticket`` gets a request ID at submit and a
lifecycle event stream::

    submitted → admitted → popped → batched → wcache_hit|map_dispatch
              → synth → fetch → fulfilled
                               └ terminal: shed / expired / cancelled /
                                 failed (with a cause)

Design constraints, in order:

* **No host sync, bounded overhead.**  Every emit point is a dict
  append under one lock — never a device fetch, file write, or
  allocation proportional to traffic.  The serve dispatch loop calls
  these per ticket per batch, so the hot-loop-sync lint
  (analysis/rules/hot_loop.py) scans the emitter bodies too.
* **No open-ended growth.**  Active traces are capped
  (``max_active``; overflow evicts oldest-first into
  ``reqtrace/dropped_total``), the ledger is capped
  (``max_ledger_rows``; overflow counted in
  ``reqtrace/ledger_dropped_total``), and the in-memory recent ring is
  a fixed deque.  Silent truncation is forbidden — every bound has a
  counter.
* **Two export forms.**  A bounded ``requests.jsonl`` ledger (one JSON
  row per terminal request: outcome, cause, e2e, the full event list)
  and Chrome-trace async events (``ph`` b/n/e keyed by the request ID)
  merged into the span tracer's ``events.jsonl`` — so one
  chrome://tracing load shows batches AND the requests they carried.
  Batch→request causal linkage is explicit both ways: each dispatch
  batch emits a ``serve_batch`` complete event listing its request IDs,
  and each request's ``batched`` event carries the batch number.

Jax-free (the CLI renders timelines from artifacts on machines with no
accelerator stack).  The process-global tracer (``get_reqtracer()``)
is what the service uses; tests construct private ``ReqTracer``
instances with fake clocks.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional

from gansformer_tpu.obs import registry as telemetry
from gansformer_tpu.obs.spans import get_tracer

# terminal event kinds — every submitted request must reach exactly one
TERMINAL_KINDS = ("fulfilled", "shed", "expired", "cancelled", "failed")
# the full lifecycle vocabulary (docs/observability.md catalog)
EVENT_KINDS = ("submitted", "admitted", "popped", "batched", "wcache_hit",
               "map_dispatch", "synth", "fetch") + TERMINAL_KINDS

# ledger rows buffered in memory before an incremental append
_LEDGER_FLUSH_EVERY = 64


class ReqTracer:
    """Request-ID allocator + per-request event recorder.

    ``begin()`` opens a trace (emitting ``submitted``), ``event()``
    appends lifecycle events, a terminal kind finalizes: the trace
    leaves the active table, lands in the recent ring, and — when a
    ledger is configured — is buffered for append to
    ``requests.jsonl``.  All methods are cheap no-ops while
    ``enabled`` is False (the measured-overhead A/B switch)."""

    def __init__(self, time_fn: Callable[[], float] = time.perf_counter,
                 wall_fn: Callable[[], float] = time.time):
        self._time = time_fn
        self._wall = wall_fn
        self._lock = threading.Lock()
        self._seq = 0
        self._pid = os.getpid()
        self._active: "OrderedDict[str, dict]" = OrderedDict()
        self._recent: "deque[dict]" = deque(maxlen=4096)
        self._buffer: List[dict] = []
        self._ledger_path: Optional[str] = None
        self._ledger_rows = 0
        self._max_ledger_rows = 20000
        self._max_active = 65536
        self._chrome = True
        self.enabled = True

    # -- configuration -------------------------------------------------------

    def configure(self, ledger_path: Optional[str] = None,
                  max_ledger_rows: int = 20000, truncate: bool = True,
                  enabled: bool = True, max_active: int = 65536,
                  chrome_events: bool = True) -> "ReqTracer":
        """Point the tracer at a run dir's ``requests.jsonl`` (or None:
        in-memory only — the recent ring still serves the chaos drill's
        terminal-coverage assertion).  Materializes the ``reqtrace/*``
        counter family so absence in telemetry.prom always means the
        wiring rotted, never "no traffic yet"."""
        with self._lock:
            self._flush_locked()
            self._ledger_path = ledger_path
            self._max_ledger_rows = int(max_ledger_rows)
            self._max_active = int(max_active)
            self._chrome = bool(chrome_events)
            self.enabled = bool(enabled)
            self._ledger_rows = 0
            if ledger_path and (truncate
                                or not os.path.exists(ledger_path)):
                parent = os.path.dirname(ledger_path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                open(ledger_path, "w").close()
        for name in ("reqtrace/requests_total", "reqtrace/events_total",
                     "reqtrace/terminal_total", "reqtrace/dropped_total",
                     "reqtrace/ledger_rows_total",
                     "reqtrace/ledger_dropped_total"):
            telemetry.counter(name)
        # the explicit on/off marker: "zero trace counters" must never
        # be ambiguous between "tracing disabled" and "wiring rotted"
        telemetry.gauge("reqtrace/enabled").set(1.0 if enabled else 0.0)
        return self

    def reset(self) -> None:
        """Drop active traces, the recent ring, and buffered rows (run
        start; the ID sequence keeps counting so IDs stay unique per
        process)."""
        with self._lock:
            self._active.clear()
            self._recent.clear()
            self._buffer.clear()
            self._ledger_rows = 0

    # -- recording -----------------------------------------------------------

    def begin(self, seed=None, psi=None) -> Optional[str]:
        """Open a trace; returns the new request ID (None while
        disabled).  Emits the ``submitted`` event at t=0."""
        if not self.enabled:
            return None
        t0 = self._time()
        with self._lock:
            self._seq += 1
            rid = f"r{self._pid}-{self._seq}"
            evicted = None
            if len(self._active) >= self._max_active:
                # oldest-first eviction: a leak upstream (tickets that
                # never resolve) must not grow this table unboundedly
                _, evicted = self._active.popitem(last=False)
            self._active[rid] = {
                "rid": rid, "t0": t0, "t_wall": self._wall(),
                "seed": seed, "psi": psi, "batch": None,
                "events": [["submitted", 0.0, None]],
            }
        telemetry.counter("reqtrace/requests_total").inc()
        telemetry.counter("reqtrace/events_total").inc()
        if evicted is not None:
            telemetry.counter("reqtrace/dropped_total").inc()
        return rid

    def event(self, rid: Optional[str], kind: str, **attrs) -> None:
        """Append one lifecycle event; a terminal kind finalizes the
        trace.  Unknown/None rids are ignored (a late event against an
        evicted trace must not crash the dispatcher)."""
        if not self.enabled or rid is None:
            return
        t = self._time()
        row = None
        with self._lock:
            rec = self._active.get(rid)
            if rec is None:
                return
            dt_ms = round((t - rec["t0"]) * 1000.0, 3)
            rec["events"].append([kind, dt_ms, attrs or None])
            if "batch" in attrs:
                rec["batch"] = attrs["batch"]
            if kind in TERMINAL_KINDS:
                del self._active[rid]
                row = self._finalize_locked(rec, kind, dt_ms, attrs)
        telemetry.counter("reqtrace/events_total").inc()
        if row is not None:
            telemetry.counter("reqtrace/terminal_total").inc()
            if row.get("_ledgered"):
                telemetry.counter("reqtrace/ledger_rows_total").inc()
            else:
                telemetry.counter("reqtrace/ledger_dropped_total").inc()
            if self._chrome:
                self._emit_chrome(row)

    def _finalize_locked(self, rec: dict, outcome: str, dt_ms: float,
                         attrs: dict) -> dict:
        row = {
            "rid": rec["rid"], "t_wall": rec["t_wall"],
            "seed": rec["seed"], "psi": rec["psi"],
            "batch": rec["batch"], "outcome": outcome,
            "cause": attrs.get("cause"), "e2e_ms": dt_ms,
            "events": [
                ({"kind": k, "t_ms": t} | (a or {}))
                for k, t, a in rec["events"]],
            "_t0": rec["t0"],
        }
        self._recent.append(row)
        ledgered = (self._ledger_path is not None
                    and self._ledger_rows < self._max_ledger_rows)
        if ledgered:
            self._ledger_rows += 1
            self._buffer.append(row)
            if len(self._buffer) >= _LEDGER_FLUSH_EVERY:
                self._flush_locked()
        row["_ledgered"] = ledgered
        return row

    def _emit_chrome(self, row: dict) -> None:
        """The finalized trace as Chrome async events on the span
        tracer's shared timeline (b/e pair enclosing per-event
        instants, keyed by the request ID)."""
        tracer = get_tracer()
        tid = threading.get_ident()
        t0 = row["_t0"]

        def ev(ph: str, dt_ms: float, args: Optional[dict]) -> dict:
            e = {"name": "request", "cat": "req", "ph": ph,
                 "id": row["rid"], "ts": tracer.ts_us(t0 + dt_ms / 1e3),
                 "pid": tracer.process_index, "tid": tid}
            if args:
                e["args"] = args
            return e

        tracer.emit(ev("b", 0.0, {"rid": row["rid"],
                                  "seed": row["seed"]}))
        for e in row["events"][1:-1]:
            tracer.emit(ev("n", e["t_ms"],
                           {k: v for k, v in e.items() if k != "t_ms"}))
        tracer.emit(ev("e", row["e2e_ms"],
                       {"outcome": row["outcome"],
                        "cause": row["cause"]}))

    def batch_span(self, batch: int, bucket: int, rids: List[str],
                   t0: float, dur_s: float) -> None:
        """The batch→requests causal link: one ``serve_batch`` complete
        event whose args list every request ID the dispatch carried."""
        if not self.enabled or not self._chrome:
            return
        tracer = get_tracer()
        tracer.emit({"name": "serve_batch", "ph": "X",
                     "ts": tracer.ts_us(t0),
                     "dur": round(max(dur_s, 0.0) * 1e6, 3),
                     "pid": tracer.process_index,
                     "tid": threading.get_ident(),
                     "args": {"batch": batch, "bucket": bucket,
                              "rids": [r for r in rids if r]}})

    # -- reading / flushing --------------------------------------------------

    def recent(self) -> List[dict]:
        """Finalized traces still in the in-memory ring (newest last),
        without the private bookkeeping keys — what the chaos drill's
        terminal-coverage assertion reads when no ledger is wired."""
        with self._lock:
            rows = list(self._recent)
        return [{k: v for k, v in r.items() if not k.startswith("_")}
                for r in rows]

    def active_rids(self) -> List[str]:
        with self._lock:
            return list(self._active)

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buffer:
            return
        if self._ledger_path is not None:
            with open(self._ledger_path, "a") as f:
                for row in self._buffer:
                    f.write(json.dumps(
                        {k: v for k, v in row.items()
                         if not k.startswith("_")}) + "\n")
        self._buffer.clear()


_REQTRACER = ReqTracer()


def get_reqtracer() -> ReqTracer:
    return _REQTRACER


def configure_reqtrace(ledger_path: Optional[str] = None,
                       **kw) -> ReqTracer:
    return _REQTRACER.configure(ledger_path, **kw)


def read_requests(path: str) -> List[dict]:
    """``requests.jsonl`` rows, torn-line-tolerant (the crashed runs
    are the ones worth inspecting — same policy as the trace CLI)."""
    out: List[dict] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict):
                out.append(row)
    return out


def render_timeline(row: dict) -> str:
    """One request's event stream as an aligned text timeline (the
    ``gansformer-telemetry requests --id`` view)."""
    head = (f"request {row.get('rid')}  seed={row.get('seed')} "
            f"psi={row.get('psi')}  outcome={row.get('outcome')}"
            + (f" cause={row['cause']}" if row.get("cause") else "")
            + (f"  batch={row['batch']}"
               if row.get("batch") is not None else "")
            + f"  e2e={row.get('e2e_ms')} ms")
    lines = [head]
    for ev in row.get("events", []):
        extras = ", ".join(f"{k}={v}" for k, v in sorted(ev.items())
                           if k not in ("kind", "t_ms") and v is not None)
        lines.append("  +{:>10.3f} ms  {:<12s}{}".format(
            float(ev.get("t_ms", 0.0)), str(ev.get("kind")),
            f"  ({extras})" if extras else ""))
    return "\n".join(lines)
