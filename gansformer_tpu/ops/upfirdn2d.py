"""upfirdn2d — pad → upsample → FIR filter → downsample, in one XLA conv.

TPU-native re-design of the reference's custom CUDA kernel
``src/dnnlib/tflib/ops/upfirdn_2d.cu`` + its Python wrapper
``src/dnnlib/tflib/ops/upfirdn_2d.py`` (SURVEY.md §2.1).  The reference
compiles a hand-written CUDA kernel at import time (via nvcc in
``custom_ops.py``) and registers a custom TF gradient (another upfirdn call
with a flipped filter).

Here the whole operation is ONE ``lax.conv_general_dilated`` call:

  * zero-insertion upsampling  -> ``lhs_dilation=(up, up)``
  * zero padding / cropping    -> the conv ``padding`` pairs (negative = crop)
  * FIR convolution            -> a depthwise kernel (``feature_group_count=C``)
                                  with the filter flipped, because XLA convs
                                  are correlations and upfirdn is a true
                                  convolution
  * downsampling               -> ``window_strides=(down, down)``

XLA lowers this straight onto the TPU convolution path, and — unlike the
reference — the gradient (and the second-order gradient R1 needs) falls out
of autodiff for free; no ``custom_vjp`` is required.

Layout note: the whole framework is NHWC (TPU-preferred), vs the reference's
NCHW.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Pad = Union[int, Tuple[int, int], Tuple[int, int, int, int]]


def setup_filter(f: Sequence[float], normalize: bool = True,
                 gain: float = 1.0) -> np.ndarray:
    """Build the 2D FIR filter from a 1D (separable) or 2D tap list.

    Mirrors the reference's ``_setup_kernel`` semantics: a 1D filter becomes
    its outer product; the filter is normalized to unit sum, then scaled by
    ``gain``.
    """
    f = np.asarray(f, dtype=np.float32)
    if f.ndim == 1:
        f = np.outer(f, f)
    assert f.ndim == 2
    if normalize:
        f = f / f.sum()
    return f * gain


def _pad4(pad: Pad) -> Tuple[int, int, int, int]:
    if isinstance(pad, int):
        return (pad, pad, pad, pad)
    if len(pad) == 2:
        return (pad[0], pad[1], pad[0], pad[1])
    assert len(pad) == 4
    return tuple(pad)  # (pady0, pady1, padx0, padx1)


def upfirdn2d(x: jax.Array, f, up: int = 1, down: int = 1,
              pad: Pad = 0, backend: str = "xla") -> jax.Array:
    """Upsample, pad, FIR-filter and downsample a batch of NHWC images.

    Semantics (matching the reference wrapper's docstring):
      1. zero-insertion upsample by ``up`` in both spatial dims,
      2. zero-pad by ``pad`` = (pady0, pady1, padx0, padx1) (negative crops),
      3. convolve with the 2D FIR filter ``f`` (true convolution),
      4. keep every ``down``-th sample.

    ``backend='pallas'`` routes through the fused pad→FIR→resample
    kernel (``ops/pallas_upfirdn.py``, ISSUE 14): whole-image or
    row-blocked per ``upfirdn_plan``; a grid where even a single row
    strip overflows VMEM falls back to the XLA lowering below and
    counts ``ops/modconv_fallback_total`` (the conv family's fallback
    counter — the blur legs are part of the family's coverage).
    """
    assert x.ndim == 4, "expected NHWC"
    if backend == "pallas":
        from gansformer_tpu.ops.pallas_upfirdn import (note_conv_fallback,
                                                       upfirdn_fits,
                                                       upfirdn2d_pallas)

        f_np = np.asarray(f, np.float32)
        if f_np.ndim == 1:
            f_np = np.outer(f_np, f_np)
        if upfirdn_fits(x.shape, f_np.shape, up, down, _pad4(pad)):
            return upfirdn2d_pallas(x, f_np, up=up, down=down, pad=pad)
        note_conv_fallback("vmem")
    f = jnp.asarray(f, dtype=x.dtype)
    assert f.ndim == 2
    pady0, pady1, padx0, padx1 = _pad4(pad)
    n, h, w, c = x.shape
    # Depthwise kernel, flipped so the XLA correlation computes a convolution.
    kernel = jnp.tile(f[::-1, ::-1, None, None], (1, 1, 1, c))  # HWIO, I=1
    # upfirdn's zero-insertion upsample yields H*up samples (zeros AFTER the
    # last sample too); lhs_dilation yields (H-1)*up+1.  Fold the missing
    # up-1 trailing zeros into the trailing padding so sizes/values match the
    # reference semantics exactly.
    return lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(down, down),
        padding=((pady0, pady1 + up - 1), (padx0, padx1 + up - 1)),
        lhs_dilation=(up, up),
        rhs_dilation=(1, 1),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
        # The FIR filter is 4 taps and depthwise — bandwidth-bound, not
        # MXU-bound — so full precision costs nothing and keeps the blur
        # numerics exact even under TPU bf16 defaults (wrong blur padding or
        # precision silently degrades FID; SURVEY.md §7.3 item 5).
        precision=lax.Precision.HIGHEST,
    )


def upsample_2d(x: jax.Array, f, factor: int = 2, gain: float = 1.0,
                backend: str = "xla") -> jax.Array:
    """Upsample with FIR anti-imaging filter (reference: ``upsample_2d``)."""
    f = setup_filter(f, gain=gain * (factor**2))
    p = f.shape[0] - factor
    return upfirdn2d(x, f, up=factor,
                     pad=((p + 1) // 2 + factor - 1, p // 2),
                     backend=backend)


def downsample_2d(x: jax.Array, f, factor: int = 2, gain: float = 1.0,
                  backend: str = "xla") -> jax.Array:
    """Blur-pool downsample (reference: ``downsample_2d``)."""
    f = setup_filter(f, gain=gain)
    p = f.shape[0] - factor
    return upfirdn2d(x, f, down=factor, pad=((p + 1) // 2, p // 2),
                     backend=backend)


def filter_2d(x: jax.Array, f, gain: float = 1.0,
              extra_pad: Tuple[int, int] = (0, 0),
              backend: str = "xla") -> jax.Array:
    """Same-resolution blur (reference: ``filter_2d``); ``extra_pad`` lets
    callers fold a following VALID conv's padding into the blur, the trick the
    reference's ``conv_downsample_2d`` / ``upsample_conv_2d`` use."""
    f = setup_filter(f, gain=gain)
    p = f.shape[0] - 1
    return upfirdn2d(x, f,
                     pad=((p + 1) // 2 + extra_pad[0], p // 2 + extra_pad[1]),
                     backend=backend)
