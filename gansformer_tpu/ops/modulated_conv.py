"""Style-modulated convolution + resampling convs.

TPU-native re-design of StyleGAN2's ``modulated_conv2d_layer`` and the
``upsample_conv_2d`` / ``conv_downsample_2d`` helpers inside the reference's
``src/training/network.py`` / ``src/dnnlib/tflib/ops/upfirdn_2d.py``
(SURVEY.md §2.1).

The reference folds the per-sample modulated weights into a single grouped
convolution ("fused" path: batch folded into channels) — a trick that exists
to keep cuDNN happy.  On TPU the better mapping is the *input-scaling*
identity the reference's non-fused path also uses:

    conv(x, w * s)  ==  conv(x * s, w)        (s broadcast over in-channels)

so every sample shares ONE large conv — exactly what the MXU wants (one big
batched contraction, no per-sample weight gather) — followed by a per-sample,
per-output-channel demodulation scale computed with a tiny einsum.  All steps
are XLA-fusable and arbitrarily differentiable (R1/path-length need 2nd-order
grads through this op; SURVEY.md §7.3).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from gansformer_tpu.ops.upfirdn2d import filter_2d, upsample_2d, setup_filter, upfirdn2d


@jax.tree_util.register_pytree_node_class
class QuantizedWeight:
    """int8 weight-only quantized kernel leaf (serve_precision='int8w').

    ``q`` keeps the ORIGINAL kernel shape in int8; ``scale`` is the
    per-output-channel fp32 scale over the LAST axis (keepdims, so it
    broadcasts for both the [fan_in, Cout] dense and [kh, kw, Cin, Cout]
    conv layouts).  Registered as a pytree node so a quantized params
    tree flows through flax ``apply`` / jit / device_put unchanged; the
    equalized-LR layers call ``resolve_weight`` on every fetched kernel,
    which is where dequantization fuses into the weight-prep that feeds
    both the XLA composites and the Pallas kernels.  ``q`` flattens
    first: flax validates only the leading leaf's shape against the
    initializer, and ``q`` keeps the original shape.
    """

    def __init__(self, q: jax.Array, scale: jax.Array):
        self.q = q
        self.scale = scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):
        return self.q.dtype

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)

    def __repr__(self):
        return (f"QuantizedWeight(q={self.q.shape}:{self.q.dtype}, "
                f"scale={self.scale.shape})")


def _dequant_int8w(q: jax.Array, scale: jax.Array) -> jax.Array:
    """int8w dequantization — the fp32 island the ``int8w-dequant``
    numeric contract anchors on (this function's frame).  The scale
    application must run fp32: int8 codes span ±127 and a bf16 product
    would re-quantize the mantissa a second time."""
    return q.astype(jnp.float32) * scale.astype(jnp.float32)


def resolve_weight(w) -> jax.Array:
    """The kernel-prep seam shared by every equalized-LR layer: plain
    fp32 kernels pass through; ``QuantizedWeight`` leaves dequantize
    here, AHEAD of the lrmul/gain scaling and the dtype cast — so the
    XLA composites and the Pallas modconv kernels both consume the same
    dequantized weights with no per-backend code."""
    if isinstance(w, QuantizedWeight):
        return _dequant_int8w(w.q, w.scale)
    return w


def _conv(x: jax.Array, w: jax.Array, stride: int = 1,
          padding: str = "SAME") -> jax.Array:
    # fp32 inputs get true-fp32 accumulation (XLA's DEFAULT precision may
    # drop fp32 convs to bf16 passes); bf16 inputs ride the MXU natively.
    precision = (lax.Precision.HIGHEST if x.dtype == jnp.float32
                 else lax.Precision.DEFAULT)
    return lax.conv_general_dilated(
        x, w.astype(x.dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        precision=precision,
    )


def _conv_transpose_poly(x: jax.Array, w: jax.Array) -> jax.Array:
    """Stride-2 transposed conv via explicit polyphase decomposition.

    Mathematically: correlate the zero-inserted 2×-upsample of ``x`` with the
    odd-sized kernel ``w`` at SAME padding — the reference's
    ``upsample_conv_2d`` transposed-conv core.  TPU-first formulation: the
    naive route materializes the 2× grid and runs a dense k×k conv at the
    doubled resolution (4× the MACs, 75% of them against structural zeros);
    here each of the 4 output phases reads only the input taps that are
    actually nonzero, giving ONE dense ⌈k/2⌉² conv at the LOW resolution with
    4·Cout outputs, interleaved by a reshape (depth-to-space).  For k=3 that
    is 16 vs 36 taps — 2.25× fewer MXU MACs — with no dilated convs for the
    backend to handle (static shapes, dense contractions; the reshape is
    layout-only and XLA-fusable).  Arbitrarily differentiable, so R1/PL
    second-order grads flow through unchanged.
    """
    kh, kw = w.shape[0], w.shape[1]
    # The tap mapping below (rh = 2·dh + 1 − a, right-only padding) encodes
    # the k=3 center offset; other odd kernels need a generalized offset AND
    # two-sided padding — gate hard rather than produce silently wrong math.
    assert kh == kw == 3, "polyphase path is derived for 3x3 kernels"
    n, h, wd, ci = x.shape
    co = w.shape[3]
    ks = (kh + 1) // 2                       # sub-kernel side (2 for k=3)
    # Phase sub-kernels: output pixel (2m+a, 2n+b) of the transposed conv
    # reads x[m+dh, n+dw] with weight w[2dh+1-a, 2dw+1-b]; taps falling
    # outside w are structural zeros, realized by indexing into a
    # one-zero-row/col padded copy (one gather — keeps the per-step graph
    # free of scatter ops).  Result [ks, ks, Ci, A, B, Co], phases
    # flattened into the output-channel axis.
    w_pad = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))
    dh = jnp.arange(ks)
    a = jnp.arange(2)
    rh = jnp.where(2 * dh[:, None] + 1 - a[None, :] < kh,
                   2 * dh[:, None] + 1 - a[None, :], kh)    # [ks, A] -> pad row
    w4 = w_pad[rh[:, None, :, None],                        # dh, a
               rh[None, :, None, :]]         # [ks, ks, A, B, Ci, Co]
    w4 = w4.transpose(0, 1, 4, 2, 3, 5)      # [ks, ks, Ci, A, B, Co]
    w4 = w4.reshape(ks, ks, ci, 4 * co)
    precision = (lax.Precision.HIGHEST if x.dtype == jnp.float32
                 else lax.Precision.DEFAULT)
    y = lax.conv_general_dilated(
        x, w4.astype(x.dtype),
        window_strides=(1, 1),
        padding=((0, ks - 1), (0, ks - 1)),   # x[m .. m+ks-1] windows
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        precision=precision,
    )                                         # [N, H, W, 4*Co]
    y = y.reshape(n, h, wd, 2, 2, co)         # [..., a, b, Co]
    y = y.transpose(0, 1, 3, 2, 4, 5)         # [N, H, a, W, b, Co]
    return y.reshape(n, 2 * h, 2 * wd, co)


def conv2d(x: jax.Array, w: jax.Array, up: int = 1, down: int = 1,
           resample_filter: Sequence[float] = (1, 3, 3, 1),
           backend: str = "xla") -> jax.Array:
    """Plain conv with optional FIR-filtered up/down-sampling.

    Capability match for the reference's ``conv2d_layer`` with
    ``up=True``/``down=True`` (blur is fused into the resampling, reference
    ``upsample_conv_2d``/``conv_downsample_2d``).  NHWC, HWIO.

    The ``up=2`` path is the reference's transposed-conv-then-blur pipeline
    (``upsample_conv_2d``), implemented polyphase (``_conv_transpose_poly``)
    so the MXU never multiplies against the zero-inserted grid.  Interior
    pixels equal the blur-first formulation exactly (the two convolutions
    commute); the ≤2-px border differs in where zero-padding truncates the
    commuted support — the reference's own border semantics, not a deviation.
    """
    assert x.ndim == 4 and w.ndim == 4
    kh, kw = w.shape[0], w.shape[1]
    # backend='pallas' (ISSUE 14): the FIR legs of every resampling chain
    # ride the fused pad→FIR→resample kernel; the dense k×k convs stay on
    # XLA here (they are plain MXU contractions — the kernel win on this
    # path is the bandwidth-bound blur/decimate legs).  The modulated
    # path's fully-fused kernels live in ops/pallas_modconv.py.
    if up == 2 and down == 1 and kh == kw == 3:
        y = _conv_transpose_poly(x, w)
        # Anti-imaging blur AFTER the transposed conv (reference order),
        # gain=up² preserving mean signal energy as in ``upsample_2d``;
        # filter_2d's centered padding lands on the same phase as the
        # blur-first pipeline — interior equality is pinned by
        # tests/test_ops.py::test_conv2d_up_polyphase_matches_blur_first.
        return filter_2d(y, resample_filter, gain=float(up * up),
                         backend=backend)
    if up > 1:
        # General fallback: zero-insert upsample + anti-imaging blur, then
        # the conv at the higher resolution.
        x = upsample_2d(x, resample_filter, factor=up, backend=backend)
    if down > 1:
        f = setup_filter(resample_filter)
        if kh == kw == 1:
            # Skip/shortcut path (D residual blocks): a 1×1 stride-``down``
            # conv reads only every ``down``-th blurred pixel, so blurring
            # the full grid wastes down² − 1 of every down² blur outputs —
            # the decimation mirror of the up-conv's structural-zero waste.
            # Decimate INSIDE the blur (upfirdn's fused stride): only kept
            # pixels are computed, cutting the depthwise work AND the
            # intermediate's HBM round-trip 4× on the largest grids.
            # Identical taps/positions to blur-then-stride — the 1×1 conv
            # commutes with decimation exactly.
            p = f.shape[0] - down
            x = upfirdn2d(x, f, down=down, pad=((p + 1) // 2, p // 2),
                          backend=backend)
            return _conv(x, w, stride=1, padding="VALID")
        # k>1: every blurred pixel is read by some stride-``down`` window,
        # so there is nothing to decimate; fold the VALID conv's padding
        # into the blur, then stride the conv.  (Folding the blur into the
        # conv kernel instead — one 6×6 dense conv — costs 4× the dense
        # MACs; rejected, PERF.md §1b''''.)
        p = (f.shape[0] - down) + (kh - 1)
        x = upfirdn2d(x, f, pad=((p + 1) // 2, p // 2), backend=backend)
        return _conv(x, w, stride=down, padding="VALID")
    return _conv(x, w, stride=1, padding="SAME")


def _demod_coeffs(w32: jax.Array, s32: jax.Array, eps: float) -> jax.Array:
    """Per-sample demod coefficients 1/||w·s||₂ — the fp32 island the
    ``demodulation`` numeric contract anchors on (this function's frame,
    forward AND the backward eqns that inherit it).  Both inputs must
    already be fp32; keeping the island in its own frame keeps the
    audit away from the surrounding compute-dtype application math."""
    sigma = jnp.einsum("hwio,ni->no", jnp.square(w32), jnp.square(s32),
                       precision=lax.Precision.HIGHEST)
    return lax.rsqrt(sigma + eps)                       # [N, Cout]


def modulated_conv2d(
    x: jax.Array,                 # [N, H, W, Cin]
    w: jax.Array,                 # [kh, kw, Cin, Cout]
    styles: jax.Array,            # [N, Cin]
    demodulate: bool = True,
    up: int = 1,
    down: int = 1,
    resample_filter: Sequence[float] = (1, 3, 3, 1),
    eps: float = 1e-8,
) -> jax.Array:
    """Modulate → conv → demodulate (StyleGAN2's core layer, SURVEY.md §2.1).

    ``styles`` are per-sample input-channel scales (already passed through the
    affine ``A`` layer by the caller).  Demodulation normalizes each output
    channel by the L2 norm of its modulated weights, computed per sample
    without materializing per-sample weights.
    """
    assert x.ndim == 4 and w.ndim == 4 and styles.ndim == 2
    n, _, _, cin = x.shape
    assert w.shape[2] == cin and styles.shape == (n, cin)

    # Demod coefficients in fp32 regardless of compute dtype (rsqrt of a sum
    # of squares is precision-sensitive; the reference keeps modulation math
    # in fp32 too).
    w32 = w.astype(jnp.float32)
    s32 = styles.astype(jnp.float32)

    x = x * styles.astype(x.dtype)[:, None, None, :]
    y = conv2d(x, w, up=up, down=down, resample_filter=resample_filter)

    if demodulate:
        d = _demod_coeffs(w32, s32, eps)                # [N, Cout]
        y = y * d.astype(y.dtype)[:, None, None, :]
    return y
