"""Style-modulated convolution + resampling convs.

TPU-native re-design of StyleGAN2's ``modulated_conv2d_layer`` and the
``upsample_conv_2d`` / ``conv_downsample_2d`` helpers inside the reference's
``src/training/network.py`` / ``src/dnnlib/tflib/ops/upfirdn_2d.py``
(SURVEY.md §2.1).

The reference folds the per-sample modulated weights into a single grouped
convolution ("fused" path: batch folded into channels) — a trick that exists
to keep cuDNN happy.  On TPU the better mapping is the *input-scaling*
identity the reference's non-fused path also uses:

    conv(x, w * s)  ==  conv(x * s, w)        (s broadcast over in-channels)

so every sample shares ONE large conv — exactly what the MXU wants (one big
batched contraction, no per-sample weight gather) — followed by a per-sample,
per-output-channel demodulation scale computed with a tiny einsum.  All steps
are XLA-fusable and arbitrarily differentiable (R1/path-length need 2nd-order
grads through this op; SURVEY.md §7.3).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from gansformer_tpu.ops.upfirdn2d import filter_2d, upsample_2d, setup_filter, upfirdn2d


def _conv(x: jax.Array, w: jax.Array, stride: int = 1,
          padding: str = "SAME") -> jax.Array:
    # fp32 inputs get true-fp32 accumulation (XLA's DEFAULT precision may
    # drop fp32 convs to bf16 passes); bf16 inputs ride the MXU natively.
    precision = (lax.Precision.HIGHEST if x.dtype == jnp.float32
                 else lax.Precision.DEFAULT)
    return lax.conv_general_dilated(
        x, w.astype(x.dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        precision=precision,
    )


def conv2d(x: jax.Array, w: jax.Array, up: int = 1, down: int = 1,
           resample_filter: Sequence[float] = (1, 3, 3, 1)) -> jax.Array:
    """Plain conv with optional FIR-filtered up/down-sampling.

    Capability match for the reference's ``conv2d_layer`` with
    ``up=True``/``down=True`` (blur is fused into the resampling, reference
    ``upsample_conv_2d``/``conv_downsample_2d``).  NHWC, HWIO.
    """
    assert x.ndim == 4 and w.ndim == 4
    kh, kw = w.shape[0], w.shape[1]
    if up > 1:
        # zero-insert upsample + anti-imaging blur, then the conv at the
        # higher resolution.  Equivalent to the reference's transposed-conv
        # formulation (convolutions commute); XLA sees the same dilated conv.
        x = upsample_2d(x, resample_filter, factor=up)
    if down > 1:
        # Fold the VALID conv's padding into the blur, then stride the conv.
        f = setup_filter(resample_filter)
        p = (f.shape[0] - down) + (kh - 1)
        x = upfirdn2d(x, f, pad=((p + 1) // 2, p // 2))
        return _conv(x, w, stride=down, padding="VALID")
    return _conv(x, w, stride=1, padding="SAME")


def modulated_conv2d(
    x: jax.Array,                 # [N, H, W, Cin]
    w: jax.Array,                 # [kh, kw, Cin, Cout]
    styles: jax.Array,            # [N, Cin]
    demodulate: bool = True,
    up: int = 1,
    down: int = 1,
    resample_filter: Sequence[float] = (1, 3, 3, 1),
    eps: float = 1e-8,
) -> jax.Array:
    """Modulate → conv → demodulate (StyleGAN2's core layer, SURVEY.md §2.1).

    ``styles`` are per-sample input-channel scales (already passed through the
    affine ``A`` layer by the caller).  Demodulation normalizes each output
    channel by the L2 norm of its modulated weights, computed per sample
    without materializing per-sample weights.
    """
    assert x.ndim == 4 and w.ndim == 4 and styles.ndim == 2
    n, _, _, cin = x.shape
    assert w.shape[2] == cin and styles.shape == (n, cin)

    # Demod coefficients in fp32 regardless of compute dtype (rsqrt of a sum
    # of squares is precision-sensitive; the reference keeps modulation math
    # in fp32 too).
    w32 = w.astype(jnp.float32)
    s32 = styles.astype(jnp.float32)

    x = x * styles.astype(x.dtype)[:, None, None, :]
    y = conv2d(x, w, up=up, down=down, resample_filter=resample_filter)

    if demodulate:
        sigma = jnp.einsum("hwio,ni->no", jnp.square(w32), jnp.square(s32),
                           precision=lax.Precision.HIGHEST)
        d = lax.rsqrt(sigma + eps)                      # [N, Cout]
        y = y * d.astype(y.dtype)[:, None, None, :]
    return y
