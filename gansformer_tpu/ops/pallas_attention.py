"""Pallas TPU kernels for the bipartite attention (SURVEY.md §2.4 "Ring
attention / blockwise" row: blockwise kernel over the n = H·W grid axis to
bound VMEM at high resolution — no ring needed).

Two directions, two kernels:

``grid_to_latent_attention``  — X←Y (the main phase): every grid position
    attends to the k ≤ 33 latents.  The softmax axis is the tiny k, so each
    n-block is independent: one fused kernel computes logits → softmax →
    value mix without ever materializing the [n, k] probability map in HBM.
    Memory traffic drops from (read q,k,v + write logits + read logits +
    write probs + read probs + write out) to (read q,k,v + write out).

``latent_to_grid_attention``  — Y←X (the duplex centroid phase): the k
    latents attend OVER the n grid positions, so the softmax spans n.  The
    kernel runs blockwise over n with running max / denominator / weighted
    accumulator (the flash-attention recurrence) in VMEM scratch — VMEM use
    is O(block_n · D) regardless of n, which is what makes 1024² (n = 1M at
    the finest attended resolution) feasible without spilling.

Both kernels are forward-path only and are wired into sampling / metric
sweeps (``ModelConfig.attention_backend = 'pallas'``); the training path
stays on the jnp composite (``ops.attention.multihead_attention``) because
R1/path-length need second-order autodiff, which a ``custom_vjp`` around an
opaque kernel would break (SURVEY.md §7.3 item 1).  Tests run the kernels in
interpret mode on CPU against the jnp oracle; on TPU, native Mosaic lowering
is where interpret-mode coverage can diverge (the (L,1) fp32 scratch shapes,
``@pl.when`` accumulation), so first use on a TPU runs ``tpu_smoke_check``
— a tiny native compile-and-compare against the jnp oracle — and the CLIs
fall back to the xla backend with a warning if it fails (ADVICE r3).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # importable on CPU builds


def _vmem():
    return pltpu.VMEM


# --------------------------------------------------------------------------
# X ← Y : grid attends to latents (softmax over the tiny latent axis)
# --------------------------------------------------------------------------

def _grid_to_latent_kernel(q_ref, k_ref, v_ref, o_ref, *, scale):
    # q: [1, bn, D]  k: [1, L, D]  v: [1, L, Dv]  o: [1, bn, Dv]
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0]
    logits = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale        # [bn, L]
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.dot(p.astype(v.dtype), v,
                preferred_element_type=jnp.float32)         # [bn, Dv]
    o_ref[0] = o.astype(o_ref.dtype)


def grid_to_latent_attention(
    q: jax.Array,    # [B, n, D]   (fold heads into B; D = head dim)
    k: jax.Array,    # [B, L, D]
    v: jax.Array,    # [B, L, Dv]
    *,
    block_n: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Fused attention where softmax runs over the latent axis L.

    Equivalent to ``softmax(q @ k.T / sqrt(D)) @ v`` — the main-phase
    direction of ``ops.attention.multihead_attention`` (per head).
    """
    b, n, d = q.shape
    _, l, dv = v.shape
    scale = 1.0 / math.sqrt(d)
    bn = min(block_n, n)
    n_pad = -n % bn
    if n_pad:
        q = jnp.pad(q, ((0, 0), (0, n_pad), (0, 0)))
    grid = (b, (n + n_pad) // bn)
    out = pl.pallas_call(
        functools.partial(_grid_to_latent_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b, n + n_pad, dv), v.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bn, d), lambda i, j: (i, j, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, l, d), lambda i, j: (i, 0, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, l, dv), lambda i, j: (i, 0, 0),
                         memory_space=_vmem()),
        ],
        out_specs=pl.BlockSpec((1, bn, dv), lambda i, j: (i, j, 0),
                               memory_space=_vmem()),
        interpret=interpret,
    )(q, k, v)
    return out[:, :n]


# --------------------------------------------------------------------------
# Y ← X : latents attend over the grid (online softmax over the big n axis)
# --------------------------------------------------------------------------

def _latent_to_grid_kernel(q_ref, k_ref, v_ref, o_ref,
                           m_ref, s_ref, acc_ref, *, scale, n_valid, block_n):
    # q: [1, L, D]  k: [1, bn, D]  v: [1, bn, Dv]  o: [1, L, Dv]
    # scratch: m [L, 1], s [L, 1], acc [L, Dv]  (flash recurrence, fp32)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        s_ref[:] = jnp.zeros_like(s_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    logits = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale          # [L, bn]
    # Mask grid positions past n (zero-padding from the wrapper).
    offs = j * block_n + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, dimension=1)
    logits = jnp.where(offs < n_valid, logits, -jnp.inf)

    m_prev = m_ref[:]                                        # [L, 1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
    # exp(-inf - -inf) guard: masked-out rows can keep m == -inf safely
    # because every block contributes 0 there.
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(logits - m_new)                              # [L, bn]
    s_ref[:] = s_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * corr + jnp.dot(
        p, v, preferred_element_type=jnp.float32)            # [L, Dv]
    m_ref[:] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _emit():
        o_ref[0] = (acc_ref[:] / s_ref[:]).astype(o_ref.dtype)


def latent_to_grid_attention(
    q: jax.Array,    # [B, L, D]
    k: jax.Array,    # [B, n, D]
    v: jax.Array,    # [B, n, Dv]
    *,
    block_n: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Fused attention where softmax runs over the grid axis n, blockwise
    with the flash-attention online recurrence (VMEM bounded by block_n)."""
    b, l, d = q.shape
    _, n, dv = v.shape
    scale = 1.0 / math.sqrt(d)
    bn = min(block_n, n)
    n_pad = -n % bn
    if n_pad:
        k = jnp.pad(k, ((0, 0), (0, n_pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, n_pad), (0, 0)))
    grid = (b, (n + n_pad) // bn)
    kern = functools.partial(_latent_to_grid_kernel, scale=scale,
                             n_valid=n, block_n=bn)
    scratch = [pltpu.VMEM((l, 1), jnp.float32),
               pltpu.VMEM((l, 1), jnp.float32),
               pltpu.VMEM((l, dv), jnp.float32)]
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((b, l, dv), v.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, l, d), lambda i, j: (i, 0, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, bn, d), lambda i, j: (i, j, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, bn, dv), lambda i, j: (i, j, 0),
                         memory_space=_vmem()),
        ],
        out_specs=pl.BlockSpec((1, l, dv), lambda i, j: (i, 0, 0),
                               memory_space=_vmem()),
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)


# --------------------------------------------------------------------------
# Drop-in multihead wrapper matching ops.attention.multihead_attention
# --------------------------------------------------------------------------

def multihead_attention_pallas(
    q: jax.Array,    # [N, Lq, D]
    k: jax.Array,    # [N, Lk, D]
    v: jax.Array,    # [N, Lk, Dv]
    num_heads: int = 1,
    *,
    block_n: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Head-folding wrapper: picks the kernel by which side is the grid.

    Returns out [N, Lq, Dv] only (no probability maps — use the jnp op when
    attention visualizations are being collected).
    """
    n, lq, d = q.shape
    _, lk, dv = v.shape
    assert d % num_heads == 0 and dv % num_heads == 0
    dh, dvh = d // num_heads, dv // num_heads

    def fold(t, dim):
        return (t.reshape(n, t.shape[1], num_heads, dim)
                .transpose(0, 2, 1, 3)
                .reshape(n * num_heads, t.shape[1], dim))

    qf, kf, vf = fold(q, dh), fold(k, dh), fold(v, dvh)
    if lq >= lk:      # grid queries, latent keys → softmax over tiny Lk
        of = grid_to_latent_attention(qf, kf, vf, block_n=block_n,
                                      interpret=interpret)
    else:             # latent queries, grid keys → online softmax over Lk
        of = latent_to_grid_attention(qf, kf, vf, block_n=block_n,
                                      interpret=interpret)
    return (of.reshape(n, num_heads, lq, dvh)
            .transpose(0, 2, 1, 3)
            .reshape(n, lq, dv))


# --------------------------------------------------------------------------
# First-use native-TPU verification gate (ADVICE r3)
# --------------------------------------------------------------------------

_TPU_SMOKE: dict = {}   # memo: {'ok': bool, 'detail': str}


def tpu_smoke_check(atol: float = 1e-2) -> tuple:
    """Compile both kernels NATIVELY on the ambient TPU at tiny shapes and
    compare against the jnp oracle.  Returns ``(ok, detail)``; memoized so
    the cost (two small compiles) is paid once per process.

    Exercises both directions, multi-head folding, and the blockwise path
    with a non-divisible n (padding + masked flash recurrence) — exactly the
    constructs where Mosaic lowering could diverge from interpret mode.
    """
    if "ok" in _TPU_SMOKE:
        return _TPU_SMOKE["ok"], _TPU_SMOKE["detail"]
    import numpy as np

    from gansformer_tpu.ops.attention import multihead_attention

    try:
        rng = np.random.RandomState(0)
        grid = jnp.asarray(rng.randn(2, 60, 32), jnp.float32)  # n=60: pad path
        lat = jnp.asarray(rng.randn(2, 8, 32), jnp.float32)
        latv = jnp.asarray(rng.randn(2, 8, 32), jnp.float32)
        gridv = jnp.asarray(rng.randn(2, 60, 32), jnp.float32)
        # X←Y (softmax over tiny L) and Y←X (blockwise online softmax over n,
        # 4 blocks of 16 + masking).
        ref_xy, _ = multihead_attention(grid, lat, latv, 2)
        got_xy = multihead_attention_pallas(grid, lat, latv, 2,
                                            interpret=False)
        ref_yx, _ = multihead_attention(lat, grid, gridv, 2)
        got_yx = multihead_attention_pallas(lat, grid, gridv, 2, block_n=16,
                                            interpret=False)
        d_xy = float(jnp.max(jnp.abs(got_xy - ref_xy)))
        d_yx = float(jnp.max(jnp.abs(got_yx - ref_yx)))
        ok = d_xy < atol and d_yx < atol
        detail = (f"max_abs_diff grid_to_latent={d_xy:.2e} "
                  f"latent_to_grid={d_yx:.2e} (atol {atol:g})")
    except Exception as e:  # Mosaic compile failures surface as many types
        ok = False
        detail = f"native compile/run failed: {type(e).__name__}: {e}"[:400]
    _TPU_SMOKE.update(ok=ok, detail=detail)
    return ok, detail


def resolve_backend(requested: str) -> str:
    """'pallas' → 'pallas' only if safe on this backend, else 'xla'.

    On CPU/GPU the pallas path runs in interpret mode (oracle-tested in CI);
    on TPU the first resolution runs the native smoke check and falls back
    to xla — with the reason printed — rather than advertising a kernel that
    never compiled on the device class it exists for.
    """
    if requested != "pallas":
        return requested
    if jax.default_backend() != "tpu":
        return "pallas"
    ok, detail = tpu_smoke_check()
    if ok:
        return "pallas"
    import sys

    print(f"[pallas] native TPU smoke check FAILED ({detail}); "
          f"falling back to the xla attention backend", file=sys.stderr)
    return "xla"
