"""Pallas TPU kernels for the bipartite attention — now differentiable to
second order, so ``attention_backend='pallas'`` is a TRAINING backend, not
just a sampling one (SURVEY.md §2.4 "Ring attention / blockwise" row:
blockwise kernels over the n = H·W grid axis to bound VMEM at high
resolution — no ring needed).

Two directions, each with a forward and a backward kernel:

``grid_to_latent_attention``  — X←Y (the main phase): every grid position
    attends to the k ≤ 33 latents.  The softmax axis is the tiny k, so each
    n-block is independent: one fused kernel computes logits → softmax →
    value mix without ever materializing the [n, k] probability map in HBM.
    The forward also emits the per-row softmax statistic ``lse`` (row max +
    log denominator, one fp32 scalar per grid position) — the residual the
    backward kernel needs to RECOMPUTE probabilities blockwise instead of
    reading a saved map.  The backward kernel walks the same n-blocks,
    rebuilds P = exp(S − lse) per block, and produces dq per block plus
    dk/dv accumulated across blocks in fp32 VMEM scratch.

``latent_to_grid_attention``  — Y←X (the duplex centroid phase): the k
    latents attend OVER the n grid positions, so the softmax spans n.  The
    forward runs blockwise with running max / denominator / weighted
    accumulator (the flash-attention recurrence) in VMEM scratch and emits
    ``lse`` at the final block.  The backward is the flash-attention
    backward recurrence: per n-block it recomputes P from ``lse``, uses the
    FlashAttention delta trick (rowsum(dP ∘ P) = rowsum(do ∘ o), computed
    once outside the kernel from the saved output), writes dk/dv for the
    block, and accumulates dq in VMEM scratch — the [k, n] map is never
    materialized in either pass.

Autodiff contract (the reason training can use these; docs/kernels.md has
the full derivation):

* The public ops are ``jax.custom_vjp`` functions whose bwd runs the
  backward kernels — first-order reverse-mode (the ``d``/``g`` step
  programs' hot path) executes kernels only.
* Every kernel composite inside fwd/bwd is itself a ``jax.custom_jvp``
  function whose rule computes the primal via the kernels (decorated
  recursion — one transform level peels per call) and the tangent via
  ``jax.jvp`` of the jnp reference formula.  ``custom_jvp_call`` survives
  in jaxprs, so when the lazy-reg programs linearize the first-order graph
  (R1's grad-of-grad, PL's HVP through synthesis) they re-enter these
  rules instead of hitting a raw ``pallas_call`` — which has no transpose
  rule and would abort the trace.  A plain ``custom_vjp`` without the
  inner jvp layer fails exactly there (verified; the jnp tangent glue
  materializes one [n, k] map, but only inside the 1/16-, 1/4-cadence reg
  programs).
* Direct forward-mode (``jax.jvp`` straight through the op) is NOT
  supported — the ``custom_vjp`` wrapper rejects it.  Nothing in the
  training/eval stack forward-diffs through attention (R1/PL are both
  formulated as reverse-mode grads, losses/gan.py).

Tests run the kernels in interpret mode on CPU against the jnp oracle
(forward, dq/dk/dv, and an R1-shaped double backward); on TPU, native
Mosaic lowering is where interpret-mode coverage can diverge (the (L,1)
fp32 scratch shapes, ``@pl.when`` accumulation, the new multi-output
blocks), so first use on a TPU runs ``tpu_smoke_check`` — a tiny native
compile-and-compare of the forward AND backward kernels against the jnp
oracle — and the CLIs fall back to the xla backend with a warning if it
fails (ADVICE r3).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # importable on CPU builds


def _vmem():
    return pltpu.VMEM


# --------------------------------------------------------------------------
# jnp reference formulas — the oracle math the kernels implement.  They are
# BOTH the parity baseline (tests) and the tangent glue of the custom_jvp
# rules below: higher-order transforms differentiate these, so they stay in
# fp32 stats exactly like ops.attention.multihead_attention.
# --------------------------------------------------------------------------


def _ref_fwd_stats(q, k, v):
    """softmax(q kᵀ/√D) v with the row statistic: returns (o, lse)."""
    s = jnp.einsum("bnd,bld->bnl", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / math.sqrt(q.shape[-1])
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    den = jnp.sum(e, axis=-1, keepdims=True)
    o = jnp.einsum("bnl,bld->bnd", (e / den).astype(v.dtype), v,
                   preferred_element_type=jnp.float32).astype(v.dtype)
    return o, (m + jnp.log(den))[..., 0]


def _ref_bwd(q, k, v, lse, do):
    """VJP of softmax attention at cotangent ``do``, probabilities
    recomputed from ``lse`` (the formula both bwd kernels implement)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    q32, k32 = q.astype(jnp.float32), k.astype(jnp.float32)
    v32, do32 = v.astype(jnp.float32), do.astype(jnp.float32)
    s = jnp.einsum("bnd,bld->bnl", q32, k32) * scale
    p = jnp.exp(s - lse[..., None])
    dv = jnp.einsum("bnl,bnd->bld", p, do32)
    dp = jnp.einsum("bnd,bld->bnl", do32, v32)
    delta = jnp.sum(dp * p, axis=-1, keepdims=True)
    ds = p * (dp - delta)
    dq = jnp.einsum("bnl,bld->bnd", ds, k32) * scale
    dk = jnp.einsum("bnl,bnd->bld", ds, q32) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _ref_bwd_with_o(q, k, v, o, lse, do):
    del o  # the delta identity rowsum(dP∘P) == rowsum(do∘o) is kernel-side
    return _ref_bwd(q, k, v, lse, do)


# --------------------------------------------------------------------------
# X ← Y : grid attends to latents (softmax over the tiny latent axis)
# --------------------------------------------------------------------------

def _grid_to_latent_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None, *,
                           scale):
    # q: [1, bn, D]  k: [1, L, D]  v: [1, L, Dv]  o: [1, bn, Dv]
    # lse: [1, bn] — row max + log denominator, the backward's residual.
    # None on the no-grad path (generate/evaluate): pallas_call cannot
    # DCE an unused output, so the sampling path must not declare one.
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0]
    logits = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale        # [bn, L]
    m = jnp.max(logits, axis=-1, keepdims=True)
    e = jnp.exp(logits - m)
    den = jnp.sum(e, axis=-1, keepdims=True)
    o = jnp.dot((e / den).astype(v.dtype), v,
                preferred_element_type=jnp.float32)         # [bn, Dv]
    o_ref[0] = o.astype(o_ref.dtype)
    if lse_ref is not None:
        lse_ref[0] = (m + jnp.log(den))[:, 0]


def _grid_to_latent_fwd(
    q: jax.Array,    # [B, n, D]   (fold heads into B; D = head dim)
    k: jax.Array,    # [B, L, D]
    v: jax.Array,    # [B, L, Dv]
    *,
    block_n: int = 512,
    interpret: bool = False,
    with_stats: bool = True,
):
    """Fused forward where softmax runs over the latent axis L; returns
    ``(out, lse)`` — lse is the fp32 softmax statistic per grid row.
    ``with_stats=False`` (the no-grad sampling path) declares only the
    ``out`` output: pallas_call cannot DCE an unused output, so the lse
    HBM write must be omitted at declaration, not ignored downstream."""
    b, n, d = q.shape
    _, l, dv = v.shape
    scale = 1.0 / math.sqrt(d)
    bn = min(block_n, n)
    n_pad = -n % bn
    if n_pad:
        q = jnp.pad(q, ((0, 0), (0, n_pad), (0, 0)))
    grid = (b, (n + n_pad) // bn)
    out_shape = [jax.ShapeDtypeStruct((b, n + n_pad, dv), v.dtype)]
    out_specs = [pl.BlockSpec((1, bn, dv), lambda i, j: (i, j, 0),
                              memory_space=_vmem())]
    if with_stats:
        out_shape.append(jax.ShapeDtypeStruct((b, n + n_pad), jnp.float32))
        out_specs.append(pl.BlockSpec((1, bn), lambda i, j: (i, j),
                                      memory_space=_vmem()))
    res = pl.pallas_call(
        functools.partial(_grid_to_latent_kernel, scale=scale),
        out_shape=tuple(out_shape),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bn, d), lambda i, j: (i, j, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, l, d), lambda i, j: (i, 0, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, l, dv), lambda i, j: (i, 0, 0),
                         memory_space=_vmem()),
        ],
        out_specs=tuple(out_specs),
        interpret=interpret,
    )(q, k, v)
    if not with_stats:
        return res[0][:, :n]
    out, lse = res
    return out[:, :n], lse[:, :n]


def _grid_to_latent_bwd_kernel(q_ref, k_ref, v_ref, lse_ref, do_ref,
                               dq_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                               *, scale):
    # Per n-block: rebuild P from lse, emit dq for the block, accumulate
    # dk/dv across blocks in fp32 scratch (same revisiting discipline as
    # the latent_to_grid forward).  Padded tail rows are safe: q rows are
    # zero → P is a finite uniform row, and do rows are zero → their
    # dk/dv contributions vanish (dP = 0 ⇒ dS = 0).
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale        # [bn, L]
    p = jnp.exp(s - lse_ref[0][:, None])
    dv_acc[:] += jax.lax.dot_general(
        p, do, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                # [L, Dv]
    dp = jax.lax.dot_general(
        do, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # [bn, L]
    delta = jnp.sum(dp * p, axis=-1, keepdims=True)
    ds = p * (dp - delta)
    dq_ref[0] = (jnp.dot(ds, k, preferred_element_type=jnp.float32)
                 * scale).astype(dq_ref.dtype)
    dk_acc[:] += jax.lax.dot_general(
        ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale        # [L, D]

    @pl.when(j == pl.num_programs(1) - 1)
    def _emit():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _grid_to_latent_bwd(q, k, v, lse, do, *, block_n: int = 512,
                        interpret: bool = False):
    """(dq, dk, dv) of the X←Y direction — probabilities recomputed
    blockwise from ``lse``; the [n, L] map never touches HBM."""
    b, n, d = q.shape
    _, l, dv = v.shape
    scale = 1.0 / math.sqrt(d)
    bn = min(block_n, n)
    n_pad = -n % bn
    if n_pad:
        q = jnp.pad(q, ((0, 0), (0, n_pad), (0, 0)))
        do = jnp.pad(do, ((0, 0), (0, n_pad), (0, 0)))
        lse = jnp.pad(lse, ((0, 0), (0, n_pad)))
    grid = (b, (n + n_pad) // bn)
    dq, dk, dvv = pl.pallas_call(
        functools.partial(_grid_to_latent_bwd_kernel, scale=scale),
        out_shape=(jax.ShapeDtypeStruct((b, n + n_pad, d), q.dtype),
                   jax.ShapeDtypeStruct((b, l, d), k.dtype),
                   jax.ShapeDtypeStruct((b, l, dv), v.dtype)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bn, d), lambda i, j: (i, j, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, l, d), lambda i, j: (i, 0, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, l, dv), lambda i, j: (i, 0, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, bn), lambda i, j: (i, j),
                         memory_space=_vmem()),
            pl.BlockSpec((1, bn, dv), lambda i, j: (i, j, 0),
                         memory_space=_vmem()),
        ],
        out_specs=(pl.BlockSpec((1, bn, d), lambda i, j: (i, j, 0),
                                memory_space=_vmem()),
                   pl.BlockSpec((1, l, d), lambda i, j: (i, 0, 0),
                                memory_space=_vmem()),
                   pl.BlockSpec((1, l, dv), lambda i, j: (i, 0, 0),
                                memory_space=_vmem())),
        scratch_shapes=[pltpu.VMEM((l, d), jnp.float32),
                        pltpu.VMEM((l, dv), jnp.float32)],
        interpret=interpret,
    )(q, k, v, lse, do)
    return dq[:, :n], dk, dvv


# --------------------------------------------------------------------------
# Y ← X : latents attend over the grid (online softmax over the big n axis)
# --------------------------------------------------------------------------

def _latent_to_grid_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                           m_ref, s_ref, acc_ref, *, scale, n_valid, block_n):
    # q: [1, L, D]  k: [1, bn, D]  v: [1, bn, Dv]  o: [1, L, Dv]
    # scratch: m [L, 1], s [L, 1], acc [L, Dv]  (flash recurrence, fp32)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        s_ref[:] = jnp.zeros_like(s_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    logits = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale          # [L, bn]
    # Mask grid positions past n (zero-padding from the wrapper).
    offs = j * block_n + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, dimension=1)
    logits = jnp.where(offs < n_valid, logits, -jnp.inf)

    m_prev = m_ref[:]                                        # [L, 1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=-1, keepdims=True))
    # exp(-inf - -inf) guard: masked-out rows can keep m == -inf safely
    # because every block contributes 0 there.
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(logits - m_new)                              # [L, bn]
    s_ref[:] = s_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * corr + jnp.dot(
        p, v, preferred_element_type=jnp.float32)            # [L, Dv]
    m_ref[:] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _emit():
        o_ref[0] = (acc_ref[:] / s_ref[:]).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0] = (m_ref[:] + jnp.log(s_ref[:]))[:, 0]


def _latent_to_grid_kernel_nostats(q_ref, k_ref, v_ref, o_ref,
                                   m_ref, s_ref, acc_ref, **kw):
    # No-grad sampling path: with one declared output the refs pallas
    # passes shift left, so lse's slot must vanish from the signature.
    _latent_to_grid_kernel(q_ref, k_ref, v_ref, o_ref, None,
                           m_ref, s_ref, acc_ref, **kw)


def _latent_to_grid_fwd(
    q: jax.Array,    # [B, L, D]
    k: jax.Array,    # [B, n, D]
    v: jax.Array,    # [B, n, Dv]
    *,
    block_n: int = 512,
    interpret: bool = False,
    with_stats: bool = True,
):
    """Fused forward where softmax runs over the grid axis n, blockwise
    with the flash-attention online recurrence (VMEM bounded by block_n);
    returns ``(out, lse)``.  ``with_stats=False`` (the no-grad sampling
    path) declares only ``out`` — see ``_grid_to_latent_fwd``."""
    b, l, d = q.shape
    _, n, dv = v.shape
    scale = 1.0 / math.sqrt(d)
    bn = min(block_n, n)
    n_pad = -n % bn
    if n_pad:
        k = jnp.pad(k, ((0, 0), (0, n_pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, n_pad), (0, 0)))
    grid = (b, (n + n_pad) // bn)
    kern = functools.partial(
        _latent_to_grid_kernel if with_stats else _latent_to_grid_kernel_nostats,
        scale=scale, n_valid=n, block_n=bn)
    scratch = [pltpu.VMEM((l, 1), jnp.float32),
               pltpu.VMEM((l, 1), jnp.float32),
               pltpu.VMEM((l, dv), jnp.float32)]
    out_shape = [jax.ShapeDtypeStruct((b, l, dv), v.dtype)]
    out_specs = [pl.BlockSpec((1, l, dv), lambda i, j: (i, 0, 0),
                              memory_space=_vmem())]
    if with_stats:
        out_shape.append(jax.ShapeDtypeStruct((b, l), jnp.float32))
        out_specs.append(pl.BlockSpec((1, l), lambda i, j: (i, 0),
                                      memory_space=_vmem()))
    res = pl.pallas_call(
        kern,
        out_shape=tuple(out_shape),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, l, d), lambda i, j: (i, 0, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, bn, d), lambda i, j: (i, j, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, bn, dv), lambda i, j: (i, j, 0),
                         memory_space=_vmem()),
        ],
        out_specs=tuple(out_specs),
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)
    return res if with_stats else res[0]


def _latent_to_grid_bwd_kernel(q_ref, k_ref, v_ref, lse_ref, delta_ref,
                               do_ref, dq_ref, dk_ref, dv_ref, dq_acc,
                               *, scale, n_valid, block_n):
    # The flash backward recurrence: P rebuilt per n-block from lse;
    # delta = rowsum(do ∘ o) (the FlashAttention identity for
    # rowsum(dP ∘ P), computed once outside); dk/dv written per block,
    # dq accumulated in fp32 scratch and emitted at the last block.
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale          # [L, bn]
    offs = j * block_n + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, dimension=1)
    # Masked (padded) columns: P = 0 kills their dk/dv rows and their
    # dq contribution in one stroke.
    p = jnp.where(offs < n_valid, jnp.exp(s - lse_ref[0][:, None]), 0.0)
    dv_ref[0] = jax.lax.dot_general(
        p, do, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp = jax.lax.dot_general(
        do, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                  # [L, bn]
    ds = p * (dp - delta_ref[0][:, None])
    dk_ref[0] = (jax.lax.dot_general(
        ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale).astype(dk_ref.dtype)
    dq_acc[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32) * scale

    @pl.when(j == pl.num_programs(1) - 1)
    def _emit():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _latent_to_grid_bwd(q, k, v, o, lse, do, *, block_n: int = 512,
                        interpret: bool = False):
    """(dq, dk, dv) of the Y←X direction via the flash backward
    recurrence; the [L, n] map never touches HBM."""
    b, l, d = q.shape
    _, n, dv = v.shape
    scale = 1.0 / math.sqrt(d)
    # FlashAttention delta trick: rowsum(dP ∘ P) == rowsum(do ∘ o), so the
    # cross-block softmax correction is a [B, L] vector computed from the
    # saved output — no second pass over the grid axis.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    bn = min(block_n, n)
    n_pad = -n % bn
    if n_pad:
        k = jnp.pad(k, ((0, 0), (0, n_pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, n_pad), (0, 0)))
    grid = (b, (n + n_pad) // bn)
    dq, dk, dvv = pl.pallas_call(
        functools.partial(_latent_to_grid_bwd_kernel, scale=scale,
                          n_valid=n, block_n=bn),
        out_shape=(jax.ShapeDtypeStruct((b, l, d), q.dtype),
                   jax.ShapeDtypeStruct((b, n + n_pad, d), k.dtype),
                   jax.ShapeDtypeStruct((b, n + n_pad, dv), v.dtype)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, l, d), lambda i, j: (i, 0, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, bn, d), lambda i, j: (i, j, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, bn, dv), lambda i, j: (i, j, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, l), lambda i, j: (i, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, l), lambda i, j: (i, 0),
                         memory_space=_vmem()),
            pl.BlockSpec((1, l, dv), lambda i, j: (i, 0, 0),
                         memory_space=_vmem()),
        ],
        out_specs=(pl.BlockSpec((1, l, d), lambda i, j: (i, 0, 0),
                                memory_space=_vmem()),
                   pl.BlockSpec((1, bn, d), lambda i, j: (i, j, 0),
                                memory_space=_vmem()),
                   pl.BlockSpec((1, bn, dv), lambda i, j: (i, j, 0),
                                memory_space=_vmem())),
        scratch_shapes=[pltpu.VMEM((l, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, lse, delta, do)
    return dq, dk[:, :n], dvv[:, :n]


# --------------------------------------------------------------------------
# Derivative rules.  Layering (see module docstring + docs/kernels.md):
#   custom_vjp  — first-order reverse runs the bwd kernels (the hot path);
#   custom_jvp  — every kernel composite re-enters a rule under further
#                 linearization (R1 grad-of-grad, PL HVP) instead of
#                 exposing an untransposable raw pallas_call.
# The jvp rules compute the primal by calling THEMSELVES (decorated
# recursion peels exactly one transform level per call, bottoming out at
# the kernels) and the tangent via jax.jvp of the jnp reference — correct
# by construction and linear in the tangents, hence transposable.
# --------------------------------------------------------------------------


@functools.partial(jax.custom_jvp, nondiff_argnums=(3, 4))
def _g2l_stats(q, k, v, block_n, interpret):
    return _grid_to_latent_fwd(q, k, v, block_n=block_n, interpret=interpret)


@_g2l_stats.defjvp
def _g2l_stats_jvp(block_n, interpret, primals, tangents):
    out = _g2l_stats(*primals, block_n, interpret)
    _, tan = jax.jvp(_ref_fwd_stats, primals, tangents)
    return out, tan


@functools.partial(jax.custom_jvp, nondiff_argnums=(5, 6))
def _g2l_grads(q, k, v, lse, do, block_n, interpret):
    return _grid_to_latent_bwd(q, k, v, lse, do, block_n=block_n,
                               interpret=interpret)


@_g2l_grads.defjvp
def _g2l_grads_jvp(block_n, interpret, primals, tangents):
    out = _g2l_grads(*primals, block_n, interpret)
    _, tan = jax.jvp(_ref_bwd, primals, tangents)
    return out, tan


@functools.partial(jax.custom_jvp, nondiff_argnums=(3, 4))
def _l2g_stats(q, k, v, block_n, interpret):
    return _latent_to_grid_fwd(q, k, v, block_n=block_n, interpret=interpret)


@_l2g_stats.defjvp
def _l2g_stats_jvp(block_n, interpret, primals, tangents):
    out = _l2g_stats(*primals, block_n, interpret)
    _, tan = jax.jvp(_ref_fwd_stats, primals, tangents)
    return out, tan


@functools.partial(jax.custom_jvp, nondiff_argnums=(6, 7))
def _l2g_grads(q, k, v, o, lse, do, block_n, interpret):
    return _latent_to_grid_bwd(q, k, v, o, lse, do, block_n=block_n,
                               interpret=interpret)


@_l2g_grads.defjvp
def _l2g_grads_jvp(block_n, interpret, primals, tangents):
    out = _l2g_grads(*primals, block_n, interpret)
    _, tan = jax.jvp(_ref_bwd_with_o, primals, tangents)
    return out, tan


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _g2l_attend(q, k, v, block_n, interpret):
    # Primal = the no-grad path (generate/evaluate/vmap): the lse-free
    # kernel, so sampling never pays the backward residual's HBM write.
    # Differentiation always enters through the fwd/bwd rule below.
    return _grid_to_latent_fwd(q, k, v, block_n=block_n,
                               interpret=interpret, with_stats=False)


def _g2l_attend_fwd(q, k, v, block_n, interpret):
    o, lse = _g2l_stats(q, k, v, block_n, interpret)
    return o, (q, k, v, lse)


def _g2l_attend_bwd(block_n, interpret, res, ct):
    q, k, v, lse = res
    return _g2l_grads(q, k, v, lse, ct, block_n, interpret)


_g2l_attend.defvjp(_g2l_attend_fwd, _g2l_attend_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _l2g_attend(q, k, v, block_n, interpret):
    # Primal = the no-grad path: lse-free kernel (see _g2l_attend).
    return _latent_to_grid_fwd(q, k, v, block_n=block_n,
                               interpret=interpret, with_stats=False)


def _l2g_attend_fwd(q, k, v, block_n, interpret):
    o, lse = _l2g_stats(q, k, v, block_n, interpret)
    return o, (q, k, v, o, lse)


def _l2g_attend_bwd(block_n, interpret, res, ct):
    q, k, v, o, lse = res
    return _l2g_grads(q, k, v, o, lse, ct, block_n, interpret)


_l2g_attend.defvjp(_l2g_attend_fwd, _l2g_attend_bwd)


# --------------------------------------------------------------------------
# Public ops — same signatures as before, now differentiable to 2nd order.
# --------------------------------------------------------------------------

def grid_to_latent_attention(
    q: jax.Array,    # [B, n, D]   (fold heads into B; D = head dim)
    k: jax.Array,    # [B, L, D]
    v: jax.Array,    # [B, L, Dv]
    *,
    block_n: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Fused attention where softmax runs over the latent axis L.

    Equivalent to ``softmax(q @ k.T / sqrt(D)) @ v`` — the main-phase
    direction of ``ops.attention.multihead_attention`` (per head).
    Differentiable to second order (reverse-mode; see module docstring).
    """
    return _g2l_attend(q, k, v, block_n, interpret)


def latent_to_grid_attention(
    q: jax.Array,    # [B, L, D]
    k: jax.Array,    # [B, n, D]
    v: jax.Array,    # [B, n, Dv]
    *,
    block_n: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Fused attention where softmax runs over the grid axis n, blockwise
    with the flash-attention online recurrence (VMEM bounded by block_n).
    Differentiable to second order (reverse-mode; see module docstring)."""
    return _l2g_attend(q, k, v, block_n, interpret)


# --------------------------------------------------------------------------
# Drop-in multihead wrapper matching ops.attention.multihead_attention
# --------------------------------------------------------------------------

def multihead_attention_pallas(
    q: jax.Array,    # [N, Lq, D]
    k: jax.Array,    # [N, Lk, D]
    v: jax.Array,    # [N, Lk, Dv]
    num_heads: int = 1,
    *,
    block_n: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Head-folding wrapper: picks the kernel by which side is the grid.

    Returns out [N, Lq, Dv] only (no probability maps — use the jnp op when
    attention visualizations are being collected).  The fold is plain
    reshape/transpose, so the wrapper inherits the kernels' autodiff.
    """
    n, lq, d = q.shape
    _, lk, dv = v.shape
    assert d % num_heads == 0 and dv % num_heads == 0
    dh, dvh = d // num_heads, dv // num_heads

    def fold(t, dim):
        return (t.reshape(n, t.shape[1], num_heads, dim)
                .transpose(0, 2, 1, 3)
                .reshape(n * num_heads, t.shape[1], dim))

    qf, kf, vf = fold(q, dh), fold(k, dh), fold(v, dvh)
    if lq >= lk:      # grid queries, latent keys → softmax over tiny Lk
        of = grid_to_latent_attention(qf, kf, vf, block_n=block_n,
                                      interpret=interpret)
    else:             # latent queries, grid keys → online softmax over Lk
        of = latent_to_grid_attention(qf, kf, vf, block_n=block_n,
                                      interpret=interpret)
    return (of.reshape(n, num_heads, lq, dvh)
            .transpose(0, 2, 1, 3)
            .reshape(n, lq, dv))


# --------------------------------------------------------------------------
# First-use native-TPU verification gate (ADVICE r3)
# --------------------------------------------------------------------------

_TPU_SMOKE: dict = {}   # memo: {'ok': bool, 'detail': str}


def tpu_smoke_check(atol: float = 1e-2) -> tuple:
    """Compile the kernels NATIVELY on the ambient TPU at tiny shapes and
    compare against the jnp oracle.  Returns ``(ok, detail)``; memoized so
    the cost (a handful of small compiles) is paid once per process.

    Exercises both directions, multi-head folding, the blockwise path with
    a non-divisible n (padding + masked flash recurrence), AND — now that
    training runs on these kernels — the backward kernels via a
    ``jax.grad`` through each direction: the (L,1) scratch shapes,
    ``@pl.when`` accumulation, and the new multi-output (o, lse) blocks
    are exactly the constructs where Mosaic lowering could diverge from
    interpret mode.
    """
    if "ok" in _TPU_SMOKE:
        return _TPU_SMOKE["ok"], _TPU_SMOKE["detail"]
    import numpy as np

    from gansformer_tpu.ops.attention import multihead_attention

    try:
        rng = np.random.RandomState(0)
        grid = jnp.asarray(rng.randn(2, 60, 32), jnp.float32)  # n=60: pad path
        lat = jnp.asarray(rng.randn(2, 8, 32), jnp.float32)
        latv = jnp.asarray(rng.randn(2, 8, 32), jnp.float32)
        gridv = jnp.asarray(rng.randn(2, 60, 32), jnp.float32)
        # X←Y (softmax over tiny L) and Y←X (blockwise online softmax over n,
        # 4 blocks of 16 + masking).
        ref_xy, _ = multihead_attention(grid, lat, latv, 2)
        got_xy = multihead_attention_pallas(grid, lat, latv, 2,
                                            interpret=False)
        ref_yx, _ = multihead_attention(lat, grid, gridv, 2)
        got_yx = multihead_attention_pallas(lat, grid, gridv, 2, block_n=16,
                                            interpret=False)
        d_xy = float(jnp.max(jnp.abs(got_xy - ref_xy)))
        d_yx = float(jnp.max(jnp.abs(got_yx - ref_yx)))
        # Backward kernels (the training path): grad of a scalar through
        # each direction vs the differentiable jnp composite.
        def loss_pl(q, k, v, heads, bn):
            out = multihead_attention_pallas(q, k, v, heads, block_n=bn,
                                             interpret=False)
            return jnp.sum(out * jnp.cos(out))

        def loss_ref(q, k, v, heads):
            out = multihead_attention(q, k, v, heads)[0]
            return jnp.sum(out * jnp.cos(out))

        g_xy = jax.grad(loss_pl, argnums=(0, 1, 2))(grid, lat, latv, 2, 512)
        g_xy_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(grid, lat, latv, 2)
        g_yx = jax.grad(loss_pl, argnums=(0, 1, 2))(lat, grid, gridv, 2, 16)
        g_yx_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(lat, grid, gridv, 2)
        b_xy = max(float(jnp.max(jnp.abs(a - b)))
                   for a, b in zip(g_xy, g_xy_ref))
        b_yx = max(float(jnp.max(jnp.abs(a - b)))
                   for a, b in zip(g_yx, g_yx_ref))
        ok = max(d_xy, d_yx, b_xy, b_yx) < atol
        detail = (f"max_abs_diff fwd grid_to_latent={d_xy:.2e} "
                  f"latent_to_grid={d_yx:.2e}; bwd grid_to_latent="
                  f"{b_xy:.2e} latent_to_grid={b_yx:.2e} (atol {atol:g})")
    except Exception as e:  # Mosaic compile failures surface as many types
        ok = False
        detail = f"native compile/run failed: {type(e).__name__}: {e}"[:400]
    _TPU_SMOKE.update(ok=ok, detail=detail)
    return ok, detail


def resolve_backend(requested: str) -> str:
    """'pallas' → 'pallas' only if safe on this backend, else 'xla'.

    On CPU/GPU the pallas path runs in interpret mode (oracle-tested in CI,
    forward AND backward); on TPU the first resolution runs the native
    smoke check — which now compiles the backward kernels too, since the
    training step programs dispatch them — and falls back to xla with the
    reason printed, rather than advertising a kernel that never compiled
    on the device class it exists for.
    """
    if requested != "pallas":
        return requested
    if jax.default_backend() != "tpu":
        return "pallas"
    ok, detail = tpu_smoke_check()
    if ok:
        return "pallas"
    import sys

    print(f"[pallas] native TPU smoke check FAILED ({detail}); "
          f"falling back to the xla attention backend", file=sys.stderr)
    return "xla"
