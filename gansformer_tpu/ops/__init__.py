from gansformer_tpu.ops.upfirdn2d import (
    upfirdn2d,
    setup_filter,
    upsample_2d,
    downsample_2d,
    filter_2d,
)
from gansformer_tpu.ops.fused_bias_act import fused_bias_act, ACTIVATIONS
from gansformer_tpu.ops.modulated_conv import (
    QuantizedWeight,
    conv2d,
    modulated_conv2d,
    resolve_weight,
)
from gansformer_tpu.ops.attention import (
    multihead_attention,
    multihead_attention_kv_sharded,
    sharded_multihead_attention,
    sinusoidal_grid_encoding,
)
_PALLAS_EXPORTS = (
    "grid_to_latent_attention",
    "latent_to_grid_attention",
    "multihead_attention_pallas",
)
# conv_backend='pallas' kernel family (ISSUE 14; row-blocking planners
# ISSUE 17) — same lazy discipline.
_PALLAS_CONV_EXPORTS = (
    "modulated_conv2d_pallas",
    "modconv_fits",
    "modconv_plan",
    "resolve_conv_backend",
)
_PALLAS_UPFIRDN_EXPORTS = (
    "upfirdn2d_pallas",
    "upfirdn_fits",
    "upfirdn_plan",
    "ConvPlan",
    "note_conv_fallback",
)


def __getattr__(name):
    # Lazy (PEP 562): keep jax.experimental.pallas out of the default
    # import path — only backend='pallas' callers pay for it.
    if name in _PALLAS_EXPORTS:
        from gansformer_tpu.ops import pallas_attention

        return getattr(pallas_attention, name)
    if name in _PALLAS_CONV_EXPORTS:
        from gansformer_tpu.ops import pallas_modconv

        return getattr(pallas_modconv, name)
    if name in _PALLAS_UPFIRDN_EXPORTS:
        from gansformer_tpu.ops import pallas_upfirdn

        return getattr(pallas_upfirdn, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
