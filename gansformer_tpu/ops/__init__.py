from gansformer_tpu.ops.upfirdn2d import (
    upfirdn2d,
    setup_filter,
    upsample_2d,
    downsample_2d,
    filter_2d,
)
from gansformer_tpu.ops.fused_bias_act import fused_bias_act, ACTIVATIONS
from gansformer_tpu.ops.modulated_conv import modulated_conv2d, conv2d
from gansformer_tpu.ops.attention import multihead_attention, sinusoidal_grid_encoding
