"""Bipartite-attention primitives.

The GANsformer's defining op (SURVEY.md §2.3): attention between the k latent
components Y (k ≤ 32) and the image feature grid X (n = H·W positions).  Cost
is O(n·k) — linear in pixels — which is the scalability property to preserve:
on TPU this is two batched einsums plus a softmax over a tiny axis, an ideal
MXU workload, and it shards trivially over the batch axis of the data mesh
(SURVEY.md §5 "Long-context": no ring/Ulysses machinery is required; if
attention were ever enabled at 1024² the n axis can be sharded with a ~50-line
shard_map — documented decision, not built).

Softmax statistics are computed in fp32 even under bfloat16 compute.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def multihead_attention(
    q: jax.Array,           # [N, Lq, D]
    k: jax.Array,           # [N, Lk, D]
    v: jax.Array,           # [N, Lk, Dv]
    num_heads: int = 1,
) -> Tuple[jax.Array, jax.Array]:
    """Batched multi-head dot-product attention over pre-projected q/k/v.

    Returns (out [N, Lq, Dv], probs [N, heads, Lq, Lk]).  The probs are
    exposed for diagnostics/visualization of the latent→region assignment
    maps (and are asserted row-stochastic in tests).
    """
    n, lq, d = q.shape
    _, lk, dv = v.shape
    assert d % num_heads == 0 and dv % num_heads == 0
    dh = d // num_heads
    qh = q.reshape(n, lq, num_heads, dh).astype(jnp.float32)
    kh = k.reshape(n, lk, num_heads, dh).astype(jnp.float32)
    vh = v.reshape(n, lk, num_heads, dv // num_heads)
    # fp32 stats at full precision; bf16 inputs would ride the MXU directly.
    prec = (jax.lax.Precision.HIGHEST if v.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    logits = jnp.einsum("nqhd,nkhd->nhqk", qh, kh,
                        precision=jax.lax.Precision.HIGHEST) / math.sqrt(dh)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("nhqk,nkhd->nqhd", probs.astype(vh.dtype), vh,
                     precision=prec)
    return out.reshape(n, lq, dv), probs


def sinusoidal_grid_encoding(height: int, width: int, dim: int) -> np.ndarray:
    """2D sinusoidal positional encoding for the n = H·W grid positions.

    Returns a static [H*W, dim] fp32 array (numpy: baked into the jaxpr as a
    constant — no recompute per step).  Matches the capability of the
    reference's sinusoidal grid encodings for the attention layers
    (SURVEY.md §2.3); learned encodings live in the model layer.
    """
    assert dim % 4 == 0, "positional dim must be divisible by 4"
    quarter = dim // 4
    freqs = 1.0 / (10000.0 ** (np.arange(quarter, dtype=np.float64) / quarter))
    ys = np.arange(height, dtype=np.float64)[:, None] * freqs[None, :]  # [H,q]
    xs = np.arange(width, dtype=np.float64)[:, None] * freqs[None, :]   # [W,q]
    enc_y = np.concatenate([np.sin(ys), np.cos(ys)], axis=-1)  # [H, dim/2]
    enc_x = np.concatenate([np.sin(xs), np.cos(xs)], axis=-1)  # [W, dim/2]
    grid = np.concatenate(
        [
            np.broadcast_to(enc_y[:, None, :], (height, width, dim // 2)),
            np.broadcast_to(enc_x[None, :, :], (height, width, dim // 2)),
        ],
        axis=-1,
    )
    return grid.reshape(height * width, dim).astype(np.float32)
