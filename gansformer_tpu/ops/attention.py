"""Bipartite-attention primitives.

The GANsformer's defining op (SURVEY.md §2.3): attention between the k latent
components Y (k ≤ 32) and the image feature grid X (n = H·W positions).  Cost
is O(n·k) — linear in pixels — which is the scalability property to preserve:
on TPU this is two batched einsums plus a softmax over a tiny axis, an ideal
MXU workload, and it shards trivially over the batch axis of the data mesh
(SURVEY.md §5 "Long-context": no ring/Ulysses machinery is required).  For
long-context/sequence parallelism the n = H·W grid axis CAN be sharded:
``multihead_attention_kv_sharded`` below is the explicit shard_map kernel
(cross-shard-stable softmax), and ``BipartiteAttention(grid_shard=True)``
reaches the same layout via GSPMD constraints — tests hold both to parity.

Softmax statistics are computed in fp32 even under bfloat16 compute.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def multihead_attention(
    q: jax.Array,           # [N, Lq, D]
    k: jax.Array,           # [N, Lk, D]
    v: jax.Array,           # [N, Lk, Dv]
    num_heads: int = 1,
) -> Tuple[jax.Array, jax.Array]:
    """Batched multi-head dot-product attention over pre-projected q/k/v.

    Returns (out [N, Lq, Dv], probs [N, heads, Lq, Lk]).  The probs are
    exposed for diagnostics/visualization of the latent→region assignment
    maps (and are asserted row-stochastic in tests).
    """
    n, lq, d = q.shape
    _, lk, dv = v.shape
    assert d % num_heads == 0 and dv % num_heads == 0
    dh = d // num_heads
    qh = q.reshape(n, lq, num_heads, dh).astype(jnp.float32)
    kh = k.reshape(n, lk, num_heads, dh).astype(jnp.float32)
    vh = v.reshape(n, lk, num_heads, dv // num_heads)
    # fp32 stats at full precision; bf16 inputs would ride the MXU directly.
    prec = (jax.lax.Precision.HIGHEST if v.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    logits = jnp.einsum("nqhd,nkhd->nhqk", qh, kh,
                        precision=jax.lax.Precision.HIGHEST) / math.sqrt(dh)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("nhqk,nkhd->nqhd", probs.astype(vh.dtype), vh,
                     precision=prec)
    return out.reshape(n, lq, dv), probs


def sinusoidal_grid_encoding(height: int, width: int, dim: int) -> np.ndarray:
    """2D sinusoidal positional encoding for the n = H·W grid positions.

    Returns a static [H*W, dim] fp32 array (numpy: baked into the jaxpr as a
    constant — no recompute per step).  Matches the capability of the
    reference's sinusoidal grid encodings for the attention layers
    (SURVEY.md §2.3); learned encodings live in the model layer.
    """
    assert dim % 4 == 0, "positional dim must be divisible by 4"
    quarter = dim // 4
    freqs = 1.0 / (10000.0 ** (np.arange(quarter, dtype=np.float64) / quarter))
    ys = np.arange(height, dtype=np.float64)[:, None] * freqs[None, :]  # [H,q]
    xs = np.arange(width, dtype=np.float64)[:, None] * freqs[None, :]   # [W,q]
    enc_y = np.concatenate([np.sin(ys), np.cos(ys)], axis=-1)  # [H, dim/2]
    enc_x = np.concatenate([np.sin(xs), np.cos(xs)], axis=-1)  # [W, dim/2]
    grid = np.concatenate(
        [
            np.broadcast_to(enc_y[:, None, :], (height, width, dim // 2)),
            np.broadcast_to(enc_x[None, :, :], (height, width, dim // 2)),
        ],
        axis=-1,
    )
    return grid.reshape(height * width, dim).astype(np.float32)


# --- Sequence/context parallelism over the grid axis -------------------------
#
# SURVEY.md §2.4 records the decision that GANsformer's O(n·k) attention never
# *needs* ring attention; when the n = H·W grid axis is sharded across the
# mesh (long-context at 1024², or a model axis used for activation
# parallelism), the only direction that needs collectives is the duplex
# centroid phase — latents attend OVER the sharded grid, so the softmax
# normalizer spans shards.  This is the promised "~50-line shard_map":
# a numerically stable cross-shard softmax (pmax for the max, psum for the
# denominator and the value-weighted sum).  The simplex direction (grid
# queries attend to the replicated k latents) is embarrassingly parallel and
# needs nothing.


def multihead_attention_kv_sharded(
    q: jax.Array,           # [N, Lq, D]        — replicated along axis_name
    k: jax.Array,           # [N, Lk/shard, D]  — sharded along its length axis
    v: jax.Array,           # [N, Lk/shard, Dv] — sharded along its length axis
    num_heads: int,
    axis_name: str,
) -> Tuple[jax.Array, jax.Array]:
    """``multihead_attention`` for use INSIDE ``shard_map`` when the key/value
    length axis is sharded across mesh axis ``axis_name``.

    Returns (out [N, Lq, Dv] — identical on every shard, local probs
    [N, heads, Lq, Lk/shard] — each shard's slice of the global row-stochastic
    map).  Differentiable (collectives are psum/pmax, both transposable), so
    R1/path-length second-order grads flow through unchanged.
    """
    n, lq, d = q.shape
    _, lk, dv = v.shape
    assert d % num_heads == 0 and dv % num_heads == 0
    dh = d // num_heads
    qh = q.reshape(n, lq, num_heads, dh).astype(jnp.float32)
    kh = k.reshape(n, lk, num_heads, dh).astype(jnp.float32)
    vh = v.reshape(n, lk, num_heads, dv // num_heads)
    logits = jnp.einsum("nqhd,nkhd->nhqk", qh, kh,
                        precision=jax.lax.Precision.HIGHEST) / math.sqrt(dh)
    # Cross-shard-stable softmax over the sharded Lk axis.
    m = jax.lax.pmax(jax.lax.stop_gradient(logits.max(axis=-1)), axis_name)
    p = jnp.exp(logits - m[..., None])                    # [n,h,lq,lk_local]
    denom = jax.lax.psum(p.sum(axis=-1), axis_name)       # [n,h,lq]
    probs = p / denom[..., None]
    prec = (jax.lax.Precision.HIGHEST if v.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    out = jnp.einsum("nhqk,nkhd->nqhd", probs.astype(vh.dtype), vh,
                     precision=prec)
    out = jax.lax.psum(out, axis_name)                    # weighted-V partials
    return out.reshape(n, lq, dv), probs


def sharded_multihead_attention(
    q: jax.Array,           # [N, Lq, D]
    k: jax.Array,           # [N, Lk, D]
    v: jax.Array,           # [N, Lk, Dv]
    num_heads: int,
    mesh: jax.sharding.Mesh,
    batch_axis: Optional[str] = "data",
    seq_axis: str = "model",
) -> Tuple[jax.Array, jax.Array]:
    """Grid-axis-sharded attention as a standalone op: shards K/V's length
    axis over ``seq_axis`` (and everyone's batch over ``batch_axis``), runs
    the explicit-collective kernel, returns globally identical output.

    The model layer reaches the same sharding via GSPMD constraints
    (``BipartiteAttention(grid_shard=True)``); this op is the hand-written
    equivalent that the tests hold GSPMD to parity against.
    """
    import inspect

    try:
        from jax import shard_map
    except ImportError:       # jax 0.4.x location
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def inner(q_, k_, v_):
        return multihead_attention_kv_sharded(q_, k_, v_, num_heads, seq_axis)

    # the replication-check kwarg was renamed check_rep → check_vma
    check_kw = ("check_vma" if "check_vma"
                in inspect.signature(shard_map).parameters else "check_rep")
    b = batch_axis
    return shard_map(
        inner, mesh=mesh,
        in_specs=(P(b, None, None), P(b, seq_axis, None), P(b, seq_axis, None)),
        out_specs=(P(b, None, None), P(b, None, None, seq_axis)),
        **{check_kw: False},
    )(q, k, v)
