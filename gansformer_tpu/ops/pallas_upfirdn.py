"""Pallas TPU kernel for upfirdn2d — pad → FIR → resample in ONE fused
kernel, differentiable to second order (``conv_backend='pallas'``).

The XLA path (``ops/upfirdn2d.py``) lowers the whole op to one
``conv_general_dilated``; this module is the hand-scheduled alternative
for the same semantics: per (batch, channel-block) grid step the kernel
loads one image block into VMEM, performs zero-insertion + padding +
cropping with a single ``lax.pad`` (interior dilation = the upsample,
negative edges = the crop), walks the FIR taps as strided VMEM slices
accumulated in fp32, and writes the decimated result — the padded
intermediate and the pre-decimation grid never touch HBM.  The filter is
a static compile-time constant (it always is in this codebase: blur
taps from ``setup_filter``), so the tap loop fully unrolls.

Optional fused epilogue: ``act(y + bias) * gain`` (linear/lrelu) rides
the same kernel — the `_conv_transpose_poly → blur → fused_bias_act`
chain of the up-conv path collapses into kernels end to end.

Autodiff contract (the PR-9 pattern, ``ops/pallas_attention.py``):

* upfirdn is LINEAR in ``x``; its exact adjoint is another upfirdn with
  the flipped filter, ``up``/``down`` swapped, and the reference's
  gradient padding (the custom TF gradient of
  ``src/dnnlib/tflib/ops/upfirdn_2d.py``).  The outer ``jax.custom_vjp``
  therefore runs the SAME forward kernel for the backward pass.
* The kernel composite is a ``jax.custom_jvp`` function whose rule
  computes the primal via the kernel (decorated recursion peels one
  transform level) and the tangent via the jnp/XLA reference — plain
  transposable glue, so R1 grad-of-grad and PL HVPs re-enter rules
  instead of dying at an untransposable ``pallas_call``.
* The filter is non-differentiable (a static resampling constant, as in
  the reference); ``bias`` is differentiable through saved-output
  activation recovery (lrelu is invertible given the sign).

Tests run the kernels in interpret mode on CPU against the XLA op and
the numpy oracle (tests/test_pallas_conv.py); on TPU first use runs
``pallas_modconv.tpu_smoke_check`` (this kernel is part of the conv
family gate) and the CLIs fall back to the xla conv backend if Mosaic
lowering fails.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # importable on CPU builds

from gansformer_tpu.ops.upfirdn2d import (_pad4 as _xla_pad4,
                                          upfirdn2d as _xla_upfirdn2d)

# Conservative per-invocation VMEM working-set budget (bytes).  The
# wrapper shrinks the channel block until the fp32 compute footprint of
# one grid step fits; if even one channel cannot fit (huge grids) the
# CALLER is expected to fall back to the XLA op.
_VMEM_BUDGET = 9 * 2**20

_SQRT2 = math.sqrt(2.0)
# act -> (apply(pre), default gain, recover dpre/dy from the SAVED
# post-act output).  Only the activations the models actually fuse
# (models/layers.py uses linear + lrelu); everything else stays an XLA
# epilogue.
_EPILOGUES = {
    "linear": (lambda u, a: u, 1.0,
               lambda y, a, g: jnp.ones_like(y)),
    "lrelu": (lambda u, a: jnp.where(u >= 0, u, u * a), _SQRT2,
              lambda y, a, g: jnp.where(y >= 0, 1.0, a).astype(y.dtype)),
}


def _out_hw(h: int, w: int, fh: int, fw: int, up: int, down: int,
            pad4: Tuple[int, int, int, int]) -> Tuple[int, int]:
    py0, py1, px0, px1 = pad4
    oh = (h * up + py0 + py1 - fh) // down + 1
    ow = (w * up + px0 + px1 - fw) // down + 1
    assert oh > 0 and ow > 0, (h, w, fh, fw, up, down, pad4)
    return oh, ow


def grad_pad4(in_h: int, in_w: int, fh: int, fw: int, up: int, down: int,
              pad4: Tuple[int, int, int, int]) -> Tuple[int, int, int, int]:
    """Padding of the adjoint upfirdn (flipped filter, up↔down swapped) —
    the reference custom gradient's pad algebra, validated against
    ``jax.grad`` of the XLA op in tests/test_pallas_conv.py."""
    py0, py1, px0, px1 = pad4
    oh, ow = _out_hw(in_h, in_w, fh, fw, up, down, pad4)
    return (fh - py0 - 1, in_h * up - oh * down + py0 - up + 1,
            fw - px0 - 1, in_w * up - ow * down + px0 - up + 1)


def _pick_block_c(h: int, w: int, c: int, fh: int, fw: int, up: int,
                  down: int, pad4: Tuple[int, int, int, int]) -> Optional[int]:
    """Largest divisor of ``c`` whose one-step fp32 footprint (padded
    input + output + one tap slice) fits the budget; None = does not fit
    even at one channel (caller falls back to XLA)."""
    oh, ow = _out_hw(h, w, fh, fw, up, down, pad4)
    ph = h * up + max(pad4[0], 0) + max(pad4[1], 0)
    pw = w * up + max(pad4[2], 0) + max(pad4[3], 0)
    per_c = 4 * (h * w + ph * pw + 2 * oh * ow)
    if per_c > _VMEM_BUDGET:
        return None
    bc = c
    while bc > 1 and per_c * bc > _VMEM_BUDGET:
        bc -= 1
        while c % bc:
            bc -= 1
    return bc


def _upfirdn_body(x_ref, b_ref, o_ref, *, f, up, down, pad4, act, alpha,
                  gain):
    py0, py1, px0, px1 = pad4
    x = x_ref[0].astype(jnp.float32)                    # [H, W, bc]
    # ONE lax.pad: interior dilation = zero-insertion upsample, negative
    # edge padding = crop.  upfirdn places up-1 zeros AFTER every sample
    # (including the last) — interior dilation stops at the last sample,
    # so the missing trailing zeros fold into the high edge pad, exactly
    # like the XLA wrapper's lhs_dilation bookkeeping.
    xp = lax.pad(x, jnp.float32(0),
                 ((py0, py1 + up - 1, up - 1),
                  (px0, px1 + up - 1, up - 1),
                  (0, 0, 0)))
    fh, fw = f.shape
    oh = (xp.shape[0] - fh) // down + 1
    ow = (xp.shape[1] - fw) // down + 1
    bc = x.shape[-1]
    ff = f[::-1, ::-1]                                  # true convolution
    acc = jnp.zeros((oh, ow, bc), jnp.float32)
    for a in range(fh):                                 # static unroll
        for b in range(fw):
            tap = float(ff[a, b])
            if tap == 0.0:
                continue
            sl = lax.slice(xp, (a, b, 0),
                           (a + (oh - 1) * down + 1,
                            b + (ow - 1) * down + 1, bc),
                           (down, down, 1))
            acc = acc + tap * sl
    if act is not None:
        fn, _, _ = _EPILOGUES[act]
        acc = fn(acc + b_ref[0].astype(jnp.float32), alpha) * gain
    o_ref[0] = acc.astype(o_ref.dtype)


def _upfirdn_kernel(x_ref, b_ref, o_ref, **kw):
    _upfirdn_body(x_ref, b_ref, o_ref, **kw)


def _upfirdn_kernel_nobias(x_ref, o_ref, **kw):
    _upfirdn_body(x_ref, None, o_ref, **kw)


def _ufd_call(x: jax.Array, f: np.ndarray, up: int, down: int,
              pad4: Tuple[int, int, int, int], bias: Optional[jax.Array],
              act: Optional[str], alpha: float, gain: float,
              interpret: bool) -> jax.Array:
    n, h, w, c = x.shape
    fh, fw = f.shape
    oh, ow = _out_hw(h, w, fh, fw, up, down, pad4)
    bc = _pick_block_c(h, w, c, fh, fw, up, down, pad4)
    assert bc is not None, "caller must gate on upfirdn_fits()"
    grid = (n, c // bc)
    kern = functools.partial(
        _upfirdn_kernel if bias is not None else _upfirdn_kernel_nobias,
        f=f, up=up, down=down, pad4=pad4, act=act, alpha=alpha, gain=gain)
    in_specs = [pl.BlockSpec((1, h, w, bc), lambda i, j: (i, 0, 0, j),
                             memory_space=pltpu.VMEM)]
    args = [x]
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, bc), lambda i, j: (0, j),
                                     memory_space=pltpu.VMEM))
        args.append(bias.reshape(1, c))
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, oh, ow, c), x.dtype),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, oh, ow, bc), lambda i, j: (i, 0, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(*args)


def upfirdn_fits(x_shape: Tuple[int, ...], f_shape: Tuple[int, int],
                 up: int, down: int,
                 pad4: Tuple[int, int, int, int]) -> bool:
    """Static VMEM-fit verdict for this call — the dispatch gate callers
    use before choosing the pallas path (False → XLA composite)."""
    _, h, w, c = x_shape
    return _pick_block_c(h, w, c, f_shape[0], f_shape[1], up, down,
                         pad4) is not None


# --------------------------------------------------------------------------
# Derivative rules (PR-9 layering: custom_vjp over kernel-running
# custom_jvp composites; tangents are jnp/XLA reference glue).
# --------------------------------------------------------------------------


def _f_np(f_tup) -> np.ndarray:
    return np.asarray(f_tup, np.float32)


@functools.partial(jax.custom_jvp, nondiff_argnums=(1, 2, 3, 4, 5))
def _ufd_plain(x, f_tup, up, down, pad4, interpret):
    return _ufd_call(x, _f_np(f_tup), up, down, pad4, None, None, 0.0,
                     1.0, interpret)


@_ufd_plain.defjvp
def _ufd_plain_jvp(f_tup, up, down, pad4, interpret, primals, tangents):
    (x,), (tx,) = primals, tangents
    out = _ufd_plain(x, f_tup, up, down, pad4, interpret)
    # upfirdn is linear: the tangent is the op applied to the tangent —
    # via the XLA reference so further transforms (the reg programs'
    # transposes) stay closed.
    tan = _xla_upfirdn2d(tx, _f_np(f_tup), up=up, down=down, pad=pad4)
    return out, tan


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def _ufd(x, f_tup, up, down, pad4, gpad4, interpret):
    return _ufd_plain(x, f_tup, up, down, pad4, interpret)


def _ufd_fwd_rule(x, f_tup, up, down, pad4, gpad4, interpret):
    return _ufd(x, f_tup, up, down, pad4, gpad4, interpret), None


def _ufd_bwd_rule(f_tup, up, down, pad4, gpad4, interpret, res, ct):
    del res
    f_flip = tuple(tuple(row) for row in _f_np(f_tup)[::-1, ::-1])
    return (_ufd_plain(ct, f_flip, down, up, gpad4, interpret),)


_ufd.defvjp(_ufd_fwd_rule, _ufd_bwd_rule)


def _ref_with_epilogue(x, b, f_np, up, down, pad4, act, alpha, gain):
    from gansformer_tpu.ops.fused_bias_act import fused_bias_act

    y = _xla_upfirdn2d(x, f_np, up=up, down=down, pad=pad4)
    return fused_bias_act(y, b, act=act, alpha=alpha, gain=gain)


@functools.partial(jax.custom_jvp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8, 9))
def _ufd_ba_plain(x, b, f_tup, up, down, pad4, act, alpha, gain, interpret):
    return _ufd_call(x, _f_np(f_tup), up, down, pad4, b, act, alpha, gain,
                     interpret)


@_ufd_ba_plain.defjvp
def _ufd_ba_plain_jvp(f_tup, up, down, pad4, act, alpha, gain, interpret,
                      primals, tangents):
    out = _ufd_ba_plain(*primals, f_tup, up, down, pad4, act, alpha, gain,
                        interpret)
    _, tan = jax.jvp(
        lambda x, b: _ref_with_epilogue(x, b, _f_np(f_tup), up, down, pad4,
                                        act, alpha, gain),
        primals, tangents)
    return out, tan


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8, 9,
                                                    10))
def _ufd_ba(x, b, f_tup, up, down, pad4, gpad4, act, alpha, gain, interpret):
    return _ufd_ba_plain(x, b, f_tup, up, down, pad4, act, alpha, gain,
                         interpret)


def _ufd_ba_fwd_rule(x, b, f_tup, up, down, pad4, gpad4, act, alpha, gain,
                     interpret):
    y = _ufd_ba(x, b, f_tup, up, down, pad4, gpad4, act, alpha, gain,
                interpret)
    return y, (y,)


def _ufd_ba_bwd_rule(f_tup, up, down, pad4, gpad4, act, alpha, gain,
                     interpret, res, ct):
    # Activation recovery from the SAVED post-act output (lrelu keeps the
    # sign through the positive gain), then the linear adjoint kernel —
    # all glue is plain jnp, so R1/PL transposes close over this rule.
    (y,) = res
    _, _, dact = _EPILOGUES[act]
    du = (ct.astype(jnp.float32) * dact(y.astype(jnp.float32), alpha, gain)
          * gain)
    db = jnp.sum(du, axis=(0, 1, 2)).astype(jnp.float32)
    f_flip = tuple(tuple(row) for row in _f_np(f_tup)[::-1, ::-1])
    dx = _ufd_plain(du.astype(ct.dtype), f_flip, down, up, gpad4, interpret)
    return dx, db


_ufd_ba.defvjp(_ufd_ba_fwd_rule, _ufd_ba_bwd_rule)


# --------------------------------------------------------------------------
# Public op
# --------------------------------------------------------------------------


def upfirdn2d_pallas(x: jax.Array, f, up: int = 1, down: int = 1,
                     pad=0, *, bias: Optional[jax.Array] = None,
                     act: Optional[str] = None, alpha: float = 0.2,
                     gain: Optional[float] = None,
                     interpret: Optional[bool] = None) -> jax.Array:
    """Fused pad→FIR→resample kernel; drop-in for ``ops.upfirdn2d`` with
    an optional fused ``act(y + bias) * gain`` epilogue (linear/lrelu).

    ``f`` must be a static (numpy) filter — it always is in this
    codebase.  Differentiable to second order in ``x`` (and ``bias``);
    ``interpret=None`` auto-selects interpret mode off-TPU, mirroring
    ``models/attention.py``'s backend dispatch.
    """
    assert x.ndim == 4, "expected NHWC"
    f_np = np.asarray(f, np.float32)
    assert f_np.ndim == 2, "2D filter (setup_filter output) required"
    pad4 = _xla_pad4(pad)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, h, w, c = x.shape
    f_tup = tuple(tuple(float(v) for v in row) for row in f_np)
    gpad4 = grad_pad4(h, w, f_np.shape[0], f_np.shape[1], up, down, pad4)
    if act is None:
        assert bias is None, "bias without act: pass act='linear'"
        return _ufd(x, f_tup, up, down, pad4, gpad4, interpret)
    assert act in _EPILOGUES, (
        f"fused epilogue supports {sorted(_EPILOGUES)}, got {act!r} — "
        f"apply other activations via ops.fused_bias_act after the kernel")
    g = _EPILOGUES[act][1] if gain is None else gain
    b = (jnp.zeros((c,), jnp.float32) if bias is None
         else bias.astype(jnp.float32))
    return _ufd_ba(x, b, f_tup, up, down, pad4, gpad4, act, alpha, float(g),
                   interpret)
