"""Pallas TPU kernel for upfirdn2d — pad → FIR → resample in ONE fused
kernel, differentiable to second order (``conv_backend='pallas'``).

The XLA path (``ops/upfirdn2d.py``) lowers the whole op to one
``conv_general_dilated``; this module is the hand-scheduled alternative
for the same semantics: per grid step the kernel loads one image block
into VMEM, performs zero-insertion + padding + cropping with a single
``lax.pad`` (interior dilation = the upsample, negative edges = the
crop), walks the FIR taps as strided VMEM slices accumulated in fp32,
and writes the decimated result — the padded intermediate and the
pre-decimation grid never touch HBM.  The filter is a static
compile-time constant (it always is in this codebase: blur taps from
``setup_filter``), so the tap loop fully unrolls.

Row blocking (halo streaming): when a whole image does not fit the VMEM
budget, ``upfirdn_plan`` tiles the OUTPUT row axis into ``bh``-row
strips.  Each strip reads an input row window through an
``pl.Unblocked`` BlockSpec whose index map returns element offsets, so
consecutive windows OVERLAP by the filter halo — no halo copies in HBM,
no extra specs.  The row algebra (``_row_geometry``): an output strip of
``bh`` rows spans ``we = (bh-1)*down + fh`` rows of the padded
zero-inserted grid; pre-padding the input with ``pa0 = ceil(py0/up)``
rows (negative = top crop) makes every window start at input row
``r*q`` with ``q = bh*down/up`` (alignment ``up | bh*down``), with a
constant phase residual ``c0 = pa0*up - py0 in [0, up)`` consumed
in-kernel as the tap start offset.  Whole-image mode is the ``bh = oh``
degenerate case of the same body.

Optional fused epilogue: ``act(y + bias) * gain`` (linear/lrelu) rides
the same kernel — the `_conv_transpose_poly → blur → fused_bias_act`
chain of the up-conv path collapses into kernels end to end.

Autodiff contract (the PR-9 pattern, ``ops/pallas_attention.py``):

* upfirdn is LINEAR in ``x``; its exact adjoint is another upfirdn with
  the flipped filter, ``up``/``down`` swapped, and the reference's
  gradient padding (the custom TF gradient of
  ``src/dnnlib/tflib/ops/upfirdn_2d.py``).  The outer ``jax.custom_vjp``
  therefore runs the SAME forward kernel for the backward pass — with
  its OWN row plan (``grows``), since the adjoint's geometry differs.
* The kernel composite is a ``jax.custom_jvp`` function whose rule
  computes the primal via the kernel (decorated recursion peels one
  transform level) and the tangent via the jnp/XLA reference — plain
  transposable glue, so R1 grad-of-grad and PL HVPs re-enter rules
  instead of dying at an untransposable ``pallas_call``.
* The filter is non-differentiable (a static resampling constant, as in
  the reference); ``bias`` is differentiable through saved-output
  activation recovery (lrelu is invertible given the sign).

This module is also the home of the conv family's shared planning
vocabulary: ``ConvPlan`` (typed whole/rows/fallback verdict, used by
``modconv_plan`` in ops/pallas_modconv.py as well) and
``note_conv_fallback`` (the dispatch seam's fallback counters) live
here because this is the lowest module in the conv import chain.

Tests run the kernels in interpret mode on CPU against the XLA op and
the numpy oracle (tests/test_pallas_conv.py); on TPU first use runs
``pallas_modconv.tpu_smoke_check`` (this kernel is part of the conv
family gate) and the CLIs fall back to the xla conv backend if Mosaic
lowering fails.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # importable on CPU builds

from gansformer_tpu.obs import registry as _obs_registry
from gansformer_tpu.ops.upfirdn2d import (_pad4 as _xla_pad4,
                                          upfirdn2d as _xla_upfirdn2d)

# Conservative per-invocation VMEM working-set budget (bytes).  Read at
# call time (tests shrink it to force row-blocking on small grids); the
# wrapper shrinks the channel block until the fp32 compute footprint of
# one grid step fits, and the planner shrinks the row block before that.
_VMEM_BUDGET = 9 * 2**20

_SQRT2 = math.sqrt(2.0)
# act -> (apply(pre), default gain, recover dpre/dy from the SAVED
# post-act output).  Only the activations the models actually fuse
# (models/layers.py uses linear + lrelu); everything else stays an XLA
# epilogue.
_EPILOGUES = {
    "linear": (lambda u, a: u, 1.0,
               lambda y, a, g: jnp.ones_like(y)),
    "lrelu": (lambda u, a: jnp.where(u >= 0, u, u * a), _SQRT2,
              lambda y, a, g: jnp.where(y >= 0, 1.0, a).astype(y.dtype)),
}


# --------------------------------------------------------------------------
# Planning vocabulary shared by the conv kernel family
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ConvPlan:
    """Static launch plan for one conv-family kernel call.

    ``mode`` is ``'whole'`` (the full image double-buffers in VMEM),
    ``'rows'`` (stream ``rows``-row output strips with a halo window),
    or ``'fallback'`` (typed refusal: ``cause='vmem'`` when even a
    single row strip overflows the budget, ``cause='shape'`` when the
    kernel family does not implement the shape at all).
    """

    mode: str
    rows: Optional[int] = None
    cause: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.mode != "fallback"


_FALLBACK_CAUSES = ("shape", "vmem")


def note_conv_fallback(cause: str) -> None:
    """Count one conv-family XLA fallback at the dispatch seam.

    Emits ``ops/modconv_fallback_total`` plus a per-cause counter
    (``.._shape_total`` / ``.._vmem_total``) — the registry is
    name-keyed, so the label rides the name.  Incremented at trace
    time; a coverage regression therefore shows up in every prom
    scrape of a run that compiled a fallback, not only in a TPU A/B.
    """
    assert cause in _FALLBACK_CAUSES, cause
    _obs_registry.counter("ops/modconv_fallback_total").inc()
    _obs_registry.counter(f"ops/modconv_fallback_{cause}_total").inc()


def _divisors_desc(n: int):
    return sorted((d for d in range(1, n + 1) if n % d == 0), reverse=True)


def _out_hw(h: int, w: int, fh: int, fw: int, up: int, down: int,
            pad4: Tuple[int, int, int, int]) -> Tuple[int, int]:
    py0, py1, px0, px1 = pad4
    oh = (h * up + py0 + py1 - fh) // down + 1
    ow = (w * up + px0 + px1 - fw) // down + 1
    assert oh > 0 and ow > 0, (h, w, fh, fw, up, down, pad4)
    return oh, ow


def grad_pad4(in_h: int, in_w: int, fh: int, fw: int, up: int, down: int,
              pad4: Tuple[int, int, int, int]) -> Tuple[int, int, int, int]:
    """Padding of the adjoint upfirdn (flipped filter, up↔down swapped) —
    the reference custom gradient's pad algebra, validated against
    ``jax.grad`` of the XLA op in tests/test_pallas_conv.py."""
    py0, py1, px0, px1 = pad4
    oh, ow = _out_hw(in_h, in_w, fh, fw, up, down, pad4)
    return (fh - py0 - 1, in_h * up - oh * down + py0 - up + 1,
            fw - px0 - 1, in_w * up - ow * down + px0 - up + 1)


def _row_geometry(bh: int, fh: int, up: int, down: int, py0: int):
    """Static per-strip row algebra (derivation in docs/pallas.md).

    Returns ``(q, we, pa0, c0, rows_in)``: input rows advanced per
    strip, padded-grid rows one strip reads, the top pre-pad (negative
    = crop), the phase residual consumed as the in-kernel tap offset,
    and the input-window row count.
    """
    assert (bh * down) % up == 0, (bh, up, down)
    q = bh * down // up
    we = (bh - 1) * down + fh
    pa0 = -((-py0) // up)
    c0 = pa0 * up - py0
    rows_in = -(-(we + c0) // up)
    return q, we, pa0, c0, rows_in


def _per_c_bytes(h: int, w: int, fh: int, fw: int, up: int, down: int,
                 pad4: Tuple[int, int, int, int],
                 bh: Optional[int] = None) -> int:
    """fp32 one-step VMEM footprint per channel: input window + padded
    zero-inserted intermediate + output strip (double-counted for the
    tap accumulator).  ``bh=None`` = whole image."""
    oh, ow = _out_hw(h, w, fh, fw, up, down, pad4)
    pw = w * up + max(pad4[2], 0) + max(pad4[3], 0)
    if bh is None:
        ph = h * up + max(pad4[0], 0) + max(pad4[1], 0)
        return 4 * (h * w + ph * pw + 2 * oh * ow)
    _, _, _, _, rows_in = _row_geometry(bh, fh, up, down, pad4[0])
    return 4 * (rows_in * w + rows_in * up * pw + 2 * bh * ow)


def upfirdn_plan(x_shape: Tuple[int, ...], f_shape: Tuple[int, int],
                 up: int, down: int,
                 pad4: Tuple[int, int, int, int]) -> ConvPlan:
    """Row-block planner for one upfirdn launch: whole image when it
    double-buffers within the budget, else the LARGEST output-row strip
    ``bh | oh`` with ``up | bh*down`` whose window fits; typed vmem
    fallback only when a single-row strip still overflows."""
    _, h, w, c = x_shape
    fh, fw = f_shape
    if _per_c_bytes(h, w, fh, fw, up, down, pad4) <= _VMEM_BUDGET:
        return ConvPlan("whole")
    oh, _ = _out_hw(h, w, fh, fw, up, down, pad4)
    for bh in _divisors_desc(oh):
        if bh == oh or (bh * down) % up:
            continue
        if _per_c_bytes(h, w, fh, fw, up, down, pad4, bh) <= _VMEM_BUDGET:
            return ConvPlan("rows", rows=bh)
    return ConvPlan("fallback", cause="vmem")


def _pick_block_c(per_c: int, c: int) -> Optional[int]:
    """Largest divisor of ``c`` whose one-step fp32 footprint fits the
    budget; None = does not fit even at one channel."""
    if per_c > _VMEM_BUDGET:
        return None
    bc = c
    while bc > 1 and per_c * bc > _VMEM_BUDGET:
        bc -= 1
        while c % bc:
            bc -= 1
    return bc


def upfirdn_fits(x_shape: Tuple[int, ...], f_shape: Tuple[int, int],
                 up: int, down: int,
                 pad4: Tuple[int, int, int, int]) -> bool:
    """Static verdict for this call — True iff BOTH the forward launch
    and its adjoint (the backward kernel reuses the forward with
    up↔down swapped) have an ok plan.  The dispatch gate callers use
    before choosing the pallas path (False → XLA composite)."""
    _, h, w, c = x_shape
    fh, fw = f_shape
    if not upfirdn_plan(x_shape, f_shape, up, down, pad4).ok:
        return False
    oh, ow = _out_hw(h, w, fh, fw, up, down, pad4)
    gpad4 = grad_pad4(h, w, fh, fw, up, down, pad4)
    return upfirdn_plan((x_shape[0], oh, ow, c), f_shape, down, up,
                        gpad4).ok


# --------------------------------------------------------------------------
# Kernel body + launch
# --------------------------------------------------------------------------


def _upfirdn_body(x_ref, b_ref, o_ref, *, f, up, down, rpad, cpad, r0, obh,
                  act, alpha, gain):
    x = x_ref[0].astype(jnp.float32)                    # [rows_in, W, bc]
    # ONE lax.pad: interior dilation = zero-insertion upsample, negative
    # edge padding = crop.  upfirdn places up-1 zeros AFTER every sample
    # (including the last) — interior dilation stops at the last sample,
    # so the missing trailing zeros fold into the high edge pad, exactly
    # like the XLA wrapper's lhs_dilation bookkeeping.  Row-blocked
    # strips arrive pre-padded/cropped (the wrapper's pa0/pa1), so their
    # row edge pads are just the trailing zero-insertion.
    xp = lax.pad(x, jnp.float32(0),
                 ((rpad[0], rpad[1], up - 1),
                  (cpad[0], cpad[1], up - 1),
                  (0, 0, 0)))
    fh, fw = f.shape
    ow = (xp.shape[1] - fw) // down + 1
    bc = x.shape[-1]
    ff = f[::-1, ::-1]                                  # true convolution
    acc = jnp.zeros((obh, ow, bc), jnp.float32)
    for a in range(fh):                                 # static unroll
        for b in range(fw):
            tap = float(ff[a, b])
            if tap == 0.0:
                continue
            # r0 = phase residual c0 in blocked mode (0 whole-image):
            # local padded row r0 + t*down + a is global padded row
            # r*bh*down + t*down + a for output strip row t.
            sl = lax.slice(xp, (r0 + a, b, 0),
                           (r0 + a + (obh - 1) * down + 1,
                            b + (ow - 1) * down + 1, bc),
                           (down, down, 1))
            acc = acc + tap * sl
    if act is not None:
        fn, _, _ = _EPILOGUES[act]
        acc = fn(acc + b_ref[0].astype(jnp.float32), alpha) * gain
    o_ref[0] = acc.astype(o_ref.dtype)


def _upfirdn_kernel(x_ref, b_ref, o_ref, **kw):
    _upfirdn_body(x_ref, b_ref, o_ref, **kw)


def _upfirdn_kernel_nobias(x_ref, o_ref, **kw):
    _upfirdn_body(x_ref, None, o_ref, **kw)


def _ufd_call(x: jax.Array, f: np.ndarray, up: int, down: int,
              pad4: Tuple[int, int, int, int], bias: Optional[jax.Array],
              act: Optional[str], alpha: float, gain: float,
              rows: Optional[int], interpret: bool) -> jax.Array:
    n, h, w, c = x.shape
    fh, fw = f.shape
    oh, ow = _out_hw(h, w, fh, fw, up, down, pad4)
    py0, py1, px0, px1 = pad4
    if rows is not None and rows >= oh:
        rows = None                                     # degenerate: whole
    kern_fn = _upfirdn_kernel if bias is not None else _upfirdn_kernel_nobias
    cpad = (px0, px1 + up - 1)
    if rows is None:
        per_c = _per_c_bytes(h, w, fh, fw, up, down, pad4)
        bc = _pick_block_c(per_c, c)
        assert bc is not None, "caller must gate on upfirdn_fits()"
        kern = functools.partial(
            kern_fn, f=f, up=up, down=down, rpad=(py0, py1 + up - 1),
            cpad=cpad, r0=0, obh=oh, act=act, alpha=alpha, gain=gain)
        grid = (n, c // bc)
        in_specs = [pl.BlockSpec((1, h, w, bc), lambda i, j: (i, 0, 0, j),
                                 memory_space=pltpu.VMEM)]
        args = [x]
        if bias is not None:
            in_specs.append(pl.BlockSpec((1, bc), lambda i, j: (0, j),
                                         memory_space=pltpu.VMEM))
            args.append(bias.reshape(1, c))
        return pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct((n, oh, ow, c), x.dtype),
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, oh, ow, bc),
                                   lambda i, j: (i, 0, 0, j),
                                   memory_space=pltpu.VMEM),
            interpret=interpret,
        )(*args)
    # Row-blocked launch: output strips of bh rows; input windows of
    # rows_in rows at element offset r*q through an Unblocked spec, so
    # consecutive windows overlap by the halo.  Row pads/crops (pa0/pa1)
    # are applied ONCE in HBM here — inside the custom_jvp primal, so
    # autodiff never sees them.
    bh = rows
    assert oh % bh == 0 and (bh * down) % up == 0, (oh, bh, up, down)
    q, _, pa0, c0, rows_in = _row_geometry(bh, fh, up, down, py0)
    nb = oh // bh
    xr = x
    if pa0 > 0:
        xr = jnp.pad(xr, ((0, 0), (pa0, 0), (0, 0), (0, 0)))
    elif pa0 < 0:
        xr = xr[:, -pa0:]
    pa1 = (nb - 1) * q + rows_in - (h + pa0)
    if pa1 > 0:
        xr = jnp.pad(xr, ((0, 0), (0, pa1), (0, 0), (0, 0)))
    per_c = _per_c_bytes(h, w, fh, fw, up, down, pad4, bh)
    bc = _pick_block_c(per_c, c)
    assert bc is not None, "caller must gate on upfirdn_fits()"
    kern = functools.partial(
        kern_fn, f=f, up=up, down=down, rpad=(0, up - 1), cpad=cpad,
        r0=c0, obh=bh, act=act, alpha=alpha, gain=gain)
    grid = (n, c // bc, nb)
    in_specs = [pl.BlockSpec((1, rows_in, w, bc),
                             lambda i, j, r: (i, r * q, 0, j * bc),
                             indexing_mode=pl.Unblocked(),
                             memory_space=pltpu.VMEM)]
    args = [xr]
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, bc), lambda i, j, r: (0, j),
                                     memory_space=pltpu.VMEM))
        args.append(bias.reshape(1, c))
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, oh, ow, c), x.dtype),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bh, ow, bc),
                               lambda i, j, r: (i, r, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(*args)


# --------------------------------------------------------------------------
# Derivative rules (PR-9 layering: custom_vjp over kernel-running
# custom_jvp composites; tangents are jnp/XLA reference glue).  The row
# plans (``rows`` for the forward launch, ``grows`` for the adjoint's
# own launch) ride the nondiff statics so every re-entry — including
# the R1/PL second-order paths — lands on a planned kernel.
# --------------------------------------------------------------------------


def _f_np(f_tup) -> np.ndarray:
    return np.asarray(f_tup, np.float32)


@functools.partial(jax.custom_jvp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def _ufd_plain(x, f_tup, up, down, pad4, rows, interpret):
    return _ufd_call(x, _f_np(f_tup), up, down, pad4, None, None, 0.0,
                     1.0, rows, interpret)


@_ufd_plain.defjvp
def _ufd_plain_jvp(f_tup, up, down, pad4, rows, interpret, primals,
                   tangents):
    (x,), (tx,) = primals, tangents
    out = _ufd_plain(x, f_tup, up, down, pad4, rows, interpret)
    # upfirdn is linear: the tangent is the op applied to the tangent —
    # via the XLA reference so further transforms (the reg programs'
    # transposes) stay closed.
    tan = _xla_upfirdn2d(tx, _f_np(f_tup), up=up, down=down, pad=pad4)
    return out, tan


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6, 7, 8))
def _ufd(x, f_tup, up, down, pad4, gpad4, rows, grows, interpret):
    return _ufd_plain(x, f_tup, up, down, pad4, rows, interpret)


def _ufd_fwd_rule(x, f_tup, up, down, pad4, gpad4, rows, grows, interpret):
    return _ufd(x, f_tup, up, down, pad4, gpad4, rows, grows,
                interpret), None


def _ufd_bwd_rule(f_tup, up, down, pad4, gpad4, rows, grows, interpret,
                  res, ct):
    del res
    f_flip = tuple(tuple(row) for row in _f_np(f_tup)[::-1, ::-1])
    return (_ufd_plain(ct, f_flip, down, up, gpad4, grows, interpret),)


_ufd.defvjp(_ufd_fwd_rule, _ufd_bwd_rule)


def _ref_with_epilogue(x, b, f_np, up, down, pad4, act, alpha, gain):
    from gansformer_tpu.ops.fused_bias_act import fused_bias_act

    y = _xla_upfirdn2d(x, f_np, up=up, down=down, pad=pad4)
    return fused_bias_act(y, b, act=act, alpha=alpha, gain=gain)


@functools.partial(jax.custom_jvp,
                   nondiff_argnums=(2, 3, 4, 5, 6, 7, 8, 9, 10))
def _ufd_ba_plain(x, b, f_tup, up, down, pad4, rows, act, alpha, gain,
                  interpret):
    return _ufd_call(x, _f_np(f_tup), up, down, pad4, b, act, alpha, gain,
                     rows, interpret)


@_ufd_ba_plain.defjvp
def _ufd_ba_plain_jvp(f_tup, up, down, pad4, rows, act, alpha, gain,
                      interpret, primals, tangents):
    out = _ufd_ba_plain(*primals, f_tup, up, down, pad4, rows, act, alpha,
                        gain, interpret)
    _, tan = jax.jvp(
        lambda x, b: _ref_with_epilogue(x, b, _f_np(f_tup), up, down, pad4,
                                        act, alpha, gain),
        primals, tangents)
    return out, tan


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12))
def _ufd_ba(x, b, f_tup, up, down, pad4, gpad4, rows, grows, act, alpha,
            gain, interpret):
    return _ufd_ba_plain(x, b, f_tup, up, down, pad4, rows, act, alpha,
                         gain, interpret)


def _ufd_ba_fwd_rule(x, b, f_tup, up, down, pad4, gpad4, rows, grows, act,
                     alpha, gain, interpret):
    y = _ufd_ba(x, b, f_tup, up, down, pad4, gpad4, rows, grows, act,
                alpha, gain, interpret)
    return y, (y,)


def _ufd_ba_bwd_rule(f_tup, up, down, pad4, gpad4, rows, grows, act, alpha,
                     gain, interpret, res, ct):
    # Activation recovery from the SAVED post-act output (lrelu keeps the
    # sign through the positive gain), then the linear adjoint kernel —
    # all glue is plain jnp, so R1/PL transposes close over this rule.
    (y,) = res
    _, _, dact = _EPILOGUES[act]
    du = (ct.astype(jnp.float32) * dact(y.astype(jnp.float32), alpha, gain)
          * gain)
    db = jnp.sum(du, axis=(0, 1, 2)).astype(jnp.float32)
    f_flip = tuple(tuple(row) for row in _f_np(f_tup)[::-1, ::-1])
    dx = _ufd_plain(du.astype(ct.dtype), f_flip, down, up, gpad4, grows,
                    interpret)
    return dx, db


_ufd_ba.defvjp(_ufd_ba_fwd_rule, _ufd_ba_bwd_rule)


# --------------------------------------------------------------------------
# Public op
# --------------------------------------------------------------------------


def upfirdn2d_pallas(x: jax.Array, f, up: int = 1, down: int = 1,
                     pad=0, *, bias: Optional[jax.Array] = None,
                     act: Optional[str] = None, alpha: float = 0.2,
                     gain: Optional[float] = None,
                     block_rows: Optional[int] = None,
                     interpret: Optional[bool] = None) -> jax.Array:
    """Fused pad→FIR→resample kernel; drop-in for ``ops.upfirdn2d`` with
    an optional fused ``act(y + bias) * gain`` epilogue (linear/lrelu).

    ``f`` must be a static (numpy) filter — it always is in this
    codebase.  Differentiable to second order in ``x`` (and ``bias``);
    ``interpret=None`` auto-selects interpret mode off-TPU, mirroring
    ``models/attention.py``'s backend dispatch.  Row blocking comes
    from ``upfirdn_plan`` (the adjoint plans its own rows);
    ``block_rows`` overrides the FORWARD launch's row strip — a test
    hook for blocked-vs-whole parity, not a tuning surface.
    """
    assert x.ndim == 4, "expected NHWC"
    f_np = np.asarray(f, np.float32)
    assert f_np.ndim == 2, "2D filter (setup_filter output) required"
    pad4 = _xla_pad4(pad)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, h, w, c = x.shape
    fh, fw = f_np.shape
    f_tup = tuple(tuple(float(v) for v in row) for row in f_np)
    oh, ow = _out_hw(h, w, fh, fw, up, down, pad4)
    gpad4 = grad_pad4(h, w, fh, fw, up, down, pad4)
    plan = upfirdn_plan(x.shape, f_np.shape, up, down, pad4)
    gplan = upfirdn_plan((n, oh, ow, c), f_np.shape, down, up, gpad4)
    assert plan.ok and gplan.ok, "caller must gate on upfirdn_fits()"
    rows = plan.rows if block_rows is None else block_rows
    grows = gplan.rows
    if act is None:
        assert bias is None, "bias without act: pass act='linear'"
        return _ufd(x, f_tup, up, down, pad4, gpad4, rows, grows, interpret)
    assert act in _EPILOGUES, (
        f"fused epilogue supports {sorted(_EPILOGUES)}, got {act!r} — "
        f"apply other activations via ops.fused_bias_act after the kernel")
    g = _EPILOGUES[act][1] if gain is None else gain
    b = (jnp.zeros((c,), jnp.float32) if bias is None
         else bias.astype(jnp.float32))
    return _ufd_ba(x, b, f_tup, up, down, pad4, gpad4, rows, grows, act,
                   alpha, float(g), interpret)
