"""fused_bias_act — ``act(x + b) * gain`` (+ optional clamp).

TPU-native re-design of the reference's custom CUDA kernel
``src/dnnlib/tflib/ops/fused_bias_act.cu`` + wrapper (SURVEY.md §2.1).  The
reference hand-fuses bias-add and activation into one kernel and hand-writes
first- AND second-order gradients (the second order is needed because R1
differentiates through the discriminator's activations).

On TPU none of that machinery is needed: this is a pure ``jnp`` composite that
XLA fuses into the preceding matmul/conv (it is exactly the elementwise
epilogue fusion the hardware wants), and autodiff provides arbitrarily-high
derivative orders.  Keeping it a plain composite — rather than a custom_vjp —
is a deliberate choice (SURVEY.md §7.3 item 1): every custom rule would have
to be differentiable itself for R1/path-length to work.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

_SQRT2 = math.sqrt(2.0)

# name -> (fn(x, alpha), default_gain).  Matches the reference's activation
# table (linear/relu/lrelu/tanh/sigmoid/elu/selu/softplus/swish).
ACTIVATIONS = {
    "linear": (lambda x, a: x, 1.0),
    "relu": (lambda x, a: jnp.maximum(x, 0.0), _SQRT2),
    "lrelu": (lambda x, a: jnp.where(x >= 0, x, x * a), _SQRT2),
    "tanh": (lambda x, a: jnp.tanh(x), 1.0),
    "sigmoid": (lambda x, a: jax.nn.sigmoid(x), 1.0),
    "elu": (lambda x, a: jax.nn.elu(x), 1.0),
    "selu": (lambda x, a: jax.nn.selu(x), 1.0),
    "softplus": (lambda x, a: jax.nn.softplus(x), 1.0),
    "swish": (lambda x, a: jax.nn.silu(x), _SQRT2),
}


def fused_bias_act(x: jax.Array, b: Optional[jax.Array] = None,
                   act: str = "linear", alpha: float = 0.2,
                   gain: Optional[float] = None,
                   clamp: Optional[float] = None) -> jax.Array:
    """Apply ``act(x + b) * gain`` with the bias broadcast over the channel
    (last) axis; optionally clamp to ``[-clamp, clamp]``."""
    fn, def_gain = ACTIVATIONS[act]
    if b is not None:
        assert b.ndim == 1 and b.shape[0] == x.shape[-1]
        x = x + b.astype(x.dtype)
    x = fn(x, alpha)
    g = def_gain if gain is None else gain
    if g != 1.0:
        x = x * jnp.asarray(g, dtype=x.dtype)
    if clamp is not None:
        assert clamp >= 0
        x = jnp.clip(x, -clamp, clamp)
    return x
