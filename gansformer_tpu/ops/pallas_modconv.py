"""Pallas TPU kernels for the modulated-conv family — the last StyleGAN2
custom-CUDA-op family (SURVEY.md §2.1) lowered by hand instead of stock
XLA (``conv_backend='pallas'``, ROADMAP item 1's next attribution tier).

Three fused kernels, each a drop-in for one link of the
``ops/modulated_conv.py`` chain:

``same`` (3×3 / 1×1)  — **modulate → conv → demodulate** in one kernel:
    the per-sample style scale ``s`` (over in-channels) and the demod
    ``rsqrt`` scale ``d`` (over out-channels) are folded into the weight
    tile in fp32 and cast once, so the conv rides the MXU in the compute
    dtype (bf16 on the flagship) while the x·s and y·d elementwise
    round-trips never touch HBM.  The k² taps unroll as shifted VMEM
    slices feeding one [H·W, Ci]×[Ci, Co-block] matmul each, accumulated
    in fp32.  Optional fused ``act(y + bias) * gain`` epilogue
    (linear/lrelu — the only activations the models fuse).

``poly`` (up=2, 3×3)   — **polyphase up-conv + depth-to-space**: the
    four output phases are computed as 2×2-tap matmuls at the LOW
    resolution (``_conv_transpose_poly``'s math) and interleaved to the
    2H×2W grid inside the kernel — the [N, H, W, 4·Co] phase tensor of
    the XLA chain never exists in HBM.  The anti-imaging blur (+ the
    bias/act epilogue) then rides ``ops/pallas_upfirdn.py``'s fused
    kernel, completing the `_conv_transpose_poly → reshape →
    fused_bias_act` chain as kernels end to end.

backward kernels       — dx via the transposed conv through the SAME
    generic kernel (fold ``d`` into the adjoint weights, emit
    ``ds = Σ_hw x ⊙ u`` from the same pass), dw via a per-tap
    accumulation kernel (fp32 VMEM scratch across the batch grid axis,
    the dk/dv discipline of ``pallas_attention``).

Autodiff contract — the PR-9 layering, verbatim
(``ops/pallas_attention.py`` module docstring, docs/pallas.md):

* ``_mc_core`` is a ``jax.custom_vjp`` whose bwd runs the backward
  kernels — first-order reverse (the d/g step programs) executes
  kernels only.
* ``_mc_fwd`` / ``_mc_grads`` are ``jax.custom_jvp`` composites: primal
  via decorated recursion into the kernels, tangent via ``jax.jvp`` of
  the jnp reference (`_ref_*`) — transposable glue, so R1 grad-of-grad
  and PL HVPs re-enter rules.
* The demodulation coefficient ``d = rsqrt(Σ (w·s)² + ε)`` is computed
  OUTSIDE the custom rules by the same differentiable fp32 einsum the
  XLA path uses and passed as a traced argument — the chain rule routes
  the demod sensitivity (∂d/∂w, ∂d/∂s) through plain jnp autodiff, so
  the hand-written kernels only ever differentiate the multilinear core
  ``y = d ⊙ conv(s ⊙ x, w)``.

Row blocking (halo streaming): every launch site is planned by
``modconv_plan`` — whole-image when the per-sample block double-buffers
within the VMEM budget, else the LARGEST row block ``bh | h`` whose
(bh + kh − 1)-row halo window fits for ALL THREE kernels (training
needs fwd, dx/ds and dw on the same split).  Halo windows ride
``pl.Unblocked`` BlockSpecs whose index maps return element offsets, so
consecutive strips overlap by kh−1 rows with no halo copies in HBM;
``ds`` accumulates across row strips as a revisited output and ``dw``
extends its fp32 scratch accumulation over the (batch, rows) grid axes.
The whole-image launch is the degenerate ``bh = h`` case of the same
code path.  A typed ``ConvPlan`` fallback ('shape' for unimplemented
geometries, 'vmem' when even a single row strip overflows) routes to
the XLA composite and counts ``ops/modconv_fallback_total`` — with row
blocking landed, no ffhq256/ffhq1024 model shape takes that branch
(tests/test_pallas_conv.py walks them all).  On TPU, first use runs
``tpu_smoke_check`` (fwd AND bwd kernels, upfirdn and a row-blocked
strip included) and the CLIs fall back to ``conv_backend='xla'`` with
the printed reason if Mosaic lowering fails — the same discipline as
the attention backend.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # importable on CPU builds

from gansformer_tpu.ops.fused_bias_act import ACTIVATIONS, fused_bias_act
from gansformer_tpu.ops.modulated_conv import (_conv, _conv_transpose_poly,
                                               modulated_conv2d)
from gansformer_tpu.ops.pallas_upfirdn import (ConvPlan, _divisors_desc,
                                               note_conv_fallback,
                                               upfirdn_fits, upfirdn2d_pallas)
from gansformer_tpu.ops.upfirdn2d import filter_2d, setup_filter

# Per-invocation VMEM budget.  The per-sample image (or row-strip)
# block is double-buffered by the pipeline, so the fit rule below
# charges fixed (channel-unblocked) inputs TWICE against this.  Read at
# call time (tests shrink it to force row plans on small grids).
# ``modconv_plan`` shrinks the row block before `_fit_blocks` shrinks
# the channel block, so every grid the kernels implement is covered —
# a vmem fallback means a SINGLE row strip overflows.
_VMEM_BUDGET = 14 * 2**20

# Supported fused epilogues and their inverses (for the backward's
# activation recovery from the saved output; lrelu is sign-preserving
# under its positive gain, so act'(u) is a function of y).
_FUSED_ACTS = ("linear", "lrelu")


def _act_apply(y32, act, alpha, gain):
    fn, _ = ACTIVATIONS[act]
    return fn(y32, alpha) * gain


def _act_dy(y32, act, alpha):
    """act'(u) recovered from the post-act value y."""
    if act == "linear":
        return jnp.ones_like(y32)
    return jnp.where(y32 >= 0, 1.0, alpha)


def _act_inv(y32, act, alpha, gain):
    """u = act⁻¹(y / gain)."""
    y32 = y32 / gain
    if act == "linear":
        return y32
    return jnp.where(y32 >= 0, y32, y32 / alpha)


def _precision(dtype):
    return (lax.Precision.HIGHEST if dtype == jnp.float32
            else lax.Precision.DEFAULT)


def _fit_blocks(co: int, per_cb: int, fixed: int) -> Optional[int]:
    """Largest divisor of ``co`` with 2·fixed + per_cb·cb ≤ budget (the
    fixed whole-image block is double-buffered by the pipeline)."""
    if 2 * fixed + per_cb > _VMEM_BUDGET:
        return None
    cb = co
    while cb > 1 and 2 * fixed + per_cb * cb > _VMEM_BUDGET:
        cb -= 1
        while co % cb:
            cb -= 1
    return cb


# --------------------------------------------------------------------------
# Weight/tap preparation (wrapper-side jnp on the SMALL weight tensors)
# --------------------------------------------------------------------------


def _poly_w4(w: jax.Array) -> jax.Array:
    """[3,3,Ci,Co] → [4, Ci, Co*4] phase sub-kernels, tap-major, with the
    flattened output axis laid out co-OUTER / phase-INNER (co*4 + a*2 + b)
    so an output-channel block slice stays contiguous."""
    ci, co = w.shape[2], w.shape[3]
    w_pad = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))
    dh = np.arange(2)
    a = np.arange(2)
    rh = np.where(2 * dh[:, None] + 1 - a[None, :] < 3,
                  2 * dh[:, None] + 1 - a[None, :], 3)       # [dh, a]
    w4 = w_pad[rh[:, None, :, None], rh[None, :, None, :]]   # [dh,dw,a,b,Ci,Co]
    w4 = w4.transpose(0, 1, 4, 5, 2, 3)                      # [dh,dw,Ci,Co,a,b]
    return w4.reshape(2, 2, ci, co * 4).reshape(4, ci, co * 4)


# The (row, (dh, a)) inverse of the polyphase tap mapping for k=3: each
# real weight row r is read by exactly one (dh, a) pair (row 3 is the
# structural-zero pad).  Used to fold dw4 back to dw.
_POLY_ROW_SRC = {0: (0, 1), 1: (0, 0), 2: (1, 1)}


def _poly_dw_fold(dw4: jax.Array, ci: int, co: int) -> jax.Array:
    """[4, Ci, Co*4] tap-major phase grads → [3,3,Ci,Co] (inverse gather
    of the ``_poly_w4`` tap mapping; the pad row's grads are dropped —
    those taps are structural zeros)."""
    g = dw4.reshape(2, 2, ci, co, 2, 2)        # [dh,dw,Ci,Co,a,b]
    rows = []
    for r1 in range(3):
        dh1, a1 = _POLY_ROW_SRC[r1]
        cols = []
        for r2 in range(3):
            dh2, a2 = _POLY_ROW_SRC[r2]
            cols.append(g[dh1, dh2, :, :, a1, a2])
        rows.append(jnp.stack(cols, axis=0))
    return jnp.stack(rows, axis=0)             # [3,3,Ci,Co]


def _space_to_depth(du: jax.Array) -> jax.Array:
    """[N,2H,2W,Co] → [N,H,W,Co*4] with the co-outer/phase-inner layout
    matching ``_poly_w4``."""
    n, h2, w2, co = du.shape
    h, w = h2 // 2, w2 // 2
    return (du.reshape(n, h, 2, w, 2, co)
            .transpose(0, 1, 3, 5, 2, 4)
            .reshape(n, h, w, co * 4))


def _geom(kind: str):
    """(offs, pads, phases) of the forward kernel — static per kind."""
    if kind == "same3":
        return (tuple((a, b) for a in range(3) for b in range(3)),
                ((1, 1), (1, 1)), 1)
    if kind == "same1":
        return (((0, 0),), ((0, 0), (0, 0)), 1)
    assert kind == "poly"
    return (((0, 0), (0, 1), (1, 0), (1, 1)), ((0, 1), (0, 1)), 4)


def _prep(kind: str, w: jax.Array):
    """(offs, pads, phases, wstack [T, Cin_k, CoutK]) for the forward."""
    offs, pads, phases = _geom(kind)
    if kind == "same3":
        return offs, pads, phases, w.reshape(9, w.shape[2], w.shape[3])
    if kind == "same1":
        return offs, pads, phases, w.reshape(1, w.shape[2], w.shape[3])
    return offs, pads, phases, _poly_w4(w)


def _prep_adjoint(kind: str, w: jax.Array):
    """(offs, pads, wT [T, CoutK, Cin_k]) of the transposed conv the
    dx/ds kernel runs (spatial flip + channel transpose)."""
    ci, co = w.shape[2], w.shape[3]
    if kind == "same3":
        wf = jnp.flip(w, (0, 1)).transpose(0, 1, 3, 2)     # [3,3,Co,Ci]
        return (tuple((a, b) for a in range(3) for b in range(3)),
                ((1, 1), (1, 1)), wf.reshape(9, co, ci))
    if kind == "same1":
        return (((0, 0),), ((0, 0), (0, 0)),
                w.transpose(0, 1, 3, 2).reshape(1, co, ci))
    assert kind == "poly"
    w4 = _poly_w4(w).reshape(2, 2, ci, co * 4)             # tap [dh,dw]
    offs, wts = [], []
    for dh in range(2):
        for dw_ in range(2):
            offs.append((1 - dh, 1 - dw_))
            wts.append(w4[dh, dw_].T)                      # [Co*4, Ci]
    return (tuple(offs), ((1, 0), (1, 0)), jnp.stack(wts, axis=0))


# --------------------------------------------------------------------------
# Kernels
# --------------------------------------------------------------------------


def _fwd_body(x_ref, w_ref, pre_ref, post_ref, b_ref, o_ref, *, offs, oh,
              ow, phases, act, alpha, gain, precision):
    x = x_ref[0]                                         # [Hp, Wp, Ci]
    ci = x.shape[-1]
    pre = pre_ref[0].astype(jnp.float32)                 # [Ci]
    post = post_ref[0].astype(jnp.float32)               # [cbK]
    cbk = post.shape[0]
    acc = jnp.zeros((oh * ow, cbk), jnp.float32)
    for t, (oy, ox) in enumerate(offs):
        xt = lax.slice(x, (oy, ox, 0),
                       (oy + oh, ox + ow, ci)).reshape(oh * ow, ci)
        # Style + demod folded into the weight tile in fp32, cast ONCE to
        # the compute dtype — the conv itself rides the MXU in bf16.
        wt = (w_ref[t].astype(jnp.float32)
              * pre[:, None] * post[None, :]).astype(x.dtype)
        acc = acc + lax.dot(xt, wt, precision=precision,
                            preferred_element_type=jnp.float32)
    if phases == 4:
        cb = cbk // 4
        # depth-to-space interleave in VMEM: [oh,ow,cb,a,b] → [2oh,2ow,cb]
        y = (acc.reshape(oh, ow, cb, 2, 2)
             .transpose(0, 3, 1, 4, 2)
             .reshape(2 * oh, 2 * ow, cb))
    else:
        y = acc.reshape(oh, ow, cbk)
    if act is not None:
        y = _act_apply(y + b_ref[0].astype(jnp.float32), act, alpha, gain)
    o_ref[0] = y.astype(o_ref.dtype)


def _bwd_body(dy_ref, w_ref, pre_ref, post_ref, x_ref, dx_ref, ds_ref, *,
              offs, oh, ow, precision):
    dy = dy_ref[0]                                       # [Hp', Wp', CoK]
    cok = dy.shape[-1]
    pre = pre_ref[0].astype(jnp.float32)                 # [CoK] (demod d)
    post = post_ref[0].astype(jnp.float32)               # [cb]  (style s)
    cb = post.shape[0]
    u = jnp.zeros((oh * ow, cb), jnp.float32)
    for t, (oy, ox) in enumerate(offs):
        dt = lax.slice(dy, (oy, ox, 0),
                       (oy + oh, ox + ow, cok)).reshape(oh * ow, cok)
        wt = (w_ref[t].astype(jnp.float32) * pre[:, None]).astype(dy.dtype)
        u = u + lax.dot(dt, wt, precision=precision,
                        preferred_element_type=jnp.float32)
    # dx = s ⊙ u; ds = Σ_hw x ⊙ u — one pass, two outputs.  ds is a
    # REVISITED output over the innermost row-strip grid axis (its index
    # map ignores r, so the block stays resident): zero it on the first
    # strip, accumulate the strip partial on every one.
    dx_ref[0] = (u * post[None, :]).reshape(oh, ow, cb).astype(dx_ref.dtype)
    x = x_ref[0].reshape(oh * ow, cb).astype(jnp.float32)

    @pl.when(pl.program_id(2) == 0)
    def _init():
        ds_ref[0] = jnp.zeros_like(ds_ref[0])

    ds_ref[0] += jnp.sum(x * u, axis=0)


def _dw_body(x_ref, dy_ref, pre_ref, post_ref, dw_ref, acc_ref, *, offs,
             oh, ow, precision):
    # Accumulation spans the (batch, row-strip) grid axes — both iterate
    # inside one output-channel block (the out spec ignores i and r).
    i = pl.program_id(1)                 # batch index
    r = pl.program_id(2)                 # row strip (fastest grid axis)

    @pl.when((i == 0) & (r == 0))
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[0]                                         # [Hp, Wp, Ci]
    ci = x.shape[-1]
    pre = pre_ref[0].astype(jnp.float32)                 # [Ci]
    post = post_ref[0].astype(jnp.float32)               # [cbK]
    dy = dy_ref[0].reshape(oh * ow, post.shape[0])
    # The per-sample modulation scales FACTOR OUT of the spatial
    # contraction: dw_n[t] = (s ⊗ d) ⊙ (xᵀ dy) — applying the rank-1
    # scale to the [Ci, cb] tap result avoids materializing a modulated
    # copy of the whole image block in VMEM.
    scale = pre[:, None] * post[None, :]
    for t, (oy, ox) in enumerate(offs):
        xt = lax.slice(x, (oy, ox, 0),
                       (oy + oh, ox + ow, ci)).reshape(oh * ow, ci)
        acc_ref[t] += scale * lax.dot_general(
            xt, dy, dimension_numbers=(((0,), (0,)), ((), ())),
            precision=precision, preferred_element_type=jnp.float32)

    @pl.when((i == pl.num_programs(1) - 1) & (r == pl.num_programs(2) - 1))
    def _emit():
        dw_ref[:] = acc_ref[:].astype(dw_ref.dtype)


# --------------------------------------------------------------------------
# Kernel call wrappers (grid/blocking decided here, from static shapes)
# --------------------------------------------------------------------------


def _pad_hw(x, pads):
    (py0, py1), (px0, px1) = pads
    if py0 or py1 or px0 or px1:
        return jnp.pad(x, ((0, 0), (py0, py1), (px0, px1), (0, 0)))
    return x


def _itemsize(dt):
    return jnp.dtype(dt).itemsize


def _fwd_call(x, wstack, pre, post, b, *, offs, pads, phases, act,
              alpha, gain, rows, interpret):
    n, h, w, ci = x.shape
    t, _, cok_full = wstack.shape
    co = cok_full // phases
    oh, ow = h, w
    up = 2 if phases == 4 else 1
    xp = _pad_hw(x, pads)
    wp = xp.shape[2]
    it = _itemsize(x.dtype)
    # Row-strip launch; whole-image is the degenerate bh = oh case.  The
    # halo window (bh + row pads) enters through an Unblocked spec whose
    # index map returns ELEMENT offsets, so consecutive strips overlap.
    bh = oh if rows is None else rows
    assert oh % bh == 0, (oh, bh)
    nb = oh // bh
    prow = pads[0][0] + pads[0][1]
    win = bh + prow
    fixed = win * wp * ci * it
    per_cb = phases * (bh * ow * (4 + it)                # accumulator + out
                       + t * ci * (4 + it))              # weight tile + copy
    cb = _fit_blocks(co, per_cb, fixed)
    assert cb is not None, "caller must gate on modconv_plan()"
    cbk = cb * phases
    kern = functools.partial(
        _fwd_body, offs=offs, oh=bh, ow=ow, phases=phases, act=act,
        alpha=alpha, gain=gain, precision=_precision(x.dtype))
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, up * oh, up * ow, co), x.dtype),
        grid=(n, co // cb, nb),
        in_specs=[
            pl.BlockSpec((1, win, wp, ci), lambda i, j, r: (i, r * bh, 0, 0),
                         indexing_mode=pl.Unblocked(),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((t, ci, cbk), lambda i, j, r: (0, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ci), lambda i, j, r: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cbk), lambda i, j, r: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cb), lambda i, j, r: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, up * bh, up * ow, cb),
                               lambda i, j, r: (i, r, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(xp, wstack, pre, post, b.reshape(1, co))


def _bwd_call(du4, wT, pre, post, x, *, offs, pads, rows, interpret):
    """dx/ds of the core at cotangent ``du4`` (phase-folded for poly):
    the transposed conv through the generic kernel.  ``pre`` = demod d
    (over the adjoint's in-channels), ``post`` = style s (over Ci).
    Row strips stream the padded cotangent through a halo window; the
    ``ds`` output is revisited across the row axis (see ``_bwd_body``)."""
    n, h, w, ci = x.shape
    t, cok, _ = wT.shape
    dup = _pad_hw(du4, pads)
    wp = dup.shape[2]
    it = _itemsize(x.dtype)
    bh = h if rows is None else rows
    assert h % bh == 0, (h, bh)
    nb = h // bh
    prow = pads[0][0] + pads[0][1]
    win = bh + prow
    fixed = win * wp * cok * it
    per_cb = bh * w * (4 + 2 * it) + t * cok * (4 + it)
    cb = _fit_blocks(ci, per_cb, fixed)
    assert cb is not None, "caller must gate on modconv_plan()"
    kern = functools.partial(_bwd_body, offs=offs, oh=bh, ow=w,
                             precision=_precision(x.dtype))
    dx, ds = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct((n, h, w, ci), x.dtype),
                   jax.ShapeDtypeStruct((n, ci), jnp.float32)),
        grid=(n, ci // cb, nb),
        in_specs=[
            pl.BlockSpec((1, win, wp, cok), lambda i, j, r: (i, r * bh, 0, 0),
                         indexing_mode=pl.Unblocked(),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((t, cok, cb), lambda i, j, r: (0, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cok), lambda i, j, r: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cb), lambda i, j, r: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bh, w, cb), lambda i, j, r: (i, r, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(pl.BlockSpec((1, bh, w, cb), lambda i, j, r: (i, r, 0, j),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, cb), lambda i, j, r: (i, j),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(dup, wT, pre, post, x)
    return dx, ds


def _dw_call(x, du4, pre, post, *, offs, pads, t, rows, interpret,
             out_dtype):
    """dw of the core: per-tap [Ci, CoK] accumulation across the
    (batch, row-strip) grid axes in fp32 VMEM scratch (emitted at the
    last batch × last strip step)."""
    n, h, w, ci = x.shape
    cok = du4.shape[-1]
    xp = _pad_hw(x, pads)
    wp = xp.shape[2]
    it = _itemsize(x.dtype)
    bh = h if rows is None else rows
    assert h % bh == 0, (h, bh)
    nb = h // bh
    prow = pads[0][0] + pads[0][1]
    win = bh + prow
    fixed = win * wp * ci * it
    per_cb = bh * w * it + t * ci * 8                    # dy + acc/out
    cb = _fit_blocks(cok, per_cb, fixed)
    assert cb is not None, "caller must gate on modconv_plan()"
    kern = functools.partial(_dw_body, offs=offs, oh=bh, ow=w,
                             precision=_precision(x.dtype))
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((t, ci, cok), out_dtype),
        grid=(cok // cb, n, nb),
        in_specs=[
            pl.BlockSpec((1, win, wp, ci), lambda j, i, r: (i, r * bh, 0, 0),
                         indexing_mode=pl.Unblocked(),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bh, w, cb), lambda j, i, r: (i, r, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ci), lambda j, i, r: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cb), lambda j, i, r: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t, ci, cb), lambda j, i, r: (0, 0, j),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((t, ci, cb), jnp.float32)],
        interpret=interpret,
    )(xp, du4, pre, post)


def _family_checks(x_shape: Tuple[int, ...], w_shape: Tuple[int, ...],
                   up: int, itemsize: int, bh: int):
    """(fixed, per_cb) VMEM charges of the three kernels at row block
    ``bh`` — the SAME formulas the launch wrappers use, so the planner,
    the fit tests and bench attribution can't drift from the kernels.
    ``bh = h`` is the whole-image launch."""
    _, h, w, ci = x_shape
    kh = w_shape[0]
    co = w_shape[3]
    phases = 4 if up == 2 else 1
    t = 4 if up == 2 else kh * kh
    it = itemsize
    cok = co * phases
    if up == 2:
        # poly fwd pads ((0,1),(0,1)); poly adjoint (the space-to-depth
        # fold of the 2H×2W cotangent) pads ((1,0),(1,0))
        prow_f = prow_a = 1
        wp = wpa = w + 1
    else:
        prow_f = prow_a = kh - 1
        wp = wpa = w + kh - 1
    return [
        # fwd: x halo window + one-channel accumulator/weights/output
        ((bh + prow_f) * wp * ci * it,
         phases * (bh * w * (4 + it) + t * ci * (4 + it))),
        # bwd dx/ds: adjoint-input halo window (CoK channels) +
        # one-ci-channel strip
        ((bh + prow_a) * wpa * cok * it,
         bh * w * (4 + 2 * it) + t * cok * (4 + it)),
        # dw: x halo window + one-channel dy/acc (scales factor out — no
        # modulated image copy, see _dw_body)
        ((bh + prow_f) * wp * ci * it, bh * w * it + t * ci * 8),
    ]


def modconv_plan(x_shape: Tuple[int, ...], w_shape: Tuple[int, ...],
                 up: int = 1, itemsize: int = 4,
                 down: int = 1) -> ConvPlan:
    """Static launch plan for the kernel family at these shapes.

    'shape' fallback for geometries the kernels don't implement
    (down-sampling, kernels other than 1×1/3×3, up∉{1,2}); otherwise
    the LARGEST row block ``bh | h`` whose halo windows double-buffer
    within the budget for ALL THREE kernels (training needs fwd, dx/ds
    and dw on the same split) — 'whole' when ``bh = h`` fits, 'rows'
    below that, and a 'vmem' fallback only when even a single-row strip
    overflows.  Shared by the dispatcher, the fit tests and bench
    attribution."""
    kh, kw = int(w_shape[0]), int(w_shape[1])
    if not (down == 1 and kh == kw
            and ((up == 1 and kh in (1, 3)) or (up == 2 and kh == 3))):
        return ConvPlan("fallback", cause="shape")
    h = x_shape[1]
    for bh in _divisors_desc(h):
        if all(2 * fixed + per <= _VMEM_BUDGET
               for fixed, per in _family_checks(x_shape, w_shape, up,
                                                itemsize, bh)):
            return (ConvPlan("whole") if bh == h
                    else ConvPlan("rows", rows=bh))
    return ConvPlan("fallback", cause="vmem")


def modconv_fits(x_shape: Tuple[int, ...], w_shape: Tuple[int, ...],
                 up: int = 1, itemsize: int = 4) -> bool:
    """Compat shim over ``modconv_plan`` — True iff the family covers
    the shape (whole-image or row-blocked)."""
    return modconv_plan(x_shape, w_shape, up, itemsize).ok


# --------------------------------------------------------------------------
# jnp reference formulas (oracle + tangent glue)
# --------------------------------------------------------------------------


def _ref_core(x, w, s, d, kind):
    xs = x * s.astype(x.dtype)[:, None, None, :]
    y = (_conv_transpose_poly(xs, w) if kind == "poly"
         else _conv(xs, w.astype(x.dtype)))
    return y * d.astype(y.dtype)[:, None, None, :]


def _ref_full(x, w, s, d, b, kind, act, alpha, gain):
    y = _ref_core(x, w, s, d, kind)
    if act is None:
        return y
    return fused_bias_act(y, b, act=act, alpha=alpha, gain=gain)


def _ref_core_grads(x, w, s, d, du, kind):
    _, vjp = jax.vjp(
        lambda x_, w_, s_, d_: _ref_core(x_, w_, s_, d_, kind), x, w, s, d)
    dx, dw, ds, _ = vjp(du)
    return dx, dw, ds


# --------------------------------------------------------------------------
# Derivative rules (PR-9 layering)
# --------------------------------------------------------------------------


@functools.partial(jax.custom_jvp, nondiff_argnums=(5, 6))
def _mc_fwd(x, w, s, d, b, spec, interpret):
    kind, act, alpha, gain, rows = spec
    offs, pads, phases, wstack = _prep(kind, w)
    post = jnp.repeat(d, 4, axis=1) if kind == "poly" else d
    return _fwd_call(x, wstack, s, post, b, offs=offs, pads=pads,
                     phases=phases, act=act, alpha=alpha, gain=gain,
                     rows=rows, interpret=interpret)


@_mc_fwd.defjvp
def _mc_fwd_jvp(spec, interpret, primals, tangents):
    kind, act, alpha, gain, _ = spec
    out = _mc_fwd(*primals, spec, interpret)
    _, tan = jax.jvp(
        lambda x, w, s, d, b: _ref_full(x, w, s, d, b, kind, act, alpha,
                                        gain),
        primals, tangents)
    return out, tan


@functools.partial(jax.custom_jvp, nondiff_argnums=(5, 6, 7))
def _mc_grads(x, w, s, d, du, kind, rows, interpret):
    offs_a, pads_a, wT = _prep_adjoint(kind, w)
    offs_f, pads_f, _ = _geom(kind)
    if kind == "poly":
        du4 = _space_to_depth(du)
        pre = jnp.repeat(d, 4, axis=1)
    else:
        du4, pre = du, d
    dx, ds = _bwd_call(du4, wT, pre, s, x, offs=offs_a, pads=pads_a,
                       rows=rows, interpret=interpret)
    t = len(offs_f)
    dwt = _dw_call(x, du4, s, pre, offs=offs_f, pads=pads_f, t=t,
                   rows=rows, interpret=interpret, out_dtype=jnp.float32)
    if kind == "poly":
        dw = _poly_dw_fold(dwt, x.shape[-1], w.shape[3])
    else:
        dw = dwt.reshape(w.shape)
    return dx, dw.astype(w.dtype), ds


@_mc_grads.defjvp
def _mc_grads_jvp(kind, rows, interpret, primals, tangents):
    out = _mc_grads(*primals, kind, rows, interpret)
    _, tan = jax.jvp(
        lambda x, w, s, d, du: _ref_core_grads(x, w, s, d, du, kind),
        primals, tangents)
    return out, tan


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _mc_core(x, w, s, d, b, spec, interpret):
    return _mc_fwd(x, w, s, d, b, spec, interpret)


def _mc_core_fwd(x, w, s, d, b, spec, interpret):
    y = _mc_fwd(x, w, s, d, b, spec, interpret)
    return y, (x, w, s, d, b, y)


def _mc_core_bwd(spec, interpret, res, ct):
    kind, act, alpha, gain, rows = spec
    x, w, s, d, b, y = res
    y32 = y.astype(jnp.float32)
    ct32 = ct.astype(jnp.float32)
    if act is None:
        du32, db, c = ct32, jnp.zeros_like(b), y32
    else:
        # Activation recovery from the saved output (plain jnp glue —
        # transposable, so the reg programs' second-order passes close).
        du32 = ct32 * _act_dy(y32, act, alpha) * gain
        db = jnp.sum(du32, axis=(0, 1, 2)).astype(b.dtype)
        c = _act_inv(y32, act, alpha, gain) - b.astype(jnp.float32)
    # dd = Σ_hw du ⊙ conv(s⊙x, w) — the pre-demod conv recovered from the
    # saved output (c = y_core = d ⊙ conv), so no recompute pass.
    dd = (jnp.sum(du32 * c, axis=(1, 2))
          / d.astype(jnp.float32)).astype(d.dtype)
    dx, dw, ds = _mc_grads(x, w, s, d, du32.astype(ct.dtype), kind, rows,
                           interpret)
    return dx, dw, ds.astype(s.dtype), dd, db


_mc_core.defvjp(_mc_core_fwd, _mc_core_bwd)


# --------------------------------------------------------------------------
# Public op — drop-in for ops.modulated_conv.modulated_conv2d
# --------------------------------------------------------------------------


def modulated_conv2d_pallas(
    x: jax.Array,                 # [N, H, W, Cin]
    w: jax.Array,                 # [kh, kw, Cin, Cout]
    styles: jax.Array,            # [N, Cin]
    demodulate: bool = True,
    up: int = 1,
    down: int = 1,
    resample_filter=(1, 3, 3, 1),
    eps: float = 1e-8,
    *,
    bias: Optional[jax.Array] = None,
    act: Optional[str] = None,
    alpha: float = 0.2,
    gain: Optional[float] = None,
    block_rows: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused modulate→conv→demodulate through the Pallas kernel family,
    with an optional fused ``act(y + bias) * gain`` epilogue.

    Same math as ``modulated_conv2d`` (+ ``fused_bias_act`` when the
    epilogue is passed); differentiable to second order.  Launches are
    planned by ``modconv_plan`` (whole-image or halo row strips);
    unsupported geometries (down-sampling, kernels other than 1×1/3×3,
    up∉{1,2}) and grids where even a single row strip overflows VMEM
    fall back to the XLA composite per call, counting
    ``ops/modconv_fallback_total`` by cause.  ``block_rows`` overrides
    the planned row block for the whole kernel family — a test hook for
    blocked-vs-whole parity, not a tuning surface.
    """
    assert x.ndim == 4 and w.ndim == 4 and styles.ndim == 2
    n, _, _, cin = x.shape
    kh, kw = w.shape[0], w.shape[1]
    co = w.shape[3]
    assert w.shape[2] == cin and styles.shape == (n, cin)
    # Same contract as upfirdn2d_pallas, enforced on EVERY dispatch path
    # (a bias would otherwise be silently dropped on the act-less kernel
    # epilogue): a caller porting from fused_bias_act must say
    # act='linear' explicitly.
    assert act is not None or bias is None, \
        "bias without act: pass act='linear'"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if act is not None and act not in _FUSED_ACTS:
        y = modulated_conv2d_pallas(
            x, w, styles, demodulate=demodulate, up=up, down=down,
            resample_filter=resample_filter, eps=eps,
            block_rows=block_rows, interpret=interpret)
        return fused_bias_act(y, bias, act=act, alpha=alpha, gain=gain)
    plan = modconv_plan(x.shape, w.shape, up, jnp.dtype(x.dtype).itemsize,
                        down=down)
    if not plan.ok:
        note_conv_fallback(plan.cause)
        y = modulated_conv2d(x, w, styles, demodulate=demodulate, up=up,
                             down=down, resample_filter=resample_filter,
                             eps=eps)
        if act is not None:
            y = fused_bias_act(y, bias, act=act, alpha=alpha, gain=gain)
        return y
    rows = (plan.rows if block_rows is None
            else (block_rows if block_rows < x.shape[1] else None))

    # Demod coefficients by the SAME differentiable fp32 einsum as the
    # XLA path — passed as a traced arg so the custom rules only handle
    # the multilinear core (module docstring).
    s32 = styles.astype(jnp.float32)
    if demodulate:
        sigma = jnp.einsum("hwio,ni->no", jnp.square(w.astype(jnp.float32)),
                           jnp.square(s32), precision=lax.Precision.HIGHEST)
        d = lax.rsqrt(sigma + eps)
    else:
        d = jnp.ones((n, co), jnp.float32)

    g = (ACTIVATIONS[act][1] if act is not None and gain is None
         else (gain if gain is not None else 1.0))
    b32 = (jnp.zeros((co,), jnp.float32) if bias is None
           else bias.astype(jnp.float32))

    if up == 1:
        kind = "same1" if kh == 1 else "same3"
        spec = (kind, act, alpha, float(g), rows)
        return _mc_core(x, w, s32, d, b32, spec, interpret)

    # up == 2: fused polyphase + depth-to-space kernel, demod folded,
    # then the anti-imaging blur (+ the epilogue) on the fused upfirdn
    # kernel — the full XLA chain `_conv_transpose_poly → reshape →
    # filter_2d → fused_bias_act` as kernels end to end.
    y = _mc_core(x, w, s32, d, jnp.zeros((co,), jnp.float32),
                 ("poly", None, alpha, 1.0, rows), interpret)
    f = setup_filter(resample_filter, gain=float(up * up))
    p = f.shape[0] - 1
    pad4 = ((p + 1) // 2, p // 2, (p + 1) // 2, p // 2)
    if upfirdn_fits(y.shape, f.shape, 1, 1, pad4):
        return upfirdn2d_pallas(y, f, pad=pad4, bias=bias, act=act,
                                alpha=alpha, gain=gain, interpret=interpret)
    note_conv_fallback("vmem")
    y = filter_2d(y, resample_filter, gain=float(up * up))
    if act is not None:
        y = fused_bias_act(y, bias, act=act, alpha=alpha, gain=gain)
    return y


# --------------------------------------------------------------------------
# First-use native-TPU verification gate + resolution (ADVICE r3 — the
# same discipline as ops.pallas_attention.resolve_backend)
# --------------------------------------------------------------------------

_TPU_SMOKE: dict = {}


def tpu_smoke_check(atol: float = 1e-2) -> tuple:
    """Native compile-and-compare of the conv kernel family (fwd AND the
    backward kernels via ``jax.grad``, upfirdn included) against the XLA
    composites at tiny shapes.  Memoized; returns ``(ok, detail)``."""
    if "ok" in _TPU_SMOKE:
        return _TPU_SMOKE["ok"], _TPU_SMOKE["detail"]
    import numpy as _np

    from gansformer_tpu.ops.upfirdn2d import upfirdn2d as _ufd_xla

    try:
        rng = _np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 8, 8, 8), jnp.float32)
        w = jnp.asarray(rng.randn(3, 3, 8, 16) * 0.2, jnp.float32)
        s = jnp.asarray(rng.randn(2, 8) * 0.3 + 1.0, jnp.float32)
        f = setup_filter((1, 3, 3, 1))
        diffs = []
        for up in (1, 2):
            ref = modulated_conv2d(x, w, s, up=up)
            got = modulated_conv2d_pallas(x, w, s, up=up, interpret=False)
            diffs.append(float(jnp.max(jnp.abs(got - ref))))

            def loss(fn):
                return lambda x_, w_, s_: jnp.sum(
                    jnp.square(fn(x_, w_, s_)))

            g_ref = jax.grad(loss(lambda *a: modulated_conv2d(*a, up=up)),
                             argnums=(0, 1, 2))(x, w, s)
            g_got = jax.grad(
                loss(lambda *a: modulated_conv2d_pallas(
                    *a, up=up, interpret=False)),
                argnums=(0, 1, 2))(x, w, s)
            diffs.append(max(float(jnp.max(jnp.abs(a - b)))
                             for a, b in zip(g_got, g_ref)))
        ref_u = _ufd_xla(x, f, up=2, pad=(2, 1))
        got_u = upfirdn2d_pallas(x, f, up=2, pad=(2, 1), interpret=False)
        diffs.append(float(jnp.max(jnp.abs(got_u - ref_u))))
        # Row-blocked strips (the Unblocked halo windows) must also
        # lower natively — exercise fwd + bwd on a forced 4-row plan.
        ref_r = modulated_conv2d(x, w, s, up=1)
        got_r = modulated_conv2d_pallas(x, w, s, up=1, block_rows=4,
                                        interpret=False)
        diffs.append(float(jnp.max(jnp.abs(got_r - ref_r))))
        g_ref = jax.grad(lambda x_: jnp.sum(jnp.square(
            modulated_conv2d(x_, w, s, up=1))))(x)
        g_got = jax.grad(lambda x_: jnp.sum(jnp.square(
            modulated_conv2d_pallas(x_, w, s, up=1, block_rows=4,
                                    interpret=False))))(x)
        diffs.append(float(jnp.max(jnp.abs(g_got - g_ref))))
        ok = max(diffs) < atol
        detail = (f"max_abs_diff modconv fwd/bwd up1={diffs[0]:.2e}/"
                  f"{diffs[1]:.2e} up2={diffs[2]:.2e}/{diffs[3]:.2e} "
                  f"upfirdn={diffs[4]:.2e} "
                  f"rowblock fwd/bwd={diffs[5]:.2e}/{diffs[6]:.2e} "
                  f"(atol {atol:g})")
    except Exception as e:  # Mosaic compile failures surface as many types
        ok = False
        detail = f"native compile/run failed: {type(e).__name__}: {e}"[:400]
    _TPU_SMOKE.update(ok=ok, detail=detail)
    return ok, detail


def resolve_conv_backend(requested: str) -> str:
    """'pallas' → 'pallas' only if safe on this backend, else 'xla' —
    the conv-family twin of ``pallas_attention.resolve_backend``."""
    if requested != "pallas":
        return requested
    if jax.default_backend() != "tpu":
        return "pallas"
    ok, detail = tpu_smoke_check()
    if ok:
        return "pallas"
    import sys

    print(f"[pallas] native TPU conv smoke check FAILED ({detail}); "
          f"falling back to the xla conv backend", file=sys.stderr)
    return "xla"
