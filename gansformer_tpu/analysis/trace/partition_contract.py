"""partition-contract — resolved shardings vs. the DECLARED intent.

``sharding-audit`` (the seedling this grew from) flags two generic
pathologies; this rule asserts the repo's actual layout design:
``parallel/contracts.py`` declares the intended ``PartitionSpec`` per
logical arg role (params, opt-state leaves, batch, rng) per entry
point, the harness lowers+compiles every entry on a simulated mesh
matrix (1/2/4 CPU devices via ``--xla_force_host_platform_device_
count``), and any resolved input, output, or donated-leaf sharding
that deviates from the contract is a finding.

Why compile instead of just reading the annotations: GSPMD propagates
shardings through the whole program, so an innocent-looking
``with_sharding_constraint`` (or a missing one) can silently re-shard
a donated state leaf or pin an output to a layout the loop never
intended — only the *compiled* program's resolved shardings tell the
truth.  Deviation on a DONATED leaf is double trouble: the intent is
broken AND XLA must copy instead of aliasing (same failure the
donation half of sharding-audit watches, here attributed to the
declared contract).
"""

from __future__ import annotations

from gansformer_tpu.analysis.trace.base import (
    EntryPoint, TraceContext, TraceRule, leaf_bytes, path_str, register,
    shardings_equivalent)


def _spec_str(sharding) -> str:
    """Compact resolved-sharding rendering: the spec when one exists
    (NamedSharding), else the full repr (GSPMD/op shardings)."""
    spec = getattr(sharding, "spec", None)
    return str(spec) if spec is not None else str(sharding)


@register
class PartitionContractRule(TraceRule):
    id = "partition-contract"
    description = ("compiled sharding deviates from the declared "
                   "PartitionSpec contract (parallel/contracts.py) for "
                   "an input, output, or donated leaf")
    hint = ("make the program resolve the declared spec (fix the "
            "constraint / input sharding), or change the contract in "
            "parallel/contracts.py if the new layout is intended")
    dynamic = True

    def check(self, ep: EntryPoint, ctx: TraceContext) -> None:
        import jax
        from jax.sharding import NamedSharding

        from gansformer_tpu.parallel.contracts import simulated_mesh

        contract = ctx.entry_contract(ep)
        if contract is None:
            ctx.notes.append(f"{ep.name}: no sharding contract declared "
                             f"(parallel/contracts.ENTRY_CONTRACTS); "
                             f"partition-contract skipped")
            return
        n_local = len(jax.devices())
        for n in ctx.mesh_sizes:
            if n > n_local:
                ctx.notes.append(
                    f"{ep.name}: {n}-device mesh needs "
                    f"XLA_FLAGS=--xla_force_host_platform_device_count="
                    f"{n} (have {n_local}); skipped")
                continue
            try:
                compiled, out_avals = ctx.compiled(ep, n)
            except Exception as e:
                ctx.report(self, ep.anchor,
                           f"{ep.name}: contract-sharded lowering failed "
                           f"on the {n}-device mesh: {type(e).__name__}: "
                           f"{str(e)[:160]}")
                continue
            self._check_one(ep, ctx, contract, compiled, out_avals,
                            simulated_mesh(n), NamedSharding)

    def _check_one(self, ep, ctx, contract, compiled, out_avals, env,
                   NamedSharding) -> None:
        import jax

        from gansformer_tpu.parallel.contracts import (
            arg_leaf_contracts, out_leaf_contracts)

        # -- inputs ----------------------------------------------------------
        # data_size lets FSDP-sentinel roles resolve per-leaf specs
        leaf_info = arg_leaf_contracts(contract, ep.abstract_args,
                                       data_size=env.data_size)
        flat_in, _ = jax.tree_util.tree_flatten(compiled.input_shardings[0])
        in_leaves = [l for _, l in
                     jax.tree_util.tree_flatten_with_path(
                         ep.abstract_args)[0]]
        if len(flat_in) != len(leaf_info) or len(in_leaves) != len(flat_in):
            ctx.notes.append(f"{ep.name}: input arity mismatch vs "
                             f"contract ({len(flat_in)} resolved, "
                             f"{len(leaf_info)} declared); skipped")
            return
        donated = set(ep.donate_argnums)
        for (argi, path, role, spec), aval, resolved in zip(
                leaf_info, in_leaves, flat_in):
            if spec is None or not hasattr(aval, "shape"):
                continue
            intended = NamedSharding(env.mesh, spec)
            if not shardings_equivalent(resolved, intended,
                                        len(aval.shape)):
                where = "donated input" if argi in donated else "input"
                self._dedup_report(
                    ctx, ep,
                    f"{where} arg{argi}/{path_str(path)} (role {role}, "
                    f"{leaf_bytes(aval)} B) resolves "
                    f"{_spec_str(resolved)}, contract says {spec}")

        # -- outputs (incl. the donated state's returned leaves) -------------
        flat_out, _ = jax.tree_util.tree_flatten(compiled.output_shardings)
        out_info = out_leaf_contracts(contract, ep.abstract_args,
                                      len(flat_out),
                                      data_size=env.data_size)
        if len(out_avals) != len(flat_out):
            ctx.notes.append(f"{ep.name}: output arity mismatch "
                             f"({len(flat_out)} shardings, "
                             f"{len(out_avals)} avals); output contract "
                             f"check skipped")
            return
        for (label, role, spec), aval, resolved in zip(
                out_info, out_avals, flat_out):
            if spec is None or not hasattr(aval, "shape"):
                continue
            intended = NamedSharding(env.mesh, spec)
            if not shardings_equivalent(resolved, intended,
                                        len(aval.shape)):
                kind = ("donated-leaf output" if label.startswith("state:")
                        and 0 in set(ep.donate_argnums) else "output")
                self._dedup_report(
                    ctx, ep,
                    f"{kind} {label} (role {role}) resolves "
                    f"{_spec_str(resolved)}, contract says {spec}")

    def _dedup_report(self, ctx, ep, detail: str) -> None:
        # Message carries no mesh size: the same deviation usually
        # reproduces on every mesh, and a mesh-tagged message would
        # triple every baseline entry under --trace-profile full.
        ctx.report(self, ep.anchor, f"{ep.name}: {detail}")
