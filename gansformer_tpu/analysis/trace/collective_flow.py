"""collective-flow — collective inventory + comms-cost attribution.

Walks the compiled HLO of every contract-covered entry point (on the
same simulated mesh matrix as ``partition-contract``; the compile is
shared via ``ctx.compiled``) for ``all-gather`` / ``all-reduce`` /
``reduce-scatter`` / ``collective-permute`` ops, attributes bytes
moved per collective per entry point, and accumulates the ranked
comms-cost table in ``ctx.comms`` — the comms twin of
``bench_components.py``'s per-op FLOP attribution (exported via
``gansformer-lint --json-out``).

Four anti-patterns become findings:

* **full-param all-gather** — a single all-gather whose payload covers
  most of the params-role input bytes: the program re-materializes the
  full parameter tree every step, i.e. params were sharded (FSDP) but
  the compute never consumes them sharded, so the sharding bought
  memory but the step pays a full gather (the missed-FSDP pattern).
* **oversized all-reduce** — an all-reduce moving more bytes than the
  whole params tree: data-parallel training only ever all-reduces
  gradients (≤ params bytes) and scalar stats, so anything bigger is
  an activation reduction that should have stayed device-local.
* **replicated opt-state** — an opt-state-role input leaf above a size
  threshold resolving fully replicated: every chip holds a full copy
  of Adam moments that FSDP would shard for free.
* **replicated compute** (ISSUE 7) — a TRAIN-STEP program compiled on
  a multi-device data mesh with ZERO all-reduces: gradient-descent
  over a sharded batch must reduce gradients across the data axis, so
  no all-reduce means the batch never sharded and N chips each run
  the full batch (the ``g_step`` defect this repo shipped for six
  PRs — a flat scaling row that was replicated work, not scaling).

Byte accounting: ``payload`` is the logical tensor moved (the HLO
result shape; for reduce-scatter, result × group).  ``wire`` is the
per-device ring-algorithm traffic — all-reduce ``2·N·(g-1)/g``,
all-gather / reduce-scatter ``N·(g-1)/g``, collective-permute ``N``.
Counts are per program TEXT: a collective inside a ``scan`` body is
counted once, not trip-count times (the table is a per-dispatch lower
bound for the fused cycle — noted in the record).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Sequence

from gansformer_tpu.analysis.trace.base import (
    EntryPoint, TraceContext, TraceRule, leaf_bytes, path_str, register)

FULL_GATHER_MIN_BYTES = 256 * 1024
FULL_GATHER_PARAM_FRACTION = 0.5
OVERSIZED_ALLREDUCE_MIN_BYTES = 1024 * 1024
OPT_REPLICATED_THRESHOLD_BYTES = 4 * 1024 * 1024

COLLECTIVE_KINDS = ("all-gather", "all-reduce", "reduce-scatter",
                    "collective-permute")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

# `%x = f32[8,4]{1,0} all-gather(...)` / `(f32[4], f32[8]) all-reduce(...)`
# — definitions only (result type right before the op name); async
# `-start` forms count, their `-done` halves don't (same transfer).
_COLLECTIVE_RE = re.compile(
    r"=\s*(\([^)]*\)|\S+)\s+"
    r"(all-gather|all-reduce|reduce-scatter|collective-permute)"
    r"(-start)?\(")
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([^}]*)\}")


def _shape_bytes_list(type_str: str) -> List[int]:
    out = []
    for dtype, dims in _SHAPE_RE.findall(type_str):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append(n * _DTYPE_BYTES.get(dtype, 4))
    return out


def _group_size(line: str, default: int) -> int:
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return max(1, int(m.group(2)))
    m = _GROUPS_LIST_RE.search(line)
    if m:
        return max(1, len([x for x in m.group(1).split(",") if x.strip()]))
    return default


def wire_bytes(kind: str, payload: int, group: int) -> int:
    """Per-device ring-traffic model for one collective."""
    if group <= 1:
        return 0
    if kind == "all-reduce":
        return int(2 * payload * (group - 1) / group)
    if kind in ("all-gather", "reduce-scatter"):
        return int(payload * (group - 1) / group)
    return int(payload)       # collective-permute


def parse_collectives(hlo_text: str, default_group: int
                      ) -> List[Dict[str, Any]]:
    """Collective op inventory of one compiled module's HLO text:
    ``{kind, payload_bytes, wire_bytes_per_device, group}`` per op."""
    out: List[Dict[str, Any]] = []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        type_str, kind, is_start = m.group(1), m.group(2), bool(m.group(3))
        group = _group_size(line, default_group)
        shapes = _shape_bytes_list(type_str)
        if is_start and kind != "all-reduce":
            # async bundle results carry (operand, result[, context]):
            # the transferred tensor is the LARGEST element — for
            # all-gather(-start) the full output, for reduce-scatter the
            # full input (already whole: no ×group below), for
            # collective-permute the tensor itself.  Summing the bundle
            # would double-count the operand.
            payload = max(shapes, default=0)
        else:
            payload = sum(shapes)
            if kind == "reduce-scatter":
                payload *= group      # result is the shard; move the whole
        out.append({"kind": kind, "payload_bytes": payload,
                    "wire_bytes_per_device": wire_bytes(kind, payload,
                                                        group),
                    "group": group})
    return out


def _role_bytes(contract, abstract_args) -> Dict[str, int]:
    import jax

    from gansformer_tpu.parallel.contracts import arg_leaf_contracts

    totals: Dict[str, int] = {}
    flat = arg_leaf_contracts(contract, abstract_args)
    leaves = [l for _, l in
              jax.tree_util.tree_flatten_with_path(abstract_args)[0]]
    for (argi, path, role, spec), aval in zip(flat, leaves):
        totals[role] = totals.get(role, 0) + leaf_bytes(aval)
    return totals


def comms_record(ep_name: str, n_devices: int, ops: List[Dict[str, Any]],
                 role_bytes: Dict[str, int]) -> Dict[str, Any]:
    """One ctx.comms entry: per-kind aggregation + totals for one
    entry×mesh compile (pure — unit-tested on synthetic inventories)."""
    by_kind: Dict[str, Dict[str, int]] = {}
    for op in ops:
        agg = by_kind.setdefault(op["kind"], {"count": 0,
                                              "payload_bytes": 0,
                                              "wire_bytes_per_device": 0})
        agg["count"] += 1
        agg["payload_bytes"] += op["payload_bytes"]
        agg["wire_bytes_per_device"] += op["wire_bytes_per_device"]
    return {
        "entry": ep_name,
        "devices": n_devices,
        "collectives": by_kind,
        "total_payload_bytes": sum(a["payload_bytes"]
                                   for a in by_kind.values()),
        "total_wire_bytes_per_device": sum(
            a["wire_bytes_per_device"] for a in by_kind.values()),
        "param_bytes": role_bytes.get("params", 0),
        "opt_state_bytes": role_bytes.get("opt_state", 0),
        "note": "static per-dispatch inventory; scan-body collectives "
                "counted once",
    }


def ranked_comms_table(comms: Sequence[Dict[str, Any]]
                       ) -> List[Dict[str, Any]]:
    """Per-entry ranked table (largest simulated mesh wins per entry,
    ranked by per-device wire bytes descending) — the ``--json-out`` /
    ``--format json`` payload."""
    best: Dict[str, Dict[str, Any]] = {}
    for rec in comms:
        cur = best.get(rec["entry"])
        if cur is None or rec["devices"] > cur["devices"]:
            best[rec["entry"]] = rec
    return sorted(best.values(),
                  key=lambda r: (-r["total_wire_bytes_per_device"],
                                 r["entry"]))


def scaling_report(comms: Sequence[Dict[str, Any]],
                   chip_counts: Sequence[int] = (1, 2, 4, 8, 16, 32, 64)
                   ) -> Dict[str, Dict[str, int]]:
    """Predicted per-device wire bytes per dispatch vs chip count.

    Collective payloads in this layout are chip-count-INDEPENDENT (the
    gradient tree / gathered params don't grow with the mesh), so the
    ring model extrapolates each kind's aggregate payload measured on
    the largest simulated mesh: the all-reduce term approaches 2·N —
    which is exactly why DP scaling efficiency flattens, and what
    ``bench.py`` turns into an expected-efficiency curve before any
    multi-chip hardware exists."""
    out: Dict[str, Dict[str, int]] = {}
    for rec in ranked_comms_table(comms):
        per_chip: Dict[str, int] = {}
        for c in chip_counts:
            total = 0
            for kind, agg in rec["collectives"].items():
                total += wire_bytes(kind, agg["payload_bytes"], c)
            per_chip[str(c)] = total
        out[rec["entry"]] = per_chip
    return out


def scaling_efficiency(wire_bytes_per_device: int, step_s: float,
                       ici_bytes_per_s: float) -> float:
    """No-overlap serial model: eff = t_comp / (t_comp + t_comms).
    Pessimistic by design (XLA overlaps collectives with compute when
    it can) — a floor, not a forecast."""
    if step_s <= 0 or ici_bytes_per_s <= 0:
        return 0.0
    return step_s / (step_s + wire_bytes_per_device / ici_bytes_per_s)


@register
class CollectiveFlowRule(TraceRule):
    id = "collective-flow"
    description = ("collective anti-pattern in the compiled SPMD "
                   "program: full-param all-gather (missed FSDP), "
                   "all-reduce larger than the gradient tree, "
                   "oversize fully-replicated opt-state, or a train "
                   "step with ZERO all-reduces on a multi-device data "
                   "mesh (replicated compute)")
    hint = ("consume params sharded (or revert the sharding), keep "
            "reductions device-local until the gradient psum, shard "
            "optimizer moments alongside their params, and constrain "
            "in-step batch draws onto the data axis")
    dynamic = True

    full_gather_min = FULL_GATHER_MIN_BYTES
    full_gather_fraction = FULL_GATHER_PARAM_FRACTION
    oversized_allreduce_min = OVERSIZED_ALLREDUCE_MIN_BYTES
    opt_replicated_threshold = OPT_REPLICATED_THRESHOLD_BYTES

    def check(self, ep: EntryPoint, ctx: TraceContext) -> None:
        import jax

        contract = ctx.entry_contract(ep)
        if contract is None:
            ctx.notes.append(f"{ep.name}: no sharding contract declared; "
                             f"collective-flow skipped")
            return
        role_bytes = _role_bytes(contract, ep.abstract_args)
        n_local = len(jax.devices())
        for n in ctx.mesh_sizes:
            if n > n_local:
                ctx.notes.append(
                    f"{ep.name}: {n}-device mesh needs "
                    f"XLA_FLAGS=--xla_force_host_platform_device_count="
                    f"{n} (have {n_local}); collective-flow skipped")
                continue
            try:
                compiled, _out = ctx.compiled(ep, n)
                hlo = compiled.as_text()
            except Exception as e:
                ctx.report(self, ep.anchor,
                           f"{ep.name}: compile/HLO read failed on the "
                           f"{n}-device mesh: {type(e).__name__}: "
                           f"{str(e)[:160]}")
                continue
            ops = parse_collectives(hlo, default_group=n)
            ctx.comms.append(comms_record(ep.name, n, ops, role_bytes))
            if n > 1:        # a 1-device program has no collectives
                self._flag_anti_patterns(ep, ctx, ops, role_bytes,
                                         compiled, contract)
                self._flag_replicated_compute(ep, ctx, ops, n)

    # -- anti-patterns -------------------------------------------------------

    def _flag_anti_patterns(self, ep, ctx, ops, role_bytes, compiled,
                            contract) -> None:
        param_bytes = role_bytes.get("params", 0)
        for op in ops:
            if (op["kind"] == "all-gather"
                    and op["payload_bytes"] >= self.full_gather_min
                    and param_bytes > 0
                    and op["payload_bytes"] >=
                    self.full_gather_fraction * param_bytes):
                ctx.report(self, ep.anchor,
                           f"{ep.name}: full-param all-gather — one "
                           f"all-gather moves "
                           f"{op['payload_bytes'] / 2**20:.1f} MiB "
                           f"(params total "
                           f"{param_bytes / 2**20:.1f} MiB): the step "
                           f"re-materializes the sharded tree every "
                           f"dispatch (missed FSDP)")
            if (op["kind"] == "all-reduce"
                    and op["payload_bytes"] >= self.oversized_allreduce_min
                    and op["payload_bytes"] > param_bytes):
                # param_bytes sums the whole params role (G + D + EMA)
                # — a deliberately GENEROUS upper bound on any single
                # step's gradient tree, so what crosses it is an
                # activation reduction beyond doubt
                ctx.report(self, ep.anchor,
                           f"{ep.name}: all-reduce of "
                           f"{op['payload_bytes'] / 2**20:.1f} MiB "
                           f"exceeds the TOTAL params bytes "
                           f"({param_bytes / 2**20:.1f} MiB, itself an "
                           f"upper bound on any gradient tree) — an "
                           f"activation-sized reduction that should "
                           f"stay device-local")
        self._flag_replicated_opt_state(ep, ctx, compiled, contract)

    def _flag_replicated_compute(self, ep, ctx, ops, n_devices) -> None:
        """Train-step × multi-device data mesh × zero all-reduces =
        replicated compute.  A gradient step over a data-sharded batch
        MUST all-reduce gradients; its absence means the in-step
        latent/batch path never sharded, so the mesh buys replicated
        work (the exact defect ISSUE 7 fixed — this check keeps it
        fixed).  Gated on ``ep.train_step``: inference programs
        (sample/ppl_pairs) legitimately compile collective-free."""
        from gansformer_tpu.parallel.contracts import simulated_mesh

        if not ep.train_step:
            return
        if simulated_mesh(n_devices).data_size <= 1:
            return           # model-only mesh: no data axis to reduce over
        if any(op["kind"] == "all-reduce" for op in ops):
            return
        ctx.report(self, ep.anchor,
                   f"{ep.name}: compiled to ZERO all-reduces on the "
                   f"{n_devices}-device data mesh — a train step over a "
                   f"sharded batch must all-reduce gradients, so this "
                   f"program's compute is replicated (N chips, N copies "
                   f"of the same work); shard the in-step latent/batch "
                   f"draws onto the data axis "
                   f"(parallel/mesh.constrain_data_axis)")

    def _flag_replicated_opt_state(self, ep, ctx, compiled,
                                   contract) -> None:
        import jax

        from gansformer_tpu.parallel.contracts import arg_leaf_contracts

        leaf_info = arg_leaf_contracts(contract, ep.abstract_args)
        flat_in, _ = jax.tree_util.tree_flatten(
            compiled.input_shardings[0])
        leaves = [l for _, l in jax.tree_util.tree_flatten_with_path(
            ep.abstract_args)[0]]
        if len(flat_in) != len(leaf_info):
            return
        for (argi, path, role, spec), aval, resolved in zip(
                leaf_info, leaves, flat_in):
            if role != "opt_state" or not hasattr(aval, "shape"):
                continue
            n = leaf_bytes(aval)
            if n < self.opt_replicated_threshold:
                continue
            if getattr(resolved, "is_fully_replicated", False):
                ctx.report(self, ep.anchor,
                           f"{ep.name}: opt-state leaf "
                           f"arg{argi}/{path_str(path)} "
                           f"({n / 2**20:.1f} MiB) is fully replicated "
                           f"— every device holds a full copy of "
                           f"optimizer moments FSDP would shard")
