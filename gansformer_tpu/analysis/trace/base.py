"""Trace-rule registry + shared context — the jaxpr-level half of graftlint.

The AST engine (``analysis/engine.py``) sees source text; this layer sees
*meaning*: it imports the repo's real jitted entry points, traces them
with abstract inputs (``jax.make_jaxpr`` / ``jax.eval_shape``), and lets
rules walk the resulting jaxprs, compilation caches, and resolved
shardings.  Findings flow into the exact same ``Finding`` /
baseline / reporter / CLI stack, so trace findings gate, suppress, and
baseline like AST findings do.

Anchoring: every trace finding points at a *source* location — the
jaxpr equation's user frame when one exists (dtype promotions land on
the line that promoted), else the traced function's ``def`` line.  An
inline ``# graftlint: disable=<rule>`` on that line suppresses the
finding, same syntax as the AST side.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from gansformer_tpu.analysis.engine import _parse_suppressions
from gansformer_tpu.analysis.findings import Finding

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


@dataclasses.dataclass
class EntryPoint:
    """One traceable jitted entry point plus everything rules need.

    ``abstract_args`` drive the structural rules (``make_jaxpr`` — no
    compile, no execution); ``make_args`` builds *fresh concrete* inputs
    for the dynamic rules (retrace probing calls the function for real).
    ``train_step`` marks the hot-loop steps — the fast profile's dynamic
    rules run on those only, the full profile on everything.
    """

    name: str                        # e.g. "steps.d_step[tiny-f32]"
    fn: Callable                     # the jitted callable
    abstract_args: Tuple[Any, ...]   # ShapeDtypeStructs / None leaves
    path: str                        # source file of the traced fn
    line: int                        # its ``def`` line (finding anchor)
    config_name: str = ""
    static_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    make_args: Optional[Callable[[], Tuple[Any, ...]]] = None
    donate_argnums: Tuple[int, ...] = ()
    train_step: bool = False
    # per-positional-arg placement tags for the sharding audit:
    # "state" | "batch" | "stack" | "repl" (see trace/sharding_audit.py)
    arg_specs: Tuple[str, ...] = ()
    # explicit sharding contract override (parallel/contracts.Contract);
    # None = look up by short name in contracts.ENTRY_CONTRACTS (the
    # real catalog path) — fixtures inject their own here.
    contract: Any = None
    # model compute dtype for this config ("float32" | "bfloat16") — the
    # dtype rule only hunts bf16→f32 upcasts when the model runs bf16.
    compute_dtype: str = "float32"

    @property
    def anchor(self) -> Tuple[str, int]:
        return (self.path, self.line)


class TraceRule:
    """Base class for jaxpr-level rules.

    ``dynamic`` rules execute or compile the entry point (retrace
    probing, sharding resolution) and are therefore orders of magnitude
    more expensive than the structural rules, which only trace.
    """

    id: str = ""
    description: str = ""
    hint: str = ""
    dynamic: bool = False

    def check(self, ep: EntryPoint, ctx: "TraceContext") -> None:
        raise NotImplementedError


_TRACE_REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    if not cls.id:
        raise ValueError(f"trace rule {cls.__name__} has no id")
    if _TRACE_REGISTRY.get(cls.id, cls) is not cls:
        raise ValueError(f"duplicate trace rule id {cls.id!r}")
    _TRACE_REGISTRY[cls.id] = cls
    return cls


def all_trace_rules() -> List[type]:
    """Every registered trace rule class (imports the bundled set)."""
    from gansformer_tpu.analysis import numerics  # noqa: F401  (registers)
    from gansformer_tpu.analysis.trace import (  # noqa: F401  (registers)
        collective_flow, const_bloat, dtype_flow, partition_contract,
        retrace, sharding_audit)

    return [_TRACE_REGISTRY[k] for k in sorted(_TRACE_REGISTRY)]


class TraceContext:
    """Shared per-run state: jaxpr cache, suppressions, findings."""

    def __init__(self, mesh_sizes: Tuple[int, ...] = (2,)):
        self.findings: List[Finding] = []
        self._jaxprs: Dict[str, Any] = {}       # entry name -> ClosedJaxpr
        self._suppress_cache: Dict[str, tuple] = {}
        self._seen: set = set()
        self.notes: List[str] = []              # non-finding diagnostics
        # graftcomms surface: the simulated-mesh device counts the
        # contract/collective rules compile against (harness sets the
        # full matrix for --trace-profile full), the shared compile
        # cache (partition-contract and collective-flow compile the
        # SAME entry×mesh programs — pay each compile once), and the
        # accumulated comms-cost table (one record per entry×mesh).
        self.mesh_sizes: Tuple[int, ...] = tuple(mesh_sizes)
        self._compiled: Dict[Tuple[str, int], Any] = {}
        self.comms: List[Dict[str, Any]] = []
        self.meshes_compiled: set = set()       # sizes that ACTUALLY built
        # graftnum surface (ISSUE 19): one fp32-island audit record per
        # entry with a numeric contract — rides the --format json /
        # selfcheck payload as the proof that e.g. the tiny-bf16
        # programs run their declared islands in fp32
        self.numerics: List[Dict[str, Any]] = []

    # -- tracing -------------------------------------------------------------

    def jaxpr(self, ep: EntryPoint):
        """``jax.make_jaxpr`` of the entry point over its abstract args —
        traced once, shared by every structural rule."""
        if ep.name not in self._jaxprs:
            import jax

            fn = ep.fn
            if ep.static_kwargs:
                import functools

                fn = functools.partial(fn, **ep.static_kwargs)
            self._jaxprs[ep.name] = jax.make_jaxpr(fn)(*ep.abstract_args)
        return self._jaxprs[ep.name]

    # -- contract-sharded compilation (graftcomms rules) ---------------------

    def entry_contract(self, ep: EntryPoint):
        """The entry's sharding contract: an injected override (fixtures)
        or the catalog entry for its short name; None = undeclared."""
        if ep.contract is not None:
            return ep.contract
        from gansformer_tpu.parallel.contracts import contract_for

        return contract_for(ep.name)

    def compiled(self, ep: EntryPoint, n_devices: int):
        """``(compiled, out_leaf_infos)`` for the entry point compiled
        with CONTRACT-sharded abstract inputs on an n×1 simulated mesh —
        cached per (entry, mesh size) so the contract and collective
        rules share one compile.  ``out_leaf_infos`` are the lowered
        program's flattened per-output-leaf shape/dtype infos (captured
        at lowering time: re-tracing outside the mesh context would
        break bare-PartitionSpec constraints).  Raises on lowering/
        compile failure (and caches the failure so the second rule
        doesn't re-pay the attempt)."""
        import jax

        key = (ep.name, n_devices)
        if key not in self._compiled:
            from gansformer_tpu.parallel.contracts import (
                sharded_abstract_args, simulated_mesh)

            contract = self.entry_contract(ep)
            if contract is None:
                raise ValueError(f"{ep.name}: no sharding contract")
            try:
                env = simulated_mesh(n_devices)
                args = sharded_abstract_args(contract, ep.abstract_args,
                                             env)
                with env.activate():
                    lowered = ep.fn.lower(*args, **ep.static_kwargs)
                    out_leaves = jax.tree_util.tree_flatten(
                        lowered.out_info)[0]
                    self._compiled[key] = (lowered.compile(), out_leaves)
            except Exception as e:
                self._compiled[key] = e
        got = self._compiled[key]
        if isinstance(got, Exception):
            raise got
        self.meshes_compiled.add(n_devices)
        return got

    # -- suppression (same inline syntax as the AST engine) ------------------

    def _suppressions(self, path: str):
        if path not in self._suppress_cache:
            try:
                with open(path, encoding="utf-8") as f:
                    lines = f.read().splitlines()
            except OSError:
                lines = []
            self._suppress_cache[path] = _parse_suppressions(lines)
        return self._suppress_cache[path]

    def is_suppressed(self, rule_id: str, path: str, line: int) -> bool:
        per_line, whole_file = self._suppressions(path)
        on_line = per_line.get(line, ())
        return (rule_id in on_line or "all" in on_line
                or rule_id in whole_file or "all" in whole_file)

    # -- reporting -----------------------------------------------------------

    def report(self, rule: TraceRule, where: Tuple[str, int], message: str,
               hint: Optional[str] = None) -> Optional[Finding]:
        """File a finding anchored at ``(path, line)``.  The Finding
        carries the ABSOLUTE path: downstream consumers (the CLI's
        line_text_lookup, Baseline key computation) resolve finding
        paths against the CWD, which for trace findings is unrelated to
        the anchor — an absolute path keeps baseline matching and
        suppression working from any working directory (Baseline
        relativizes against its own root when writing keys)."""
        path, line = where
        abspath = path if os.path.isabs(path) else \
            os.path.join(_REPO_ROOT, path)
        key = (rule.id, abspath, line, message)
        if key in self._seen:
            return None
        self._seen.add(key)
        f = Finding(rule=rule.id, path=abspath, line=line, col=0,
                    message=message,
                    hint=rule.hint if hint is None else hint,
                    suppressed=self.is_suppressed(rule.id, abspath, line))
        self.findings.append(f)
        return f


# -- jaxpr walking utilities (shared by the structural rules) ----------------

def sub_jaxprs(value) -> List[Any]:
    """The Jaxpr objects nested inside one eqn-param value (pjit/scan/
    cond bodies arrive as ClosedJaxpr or Jaxpr, sometimes in lists)."""
    import jax.core as jcore

    if isinstance(value, jcore.ClosedJaxpr):
        return [value.jaxpr]
    if isinstance(value, jcore.Jaxpr):
        return [value]
    if isinstance(value, (list, tuple)):
        out: List[Any] = []
        for v in value:
            out.extend(sub_jaxprs(v))
        return out
    return []


def iter_eqns(jaxpr) -> Iterable[Any]:
    """Every eqn in a jaxpr, recursing into pjit/scan/cond sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in sub_jaxprs(v):
                yield from iter_eqns(sub)


def iter_consts(closed) -> Iterable[Any]:
    """Every constant closed over anywhere in the program: the top-level
    ``ClosedJaxpr.consts`` plus the consts of every nested ClosedJaxpr
    (a jitted function's closure constants live on the inner pjit
    jaxpr, not the outer one)."""
    import jax.core as jcore

    yield from closed.consts
    for eqn in iter_eqns(closed.jaxpr):
        for v in eqn.params.values():
            for item in (v if isinstance(v, (list, tuple)) else [v]):
                if isinstance(item, jcore.ClosedJaxpr):
                    yield from item.consts


def eqn_frame(eqn) -> Optional[Tuple[str, int]]:
    """(file, line) of the user frame that generated this eqn, or None
    (library-internal eqns carry no user frame)."""
    try:
        import jax._src.source_info_util as siu

        frame = siu.user_frame(eqn.source_info)
        if frame is not None:
            return (frame.file_name, frame.start_line)
    except Exception:
        pass
    return None


def in_repo(path: Optional[str]) -> bool:
    if not path:
        return False
    try:
        return os.path.abspath(path).startswith(_REPO_ROOT + os.sep)
    except ValueError:
        return False


def line_text(path: str, line: int) -> str:
    abspath = path if os.path.isabs(path) else os.path.join(_REPO_ROOT, path)
    try:
        with open(abspath, encoding="utf-8") as f:
            lines = f.read().splitlines()
        return lines[line - 1] if 0 < line <= len(lines) else ""
    except OSError:
        return ""


def def_site(fn: Callable) -> Tuple[str, int]:
    """(path, def line) of the *user* function under a jit wrapper —
    falls back to the wrapper itself, then to a placeholder."""
    import functools
    import inspect

    probe = fn
    for _ in range(8):
        if isinstance(probe, functools.partial):
            probe = probe.func
            continue
        wrapped = getattr(probe, "__wrapped__", None)
        if wrapped is None:
            break
        probe = wrapped
    try:
        path = inspect.getsourcefile(probe) or "<unknown>"
        _, line = inspect.getsourcelines(probe)
        return (path, line)
    except (OSError, TypeError):
        return ("<unknown>", 0)


def leaf_bytes(aval) -> int:
    """Best-effort byte size of an abstract leaf (0 when shapeless)."""
    import numpy as np

    try:
        return int(np.prod(aval.shape) * np.dtype(aval.dtype).itemsize)
    except Exception:
        return 0


def path_str(path) -> str:
    """Human-readable pytree path (GetAttrKey/DictKey/SequenceKey)."""
    out = []
    for p in path:
        out.append(str(getattr(p, "name", getattr(p, "key",
                                                  getattr(p, "idx", p)))))
    return "/".join(out)


def shardings_equivalent(a, b, ndim: int) -> bool:
    """Resolved-vs-intended sharding equivalence, tolerant of the
    GSPMD/NamedSharding representation split (string fallback)."""
    try:
        return bool(a.is_equivalent_to(b, ndim))
    except Exception:
        return str(a) == str(b)


def sizeof(const) -> int:
    """Best-effort byte size of a jaxpr constant."""
    nbytes = getattr(const, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    try:
        import numpy as np

        return int(np.asarray(const).nbytes)
    except Exception:
        return 0
