"""graftcheck — jaxpr-level semantic analysis (ISSUE 4).

The AST half of graftlint (``analysis/engine.py`` + ``rules/``) reads
source text; this package reads *traced programs*.  It imports the
repo's real jitted entry points (``train/steps.py``), traces them with
abstract inputs over a tiny config matrix, and runs rules against what
XLA will actually see — the bug classes that burn TPU hours without
ever looking wrong in source:

* ``retrace``        — retrace-hazard: equivalent-but-differently-
                       constructed inputs must not trigger a second
                       compile (weak types, static kwargs, closures).
* ``const_bloat``    — jaxpr-const-bloat: big arrays closed over
                       instead of passed, baked into every executable.
* ``dtype_flow``     — dtype-promotion: silent bf16→f32 / →f64 upcasts
                       inserted by type promotion.
* ``sharding_audit`` — sharding-audit: oversize fully-replicated
                       params and donation-defeating output shardings,
                       resolved on a fake 2-device mesh.

The graftcomms layer (ISSUE 6) extends the dynamic half over the
SPMD-compiled programs, against the declared layout in
``parallel/contracts.py`` and across a 1/2/4-device simulated mesh
matrix (compiles shared through ``TraceContext.compiled``):

* ``partition_contract`` — partition-contract: resolved input/output/
                       donated-leaf shardings must match the intended
                       PartitionSpec per arg role per entry point.
* ``collective_flow``  — collective-flow: per-collective bytes-moved
                       attribution (the ranked comms table behind
                       ``gansformer-lint --json-out`` and bench.py's
                       expected-scaling section) + anti-pattern
                       findings: full-param all-gathers (missed FSDP),
                       all-reduces larger than the gradient tree,
                       oversize replicated opt-state.

Findings feed the SAME engine stack as the AST rules — ``Finding``
objects, inline ``# graftlint: disable=`` suppressions (anchored on
real source lines), the checked-in baseline, text/JSON reporters, and
the ``gansformer-lint --trace`` CLI exit-code contract.

See docs/static-analysis.md ("Trace rules") for the catalog and the
"why AST lint can't see this" discussion.
"""

from gansformer_tpu.analysis.trace.base import (  # noqa: F401
    EntryPoint, TraceContext, TraceRule, all_trace_rules, register)
from gansformer_tpu.analysis.trace.harness import (  # noqa: F401
    PROFILES, run_trace)
