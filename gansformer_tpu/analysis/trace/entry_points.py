"""The traced entry-point catalog: the repo's REAL jitted functions.

This module is the contract between the trace rules and the training
stack: it builds ``make_train_steps`` for a small config matrix and
describes each jitted function — abstract input shapes for structural
tracing, fresh-concrete-input builders for the dynamic rules, donation
positions, and the source anchor findings point at.

The matrix is deliberately tiny (resolution 16, k=2, batch 2): jaxpr
STRUCTURE — dtype flow, closed-over constants, sharding decisions,
cache keying — is shape-independent for this model family, so the tiny
trace stands in for the flagship config at a fraction of the cost.

* ``tiny-f32``  — default float32 model; the retrace / const / sharding
                  reference member.  Its interval choice (d_reg == g_reg
                  == 2) makes ``make_train_steps`` build the fused
                  ``cycle`` program too, so the flagship dispatch mode
                  is traced without a third config.
* ``tiny-bf16`` — bfloat16 compute path; the dtype-promotion member
                  (bf16→f32 upcasts only exist here).
* ``tiny-pallas`` — attention_backend='pallas' (interpret mode off-TPU)
                  on the DUPLEX model, so both kernel directions and
                  their backward kernels sit inside the traced programs.
                  Like tiny-bf16 it contributes only the superset
                  programs (the second-order reg pair): the backend
                  changes the attention compute path, not the step
                  structure, so re-tracing the whole catalog would
                  double cost for no new coverage (ISSUE 9).

The serving split (ISSUE 10) rides the same matrix via
``build_serve_entry_points``: ``serve_map_seeds`` / ``serve_map_z`` /
``serve_synth`` over the tiny reference config, contracts declared in
``parallel/contracts.ENTRY_CONTRACTS`` like every train entry.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from gansformer_tpu.analysis.trace.base import EntryPoint, def_site
from gansformer_tpu.core.config import (
    DataConfig, ExperimentConfig, MeshConfig, ModelConfig, TrainConfig)

_BATCH = 2
_RES = 16


def tiny_config(dtype: str = "float32", fused: bool = False,
                attention: str = "simplex",
                backend: str = "xla") -> ExperimentConfig:
    return ExperimentConfig(
        name=f"trace-tiny-{dtype}{'-fused' if fused else ''}"
             f"{'-pallas' if backend == 'pallas' else ''}",
        model=ModelConfig(resolution=_RES, components=2, latent_dim=16,
                          w_dim=16, mapping_dim=16, mapping_layers=2,
                          fmap_base=64, fmap_max=32, attention=attention,
                          attn_start_res=8, attn_max_res=8,
                          mbstd_group_size=2, dtype=dtype,
                          attention_backend=backend),
        train=TrainConfig(batch_size=_BATCH, total_kimg=1, d_reg_interval=2,
                          g_reg_interval=2, pl_batch_shrink=2, ema_kimg=0.01,
                          style_mixing_prob=0.5, fused_cycle=fused),
        data=DataConfig(resolution=_RES, source="synthetic"),
        mesh=MeshConfig())


def trace_configs() -> Dict[str, ExperimentConfig]:
    return {
        "tiny-f32": tiny_config("float32"),
        "tiny-bf16": tiny_config("bfloat16"),
        # duplex: both kernel directions (and both backward kernels) are
        # inside the traced second-order programs (ISSUE 9)
        "tiny-pallas": tiny_config("float32", attention="duplex",
                                   backend="pallas"),
    }


def _abstract_state(cfg: ExperimentConfig):
    import jax

    from gansformer_tpu.train.state import create_train_state

    return jax.eval_shape(lambda k: create_train_state(cfg, k),
                          jax.random.PRNGKey(0))


class _StateFactory:
    """Fresh, independently-constructed concrete TrainStates.

    The retrace rule needs EVERY input rebuilt per probing call (a
    donated buffer from call N must never be re-passed at call N+1, and
    "equivalent but differently constructed" is the whole point), so the
    real init runs once and each ``fresh()`` re-materializes the pytree
    from host copies.
    """

    def __init__(self, cfg: ExperimentConfig):
        self._cfg = cfg
        self._host = None

    def fresh(self):
        import jax
        import numpy as np

        if self._host is None:
            from gansformer_tpu.train.state import create_train_state

            state = create_train_state(self._cfg, jax.random.PRNGKey(0))
            self._host = jax.tree_util.tree_map(np.asarray,
                                                jax.device_get(state))
        return jax.tree_util.tree_map(lambda x: np.array(x), self._host)


def build_entry_points(config_name: str,
                       cfg: Optional[ExperimentConfig] = None,
                       include: Optional[List[str]] = None,
                       fsdp: bool = False
                       ) -> List[EntryPoint]:
    """EntryPoints for one config.  ``include`` filters by short name
    (``d_step``, ``g_step``, …); None = all for that config.
    ``fsdp=True`` attaches the FSDP contract overlay
    (``parallel/contracts.entry_contracts(fsdp=True)``) so the mesh
    rules assert the sharded-opt-state intent — the step functions
    themselves are identical (the layout is input-sharding-driven)."""
    import dataclasses

    import jax
    import numpy as np

    from gansformer_tpu.train.steps import make_train_steps

    cfg = cfg or trace_configs()[config_name]
    if fsdp and not cfg.mesh.fsdp:
        # the in-step layout pin (pin_state_layout, a closure inside
        # steps.make_train_steps) is driven by the config — the FSDP
        # entries must trace the fsdp program
        cfg = dataclasses.replace(
            cfg, mesh=dataclasses.replace(cfg.mesh, fsdp=True))
    m, t = cfg.model, cfg.train
    fns = make_train_steps(cfg, None, batch_size=t.batch_size)
    state_abs = _abstract_state(cfg)
    states = _StateFactory(cfg)
    imgs_abs = jax.ShapeDtypeStruct(
        (t.batch_size, m.resolution, m.resolution, m.img_channels), np.uint8)
    key_abs = jax.ShapeDtypeStruct((2,), np.uint32)
    z_abs = jax.ShapeDtypeStruct(
        (t.batch_size, m.num_ws, m.latent_dim), np.float32)
    w_avg_abs = jax.ShapeDtypeStruct((m.w_dim,), np.float32)
    ts_abs = jax.ShapeDtypeStruct((t.batch_size,), np.float32)

    def imgs():
        return np.random.RandomState(0).randint(
            0, 255, imgs_abs.shape, dtype=np.uint8)

    def key(seed: int):
        return np.asarray(jax.random.PRNGKey(seed))

    def z(seed: int):
        return np.random.RandomState(seed).normal(
            size=z_abs.shape).astype(np.float32)

    common = dict(config_name=config_name, compute_dtype=m.dtype)
    eps: List[EntryPoint] = []

    def add(short, fn, abstract_args, make_args, *, donate=(),
            static_kwargs=None, train_step=False, arg_specs=()):
        if include is not None and short not in include:
            return
        # Loud coverage contract: every entry must carry a complete
        # per-arg placement tag set AND a declared PartitionSpec
        # contract — the audits' "skipped" note paths exist for
        # FIXTURES, not for the real catalog (they silently exempted
        # the inference programs the serving path will reuse).
        from gansformer_tpu.parallel.contracts import contract_for

        if len(arg_specs) != len(abstract_args):
            raise ValueError(
                f"entry point {short!r}: {len(arg_specs)} arg_specs for "
                f"{len(abstract_args)} args — the sharding audit would "
                f"silently skip it")
        if contract_for(short) is None:
            raise ValueError(
                f"entry point {short!r}: no sharding contract in "
                f"parallel/contracts.ENTRY_CONTRACTS — declare the "
                f"intended PartitionSpecs before adding the entry")
        from gansformer_tpu.analysis.numerics.contracts import (
            numeric_contract_for)

        if numeric_contract_for(short) is None:
            raise ValueError(
                f"entry point {short!r}: no numeric contract in "
                f"analysis/numerics/contracts.NUMERIC_CONTRACTS — "
                f"declare the fp32-island intent before adding the "
                f"entry (ISSUE 19)")
        path, line = def_site(fn)
        eps.append(EntryPoint(
            name=f"steps.{short}[{config_name}]", fn=fn,
            abstract_args=abstract_args, make_args=make_args,
            static_kwargs=static_kwargs or {}, path=path, line=line,
            donate_argnums=donate, train_step=train_step,
            arg_specs=arg_specs,
            contract=contract_for(short, fsdp=True) if fsdp else None,
            **common))

    add("d_step", fns.d_step, (state_abs, imgs_abs, key_abs),
        lambda: (states.fresh(), imgs(), key(1)),
        donate=(0,), train_step=True, arg_specs=("state", "batch", "repl"))
    add("d_step_r1", fns.d_step_r1, (state_abs, imgs_abs, key_abs),
        lambda: (states.fresh(), imgs(), key(2)),
        donate=(0,), train_step=True, arg_specs=("state", "batch", "repl"))
    add("g_step", fns.g_step, (state_abs, key_abs),
        lambda: (states.fresh(), key(3)),
        donate=(0,), train_step=True, arg_specs=("state", "repl"))
    add("g_step_pl", fns.g_step_pl, (state_abs, key_abs),
        lambda: (states.fresh(), key(4)),
        donate=(0,), train_step=True, arg_specs=("state", "repl"))
    if fns.cycle is not None:
        k = fns.cycle_len
        stack_abs = jax.ShapeDtypeStruct((k,) + imgs_abs.shape, np.uint8)

        def stack():
            return np.random.RandomState(5).randint(
                0, 255, stack_abs.shape, dtype=np.uint8)

        add("cycle", fns.cycle, (state_abs, stack_abs, key_abs, 0),
            lambda: (states.fresh(), stack(), key(6), 0),
            donate=(0,), train_step=True,
            arg_specs=("state", "stack", "repl", "repl"))
    add("sample", fns.sample,
        (state_abs.ema_params, w_avg_abs, z_abs, key_abs),
        lambda: (states.fresh().ema_params, np.zeros(w_avg_abs.shape,
                                                     np.float32),
                 z(7), key(8)),
        static_kwargs={"truncation_psi": 0.7},
        arg_specs=("state", "repl", "batch", "repl"))
    add("ppl_pairs", fns.ppl_pairs,
        (state_abs.ema_params, z_abs, z_abs, ts_abs, key_abs),
        lambda: (states.fresh().ema_params, z(9), z(10),
                 np.linspace(0, 1, t.batch_size).astype(np.float32),
                 key(11)),
        static_kwargs={"epsilon": 1e-4},
        arg_specs=("state", "batch", "batch", "batch", "repl"))
    return eps


def build_serve_entry_points(config_name: str = "tiny-f32",
                             bucket: int = _BATCH,
                             include: Optional[List[str]] = None
                             ) -> List[EntryPoint]:
    """EntryPoints for the serving split (serve/programs.py, ISSUE 10):
    ``serve_map_seeds`` / ``serve_map_z`` / ``serve_synth`` over the
    tiny trace config, so partition-contract / collective-flow gate the
    REAL serving programs — replicated params, per-request rows on
    ``data`` — not a proxy.  ``bucket`` is the traced batch bucket
    (default: the matrix batch, divisible by every simulated data
    axis)."""
    import dataclasses

    import jax
    import numpy as np

    from gansformer_tpu.parallel.contracts import contract_for
    from gansformer_tpu.serve.programs import generator_fns
    from gansformer_tpu.serve.quant import quantize_params

    cfg = trace_configs()[config_name]
    m = cfg.model
    fns = generator_fns(cfg)
    # the serving precision axis (ISSUE 20): bf16/int8w synthesis runs
    # the model at bf16 compute — same flip ServePrograms applies
    bf16_cfg = dataclasses.replace(
        cfg, model=dataclasses.replace(m, dtype="bfloat16"))
    bf16_fns = generator_fns(bf16_cfg)
    params_abs = _abstract_state(cfg).ema_params
    states = _StateFactory(cfg)

    def qparams():
        return quantize_params(states.fresh().ema_params)

    # abstract twin of the quantized tree (QuantizedWeight is a pytree
    # node, so the map descends into its int8 codes + fp32 scales)
    qparams_abs = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), qparams())
    seeds_abs = jax.ShapeDtypeStruct((bucket,), np.int32)
    z_abs = jax.ShapeDtypeStruct((bucket, m.num_ws, m.latent_dim),
                                 np.float32)
    ws_abs = jax.ShapeDtypeStruct((bucket, m.num_ws, m.w_dim), np.float32)
    w_avg_abs = jax.ShapeDtypeStruct((m.w_dim,), np.float32)
    psi_abs = jax.ShapeDtypeStruct((bucket,), np.float32)
    key_abs = jax.ShapeDtypeStruct((2,), np.uint32)
    tags_abs = jax.ShapeDtypeStruct((bucket,), np.uint32)

    def rand(seed, shape):
        return np.random.RandomState(seed).normal(
            size=shape).astype(np.float32)

    synth_abs = (params_abs, w_avg_abs, ws_abs, psi_abs, key_abs, tags_abs)
    synth_specs = ("state", "repl", "batch", "batch", "repl", "batch")

    def synth_args(params_fn):
        return lambda: (params_fn(),
                        np.zeros(w_avg_abs.shape, np.float32),
                        rand(21, ws_abs.shape),
                        np.full((bucket,), 0.7, np.float32),
                        np.asarray(jax.random.PRNGKey(22)),
                        np.arange(bucket, dtype=np.uint32))

    table = {
        "serve_map_seeds": (
            fns.map_seeds, (params_abs, seeds_abs),
            lambda: (states.fresh().ema_params,
                     np.arange(1, bucket + 1, dtype=np.int32)),
            ("state", "batch"), m.dtype),
        "serve_map_z": (
            fns.map_z, (params_abs, z_abs),
            lambda: (states.fresh().ema_params, rand(20, z_abs.shape)),
            ("state", "batch"), m.dtype),
        "serve_synth": (
            fns.synthesize, synth_abs,
            synth_args(lambda: states.fresh().ema_params),
            synth_specs, m.dtype),
        # the precision variants gate the programs a non-f32 serving
        # floor actually compiles: bf16 activations over the f32 tree,
        # and int8w over the QuantizedWeight tree (dequant island
        # asserted by the fp32-island-contract rule, ISSUE 20)
        "serve_synth_bf16": (
            bf16_fns.synthesize, synth_abs,
            synth_args(lambda: states.fresh().ema_params),
            synth_specs, "bfloat16"),
        "serve_synth_int8w": (
            bf16_fns.synthesize,
            (qparams_abs,) + synth_abs[1:],
            synth_args(qparams),
            synth_specs, "bfloat16"),
    }
    eps: List[EntryPoint] = []
    for short, (fn, abstract_args, make_args, arg_specs,
                compute_dtype) in table.items():
        if include is not None and short not in include:
            continue
        if contract_for(short) is None:   # same loud gate as add()
            raise ValueError(
                f"serve entry point {short!r}: no sharding contract in "
                f"parallel/contracts.ENTRY_CONTRACTS")
        from gansformer_tpu.analysis.numerics.contracts import (
            numeric_contract_for)

        if numeric_contract_for(short) is None:
            raise ValueError(
                f"serve entry point {short!r}: no numeric contract in "
                f"analysis/numerics/contracts.NUMERIC_CONTRACTS")
        path, line = def_site(fn)
        # keep_unused=True: the split programs each use a SUBSET of the
        # params tree (map touches only the mapping network) and XLA
        # would prune the rest from the compiled signature — the
        # contract audit needs the resolved input shardings aligned
        # 1:1 with the declared leaves
        eps.append(EntryPoint(
            name=f"serve.{short}[{config_name}]",
            fn=jax.jit(fn, keep_unused=True),
            abstract_args=abstract_args, make_args=make_args,
            path=path, line=line, config_name=config_name,
            compute_dtype=compute_dtype, arg_specs=arg_specs))
    return eps


# The default trace surface per profile.  Structural rules only trace
# (no compile), so ``fast`` keeps full entry coverage on the reference
# config and targets the *added-value* members of the other two: bf16
# exists only for dtype flow, tiny-fused only for the cycle program.
FAST_MATRIX = {
    "tiny-f32": None,                       # all entry points
    "tiny-bf16": ["d_step_r1", "g_step_pl"],  # superset programs (R1+PL)
    # pallas training backend (ISSUE 9): the second-order reg pair holds
    # every kernel (fwd + bwd, both directions) inside real programs
    "tiny-pallas": ["d_step_r1", "g_step_pl"],
}


# Under ``full`` the backend member still contributes only its superset
# pair: the other five programs differ from tiny-f32's only inside the
# attention compute (same step structure, same layouts), and every kernel
# already sits inside the R1/PL programs.
FULL_INCLUDE = {"tiny-pallas": ["d_step_r1", "g_step_pl"]}


def build_matrix(profile: str = "fast") -> List[EntryPoint]:
    out: List[EntryPoint] = []
    if profile == "fast":
        for cname, include in FAST_MATRIX.items():
            out.extend(build_entry_points(cname, include=include))
        # the serving split (ISSUE 10): map is the cache-feeding half,
        # synth the per-request hot program — the pair the service
        # dispatches; map_z (the generate-CLI flavor) differs from
        # map_seeds only by the latent draw, so full keeps it alone
        out.extend(build_serve_entry_points(
            include=["serve_map_seeds", "serve_synth"]))
    else:
        for cname in trace_configs():
            out.extend(build_entry_points(cname,
                                          include=FULL_INCLUDE.get(cname)))
        out.extend(build_serve_entry_points())
    return out
