"""sharding-audit — resolved shardings vs. intent, on a fake 2-device mesh.

Two silent failure modes only visible after GSPMD propagation:

* a parameter above a size threshold whose *resolved* sharding is fully
  replicated — every device holds a full copy.  Replication is the
  deliberate data-parallel layout for this model family's small params,
  so the threshold is what makes the rule meaningful: anything crossing
  it deserves an explicit sharding decision, not a default.
* a donated argument whose output sharding differs from its input
  sharding — XLA cannot alias the buffers, so it inserts a full copy
  and the donation quietly buys nothing.

The audit runs on a 2-device mesh (tests and the CLI child force
``--xla_force_host_platform_device_count``), lowers the entry point
with sharded abstract inputs matching the real loop's placement
(state replicated, batches on the ``data`` axis), compiles, and reads
``compiled.input_shardings`` / ``output_shardings``.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from gansformer_tpu.analysis.trace.base import (
    EntryPoint, TraceContext, TraceRule, leaf_bytes as _leaf_bytes,
    path_str as _path_str, register, shardings_equivalent)

REPLICATED_THRESHOLD_BYTES = 8 * 1024 * 1024


def make_sharded_args(ep: EntryPoint, env) -> Optional[Tuple[Any, ...]]:
    """``abstract_args`` re-annotated with the real loop's shardings,
    driven by the entry point's ``arg_specs`` tags."""
    import jax

    if not ep.arg_specs or len(ep.arg_specs) != len(ep.abstract_args):
        return None
    tag_to_sharding = {
        "state": env.replicated(), "repl": env.replicated(),
        "batch": env.batch(), "stack": env.batch_stack(),
    }

    def annotate(leaf, sharding):
        if leaf is None or not hasattr(leaf, "shape"):
            return leaf
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                    sharding=sharding)

    out = []
    for tag, arg in zip(ep.arg_specs, ep.abstract_args):
        sh = tag_to_sharding[tag]
        if hasattr(arg, "shape") or arg is None:
            out.append(annotate(arg, sh))
        elif isinstance(arg, (int, float)):
            out.append(arg)                     # scalar — no sharding
        else:
            out.append(jax.tree_util.tree_map(
                lambda l: annotate(l, sh), arg))
    return tuple(out)


_equivalent = shardings_equivalent


@register
class ShardingAuditRule(TraceRule):
    id = "sharding-audit"
    description = ("resolved sharding defeats intent: oversize fully-"
                   "replicated parameter, or donated input whose output "
                   "sharding differs (donation degrades to a copy)")
    hint = ("give big params an explicit NamedSharding (or shard them "
            "over the model axis); keep donated outputs on the same "
            "sharding as their inputs")
    dynamic = True

    replicated_threshold = REPLICATED_THRESHOLD_BYTES

    def check(self, ep: EntryPoint, ctx: TraceContext) -> None:
        import jax

        from gansformer_tpu.core.config import MeshConfig
        from gansformer_tpu.parallel.mesh import make_mesh

        devices = jax.devices()
        if len(devices) < 2:
            ctx.notes.append(
                f"{ep.name}: sharding audit needs ≥2 devices (run under "
                f"XLA_FLAGS=--xla_force_host_platform_device_count=2); "
                f"skipped")
            return
        env = make_mesh(MeshConfig(data=2, model=1), devices=devices[:2])
        args = make_sharded_args(ep, env)
        if args is None:
            ctx.notes.append(f"{ep.name}: no arg_specs; sharding audit "
                             f"skipped")
            return
        try:
            with env.activate():
                compiled = ep.fn.lower(*args, **ep.static_kwargs).compile()
        except Exception as e:
            ctx.report(self, ep.anchor,
                       f"{ep.name}: sharded lowering failed: "
                       f"{type(e).__name__}: {str(e)[:160]}")
            return

        in_tree = compiled.input_shardings[0]
        flat_in, _ = jax.tree_util.tree_flatten(in_tree)
        in_leaves = jax.tree_util.tree_flatten_with_path(args)[0]
        if len(flat_in) != len(in_leaves):
            ctx.notes.append(f"{ep.name}: input sharding arity mismatch; "
                             f"audit skipped")
            return

        # -- oversize fully-replicated params --------------------------------
        for (path, aval), sharding in zip(in_leaves, flat_in):
            if not hasattr(aval, "shape"):
                continue
            n = _leaf_bytes(aval)
            if n < self.replicated_threshold:
                continue
            if getattr(sharding, "is_fully_replicated", False):
                ctx.report(self, ep.anchor,
                           f"{ep.name}: input {_path_str(path)} "
                           f"({n / 2**20:.1f} MiB) resolves fully "
                           f"replicated — every device holds a copy")

        # -- donated input vs output sharding --------------------------------
        # Repo convention: donate_argnums == (0,) and output[0] is the
        # updated version of the donated pytree (same treedef).
        if ep.donate_argnums != (0,):
            return
        flat_out, _ = jax.tree_util.tree_flatten(compiled.output_shardings)
        state_leaves = jax.tree_util.tree_flatten_with_path(args[0])[0]
        n_state = len(state_leaves)
        if len(flat_out) < n_state:
            ctx.notes.append(f"{ep.name}: output sharding arity "
                             f"({len(flat_out)}) smaller than donated "
                             f"input ({n_state}); donation audit skipped")
            return
        in_state_shardings = flat_in[:n_state]
        out_state_shardings = flat_out[:n_state]
        for (path, aval), s_in, s_out in zip(
                state_leaves, in_state_shardings, out_state_shardings):
            ndim = len(getattr(aval, "shape", ()))
            if not _equivalent(s_in, s_out, ndim):
                ctx.report(self, ep.anchor,
                           f"{ep.name}: donated arg leaf "
                           f"{_path_str(path)} changes sharding "
                           f"{s_in} → {s_out}; XLA must copy instead of "
                           f"aliasing, defeating donation")
