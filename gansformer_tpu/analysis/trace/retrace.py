"""retrace-hazard — a second compile for equivalent inputs.

The most expensive silent bug on a TPU fleet: a jitted function whose
cache key depends on how the caller *constructed* an input rather than
what it means — a python scalar one tick and an np scalar the next
(weak vs strong dtype), a rebuilt static kwarg that hashes differently,
a closure re-jitted per call.  Every occurrence is a full XLA compile
(minutes at flagship scale) in the middle of the hot loop.

The probe is empirical, not heuristic: call the real entry point with
its reference inputs, then again with *equivalent but differently
constructed* variants —

* ``rebuilt``       — every array freshly allocated (same values,
                      dtypes, shapes), static kwargs re-created as
                      equal-but-not-identical objects;
* ``scalar-flavor`` — python scalars flipped to np scalars and vice
                      versa (the weak-type axis).

Any cache growth after the first call is a finding.  The static
companion rule (``retrace-static``, AST side) catches the same family
in code the harness cannot execute.
"""

from __future__ import annotations

import copy
from typing import Any, Optional, Tuple

from gansformer_tpu.analysis.trace.base import (
    EntryPoint, TraceContext, TraceRule, register)


def cache_size(fn) -> Optional[int]:
    """Number of executables in the jit's in-memory cache — the number
    of distinct trace keys seen.  Independent of the persistent
    compilation cache (a disk hit still means a retrace happened)."""
    probe = getattr(fn, "_cache_size", None)
    if callable(probe):
        try:
            return int(probe())
        except Exception:
            return None
    return None


_TRACE_EVENTS = ("jaxpr_trace_duration",)
_trace_counter = {"n": 0, "installed": False}


def _install_trace_counter() -> None:
    if _trace_counter["installed"]:
        return
    from jax import monitoring

    def _on_duration(event: str, duration: float, **kw) -> None:
        if any(t in event for t in _TRACE_EVENTS):
            _trace_counter["n"] += 1

    monitoring.register_event_duration_secs_listener(_on_duration)
    _trace_counter["installed"] = True


def count_traces(fn, call) -> Tuple[Any, int]:
    """Run ``call()`` and return (result, traces-it-caused).  Prefers the
    jit cache size delta; falls back to jax.monitoring trace events for
    wrapped entry points that don't expose a cache."""
    before = cache_size(fn)
    if before is not None:
        result = call()
        return result, (cache_size(fn) or before) - before
    _install_trace_counter()
    n0 = _trace_counter["n"]
    result = call()
    return result, _trace_counter["n"] - n0


def _flip_scalar(x):
    import numpy as np

    if isinstance(x, bool) or isinstance(x, np.bool_):
        return None
    if isinstance(x, int):
        return np.int32(x)
    if isinstance(x, float):
        return np.float32(x)
    if isinstance(x, np.integer):
        return int(x)
    if isinstance(x, np.floating):
        return float(x)
    return None


def scalar_flavor_variant(args: tuple) -> Optional[tuple]:
    """Flip the construction flavor of top-level scalar args (python ↔
    np) — the weak-type axis of the equivalence matrix.  None when the
    signature has no scalar args (the variant would be identical)."""
    flipped = False
    out = []
    for a in args:
        f = _flip_scalar(a)
        if f is None:
            out.append(a)
        else:
            out.append(f)
            flipped = True
    return tuple(out) if flipped else None


@register
class RetraceHazardRule(TraceRule):
    id = "retrace-hazard"
    description = ("equivalent-but-differently-constructed inputs caused "
                   "a second compilation (weak-type / static-kwarg / "
                   "closure cache-key instability)")
    hint = ("canonicalize scalar inputs at the jit boundary (int(...) / "
            "jnp.asarray with an explicit dtype) and keep static kwargs "
            "hash-stable")
    dynamic = True

    def check(self, ep: EntryPoint, ctx: TraceContext) -> None:
        import jax

        if ep.make_args is None:
            ctx.notes.append(f"{ep.name}: no concrete-input builder; "
                             f"retrace probe skipped")
            return
        try:
            ref = ep.make_args()
            out, first = count_traces(
                ep.fn, lambda: ep.fn(*ref, **ep.static_kwargs))
            jax.block_until_ready(out)
        except Exception as e:   # a broken entry point is its own finding
            ctx.report(self, ep.anchor,
                       f"{ep.name}: reference call failed during retrace "
                       f"probe: {type(e).__name__}: {str(e)[:160]}")
            return

        variants = [
            ("rebuilt", ep.make_args(),
             {k: copy.deepcopy(v) for k, v in ep.static_kwargs.items()}),
        ]
        flavored = scalar_flavor_variant(ep.make_args())
        if flavored is not None:
            variants.append(("scalar-flavor", flavored,
                             dict(ep.static_kwargs)))

        for label, args, statics in variants:
            try:
                out, traced = count_traces(
                    ep.fn, lambda: ep.fn(*args, **statics))
                jax.block_until_ready(out)
            except Exception as e:
                ctx.report(self, ep.anchor,
                           f"{ep.name}: '{label}' variant call failed: "
                           f"{type(e).__name__}: {str(e)[:160]}")
                continue
            if traced > 0:
                ctx.report(self, ep.anchor,
                           f"{ep.name}: recompiled for the '{label}' "
                           f"input variant (equivalent inputs, new cache "
                           f"entry) — every such call site pays a full "
                           f"XLA compile at scale")
