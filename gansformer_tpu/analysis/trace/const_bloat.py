"""jaxpr-const-bloat — large constants baked into the compiled program.

An array closed over by a jitted function (instead of passed as an
argument) becomes a jaxpr *constant*: it is embedded in every
executable specialization, re-uploaded per compile, and duplicated in
HBM — invisible in the source, obvious in the jaxpr.  The classic form
is an ``np.ndarray`` captured by a closure (filter banks, positional
grids, precomputed tables).

Threshold: constants are everywhere (scalar literals, tiny index
vectors) and harmless below a few KiB; the rule flags only constants
whose byte size crosses ``THRESHOLD_BYTES`` — at the tiny trace config
that means anything big enough there will be *proportionally* enormous
at the flagship resolution.
"""

from __future__ import annotations

from gansformer_tpu.analysis.trace.base import (
    EntryPoint, TraceContext, TraceRule, iter_consts, register, sizeof)

THRESHOLD_BYTES = 64 * 1024


@register
class ConstBloatRule(TraceRule):
    id = "jaxpr-const-bloat"
    description = ("closed-over array baked into the jaxpr as a large "
                   "constant (duplicated per executable, re-uploaded per "
                   "compile)")
    hint = ("pass the array as a function argument (donate or shard it "
            "like any other input) instead of closing over it")

    threshold = THRESHOLD_BYTES

    def __init__(self):
        # spans all entry points of one run: the same def traced under
        # two matrix configs anchors at the same line — report each
        # (function, const) once so the baseline entry count doesn't
        # depend on the profile's config coverage
        self._seen = set()

    def check(self, ep: EntryPoint, ctx: TraceContext) -> None:
        closed = ctx.jaxpr(ep)
        entry = ep.name.split("[")[0]        # config-independent identity
        for const in iter_consts(closed):
            n = sizeof(const)
            if n < self.threshold:
                continue
            shape = getattr(const, "shape", ())
            dtype = getattr(const, "dtype", type(const).__name__)
            key = (entry, ep.anchor, tuple(shape), str(dtype))
            if key in self._seen:
                continue
            self._seen.add(key)
            ctx.report(self, ep.anchor,
                       f"{ep.name}: jaxpr constant {tuple(shape)} {dtype} "
                       f"({n / 1024:.0f} KiB) baked into the program")
