"""The trace-analysis driver: entry points × rules → Findings.

Profiles bound the cost (the structural rules only *trace* — Python
speed; the dynamic rules *execute/compile* — XLA speed):

* ``structural`` — tracing only; never compiles or executes.  Safe in
  any process (no device-count or cache side effects).
* ``contracts`` — structural plus the ``partition-contract`` check on
  the four train-step programs (2-device simulated mesh).  This is the
  ``--selfcheck`` / pre-commit budget: one contract-sharded compile per
  train step, mostly cached on re-runs via the persistent compile
  cache.
* ``fast`` — contracts plus the retrace probe on the plain train-step
  pair (``d_step``/``g_step``) and the sharding/collective audits on
  all four train-step programs, all on the f32 reference config and
  the 2-device mesh.
* ``full`` — every rule over every entry point of every matrix config,
  with the graftcomms pair (partition-contract, collective-flow) run
  across the whole simulated mesh matrix (1/2/4 devices —
  ``parallel/contracts.MESH_MATRIX``; sharding-audit keeps its legacy
  fixed 2-device mesh).  The ``slow``-marked test and explicit
  ``--trace-profile full`` runs.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

from gansformer_tpu.analysis.findings import Finding
from gansformer_tpu.analysis.trace.base import (
    EntryPoint, TraceContext, all_trace_rules)
from gansformer_tpu.analysis.trace.entry_points import build_matrix

PROFILES = ("structural", "contracts", "fast", "full")

# fast-profile dynamic surface (see module docstring)
_FAST_RETRACE = ("steps.d_step[tiny-f32]", "steps.g_step[tiny-f32]")
# ALL FOUR train-step programs: a sharding/contract regression in the
# reg variants (the second-order programs with the heaviest layouts)
# must not hide behind a d_step-only fast probe.
_FAST_MESH = ("steps.d_step[tiny-f32]", "steps.d_step_r1[tiny-f32]",
              "steps.g_step[tiny-f32]", "steps.g_step_pl[tiny-f32]")
# the rules that lower+compile on the simulated mesh matrix
_MESH_RULES = ("sharding-audit", "partition-contract", "collective-flow")


def _dynamic_entries(rule_id: str, profile: str,
                     entries: List[EntryPoint]) -> List[EntryPoint]:
    if profile == "structural":
        return []
    if profile == "contracts":
        if rule_id == "partition-contract":
            return [ep for ep in entries if ep.name in _FAST_MESH]
        return []
    if profile == "full":
        if rule_id == "sharding-audit":
            return [ep for ep in entries if ep.arg_specs]
        if rule_id in ("partition-contract", "collective-flow"):
            # Sharding/collective STRUCTURE is dtype-independent: the
            # bf16 matrix member exists for dtype flow, and re-compiling
            # its programs across the whole mesh matrix would double the
            # cost for zero new layout coverage.  Fixture entries carry
            # no config_name and pass through.
            return [ep for ep in entries
                    if ep.config_name in ("", "tiny-f32")]
        return entries
    wanted = _FAST_MESH if rule_id in _MESH_RULES else _FAST_RETRACE
    return [ep for ep in entries if ep.name in wanted]


def mesh_sizes_for(profile: str) -> Tuple[int, ...]:
    """Simulated-mesh device counts for the mesh-compiling rules: the
    full matrix only under ``full`` (3× the compiles), the cheap
    2-device mesh everywhere else."""
    if profile == "full":
        from gansformer_tpu.parallel.contracts import MESH_MATRIX

        return MESH_MATRIX
    return (2,)


def run_trace(profile: str = "fast",
              rules: Optional[Iterable[type]] = None,
              entries: Optional[List[EntryPoint]] = None,
              mesh_sizes: Optional[Tuple[int, ...]] = None
              ) -> Tuple[List[Finding], TraceContext]:
    """Run the trace rules; returns (findings, context).  ``entries``
    overrides the built-in matrix (tests inject fixtures this way) —
    with an override, profile only selects structural vs dynamic, not
    which entries the dynamic rules see.  ``mesh_sizes`` overrides the
    profile's simulated-mesh matrix; the context carries the
    accumulated comms-cost table (``ctx.comms``)."""
    if profile not in PROFILES:
        raise ValueError(f"unknown trace profile {profile!r}; "
                         f"have {PROFILES}")
    rule_classes = list(rules) if rules is not None else all_trace_rules()
    injected = entries is not None
    built: List[List[EntryPoint]] = []   # lazy: building the matrix means
                                         # constructing real train steps —
                                         # skip it when no rule has targets
                                         # (e.g. structural + dynamic-only)

    def eps() -> List[EntryPoint]:
        if not built:
            built.append(entries if injected else build_matrix(
                "full" if profile == "full" else "fast"))
        return built[0]

    ctx = TraceContext(mesh_sizes=mesh_sizes if mesh_sizes is not None
                       else mesh_sizes_for(profile))
    for cls in rule_classes:
        rule = cls()
        if rule.dynamic:
            if profile == "structural":
                continue
            targets = (eps() if injected
                       else _dynamic_entries(rule.id, profile, eps()))
        else:
            targets = eps()
        for ep in targets:
            rule.check(ep, ctx)
    ctx.findings.sort(key=Finding.sort_key)
    return ctx.findings, ctx
