"""The trace-analysis driver: entry points × rules → Findings.

Profiles bound the cost (the structural rules only *trace* — Python
speed; the dynamic rules *execute/compile* — XLA speed):

* ``fast`` — structural rules over the whole fast matrix; the retrace
  probe on the plain train-step pair (``d_step``/``g_step``) and the
  sharding audit on ``d_step``, all on the f32 reference config.  This
  is the tier-1 / ``--selfcheck`` budget (<~1 min cold, mostly cached
  on re-runs via the persistent compile cache).
* ``full`` — every rule over every entry point of every matrix config
  (the ``slow``-marked test and explicit ``--trace-profile full`` runs).
* ``structural`` — tracing only; never compiles or executes.  Safe in
  any process (no device-count or cache side effects).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

from gansformer_tpu.analysis.findings import Finding
from gansformer_tpu.analysis.trace.base import (
    EntryPoint, TraceContext, all_trace_rules)
from gansformer_tpu.analysis.trace.entry_points import build_matrix

PROFILES = ("structural", "fast", "full")

# fast-profile dynamic surface (see module docstring)
_FAST_RETRACE = ("steps.d_step[tiny-f32]", "steps.g_step[tiny-f32]")
_FAST_SHARDING = ("steps.d_step[tiny-f32]",)


def _dynamic_entries(rule_id: str, profile: str,
                     entries: List[EntryPoint]) -> List[EntryPoint]:
    if profile == "structural":
        return []
    if profile == "full":
        if rule_id == "sharding-audit":
            return [ep for ep in entries if ep.arg_specs]
        return entries
    wanted = _FAST_SHARDING if rule_id == "sharding-audit" else _FAST_RETRACE
    return [ep for ep in entries if ep.name in wanted]


def run_trace(profile: str = "fast",
              rules: Optional[Iterable[type]] = None,
              entries: Optional[List[EntryPoint]] = None
              ) -> Tuple[List[Finding], TraceContext]:
    """Run the trace rules; returns (findings, context).  ``entries``
    overrides the built-in matrix (tests inject fixtures this way) —
    with an override, profile only selects structural vs dynamic, not
    which entries the dynamic rules see."""
    if profile not in PROFILES:
        raise ValueError(f"unknown trace profile {profile!r}; "
                         f"have {PROFILES}")
    rule_classes = list(rules) if rules is not None else all_trace_rules()
    injected = entries is not None
    built: List[List[EntryPoint]] = []   # lazy: building the matrix means
                                         # constructing real train steps —
                                         # skip it when no rule has targets
                                         # (e.g. structural + dynamic-only)

    def eps() -> List[EntryPoint]:
        if not built:
            built.append(entries if injected else build_matrix(
                "full" if profile == "full" else "fast"))
        return built[0]

    ctx = TraceContext()
    for cls in rule_classes:
        rule = cls()
        if rule.dynamic:
            if profile == "structural":
                continue
            targets = (eps() if injected
                       else _dynamic_entries(rule.id, profile, eps()))
        else:
            targets = eps()
        for ep in targets:
            rule.check(ep, ctx)
    ctx.findings.sort(key=Finding.sort_key)
    return ctx.findings, ctx
