"""dtype-promotion — silent float upcasts visible only in the jaxpr.

NumPy-style type promotion inserts ``convert_element_type`` equations
the source never wrote: a bf16 activation meeting an f32 literal
silently computes the rest of the expression in f32 (twice the HBM
traffic and matmul cost the bf16 config was chosen to avoid), and any
f64 appearing under an accidentally-enabled ``jax_enable_x64`` poisons
everything downstream.

The rule walks every ``convert_element_type`` in the traced program and
flags *silent* float upcasts: the source line that produced the convert
(via the eqn's user frame) does not itself spell a dtype or an
``astype`` — if the cast is written out (``x.astype(jnp.float32)`` for
loss accumulation, an f32 head layer) it is a decision, not a leak.
Findings anchor on the promoting source line, so the usual inline
``# graftlint: disable=dtype-promotion`` suppresses intentional cases
the heuristic cannot see.
"""

from __future__ import annotations

import re

from gansformer_tpu.analysis.trace.base import (
    EntryPoint, TraceContext, TraceRule, eqn_frame, in_repo, iter_eqns,
    line_text, register)

_FLOAT_BITS = {"bfloat16": 16, "float16": 16, "float32": 32, "float64": 64}

# A source line that spells any of these made its cast on purpose.
_EXPLICIT = re.compile(
    r"astype|convert_element_type|float32|float64|float16|bfloat16"
    r"|\.dtype|dtype=")


def _bits(dtype) -> int:
    return _FLOAT_BITS.get(getattr(dtype, "name", str(dtype)), 0)


@register
class DtypePromotionRule(TraceRule):
    id = "dtype-promotion"
    description = ("silent float upcast (bf16→f32 / →f64) inserted by "
                   "type promotion, not written in the source")
    hint = ("make the cast explicit (x.astype(...)) if intended, or fix "
            "the stray wide-dtype operand (jnp.float32 literals, default-"
            "dtype jnp.arange/linspace) if not")

    def __init__(self):
        # spans ALL entry points of one run: a single promoting line in
        # shared model code is traced via many entries — one finding per
        # line keeps `--fix-baseline` output independent of how many
        # entries (fast vs full profile) happened to reach the line
        self._seen = set()

    def check(self, ep: EntryPoint, ctx: TraceContext) -> None:
        closed = ctx.jaxpr(ep)
        seen = self._seen
        for eqn in iter_eqns(closed.jaxpr):
            if eqn.primitive.name != "convert_element_type":
                continue
            in_aval = eqn.invars[0].aval
            out_aval = eqn.outvars[0].aval
            ib = _bits(getattr(in_aval, "dtype", None))
            ob = _bits(getattr(out_aval, "dtype", None))
            if not ib or ob <= ib:
                continue    # not a float→wider-float conversion
            if ob < 64 and ep.compute_dtype != "bfloat16":
                # in an all-f32 model, f32 converts are not a regression;
                # only ever-wider f64 is. bf16 models audit bf16→f32 too.
                continue
            frame = eqn_frame(eqn)
            if frame is None or not in_repo(frame[0]):
                continue    # library-internal promotion; not actionable
            text = line_text(*frame)
            if _EXPLICIT.search(text):
                continue    # cast is written in the source — a decision
            key = (frame[0], frame[1], str(in_aval.dtype),
                   str(out_aval.dtype))
            if key in seen:
                continue
            seen.add(key)
            ctx.report(self, frame,
                       f"silent {in_aval.dtype}→{out_aval.dtype} promotion "
                       f"(first traced via {ep.name})")
