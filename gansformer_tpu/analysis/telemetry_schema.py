"""Run-dir telemetry artifact lint (migrated from scripts/check_telemetry.py).

The non-AST member of the analysis family: validates what a real (smoke)
run actually wrote —

* ``events.jsonl`` — every line is a Chrome-trace event: complete
  spans (``ph`` == "X" with numeric non-negative ``ts``/``dur``) or the
  request tracer's async events (``ph`` in "b"/"n"/"e", which carry an
  ``id`` instead of a ``dur``); always ``name`` str, numeric
  non-negative ``ts``, integer ``pid``/``tid``.
* ``requests.jsonl`` — the request tracer's ledger: one row per
  terminal request with rid / outcome from the terminal vocabulary /
  cause on non-fulfilled outcomes / monotone event timeline.
  Values-aware against ``telemetry.prom``'s ``reqtrace_*`` counters.
* ``telemetry.prom`` — Prometheus text exposition: well-formed
  ``# TYPE <name> <kind>`` comments, every sample line
  ``<legal_name> <float>``, and every sample's family declared by a
  preceding TYPE line (``_count``/``_sum``/``_min``/``_max`` suffixes
  resolve to their summary family).
* ``heartbeat-p*.json`` — required keys with sane types.

``check_events``/``check_prom``/``check_heartbeat``/``check_run_dir``
keep the pre-framework API (the script shim and tests/test_obs.py call
them directly); ``lint_run_dir`` adapts the same errors into ``Finding``
objects so the ``gansformer-lint --run-dir`` path reports through the
shared reporters.  This lint pairs with the AST-side
telemetry-name-convention rule: that one pins the *source* names, this
one the *artifact* schema.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import List, Optional

from gansformer_tpu.analysis.findings import Finding

PROM_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
PROM_TYPES = {"counter", "gauge", "summary", "histogram", "untyped"}
EVENT_KEYS = {"name": str, "ph": str, "ts": (int, float),
              "pid": int, "tid": int}
# "X" = complete span (needs dur); b/n/e = the request tracer's async
# begin/instant/end triple (needs the correlation id instead)
EVENT_PHASES = {"X", "b", "n", "e"}
HEARTBEAT_KEYS = {"process": int, "pid": int, "host": str,
                  "time": (int, float), "step": int, "kimg": (int, float)}


def check_events(path: str) -> List[str]:
    errors = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if not line.strip():
                continue
            try:
                ev = json.loads(line)
            except ValueError as e:
                errors.append(f"{path}:{i}: not JSON ({e})")
                continue
            ph = ev.get("ph")
            keys = dict(EVENT_KEYS)
            if ph == "X":
                keys["dur"] = (int, float)
            elif ph in EVENT_PHASES:
                keys["id"] = str
            for key, typ in keys.items():
                if key not in ev:
                    errors.append(f"{path}:{i}: missing {key!r}")
                elif not isinstance(ev[key], typ) or \
                        isinstance(ev[key], bool):
                    errors.append(
                        f"{path}:{i}: {key}={ev[key]!r} is not {typ}")
            if ph not in EVENT_PHASES:
                errors.append(f"{path}:{i}: ph={ph!r} (expected one of "
                              f"{sorted(EVENT_PHASES)})")
            for key in ("ts", "dur"):
                if isinstance(ev.get(key), (int, float)) and ev[key] < 0:
                    errors.append(f"{path}:{i}: negative {key}")
    return errors


def check_requests(path: str,
                   prom_path: Optional[str] = None) -> List[str]:
    """``requests.jsonl`` ledger schema + cross-artifact consistency.

    Row-level: rid str, outcome from the terminal vocabulary, a cause
    on every non-fulfilled outcome, numeric non-negative ``e2e_ms``,
    events a non-empty list opening with ``submitted`` at t 0, closing
    with the outcome, kinds from the lifecycle vocabulary, timestamps
    monotone non-decreasing.  Torn trailing lines are tolerated (a
    killed service mid-append is this ledger's subject matter) — torn
    lines mid-file are errors.

    Values-aware (``prom_path`` given): when the tracer reports no
    ledger overflow (``reqtrace_ledger_dropped_total`` == 0), the row
    count must equal ``reqtrace_ledger_rows_total``; fulfilled rows
    imply ``serve_requests_total`` moved — a ledger describing traffic
    the service never counted means the two planes came from different
    runs."""
    from gansformer_tpu.obs.reqtrace import EVENT_KINDS, TERMINAL_KINDS

    errors = []
    with open(path) as f:
        lines = [(i, line) for i, line in enumerate(f, 1)
                 if line.strip()]
    rows = []
    for n, (i, line) in enumerate(lines):
        try:
            row = json.loads(line)
        except ValueError as e:
            if n == len(lines) - 1:
                continue           # torn final append: expected ending
            errors.append(f"{path}:{i}: not JSON ({e})")
            continue
        if not isinstance(row, dict):
            errors.append(f"{path}:{i}: not a JSON object")
            continue
        rows.append(row)
        if not isinstance(row.get("rid"), str):
            errors.append(f"{path}:{i}: rid={row.get('rid')!r} "
                          f"is not a string")
        outcome = row.get("outcome")
        if outcome not in TERMINAL_KINDS:
            errors.append(f"{path}:{i}: outcome={outcome!r} outside "
                          f"the terminal vocabulary {TERMINAL_KINDS}")
        if outcome in TERMINAL_KINDS and outcome != "fulfilled" \
                and not row.get("cause"):
            errors.append(f"{path}:{i}: {outcome} row without a cause")
        e2e = row.get("e2e_ms")
        if not isinstance(e2e, (int, float)) or isinstance(e2e, bool) \
                or e2e < 0:
            errors.append(f"{path}:{i}: e2e_ms={e2e!r} is not a "
                          f"non-negative number")
        events = row.get("events")
        if not isinstance(events, list) or not events:
            errors.append(f"{path}:{i}: events is not a non-empty list")
            continue
        kinds = [ev.get("kind") for ev in events
                 if isinstance(ev, dict)]
        if len(kinds) != len(events):
            errors.append(f"{path}:{i}: non-object event entry")
            continue
        for k in kinds:
            if k not in EVENT_KINDS:
                errors.append(f"{path}:{i}: event kind {k!r} outside "
                              f"the lifecycle vocabulary")
        if kinds and kinds[0] != "submitted":
            errors.append(f"{path}:{i}: first event {kinds[0]!r} "
                          f"(expected 'submitted')")
        if kinds and outcome in TERMINAL_KINDS and kinds[-1] != outcome:
            errors.append(f"{path}:{i}: last event {kinds[-1]!r} "
                          f"does not match outcome {outcome!r}")
        ts = [ev.get("t_ms") for ev in events]
        if any(not isinstance(t, (int, float)) or isinstance(t, bool)
               or t < 0 for t in ts):
            errors.append(f"{path}:{i}: non-numeric or negative t_ms")
        elif any(b < a for a, b in zip(ts, ts[1:])):
            errors.append(f"{path}:{i}: event timeline not monotone")
    seen = set()
    for row in rows:
        rid = row.get("rid")
        if isinstance(rid, str):
            if rid in seen:
                errors.append(f"{path}: duplicate terminal row for "
                              f"request {rid!r}")
            seen.add(rid)
    if prom_path is not None and os.path.exists(prom_path):
        from gansformer_tpu.obs.registry import parse_prom_values

        vals = parse_prom_values(prom_path)
        ledgered = vals.get("reqtrace_ledger_rows_total")
        dropped = vals.get("reqtrace_ledger_dropped_total", 0.0)
        if ledgered is not None and dropped == 0.0 \
                and len(rows) != int(ledgered):
            errors.append(
                f"{path}: {len(rows)} ledger rows but "
                f"reqtrace_ledger_rows_total is {ledgered:g} with no "
                f"overflow recorded — rows were lost outside the "
                f"declared bound")
        fulfilled = sum(1 for r in rows
                        if r.get("outcome") == "fulfilled")
        if fulfilled > 0 and vals.get("serve_requests_total", 0.0) <= 0:
            errors.append(
                f"{path}: {fulfilled} fulfilled rows but "
                f"serve_requests_total never moved — ledger and prom "
                f"describe different runs")
    return errors


def check_prom(path: str) -> List[str]:
    errors = []
    declared = set()
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.rstrip("\n")
            if not line.strip():
                continue
            if line.startswith("#"):
                parts = line.split()
                if len(parts) >= 2 and parts[1] == "TYPE":
                    if len(parts) != 4 or not PROM_NAME.match(parts[2]) \
                            or parts[3] not in PROM_TYPES:
                        errors.append(f"{path}:{i}: malformed TYPE line")
                    else:
                        declared.add(parts[2])
                continue
            parts = line.split()
            if len(parts) != 2:
                errors.append(f"{path}:{i}: expected '<name> <value>'")
                continue
            name, value = parts
            base = name.split("{")[0]
            if not PROM_NAME.match(base):
                errors.append(f"{path}:{i}: illegal metric name {base!r}")
            try:
                float(value)
            except ValueError:
                errors.append(f"{path}:{i}: non-numeric value {value!r}")
            family = re.sub(r"_(count|sum|min|max)$", "", base)
            if base not in declared and family not in declared:
                errors.append(f"{path}:{i}: sample {base!r} has no "
                              f"preceding # TYPE declaration")
    return errors


def check_metric_families(path: str) -> List[str]:
    """Device-truth metric families (ISSUE 8): telemetry.prom must
    answer "is device truth being measured?" EXPLICITLY — either with
    the family's gauges or with its off/unavailable marker, never by
    silent absence (absence would be indistinguishable from "the wiring
    rotted").

    * ``device/*`` — ``device_sampler_off`` marker always; when the
      sampler is on and a sample landed, the divergence gauge
      ``device_wall_busy_ratio`` + ``device_busy_ms`` must exist.
    * ``hbm/*`` — ``hbm_unavailable`` marker always; when the backend
      reports (0.0), ``hbm_bytes_in_use`` + ``hbm_peak_bytes``.
    * ``compile/*`` — ``compile_compiles_total`` (materialized at
      listener install) and ``compile_retraces_total`` (materialized at
      the tick-0 arm).
    * ``data/*`` robustness family (ISSUE 15) — the retry/quarantine/
      stall counters, materialized by the loop at setup so absence
      always means rotted wiring.  Values-aware: quarantines > 0 imply
      the ``data_quarantine.jsonl`` ledger exists beside the prom (a
      counter that moved without its offset+cause evidence is
      unreviewable).
    * ``train/nonfinite*`` cross-check family (ISSUE 19) — the runtime
      twin of the graftnum fp32-island audit, materialized by the loop
      at setup; the cause-labelled counters (loss/grad/param) classify
      any non-finite tick stat on already-fetched host values.
    """
    from gansformer_tpu.obs.registry import parse_prom_values

    vals = parse_prom_values(path)
    errors = []
    if "device_sampler_off" not in vals:
        errors.append(f"{path}: missing device/* family — no "
                      f"device_sampler_off marker (is the device-time "
                      f"sampler wired?)")
    elif vals["device_sampler_off"] == 0.0:
        if "device_samples_total" not in vals:
            errors.append(f"{path}: device sampler on but no "
                          f"device_samples_total counter")
        elif vals["device_samples_total"] > 0 and (
                "device_wall_busy_ratio" not in vals
                or "device_busy_ms" not in vals):
            errors.append(f"{path}: device sample landed but the "
                          f"divergence gauges (device_wall_busy_ratio/"
                          f"device_busy_ms) are missing")
    if "hbm_unavailable" not in vals:
        errors.append(f"{path}: missing hbm/* family — no "
                      f"hbm_unavailable marker")
    elif vals["hbm_unavailable"] == 0.0 and (
            "hbm_bytes_in_use" not in vals or "hbm_peak_bytes" not in vals):
        errors.append(f"{path}: backend reports memory but "
                      f"hbm_bytes_in_use/hbm_peak_bytes are missing")
    for name in ("compile_compiles_total", "compile_retraces_total"):
        if name not in vals:
            errors.append(f"{path}: missing {name}")
    for name in ("data_read_retries_total", "data_corrupt_records_total",
                 "data_stalls_total"):
        if name not in vals:
            errors.append(f"{path}: missing data/* robustness family "
                          f"member {name} (is the ISSUE-15 data plane "
                          f"wired?)")
    for name in ("ops_modconv_fallback_total",
                 "ops_modconv_fallback_shape_total",
                 "ops_modconv_fallback_vmem_total"):
        if name not in vals:
            errors.append(f"{path}: missing conv-family fallback counter "
                          f"{name} (is the ISSUE-17 dispatch seam "
                          f"wired?) — a 0 here is the positive 'no "
                          f"silent XLA fallback' claim")
    for name in ("train_nonfinite_total", "train_nonfinite_loss_total",
                 "train_nonfinite_grad_total",
                 "train_nonfinite_param_total"):
        if name not in vals:
            errors.append(f"{path}: missing nonfinite cross-check "
                          f"counter {name} (is the ISSUE-19 graftnum "
                          f"runtime twin wired?) — a 0 here is the "
                          f"positive 'no NaN/inf reached the host' claim")
    if vals.get("data_corrupt_records_total", 0.0) > 0:
        ledger = os.path.join(os.path.dirname(os.path.abspath(path)),
                              "data_quarantine.jsonl")
        if not os.path.exists(ledger):
            errors.append(
                f"{path}: data_corrupt_records_total = "
                f"{vals['data_corrupt_records_total']:g} but no "
                f"data_quarantine.jsonl ledger beside it — quarantines "
                f"without offset+cause evidence are unreviewable")
    return errors


# Serving health vocabulary (ISSUE 13) — the ONE jax-free home both
# CLI graders (gansformer-serve --healthcheck, the doctor's serving
# section) import, so the probe and the doctor can't diverge on the
# same prom file.  serve/service.py keeps a private mirror (importing
# analysis from the serving hot path would invert the layering).
SERVE_HEALTH_NAMES = {0: "ready", 1: "degraded", 2: "unhealthy",
                      3: "closed"}


def serve_dead_with_work(alive, queue_depth) -> bool:
    """A dispatcher that is down while requests sit queued: those
    tickets are hung — the one liveness verdict that must outrank a
    merely 'degraded' health state."""
    return alive == 0.0 and (queue_depth or 0.0) > 0


_REPLICA_METRIC = re.compile(r"^serve_replica(\d+)_")


def serve_replica_ordinals(vals: dict) -> List[int]:
    """Replica ordinals present in a parsed prom dict (the
    ``serve_replica<i>_*`` member families written by replica-mode
    services, ISSUE 20).  Empty = single-service prom."""
    return sorted({int(m.group(1)) for name in vals
                   if (m := _REPLICA_METRIC.match(name))})


def serve_fleet_alive(vals: dict) -> bool:
    """ANY-replica-alive semantics (ISSUE 20): the fleet serves as long
    as one member's dispatcher runs.  Single-service proms (no replica
    families) fall back to the global ``serve_dispatcher_alive``
    gauge — same verdict the pre-fleet healthcheck gave."""
    ords = serve_replica_ordinals(vals)
    if not ords:
        return vals.get("serve_dispatcher_alive", 0.0) > 0
    return any(vals.get(f"serve_replica{i}_dispatcher_alive", 0.0) > 0
               for i in ords)


def serve_fleet_dead_with_work(vals: dict) -> bool:
    """Fleet flavor of ``serve_dead_with_work``: hung tickets exist
    when SOME replica's queue is non-empty while ALL dispatchers are
    dead — a live member anywhere can still be routed to, so one dead
    member with queued work is quarantine's problem, not a page."""
    ords = serve_replica_ordinals(vals)
    if not ords:
        return serve_dead_with_work(
            vals.get("serve_dispatcher_alive", 0.0),
            vals.get("serve_queue_depth_now", 0.0))
    any_queued = any(
        vals.get(f"serve_replica{i}_queue_depth_now", 0.0) > 0
        for i in ords)
    return any_queued and not serve_fleet_alive(vals)


def check_serve_metric_families(path: str,
                                expect_overload: bool = False) -> List[str]:
    """Serving SLO families (ISSUE 10 + 13): a service's
    ``telemetry.prom`` must carry the queue-depth / batch-fill /
    latency histograms, the dispatch counters, and the robustness
    family — absence means the SLO wiring rotted, and a load-test
    artifact without them is unreviewable.  Values-aware the same way
    the device-truth check is: traffic served implies latency samples
    landed, and ``expect_overload=True`` (set by callers that DROVE
    overload traffic, e.g. the chaos loadtest) implies the shed counter
    moved — a bound-hitting burst with zero sheds means admission
    control rotted into unbounded queueing.  (Overload is declared by
    the caller, not inferred from queue-depth values: a healthy queue
    may legitimately fill to its bound and drain without ever refusing
    a submit.)"""
    from gansformer_tpu.obs.registry import parse_prom_values

    vals = parse_prom_values(path)
    errors = []
    for name in ("serve_queue_depth_count", "serve_batch_fill_count",
                 "serve_e2e_ms_count", "serve_requests_total",
                 "serve_images_total", "serve_map_dispatch_total",
                 "serve_synth_dispatch_total",
                 "serve_wcache_hits_total", "serve_wcache_misses_total",
                 # the ISSUE 13 robustness family — materialized at
                 # service init, so absence always means rotted wiring
                 "serve_shed_total", "serve_expired_total",
                 "serve_cancelled_total",
                 "serve_dispatcher_restarts_total",
                 "serve_health_state", "serve_dispatcher_alive",
                 "serve_queue_bound", "serve_queue_depth_now",
                 # the ISSUE 16 request-tracing family — materialized at
                 # service init alongside the robustness family
                 "reqtrace_requests_total", "reqtrace_events_total",
                 "reqtrace_terminal_total", "reqtrace_dropped_total",
                 "reqtrace_ledger_rows_total",
                 "reqtrace_ledger_dropped_total", "reqtrace_enabled"):
        if name not in vals:
            errors.append(f"{path}: missing serve/* family member "
                          f"{name} (is the serving telemetry wired?)")
    if vals.get("serve_requests_total", 0.0) > 0 and \
            vals.get("serve_e2e_ms_count", 0.0) <= 0:
        errors.append(f"{path}: requests were served but no "
                      f"serve_e2e_ms latency samples landed")
    if vals.get("reqtrace_enabled", 0.0) > 0 and \
            vals.get("serve_requests_total", 0.0) > 0:
        # tracing was ON and traffic was admitted: traces must have
        # opened AND reached terminal events — a nonzero gap between the
        # two planes means ticket lifecycles are leaking mid-flight
        if vals.get("reqtrace_requests_total", 0.0) <= 0:
            errors.append(f"{path}: tracing enabled and requests "
                          f"admitted but reqtrace_requests_total never "
                          f"moved — request tracing rotted")
        elif vals.get("reqtrace_terminal_total", 0.0) <= 0:
            errors.append(f"{path}: traces opened but none reached a "
                          f"terminal event — ticket lifecycles leak")
    if expect_overload and vals.get("serve_shed_total", 0.0) <= 0:
        errors.append(f"{path}: overload traffic was driven (bound "
                      f"{vals.get('serve_queue_bound', 0.0):g}) but "
                      f"serve_shed_total never moved — is admission "
                      f"control wired?")
    # Replica-fleet families (ISSUE 20) — CONDITIONAL on the prom being
    # fleet-shaped (serve_replicas present, written by ReplicaSet):
    # single-service runs keep the exact pre-fleet schema.
    if "serve_replicas" in vals:
        for name in ("serve_scale_out_total", "serve_scale_in_total"):
            if name not in vals:
                errors.append(f"{path}: fleet prom (serve_replicas "
                              f"present) missing {name} (is the "
                              f"autoscaler telemetry wired?)")
        ords = serve_replica_ordinals(vals)
        if not ords:
            errors.append(f"{path}: serve_replicas = "
                          f"{vals['serve_replicas']:g} but no "
                          f"serve_replica<i>_* member families — "
                          f"replica metric redirection rotted")
        for i in ords:
            for member in ("health_state", "dispatcher_alive",
                           "queue_depth_now", "queue_bound",
                           "requests_total", "images_total",
                           "batch_ms_count", "batch_fill_count"):
                name = f"serve_replica{i}_{member}"
                if name not in vals:
                    errors.append(f"{path}: replica {i} missing member "
                                  f"family {name}")
            # values-aware: a replica that DELIVERED images ran batches,
            # and every batch observes the member latency histogram —
            # traffic without samples means attribution rotted
            if vals.get(f"serve_replica{i}_images_total", 0.0) > 0 and \
                    vals.get(f"serve_replica{i}_batch_ms_count", 0.0) <= 0:
                errors.append(
                    f"{path}: replica {i} delivered images but its "
                    f"serve_replica{i}_batch_ms histogram has no "
                    f"samples — per-replica attribution rotted")
    return errors


def check_fleet_metric_families(path: str) -> List[str]:
    """Fleet-aggregation families (ISSUE 16): a ``fleet.prom`` written
    by ``obs.aggregate`` must carry the roster gauges, the partial-view
    marker, the step-skew / restart-asymmetry signals — the aggregator
    materializes all of them unconditionally, so absence means the file
    came from somewhere else.  Values-aware: a non-partial fleet must
    have every rostered process reporting (the partial marker and the
    roster arithmetic asserting the same fact is the cross-check that
    catches a rotted marker)."""
    from gansformer_tpu.obs.registry import parse_prom_values

    vals = parse_prom_values(path)
    errors = []
    for name in ("fleet_partial", "fleet_processes",
                 "fleet_processes_reporting", "fleet_processes_missing",
                 "fleet_processes_stale", "fleet_step_skew",
                 "fleet_heartbeat_age_max_s", "fleet_gauge_ts_conflict",
                 "fleet_restarts_total", "fleet_restart_spread"):
        if name not in vals:
            errors.append(f"{path}: missing fleet/* family member "
                          f"{name} (is this a fleet.prom?)")
    total = vals.get("fleet_processes")
    reporting = vals.get("fleet_processes_reporting")
    if total is not None and reporting is not None:
        if reporting > total:
            errors.append(f"{path}: fleet_processes_reporting "
                          f"{reporting:g} > fleet_processes {total:g}")
        if vals.get("fleet_partial") == 0.0 and reporting < total:
            errors.append(
                f"{path}: fleet_partial claims a complete view but only "
                f"{reporting:g}/{total:g} processes report — the "
                f"partial marker rotted")
    return errors


def check_supervise_metric_families(path: str) -> List[str]:
    """Supervisor availability families (ISSUE 12): a run supervised by
    ``gansformer-supervise`` writes ``supervisor.prom``, and the whole
    family is materialized at supervisor start — absence of any member
    means the wiring rotted, never "nothing happened yet" (the same
    explicit-marker discipline as the device-truth check).  Values-aware:
    the per-cause counters must sum to the exit total."""
    from gansformer_tpu.obs.registry import parse_prom_values

    vals = parse_prom_values(path)
    errors = []
    members = ("supervise_restarts_total", "supervise_exits_total",
               "supervise_clean_exits_total", "supervise_crashes_total",
               "supervise_preemptions_total", "supervise_hangs_total",
               "supervise_data_corrupt_exits_total",
               "supervise_data_stall_exits_total",
               "supervise_availability_ratio",
               "supervise_uptime_s_total", "supervise_downtime_s_total",
               "supervise_restart_budget_remaining")
    for name in members:
        if name not in vals:
            errors.append(f"{path}: missing supervise/* family member "
                          f"{name} (is the supervisor telemetry wired?)")
    total = vals.get("supervise_exits_total")
    by_cause = [vals.get(f"supervise_{c}", 0.0)
                for c in ("clean_exits_total", "crashes_total",
                          "preemptions_total", "hangs_total",
                          "data_corrupt_exits_total",
                          "data_stall_exits_total")]
    if total is not None and sum(by_cause) != total:
        errors.append(f"{path}: per-cause exit counters sum to "
                      f"{sum(by_cause):g} but supervise_exits_total is "
                      f"{total:g} — an exit went unclassified")
    return errors


SUPERVISOR_EVENT_KEYS = {"kind": str, "time": (int, float), "pid": int}


def check_supervisor_events(path: str) -> List[str]:
    """``supervisor_events.jsonl`` schema: every line a JSON object with
    kind/time/pid; exit events carry a cause from the supervisor's
    vocabulary and an exit code.  Torn trailing lines are tolerated (a
    SIGKILL mid-append is this ledger's subject matter, and the readers
    all skip them) — but only as the LAST line; torn lines mid-file mean
    something other than a crash wrote garbage."""
    from gansformer_tpu.supervise.events import CAUSES, KINDS

    errors = []
    with open(path) as f:
        lines = [(i, line) for i, line in enumerate(f, 1) if line.strip()]
    for n, (i, line) in enumerate(lines):
        try:
            rec = json.loads(line)
        except ValueError as e:
            if n == len(lines) - 1:
                continue           # torn final append: expected ending
            errors.append(f"{path}:{i}: not JSON ({e})")
            continue
        if not isinstance(rec, dict):
            errors.append(f"{path}:{i}: not a JSON object "
                          f"({type(rec).__name__})")
            continue
        for key, typ in SUPERVISOR_EVENT_KEYS.items():
            if key not in rec:
                errors.append(f"{path}:{i}: missing {key!r}")
            elif not isinstance(rec[key], typ) or \
                    isinstance(rec[key], bool):
                errors.append(f"{path}:{i}: {key}={rec[key]!r} is not "
                              f"{typ}")
        kind = rec.get("kind")
        if isinstance(kind, str) and kind not in KINDS:
            errors.append(f"{path}:{i}: unknown event kind {kind!r} "
                          f"(have {KINDS})")
        if kind == "exit":
            if "cause" not in rec or "exit_code" not in rec:
                errors.append(f"{path}:{i}: exit event without "
                              f"cause/exit_code")
            elif rec["cause"] not in CAUSES:
                errors.append(f"{path}:{i}: exit cause {rec['cause']!r} "
                              f"outside the vocabulary {CAUSES}")
    return errors


def check_heartbeat(path: str) -> List[str]:
    errors = []
    try:
        with open(path) as f:
            rec = json.load(f)
    except ValueError as e:
        return [f"{path}: not JSON ({e})"]
    for key, typ in HEARTBEAT_KEYS.items():
        if key not in rec:
            errors.append(f"{path}: missing {key!r}")
        elif not isinstance(rec[key], typ) or isinstance(rec[key], bool):
            errors.append(f"{path}: {key}={rec[key]!r} is not {typ}")
    return errors


def check_run_dir(run_dir: str) -> dict:
    """{ok, checked, errors} over every telemetry artifact present.
    A MISSING artifact is an error — the lint runs against a smoke run
    that must have produced all of them."""
    errors: List[str] = []
    checked: List[str] = []
    for fname, checker in (("events.jsonl", check_events),
                           ("telemetry.prom", check_prom)):
        path = os.path.join(run_dir, fname)
        if not os.path.exists(path):
            errors.append(f"{path}: missing")
            continue
        checked.append(fname)
        errors += checker(path)
        if fname == "telemetry.prom":
            errors += check_metric_families(path)
    beats = sorted(glob.glob(os.path.join(run_dir, "heartbeat-p*.json")))
    if not beats:
        errors.append(f"{run_dir}: no heartbeat-p*.json")
    for path in beats:
        checked.append(os.path.basename(path))
        errors += check_heartbeat(path)
    # Supervisor artifacts are OPTIONAL (unsupervised smoke runs don't
    # have them) but schema-checked when present.
    sup_prom = os.path.join(run_dir, "supervisor.prom")
    if os.path.exists(sup_prom):
        checked.append("supervisor.prom")
        errors += check_prom(sup_prom)
        errors += check_supervise_metric_families(sup_prom)
    sup_events = os.path.join(run_dir, "supervisor_events.jsonl")
    if os.path.exists(sup_events):
        checked.append("supervisor_events.jsonl")
        errors += check_supervisor_events(sup_events)
    # Request ledger and fleet rollup are likewise OPTIONAL (train-only
    # runs have neither) but schema-checked when present.
    requests = os.path.join(run_dir, "requests.jsonl")
    if os.path.exists(requests):
        checked.append("requests.jsonl")
        errors += check_requests(
            requests, prom_path=os.path.join(run_dir, "telemetry.prom"))
    fleet_prom = os.path.join(run_dir, "fleet.prom")
    if os.path.exists(fleet_prom):
        checked.append("fleet.prom")
        errors += check_prom(fleet_prom)
        errors += check_fleet_metric_families(fleet_prom)
    return {"ok": not errors, "checked": checked, "errors": errors}


_ERR_LOC = re.compile(r"^(?P<path>.+?):(?P<line>\d+): (?P<msg>.*)$")


def lint_run_dir(run_dir: str) -> List[Finding]:
    """The same schema errors as ``check_run_dir``, as Findings (rule id
    ``telemetry-schema``) for the shared reporters."""
    out: List[Finding] = []
    for err in check_run_dir(run_dir)["errors"]:
        m = _ERR_LOC.match(err)
        if m:
            out.append(Finding(rule="telemetry-schema",
                               path=m.group("path"),
                               line=int(m.group("line")), col=0,
                               message=m.group("msg")))
        else:
            path, _, msg = err.partition(": ")
            out.append(Finding(rule="telemetry-schema", path=path or run_dir,
                               line=0, col=0, message=msg or err))
    return out


def main(argv=None) -> int:
    """Legacy CLI: ``python -m …telemetry_schema <run_dir>`` — one JSON
    line {ok, checked, errors}; exit 0 iff ok (the script shim's
    contract)."""
    import argparse

    p = argparse.ArgumentParser(
        description="Schema lint for a run dir's telemetry artifacts")
    p.add_argument("run_dir")
    args = p.parse_args(argv)
    result = check_run_dir(args.run_dir)
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
