"""Finding renderers: human text and machine JSON.

Both render the same partition — *new* findings fail the lint;
*suppressed* (inline comment) and *baselined* (checked-in debt) stay
visible so they can be audited, but don't gate.
"""

from __future__ import annotations

import json
from typing import List

from gansformer_tpu.analysis.findings import Finding


def counts(findings: List[Finding]) -> dict:
    return {
        "total": len(findings),
        "new": sum(f.new for f in findings),
        "suppressed": sum(f.suppressed for f in findings),
        "baselined": sum(f.baselined for f in findings),
    }


def render_text(findings: List[Finding], files_checked: int,
                verbose: bool = False) -> str:
    """One line per reportable finding + summary.  Suppressed/baselined
    findings print only with ``verbose`` (tagged, for auditing)."""
    lines = []
    for f in sorted(findings, key=Finding.sort_key):
        if not f.new and not verbose:
            continue
        tag = "" if f.new else \
            (" [suppressed]" if f.suppressed else " [baselined]")
        hint = f"  (fix: {f.hint})" if f.hint and f.new else ""
        lines.append(f"{f.location}: {f.rule}: {f.message}{hint}{tag}")
    c = counts(findings)
    lines.append(
        f"graftlint: {files_checked} file(s), {c['total']} finding(s) — "
        f"{c['new']} new, {c['suppressed']} suppressed, "
        f"{c['baselined']} baselined")
    return "\n".join(lines)


def render_json(findings: List[Finding], files_checked: int,
                extra: dict = None) -> str:
    """``extra`` merges additional top-level sections into the payload
    (the trace run's comms-cost table rides here) — reserved keys win."""
    c = counts(findings)
    payload = dict(extra or {})
    payload.update({
        "version": 1,
        "ok": c["new"] == 0,
        "files_checked": files_checked,
        "counts": c,
        "findings": [f.to_dict()
                     for f in sorted(findings, key=Finding.sort_key)],
    })
    return json.dumps(payload, indent=1, sort_keys=True)
