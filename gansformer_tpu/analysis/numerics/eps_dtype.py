"""eps-dtype-mismatch — eps literals below the operand dtype's machine
epsilon (ISSUE 19, the AST half of graftnum).

bfloat16 keeps float32's exponent range, so ``1e-8`` is perfectly
representable — and perfectly useless: with ~8 mantissa bits,
``x + 1e-8 == x`` for any ``x`` of normal magnitude, so an eps guard
copied from fp32 code silently evaporates and the rsqrt/log it was
guarding is back to dividing by zero.

The rule is deliberately conservative, because ambient dtypes are the
jaxpr half's job (``fp32-island-contract`` sees the truth the source
can't spell): it fires only when the *source* resolves the operand to
a narrow dtype — a name assigned through ``.astype(jnp.bfloat16)`` /
``astype('float16')``-style casts — and a positive literal below that
dtype's machine epsilon is added to it (or ``jnp.maximum``-ed against
it).  Names resolved to an fp32 island the way ``_instance_norm``
spells it (``x32 = x.astype(jnp.float32)``) are quiet, as are
unresolved names.  Thresholds come from ``dtypes.EPS_FLOOR`` — the
same table ``tests/tolerances.py`` keys its bands off.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional

from gansformer_tpu.analysis.engine import FileContext, Rule, register

from gansformer_tpu.analysis.numerics.dtypes import (
    EPS_FLOOR, NARROW_FLOAT_DTYPES)

_WIDE = ("float32", "float64")


def _dtype_token(node: ast.AST) -> Optional[str]:
    """The dtype a cast argument spells: ``jnp.bfloat16``,
    ``'bfloat16'``, ``np.float32`` … → its name, else None."""
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value
    else:
        return None
    return name if name in NARROW_FLOAT_DTYPES + _WIDE else None


def _cast_dtype(node: ast.AST) -> Optional[str]:
    """dtype of an explicit cast call: ``x.astype(D)``,
    ``jnp.asarray(x, D)`` / ``dtype=D`` kwargs,
    ``lax.convert_element_type(x, D)``."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "astype" and node.args:
        return _dtype_token(node.args[0])
    if isinstance(fn, ast.Attribute) and \
            fn.attr in ("asarray", "array", "full", "zeros", "ones",
                        "convert_element_type"):
        for kw in node.keywords:
            if kw.arg == "dtype":
                return _dtype_token(kw.value)
        if fn.attr == "convert_element_type" and len(node.args) >= 2:
            return _dtype_token(node.args[1])
        if fn.attr in ("asarray", "full") and len(node.args) >= 2:
            return _dtype_token(node.args[1])
    return None


def _class_of(dtype: Optional[str]) -> Optional[str]:
    if dtype in NARROW_FLOAT_DTYPES:
        return dtype          # keep the dtype — the threshold needs it
    if dtype in _WIDE:
        return "wide"
    return None


def _expr_class(expr: ast.AST, env: Dict[str, str]) -> Optional[str]:
    """Resolve an expression's dtype class from the source: the
    expression's own top-level cast wins; otherwise any referenced
    wide-resolved name makes it wide (islands stay quiet), else the
    first narrow-resolved name makes it narrow."""
    top = _cast_dtype(expr)
    if top is not None:
        return _class_of(top)
    classes = [env[n.id] for n in ast.walk(expr)
               if isinstance(n, ast.Name) and n.id in env]
    if "wide" in classes:
        return "wide"
    for c in classes:
        if c != "wide":
            return c
    return None


def _literal_value(node: ast.AST,
                   lits: Dict[str, float]) -> Optional[float]:
    if isinstance(node, ast.Constant) and \
            isinstance(node.value, float):
        return node.value
    if isinstance(node, ast.Name) and node.id in lits:
        return lits[node.id]
    return None


@register
class EpsDtypeMismatchRule(Rule):
    id = "eps-dtype-mismatch"
    description = ("eps literal below the operand dtype's machine "
                   "epsilon — x + 1e-8 is a no-op guard in bfloat16")
    hint = ("compute the guarded op in an fp32 island (x32 = "
            "x.astype(jnp.float32), like _instance_norm) or use an eps "
            "the dtype can represent (see analysis/numerics/dtypes."
            "EPS_FLOOR and tests/tolerances.py)")
    node_types = (ast.FunctionDef, ast.AsyncFunctionDef)

    def check(self, node: ast.AST, ctx: FileContext) -> None:
        env: Dict[str, str] = {}
        lits: Dict[str, float] = {}
        # float parameter defaults are the classic carrier of a copied
        # fp32 eps (def f(x, eps=1e-8): …)
        args = node.args
        pos = args.posonlyargs + args.args
        for arg, default in zip(pos[len(pos) - len(args.defaults):],
                                args.defaults):
            if isinstance(default, ast.Constant) and \
                    isinstance(default.value, float):
                lits[arg.arg] = default.value
        for kwarg, default in zip(args.kwonlyargs, args.kw_defaults):
            if isinstance(default, ast.Constant) and \
                    isinstance(default.value, float):
                lits[kwarg.arg] = default.value
        stmts = sorted(
            (n for n in ast.walk(node)
             if isinstance(n, (ast.Assign, ast.AnnAssign))),
            key=lambda n: n.lineno)
        for stmt in stmts:
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            value = stmt.value
            if value is None:
                continue
            cls = _expr_class(value, env)
            if cls is None:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    env[t.id] = cls
        for sub in ast.walk(node):
            if isinstance(sub, ast.BinOp) and \
                    isinstance(sub.op, ast.Add):
                pairs = ((sub.left, sub.right), (sub.right, sub.left))
            elif isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in ("maximum", "minimum") and \
                    len(sub.args) == 2:
                pairs = ((sub.args[0], sub.args[1]),
                         (sub.args[1], sub.args[0]))
            else:
                continue
            for lit_node, operand in pairs:
                eps = _literal_value(lit_node, lits)
                if eps is None or not 0.0 < eps:
                    continue
                cls = _expr_class(operand, env)
                if cls is None or cls == "wide":
                    continue
                floor = EPS_FLOOR[cls]
                if eps >= floor:
                    continue
                ctx.report(self, sub,
                           f"eps literal {eps:g} is below {cls}'s "
                           f"machine epsilon ({floor:g}): the guard is "
                           f"a no-op in {cls} arithmetic")
                break
