"""Declared fp32-island contracts — the numeric twin of
``parallel/contracts.ENTRY_CONTRACTS`` (ISSUE 19).

The bf16 training path survives on hand-placed fp32 islands: the
instance-norm statistics in ``models/attention.py``, the demodulation
sum-of-squares/rsqrt in ``ops/modulated_conv.py`` (and its Pallas
kernels), the attention softmax/lse in ``ops/attention.py`` /
``ops/pallas_attention.py``, the loss and penalty reductions in
``losses/gan.py``, and the optimizer moments.  None of that intent was
written down anywhere a tool could check — this table declares it per
entry point, and ``analysis/trace/`` rule ``fp32-island-contract``
audits the *compiled* programs against it (the graftcomms declared-
contract→compiled-audit shape applied to dtypes).

Islands are matched in the traced jaxpr by (user-frame anchor,
primitive set): an equation whose user frame lands in one of the
island's anchor (file, function) pairs and whose primitive is in the
island's set belongs to the island and must compute on float32
operands.  Library formulations anchor correctly because
``source_info_util.user_frame`` skips jax-internal frames — the
``jax.nn.softmax`` reductions inside ``multihead_attention`` anchor at
the repo call line, in that function.

Kept import-light: ``parallel/contracts`` pulls jax at module import,
so ``short_entry_name`` is imported lazily — the AST half of graftlint
must keep working in jax-free environments.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Island:
    """One fp32 computation the narrow-dtype path depends on.

    ``anchors`` are (path suffix, function name) pairs; ``None`` as the
    function matches any function in that file (the Pallas kernel
    modules, where the island spans several kernel bodies).  An island
    may list several anchors when backends move the same math
    (xla attention vs the Pallas kernels).
    """

    name: str
    anchors: Tuple[Tuple[str, Optional[str]], ...]
    primitives: frozenset
    rationale: str = ""

    def matches_frame(self, file_name: str, function_name: Optional[str]
                      ) -> bool:
        norm = (file_name or "").replace("\\", "/")
        for suffix, fn in self.anchors:
            if not norm.endswith(suffix):
                continue
            if fn is None or fn == function_name:
                return True
        return False


ISLANDS: Dict[str, Island] = {
    "instance-norm": Island(
        name="instance-norm",
        anchors=(("models/attention.py", "_instance_norm"),),
        primitives=frozenset({"reduce_sum", "rsqrt"}),
        rationale="normalization statistics: mean/var reductions and "
                  "the rsqrt over (var + eps) — bf16 variance of a "
                  "near-constant grid cancels to noise"),
    "attention-lse": Island(
        name="attention-lse",
        anchors=(("ops/attention.py", "multihead_attention"),
                 ("ops/attention.py", "multihead_attention_kv_sharded"),
                 ("ops/pallas_attention.py", None)),
        primitives=frozenset({"reduce_max", "reduce_sum", "exp", "div"}),
        rationale="softmax log-sum-exp: the max-subtraction, exp, and "
                  "normalizing sum must run fp32 or bf16 logits "
                  "saturate the attention distribution"),
    "demodulation": Island(
        name="demodulation",
        # anchored on the coefficient helper, not modulated_conv2d
        # itself: the scale-application muls there (and their backward
        # broadcast-reductions) intentionally ride the compute dtype,
        # like the conv they wrap — the fp32 contract is the
        # sum-of-squares/rsqrt coefficient math.
        anchors=(("ops/modulated_conv.py", "_demod_coeffs"),
                 ("ops/pallas_modconv.py", None)),
        primitives=frozenset({"rsqrt", "dot_general", "reduce_sum"}),
        rationale="demod coefficients: rsqrt of a sum of squares over "
                  "kh*kw*Cin terms — precision-sensitive at any width, "
                  "catastrophic at bf16"),
    "loss-reductions": Island(
        name="loss-reductions",
        anchors=(("losses/gan.py", None),),
        primitives=frozenset({"reduce_sum"}),
        rationale="loss/penalty means and the R1/PL sums of squares: "
                  "the scalars the optimizer actually follows"),
    "int8w-dequant": Island(
        name="int8w-dequant",
        # the q*scale expansion in ops.resolve_weight's helper — the
        # int8 codes and fp32 per-channel scales are both explicitly
        # cast to f32 BEFORE the mul, so the dequantized kernel enters
        # the (possibly bf16) layer math at full precision and the
        # equalized-lr gain/coef scaling stays bit-matched to the f32
        # params tree (ISSUE 20, serve_precision='int8w').
        anchors=(("ops/modulated_conv.py", "_dequant_int8w"),),
        primitives=frozenset({"mul"}),
        rationale="weight dequantization q*scale: rounding already cost "
                  "~0.4% per weight; doing the expansion in bf16 would "
                  "double the error before the kernel is even used"),
}


@dataclasses.dataclass(frozen=True)
class NumericContract:
    """Per-entry fp32 intent: islands that must appear in the traced
    program AND compute on fp32 operands, plus whether the optimizer
    moment leaves (g_opt/d_opt float state) must be fp32."""

    islands: Tuple[str, ...]
    opt_moments: bool = False


_TRAIN = NumericContract(
    islands=("instance-norm", "attention-lse", "demodulation",
             "loss-reductions"),
    opt_moments=True)
# Pure-synthesis programs (no loss, no optimizer): the three model
# islands only.
_SYNTH = NumericContract(
    islands=("instance-norm", "attention-lse", "demodulation"))
# Mapping-network-only programs: no islands required (anything matched
# would still be audited, but the mapping MLP has none).
_MAP = NumericContract(islands=())
# int8w serving (ISSUE 20): the synthesis islands PLUS the dequant
# expansion — the audit now asserts every QuantizedWeight leaf is
# expanded to f32 before it meets the compute dtype.
_SYNTH_INT8W = NumericContract(
    islands=("instance-norm", "attention-lse", "demodulation",
             "int8w-dequant"))

# Keyed by short entry name (parallel.contracts.short_entry_name), one
# entry per ENTRY_CONTRACTS member — entry_points.add() refuses a new
# entry without a declaration here, same loud guard as the sharding
# contract.  The quantized-synthesis direction (ROADMAP item 3) changes
# THIS table and the audit starts asserting the new intent.
NUMERIC_CONTRACTS: Dict[str, NumericContract] = {
    "d_step": _TRAIN,
    "d_step_r1": _TRAIN,
    "g_step": _TRAIN,
    "g_step_pl": _TRAIN,
    "cycle": _TRAIN,
    "sample": _SYNTH,
    "ppl_pairs": _SYNTH,
    "serve_map_seeds": _MAP,
    "serve_map_z": _MAP,
    "serve_synth": _SYNTH,
    # the serving precision axis (ISSUE 20): bf16 keeps the declared
    # islands fp32 while activations narrow; int8w adds the dequant
    # island on top
    "serve_synth_bf16": _SYNTH,
    "serve_synth_int8w": _SYNTH_INT8W,
}


def numeric_contract_for(name: str) -> Optional[NumericContract]:
    """Contract for an entry-point name ("steps.d_step[tiny-f32]" or a
    bare short name); None = undeclared (fixtures)."""
    from gansformer_tpu.parallel.contracts import short_entry_name

    return NUMERIC_CONTRACTS.get(short_entry_name(name))
