"""Dtype numerics facts the graftnum rules key off (ISSUE 19).

Kept jax-free on purpose: the AST half (``eps_dtype.py``) runs in
pre-commit environments without accelerator libs, and
``tests/tolerances.py`` imports this table so the test suite's
tolerance bands and the lint's thresholds cannot drift apart.  The
values are pinned against ``jnp.finfo`` by
``tests/test_numerics_rules.py::test_machine_eps_matches_jnp_finfo``.

The central fact: bfloat16 keeps float32's 8-bit exponent (so ``1e-8``
is *representable*) but only ~8 mantissa bits — ``x + 1e-8 == x`` for
any ``x`` of normal magnitude, which is why an eps guard below the
machine epsilon is a silent no-op rather than an overflow.
"""

from __future__ import annotations

# Machine epsilon (ulp of 1.0): the smallest e with 1.0 + e != 1.0.
MACHINE_EPS = {
    "bfloat16": 2.0 ** -7,     # 0.0078125
    "float16": 2.0 ** -10,     # 0.0009765625
    "float32": 2.0 ** -23,     # ~1.1920929e-07
    "float64": 2.0 ** -52,     # ~2.220446e-16
}

# An additive eps below this floor cannot move a same-dtype operand of
# normal magnitude — the eps-dtype-mismatch threshold.
EPS_FLOOR = MACHINE_EPS

# Dtypes whose accumulations/eps-guards the rules treat as hazardous.
NARROW_FLOAT_DTYPES = ("bfloat16", "float16")

# reduction-accumulation: a narrow-dtype reduce_sum/reduce_max/
# dot_general folding at least this many elements without an fp32
# accumulator is a finding.  At 4096 bf16 terms the worst-case relative
# accumulation error (n * eps/2) reaches ~16 ulps of the result — the
# scale at which the replication paper's FID drift became visible.
ACCUM_THRESHOLD = 4096


def is_narrow_name(dtype_name: str) -> bool:
    return dtype_name in NARROW_FLOAT_DTYPES
