"""graftnum — the numerics analysis layer (ISSUE 19).

Importing this package registers both halves into the shared graftlint
stacks (one module per rule, the ``analysis/rules`` convention; see
docs/static-analysis.md "Numerics catalog"):

AST half (``analysis/engine.py`` registry — jax-free):

* ``eps_dtype``           — eps-dtype-mismatch

jaxpr half (``analysis/trace/base.py`` registry; structural — runs on
every entry of every trace profile, pre-commit's ``contracts`` profile
included):

* ``island_contract``     — fp32-island-contract (audits
                            ``contracts.NUMERIC_CONTRACTS``, the dtype
                            twin of parallel/contracts.ENTRY_CONTRACTS)
* ``reduction_accum``     — reduction-accumulation
* ``unstable_primitive``  — unstable-primitive

``dtypes.py`` carries the machine-epsilon/threshold tables (shared
with tests/tolerances.py); ``jaxpr_util.py`` the dataflow searches.
Everything imports jax lazily — the package itself loads in jax-free
environments (the pre-commit AST hooks).
"""

from gansformer_tpu.analysis.numerics import (  # noqa: F401
    contracts,
    dtypes,
    eps_dtype,
    island_contract,
    reduction_accum,
    unstable_primitive,
)
