"""fp32-island-contract — audit the declared fp32 islands in the
compiled step programs (ISSUE 19).

The graftcomms ``partition-contract`` shape applied to dtypes: the
declared side is ``contracts.NUMERIC_CONTRACTS`` (per entry point, the
islands that MUST compute in fp32); the audit side walks the traced
jaxpr and checks every island-matched equation's float operand avals.
Two failure modes, both findings:

* a matched equation computes on bf16/f16 operands — the island cast
  rotted (or a new code path skipped it);
* a *required* island matches nothing — the contract anchors rotted
  (the formulation moved file/function) or the math disappeared, which
  is exactly how a silently-narrowed accumulator would present.

Backward-pass equations inherit the forward line's source info, so the
audit covers the gradient half of each island for free.  Per-entry
audit records land in ``TraceContext.numerics`` — the ``--format
json`` / selfcheck artifact's proof that e.g. the tiny-bf16 programs
run instance-norm, demodulation, and the attention lse in fp32.

The optimizer-moment half cannot anchor on frames (optax internals are
not repo frames): it checks the float leaves under ``g_opt``/``d_opt``
of the entry's abstract state instead.
"""

from __future__ import annotations

from gansformer_tpu.analysis.trace.base import (
    EntryPoint, TraceContext, TraceRule, iter_eqns, path_str, register)

from gansformer_tpu.analysis.numerics.contracts import (
    ISLANDS, numeric_contract_for)
from gansformer_tpu.analysis.numerics.jaxpr_util import (
    dtype_name, is_float, is_narrow_float, user_frame)


@register
class Fp32IslandContractRule(TraceRule):
    id = "fp32-island-contract"
    description = ("declared fp32 island (norm stats, demod rsqrt, "
                   "attention lse, loss reductions, optimizer moments) "
                   "computing on narrow-dtype operands, or missing from "
                   "the traced program")
    hint = ("restore the island cast (x32 = x.astype(jnp.float32) before "
            "the reduction/rsqrt) or update analysis/numerics/"
            "contracts.py if the formulation legitimately moved")
    dynamic = False

    def __init__(self):
        # shared model lines are traced via many entries — one finding
        # per (island, line, dtype) keeps reports and baselines stable
        # across profiles
        self._seen = set()

    def check(self, ep: EntryPoint, ctx: TraceContext) -> None:
        contract = numeric_contract_for(ep.name)
        if contract is None:
            ctx.notes.append(f"fp32-island-contract: {ep.name}: no "
                             f"numeric contract declared — skipped "
                             f"(fixture entry?)")
            return
        closed = ctx.jaxpr(ep)
        islands = [ISLANDS[n] for n in contract.islands]
        audit = {isl.name: {"eqns": 0, "violations": 0, "dtypes": set()}
                 for isl in islands}
        for eqn in iter_eqns(closed.jaxpr):
            frame = user_frame(eqn)
            if frame is None:
                continue
            file_name, fn_name, line = frame
            for isl in islands:
                if eqn.primitive.name not in isl.primitives:
                    continue
                if not isl.matches_frame(file_name, fn_name):
                    continue
                rec = audit[isl.name]
                rec["eqns"] += 1
                float_in = [v.aval for v in eqn.invars
                            if is_float(v.aval)]
                rec["dtypes"] |= {dtype_name(a) for a in float_in}
                narrow = [a for a in float_in if is_narrow_float(a)]
                if narrow:
                    rec["violations"] += 1
                    key = (isl.name, file_name, line,
                           dtype_name(narrow[0]))
                    if key not in self._seen:
                        self._seen.add(key)
                        ctx.report(self, (file_name, line),
                                   f"{isl.name} island: "
                                   f"{eqn.primitive.name} computes on "
                                   f"{dtype_name(narrow[0])} operands — "
                                   f"contract requires float32 "
                                   f"({isl.rationale}; first traced via "
                                   f"{ep.name})")
        for isl in islands:
            if audit[isl.name]["eqns"] == 0:
                ctx.report(self, ep.anchor,
                           f"{ep.name}: required fp32 island "
                           f"{isl.name!r} matched no equation in the "
                           f"traced program — the contract anchors "
                           f"rotted or the formulation moved (declare "
                           f"the new anchor in analysis/numerics/"
                           f"contracts.py)")
        if contract.opt_moments:
            self._check_opt_moments(ep, ctx, audit)
        ctx.numerics.append({
            "entry": ep.name,
            "compute_dtype": ep.compute_dtype,
            "islands": {name: {"eqns": rec["eqns"],
                               "violations": rec["violations"],
                               "dtypes": sorted(rec["dtypes"]),
                               "ok": rec["eqns"] > 0
                               and rec["violations"] == 0}
                        for name, rec in audit.items()},
        })

    def _check_opt_moments(self, ep: EntryPoint, ctx: TraceContext,
                           audit: dict) -> None:
        import jax

        from gansformer_tpu.parallel.contracts import key_str

        state_abs = ep.abstract_args[0]
        bad = []
        dtypes = set()
        n = 0
        for path, leaf in jax.tree_util.tree_leaves_with_path(state_abs):
            head = key_str(path[0]) if path else ""
            if head not in ("g_opt", "d_opt") or not is_float(leaf):
                continue
            n += 1
            dtypes.add(dtype_name(leaf))
            if is_narrow_float(leaf):
                bad.append((path_str(path), dtype_name(leaf)))
        for leaf_path, dt in bad[:4]:     # a narrowed tree repeats per leaf
            ctx.report(self, ep.anchor,
                       f"{ep.name}: optimizer moment {leaf_path} is {dt} "
                       f"— moment accumulators must stay float32 "
                       f"(narrow moments forget small gradients)")
        audit["optimizer-moments"] = {
            "eqns": n, "violations": len(bad), "dtypes": sorted(dtypes),
            "ok": n > 0 and not bad}
