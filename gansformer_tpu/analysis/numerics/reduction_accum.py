"""reduction-accumulation — large narrow-dtype accumulations (ISSUE 19).

A ``reduce_sum``/``reduce_max``/``dot_general`` folding thousands of
bf16/f16 elements without an fp32 accumulator loses low-order bits on
every partial sum — wall-clock-invisible, bit-identical across runs,
and exactly the class of defect that surfaces weeks later as FID
drift.  The rule flags any such equation accumulating at least
``dtypes.ACCUM_THRESHOLD`` elements whose *output* is still narrow
(an f32 output means the upcast already happened —
``preferred_element_type``/``dtype=`` accumulation) and whose
producing source line does not itself spell a cast.

Anchoring reuses ``dtype_flow.py``'s discipline: the eqn's user frame
is the finding line, the ``_EXPLICIT`` regex treats a written-out
dtype as a decision, and inline ``# graftlint:
disable=reduction-accumulation`` works on that line.
"""

from __future__ import annotations

from gansformer_tpu.analysis.trace.base import (
    EntryPoint, TraceContext, TraceRule, eqn_frame, in_repo, iter_eqns,
    line_text, register)
# the one explicit-cast vocabulary — a line that spells its dtype made a
# decision, for this rule exactly as for dtype-promotion
from gansformer_tpu.analysis.trace.dtype_flow import _EXPLICIT

from gansformer_tpu.analysis.numerics.dtypes import ACCUM_THRESHOLD
from gansformer_tpu.analysis.numerics.jaxpr_util import (
    dtype_name, is_narrow_float)


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


@register
class ReductionAccumulationRule(TraceRule):
    id = "reduction-accumulation"
    description = (f"reduce_sum/reduce_max/dot_general folding >= "
                   f"{ACCUM_THRESHOLD} elements at bf16/f16 without an "
                   f"fp32 accumulator")
    hint = ("accumulate in fp32: x.astype(jnp.float32) before the "
            "reduction, jnp.sum(..., dtype=jnp.float32), or "
            "preferred_element_type=jnp.float32 on the contraction")
    dynamic = False

    def __init__(self):
        # one finding per producing line across all entries of a run
        self._seen = set()

    def check(self, ep: EntryPoint, ctx: TraceContext) -> None:
        closed = ctx.jaxpr(ep)
        for eqn in iter_eqns(closed.jaxpr):
            prim = eqn.primitive.name
            if prim in ("reduce_sum", "reduce_max"):
                aval = eqn.invars[0].aval
                if not is_narrow_float(aval) \
                        or not is_narrow_float(eqn.outvars[0].aval):
                    continue
                axes = eqn.params.get("axes", ())
                n = _prod(aval.shape[a] for a in axes)
            elif prim == "dot_general":
                lhs = eqn.invars[0].aval
                rhs = eqn.invars[1].aval
                if not (is_narrow_float(lhs) or is_narrow_float(rhs)) \
                        or not is_narrow_float(eqn.outvars[0].aval):
                    continue
                (lhs_c, _), _ = eqn.params["dimension_numbers"]
                aval = lhs
                n = _prod(lhs.shape[d] for d in lhs_c)
            else:
                continue
            if n < ACCUM_THRESHOLD:
                continue
            frame = eqn_frame(eqn)
            if frame is None or not in_repo(frame[0]):
                continue
            if _EXPLICIT.search(line_text(*frame)):
                continue    # the cast/dtype is written — a decision
            key = (frame[0], frame[1], prim)
            if key in self._seen:
                continue
            self._seen.add(key)
            ctx.report(self, frame,
                       f"{prim} folds {n} elements at "
                       f"{dtype_name(aval)} with a "
                       f"{dtype_name(eqn.outvars[0].aval)} accumulator "
                       f"(first traced via {ep.name})")
