"""Shared jaxpr-walking helpers for the numerics trace rules.

Extends ``analysis/trace/base.py``'s utilities with what dtype-level
auditing needs: user frames *with the function name* (island anchors
match on it), a producer map over the whole recursed program, and
bounded dataflow searches for eps guards and max-domination.  All jax
imports are lazy — the module must import cleanly in jax-free
environments (the AST half of graftlint pulls the package in).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from gansformer_tpu.analysis.trace.base import iter_eqns

from gansformer_tpu.analysis.numerics.dtypes import (
    MACHINE_EPS, NARROW_FLOAT_DTYPES)

# Shape/dtype plumbing that neither accumulates nor rescales: a guard
# or a max-subtraction survives passing through these.
TRANSPARENT_PRIMS = frozenset({
    "convert_element_type", "broadcast_in_dim", "reshape", "squeeze",
    "expand_dims", "transpose", "slice", "copy", "stop_gradient",
})

_SEARCH_DEPTH = 16      # bounded best-effort; chains are short in practice


def dtype_name(aval) -> str:
    return str(getattr(getattr(aval, "dtype", None), "name",
                       getattr(aval, "dtype", "?")))


def is_float(aval) -> bool:
    # name-based first: np.issubdtype(bfloat16, np.floating) is False
    # (ml_dtypes extension types are not numpy floating subtypes), and
    # missing bf16 here would make the island audit report false cleans
    if dtype_name(aval) in MACHINE_EPS:
        return True
    try:
        import numpy as np

        return bool(np.issubdtype(aval.dtype, np.floating))
    except Exception:
        return False


def is_narrow_float(aval) -> bool:
    return dtype_name(aval) in NARROW_FLOAT_DTYPES


def user_frame(eqn) -> Optional[Tuple[str, Optional[str], int]]:
    """(file, function name, line) of the user frame that generated the
    eqn — ``base.eqn_frame`` plus the function name the island anchors
    match on.  None for library-internal eqns."""
    try:
        import jax._src.source_info_util as siu

        frame = siu.user_frame(eqn.source_info)
        if frame is not None:
            return (frame.file_name,
                    getattr(frame, "function_name", None),
                    frame.start_line)
    except Exception:
        pass
    return None


class _BoundaryAlias:
    """Synthetic pass-through eqn bridging a pjit-style sub-jaxpr
    boundary: the inner jaxpr's invar 'produces' the matching outer
    operand through a value-preserving copy, so the dataflow searches
    keep walking instead of dead-ending at the boundary."""

    class _Prim:
        name = "copy"

    primitive = _Prim()
    params: Dict[str, Any] = {}
    outvars: Tuple[Any, ...] = ()

    def __init__(self, outer_var):
        self.invars = (outer_var,)


def producer_map(jaxpr) -> Dict[Any, Any]:
    """{outvar: producing eqn} over the program including sub-jaxprs.

    Call-style eqns (pjit, closed_call, custom_*: one inner invar per
    outer operand, in order) additionally alias each sub-jaxpr invar
    to its outer operand via a synthetic copy, so chains cross the
    boundary.  Loop/branch bodies (scan carry offsets, cond operand
    dropping) are NOT bridged — their invars stay unknown, which only
    costs precision, never soundness of the quiet direction."""
    import jax.core as jcore

    out: Dict[Any, Any] = {}
    for eqn in iter_eqns(jaxpr):
        for v in eqn.outvars:
            out[v] = eqn
        for value in eqn.params.values():
            for item in (value if isinstance(value, (list, tuple))
                         else [value]):
                inner = (item.jaxpr
                         if isinstance(item, jcore.ClosedJaxpr) else item)
                if not isinstance(inner, jcore.Jaxpr):
                    continue
                if (len(inner.invars) != len(eqn.invars)
                        or len(inner.outvars) != len(eqn.outvars)):
                    continue
                for iv, ov in zip(inner.invars, eqn.invars):
                    out.setdefault(iv, _BoundaryAlias(ov))
                # and outward: the call's result IS the body's result,
                # so searches walk through the call into the body
                for ov, iv in zip(eqn.outvars, inner.outvars):
                    out[ov] = _BoundaryAlias(iv)
    return out


def const_map(closed) -> Dict[Any, Any]:
    """{constvar: concrete value} for the top-level ClosedJaxpr and
    every nested one (pjit/scan/cond) — a jitted function's closure
    constants live on the inner pjit jaxpr, not the outer one."""
    import jax.core as jcore

    out: Dict[Any, Any] = {}

    def add(cj):
        for var, val in zip(cj.jaxpr.constvars, cj.consts):
            out[var] = val

    add(closed)
    for eqn in iter_eqns(closed.jaxpr):
        for v in eqn.params.values():
            for item in (v if isinstance(v, (list, tuple)) else [v]):
                if isinstance(item, jcore.ClosedJaxpr):
                    add(item)
    return out


_FOLD_MAX_SIZE = 64     # only fold scalars / tiny constant arrays


def _const_eval(v, producers: Dict[Any, Any], consts: Dict[Any, Any],
                depth: int):
    """Numerically evaluate ``v`` when its producer chain terminates
    only in literals and closed-over constants (``jnp.var``'s
    ``n - ddof`` normalizer, precomputed scale factors, …).  Returns a
    numpy value, or None when any input is runtime data."""
    import numpy as np

    if depth <= 0:
        return None
    if _is_literal(v):
        return np.asarray(v.val)
    if v in consts:
        val = np.asarray(consts[v])
        return val if val.size <= _FOLD_MAX_SIZE else None
    eqn = producers.get(v)
    if eqn is None:
        return None
    p = eqn.primitive.name
    args = None
    if p in TRANSPARENT_PRIMS or p in ("neg", "sqrt", "rsqrt", "exp",
                                       "log", "abs", "sign",
                                       "integer_pow", "add", "sub",
                                       "mul", "div", "max", "min",
                                       "pow"):
        args = [_const_eval(i, producers, consts, depth - 1)
                for i in eqn.invars]
        if any(a is None for a in args):
            return None
    else:
        return None
    try:
        if p == "convert_element_type":
            return np.asarray(args[0], dtype=eqn.params["new_dtype"])
        if p in TRANSPARENT_PRIMS:
            return args[0]      # value-preserving for positivity checks
        if p == "integer_pow":
            return args[0] ** eqn.params["y"]
        un = {"neg": np.negative, "sqrt": np.sqrt,
              "rsqrt": lambda x: 1.0 / np.sqrt(x), "exp": np.exp,
              "log": np.log, "abs": np.abs, "sign": np.sign}
        if p in un:
            return un[p](args[0])
        bi = {"add": np.add, "sub": np.subtract, "mul": np.multiply,
              "div": np.divide, "max": np.maximum, "min": np.minimum,
              "pow": np.power}
        with np.errstate(all="ignore"):
            return bi[p](args[0], args[1])
    except Exception:
        return None


def _is_literal(v) -> bool:
    try:
        import jax.core as jcore

        return isinstance(v, jcore.Literal)
    except Exception:
        return False


def _literal_positive(v) -> bool:
    try:
        import numpy as np

        return bool(np.all(np.asarray(v.val) > 0))
    except Exception:
        return False


def has_positive_floor(v, producers: Dict[Any, Any],
                       depth: int = _SEARCH_DEPTH,
                       consts: Optional[Dict[Any, Any]] = None) -> bool:
    """Can we prove ``v`` is bounded away from zero from below?

    The eps-guard question for ``log``/``div``/``rsqrt``: a positive
    literal reached through adds/maxes is a floor; ``exp`` output is a
    floor by construction (the softmax-denominator idiom: after max
    subtraction the max term contributes exp(0) = 1); products, sums,
    and (r)sqrt of floored values keep the floor; a value computable
    entirely from closed-over constants (``jnp.var``'s ``n - ddof``
    normalizer) is folded numerically.  Unknown producers (entry
    inputs, sub-jaxpr boundaries) prove nothing — the caller treats
    unprovable as a finding, and the sanctioned-idiom table / inline
    suppressions absorb formulations the search cannot see.
    """
    if depth <= 0:
        return False
    if _is_literal(v):
        return _literal_positive(v)
    if consts is not None:
        val = _const_eval(v, producers, consts, depth)
        if val is not None:
            import numpy as np

            return bool(np.all(val > 0))
    eqn = producers.get(v)
    if eqn is None:
        return False
    p = eqn.primitive.name
    if p in TRANSPARENT_PRIMS:
        return has_positive_floor(eqn.invars[0], producers, depth - 1,
                                  consts)
    if p == "exp":
        return True
    if p in ("add", "max"):
        return any(has_positive_floor(i, producers, depth - 1, consts)
                   for i in eqn.invars)
    if p == "mul":
        return all(has_positive_floor(i, producers, depth - 1, consts)
                   for i in eqn.invars)
    if p in ("reduce_sum", "reduce_max", "reduce_prod", "sqrt", "rsqrt"):
        return has_positive_floor(eqn.invars[0], producers, depth - 1,
                                  consts)
    return False


def _chain_contains_max(v, producers: Dict[Any, Any], depth: int) -> bool:
    if depth <= 0 or _is_literal(v):
        return False
    eqn = producers.get(v)
    if eqn is None:
        return False
    p = eqn.primitive.name
    if p in ("reduce_max", "max", "pmax", "argmax"):
        return True
    if p in TRANSPARENT_PRIMS:
        return _chain_contains_max(eqn.invars[0], producers, depth - 1)
    return False


def _chain_contains_abs(v, producers: Dict[Any, Any], depth: int) -> bool:
    if depth <= 0 or _is_literal(v):
        return False
    eqn = producers.get(v)
    if eqn is None:
        return False
    p = eqn.primitive.name
    if p == "abs":
        return True
    if p in TRANSPARENT_PRIMS:
        return _chain_contains_abs(eqn.invars[0], producers, depth - 1)
    return False


def dominated_by_max(v, producers: Dict[Any, Any],
                     depth: int = _SEARCH_DEPTH) -> bool:
    """Is ``exp(v)`` overflow-safe — i.e. is ``v`` bounded above?

    The log-sum-exp question: ``x - max(x)`` (the stable softmax
    shift, including a ``pmax``/``stop_gradient``-wrapped max), a
    ``min`` clamp, or ``-|x|`` (the stable softplus/logaddexp interior)
    all bound the exponent at 0.  Unknown producers prove nothing.
    """
    if depth <= 0:
        return False
    if _is_literal(v):
        return True
    eqn = producers.get(v)
    if eqn is None:
        return False
    p = eqn.primitive.name
    if p in TRANSPARENT_PRIMS:
        return dominated_by_max(eqn.invars[0], producers, depth - 1)
    if p == "sub":
        return (_chain_contains_max(eqn.invars[1], producers, depth - 1)
                or dominated_by_max(eqn.invars[0], producers, depth - 1))
    if p == "add":
        return any(dominated_by_max(i, producers, depth - 1)
                   for i in eqn.invars)
    if p == "neg":
        return _chain_contains_abs(eqn.invars[0], producers, depth - 1)
    if p == "min":
        return True
    return False
