"""unstable-primitive — exp/log/div/rsqrt without a provable guard
(ISSUE 19).

Jaxpr-level stability lint over the compiled step programs:

* ``exp`` whose exponent is not provably bounded above (no
  max-subtraction / min-clamp / -|x| in its producer chain) can
  overflow — the log-sum-exp hazard;
* ``log``/``rsqrt`` whose operand has no provable positive floor
  (no ``+ eps`` with a positive literal, no ``max(x, c>0)``, no
  ``exp`` ancestor) can hit 0 → -inf/inf — including in the BACKWARD
  pass, whose equations inherit the forward line's source info;
* ``div`` whose divisor is neither a literal nor floored likewise.

The dataflow searches (``jaxpr_util``) are bounded and best-effort:
*unprovable* counts as a finding, and two escape hatches absorb sound
formulations the search cannot see — the sanctioned-idiom table below
(file/function-granular, each entry with a rationale, mirrored in
docs/static-analysis.md) and the usual inline suppression on the
anchored line.  Lines that call jax.nn's internally-stabilized
routines (softmax/softplus/logsumexp/…) are sanctioned wholesale: the
library formulation IS the stable idiom, and its interior equations
anchor at the repo call line.
"""

from __future__ import annotations

import re

from gansformer_tpu.analysis.trace.base import (
    EntryPoint, TraceContext, TraceRule, in_repo, iter_eqns, line_text,
    register)

from gansformer_tpu.analysis.numerics.jaxpr_util import (
    const_map, dominated_by_max, dtype_name, has_positive_floor,
    is_float, producer_map, user_frame)

# jax.nn / jnp routines that are internally stabilized: equations from
# their interiors anchor at the repo line that *calls* them, so a line
# spelling one of these is running the library's stable formulation.
_STABLE_CALL = re.compile(
    r"jax\.nn\.(?:softmax|log_softmax|softplus|logsumexp|sigmoid|"
    r"log_sigmoid|gelu|silu|standardize)|jnp\.logaddexp|nn\.softplus|"
    r"nn\.softmax")

# (path suffix, function or None) → rationale.  Hand-written stable
# formulations whose structure the bounded dataflow search cannot
# prove; each entry is documented in docs/static-analysis.md and the
# kernel entries are pinned by the Pallas parity tests.
SANCTIONED_IDIOMS = {
    ("ops/attention.py", "multihead_attention_kv_sharded"):
        "streamed lse: exp is dominated by a pmax'd stop_gradient max "
        "(opaque to the chain search) and the softmax denominator is "
        ">= exp(0) by construction",
    ("ops/pallas_attention.py", None):
        "kernel-side lse: running max/denominator live in fp32 scratch "
        "refs, which break producer chains; the formulation is the "
        "textbook online softmax, pinned by the kernel parity tests",
    ("ops/pallas_modconv.py", None):
        "kernel-side demod: sigma accumulates in fp32 scratch before "
        "rsqrt(sigma + eps); the eps add sits across a ref boundary "
        "the chain search cannot cross",
}

_CHECKED = ("exp", "log", "div", "rsqrt")


def _sanctioned(file_name: str, fn_name) -> bool:
    norm = (file_name or "").replace("\\", "/")
    for (suffix, fn), _ in SANCTIONED_IDIOMS.items():
        if norm.endswith(suffix) and (fn is None or fn == fn_name):
            return True
    return False


@register
class UnstablePrimitiveRule(TraceRule):
    id = "unstable-primitive"
    description = ("exp not dominated by a max-subtraction, or "
                   "log/div/rsqrt whose operand lacks a provable "
                   "positive floor (eps guard)")
    hint = ("guard the operand (x + eps with a representable eps, "
            "jnp.maximum(x, eps)) or subtract the max before exp; for "
            "a formulation that is stable by construction, add it to "
            "analysis/numerics/unstable_primitive.SANCTIONED_IDIOMS "
            "with a rationale")
    dynamic = False

    def __init__(self):
        self._seen = set()

    def check(self, ep: EntryPoint, ctx: TraceContext) -> None:
        closed = ctx.jaxpr(ep)
        producers = producer_map(closed.jaxpr)
        consts = const_map(closed)
        for eqn in iter_eqns(closed.jaxpr):
            prim = eqn.primitive.name
            if prim not in _CHECKED:
                continue
            frame = user_frame(eqn)
            if frame is None or not in_repo(frame[0]):
                continue
            file_name, fn_name, line = frame
            if _sanctioned(file_name, fn_name):
                continue
            if _STABLE_CALL.search(line_text(file_name, line)):
                continue
            if prim == "exp":
                if not is_float(eqn.invars[0].aval):
                    continue
                if dominated_by_max(eqn.invars[0], producers):
                    continue
                what = ("exp whose exponent is not provably bounded "
                        "above (no max-subtraction) — overflow hazard")
            elif prim == "div":
                divisor = eqn.invars[1]
                if not is_float(eqn.outvars[0].aval):
                    continue
                if has_positive_floor(divisor, producers, consts=consts):
                    continue
                what = ("div whose divisor has no provable positive "
                        "floor — 1/0 hazard")
            else:       # log / rsqrt
                operand = eqn.invars[0]
                if not is_float(eqn.outvars[0].aval):
                    continue
                if has_positive_floor(operand, producers, consts=consts):
                    continue
                what = (f"{prim} whose operand has no provable "
                        f"positive floor (eps guard)")
            key = (file_name, line, prim)
            if key in self._seen:
                continue
            self._seen.add(key)
            ctx.report(self, (file_name, line),
                       f"{what} at {dtype_name(eqn.invars[0].aval)} "
                       f"(first traced via {ep.name})")
