"""Run-dir learning-evidence lint (migrated from
scripts/check_learning_trend.py — the last ad-hoc checker outside
``analysis/``; the script remains as a shim).

The reference's verification model is golden-metric empiricism: train,
then watch FID fall (SURVEY.md §4 item 1).  This rule makes that an
assertable artifact property: given a run dir, it reads the recorded
``metric-*.txt`` series (written by the tick loop / evaluate CLI) and
``stats.jsonl``, and asserts

  * >= ``min_points`` metric evaluations exist,
  * the metric IMPROVED: fitted last < fitted first by >= ``min_drop``
    (relative), using a least-squares line over the series so a noisy
    final tick cannot fake or hide a trend,
  * losses in stats.jsonl stayed finite throughout.

``check`` keeps the pre-framework result-dict contract (the script shim
and tests/test_learning_trend.py call it directly);
``lint_learning_trend`` adapts the same failures into ``Finding``
objects (rule id ``learning-trend``) for
``gansformer-lint --run-dir <dir> --learning-trend``.
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
from typing import List, Optional, Tuple

from gansformer_tpu.analysis.findings import Finding


def read_metric_series(run_dir: str, metric: Optional[str]):
    """[(kimg, value)] from metric-<name>.txt (tick-loop format:
    'kimg <k> <name> <v>').  metric=None picks the first fid* file."""
    if metric:
        paths = [os.path.join(run_dir, f"metric-{metric}.txt")]
    else:
        paths = sorted(glob.glob(os.path.join(run_dir, "metric-fid*.txt")))
    if not paths or not os.path.exists(paths[0]):
        return None, []
    name = os.path.basename(paths[0])[len("metric-"):-len(".txt")]
    series = []
    with open(paths[0]) as f:
        for line in f:
            m = re.match(r"kimg\s+([\d.]+)\s+\S+\s+([\d.eE+-]+)", line)
            if m:
                series.append((float(m.group(1)), float(m.group(2))))
    return name, series


def fit_line(series) -> Tuple[float, float]:
    """Least-squares (intercept, slope) over (kimg, value)."""
    n = len(series)
    xs = [k for k, _ in series]
    ys = [v for _, v in series]
    mx, my = sum(xs) / n, sum(ys) / n
    var = sum((x - mx) ** 2 for x in xs) or 1e-12
    slope = sum((x - mx) * (y - my) for x, y in series) / var
    return my - slope * mx, slope


def check(run_dir: str, metric: Optional[str], min_points: int,
          min_drop: float) -> dict:
    """{ok, metric, first, last, fit_drop_rel, points[, error]} — the
    legacy contract."""
    name, series = read_metric_series(run_dir, metric)
    out = {"ok": False, "run_dir": run_dir, "metric": name,
           "points": len(series)}
    if len(series) < min_points:
        out["error"] = (f"only {len(series)} metric points "
                        f"(need >= {min_points})")
        return out
    b, a = fit_line(series)
    first_fit = b + a * series[0][0]
    last_fit = b + a * series[-1][0]
    drop = (first_fit - last_fit) / abs(first_fit) if first_fit else 0.0
    out.update({
        "first": round(series[0][1], 4), "last": round(series[-1][1], 4),
        "first_fit": round(first_fit, 4), "last_fit": round(last_fit, 4),
        "fit_drop_rel": round(drop, 4), "slope_per_kimg": round(a, 6),
    })
    if drop < min_drop:
        out["error"] = (f"fitted {name} fell only {drop * 100:.1f}% "
                        f"(need >= {min_drop * 100:.0f}%) — no learning "
                        f"evidence")
        return out
    stats_path = os.path.join(run_dir, "stats.jsonl")
    if os.path.exists(stats_path):
        for line in open(stats_path):
            row = json.loads(line)
            for k, v in row.items():
                if k.startswith("Loss/") and isinstance(v, float) \
                        and not math.isfinite(v):
                    out["error"] = f"non-finite {k} at tick " \
                                   f"{row.get('Progress/tick')}"
                    return out
    out["ok"] = True
    return out


def lint_learning_trend(run_dir: str, metric: Optional[str] = None,
                        min_points: int = 3,
                        min_drop: float = 0.10) -> List[Finding]:
    """``check``'s verdict as Findings (rule id ``learning-trend``) for
    the shared reporters/CLI.  One finding per failed run dir."""
    result = check(run_dir, metric, min_points, min_drop)
    if result["ok"]:
        return []
    return [Finding(
        rule="learning-trend", path=run_dir, line=0, col=0,
        message=result.get("error", "no learning evidence"),
        hint="train longer / fix the regression, or point --run-dir at "
             "a run that recorded a metric series")]


def main(argv=None) -> int:
    """Legacy CLI contract: one JSON line {ok, ...}; exit 0 iff ok."""
    import argparse

    p = argparse.ArgumentParser(
        description="Assert a run dir shows learning evidence")
    p.add_argument("run_dir")
    p.add_argument("--metric", default=None,
                   help="metric name (default: first metric-fid*.txt)")
    p.add_argument("--min-points", type=int, default=3)
    p.add_argument("--min-drop", type=float, default=0.10,
                   help="required relative drop of the fitted line")
    args = p.parse_args(argv)
    out = check(args.run_dir, args.metric, args.min_points, args.min_drop)
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
