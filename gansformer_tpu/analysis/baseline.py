"""Checked-in baseline: pre-existing findings that don't fail the lint.

The baseline file is JSON with one entry per allowed finding, keyed by
``rule :: path :: stripped-text-of-flagged-line`` (see
``Finding.baseline_key``) — content-addressed so pure line-number drift
doesn't invalidate it, while editing the flagged line itself does (the
finding then resurfaces as *new*, which is the point: touched code must
meet the current bar).

Paths inside the file are stored relative to the baseline file's
directory with ``/`` separators, and entries are written sorted — so
``--fix-baseline`` is deterministic byte-for-byte and diffs are small.
"""

from __future__ import annotations

import collections
import json
import os
from typing import Dict, Iterable, List

from gansformer_tpu.analysis.findings import Finding

VERSION = 1


def _rel(path: str, root: str) -> str:
    try:
        rel = os.path.relpath(os.path.abspath(path), root)
    except ValueError:       # different drive (windows) — keep absolute
        rel = os.path.abspath(path)
    return rel.replace(os.sep, "/")


class Baseline:
    """Multiset of baseline keys (several identical lines may each carry
    the same finding; each baselined occurrence needs its own entry)."""

    def __init__(self, root: str = ".",
                 keys: Iterable[str] = ()):
        self.root = os.path.abspath(root)
        self._keys = collections.Counter(keys)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        root = os.path.dirname(os.path.abspath(path)) or "."
        if not os.path.exists(path):
            return cls(root)
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        return cls(root, (e["key"] for e in data.get("entries", ())))

    def _key(self, finding: Finding, line_text: str) -> str:
        rel = _rel(finding.path, self.root)
        return Finding(**{**finding.__dict__, "path": rel}) \
            .baseline_key(line_text)

    def apply(self, findings: List[Finding],
              line_text_of) -> None:
        """Mark matching findings ``baselined`` (consuming entries, so N
        baseline entries absolve at most N identical findings).
        ``line_text_of(finding)`` returns the flagged line's text."""
        from gansformer_tpu.analysis.engine import legacy_ids

        budget = collections.Counter(self._keys)
        for f in findings:
            if f.suppressed:
                continue
            key = self._key(f, line_text_of(f))
            # retired-alias compatibility: an entry keyed by a retired
            # rule id (thread-shared-state::…) still absolves the
            # successor rule's finding on the same line
            candidates = [key] + [old + key[len(f.rule):]
                                  for old in legacy_ids(f.rule)]
            for k in candidates:
                if budget[k] > 0:
                    budget[k] -= 1
                    f.baselined = True
                    break

    @staticmethod
    def write(path: str, findings: List[Finding], line_text_of) -> None:
        """Regenerate the baseline from current (non-suppressed)
        findings — sorted, relative paths, trailing newline; running it
        twice on the same tree produces identical bytes."""
        root = os.path.dirname(os.path.abspath(path)) or "."
        entries = []
        for f in findings:
            if f.suppressed:
                continue
            rel = _rel(f.path, root)
            key = Finding(**{**f.__dict__, "path": rel}) \
                .baseline_key(line_text_of(f))
            entries.append({"rule": f.rule, "path": rel, "line": f.line,
                            "key": key})
        entries.sort(key=lambda e: (e["path"], e["rule"], e["line"],
                                    e["key"]))
        payload = {"version": VERSION, "entries": entries}
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f_out:
            json.dump(payload, f_out, indent=1, sort_keys=True)
            f_out.write("\n")
        os.replace(tmp, path)


def line_text_lookup(cache: Dict[str, List[str]] = None):
    """A ``line_text_of(finding)`` reading (and caching) source files —
    the default used by the CLI."""
    cache = {} if cache is None else cache

    def look(f: Finding) -> str:
        if f.path not in cache:
            try:
                with open(f.path, encoding="utf-8") as fh:
                    cache[f.path] = fh.read().splitlines()
            except OSError:
                cache[f.path] = []
        lines = cache[f.path]
        return lines[f.line - 1] if 0 < f.line <= len(lines) else ""

    return look
