"""Bundled graftlint rules — importing this package registers them all.

One module per rule (see docs/static-analysis.md for the catalog):

* ``host_sync``       — host-sync-in-jit
* ``donation``        — donation-after-use
* ``rng_reuse``       — rng-key-reuse
* ``hot_loop``        — hot-loop-sync (migrated from
                        scripts/check_hot_loop.py, which is now a shim)
* ``thread_state``    — thread-shared-state
* ``telemetry_names`` — telemetry-name-convention
"""

from gansformer_tpu.analysis.rules import (  # noqa: F401
    donation,
    host_sync,
    hot_loop,
    rng_reuse,
    telemetry_names,
    thread_state,
)
