"""Bundled graftlint rules — importing this package registers them all.

One module per rule (see docs/static-analysis.md for the catalog):

* ``host_sync``       — host-sync-in-jit
* ``donation``        — donation-after-use
* ``rng_reuse``       — rng-key-reuse
* ``hot_loop``        — hot-loop-sync (migrated from
                        scripts/check_hot_loop.py, which is now a shim)
* ``telemetry_names`` — telemetry-name-convention
* ``retrace_static``  — retrace-static (the AST companion of the
                        jaxpr-level retrace-hazard trace rule, ISSUE 4)

The old ``thread_state`` module (thread-shared-state) is RETIRED into
``analysis/concurrency/shared_state.py`` (ISSUE 18) — the id survives
as an alias of ``unguarded-shared-attribute``, so existing
``# graftlint: disable=thread-shared-state`` comments, baseline keys,
and ``--select`` spellings keep working.
"""

from gansformer_tpu.analysis.rules import (  # noqa: F401
    donation,
    host_sync,
    hot_loop,
    retrace_static,
    rng_reuse,
    telemetry_names,
)
