"""thread-shared-state: background threads mutating module state unlocked.

The overlap layer (utils/background.py, data/device_prefetch.py) runs
real work on ``threading.Thread`` targets.  Instance state those threads
touch is protected by each class's lock; what nothing protects is
*module-level* mutable state — a module dict used as a cache, a list
used as a log — mutated from a thread target while the main thread
reads it.  CPython's GIL makes most such races "work" until a compound
update tears under a tick boundary.

The rule finds, per module:

* **module-level mutables** — top-level names assigned list/dict/set
  literals or comprehensions;
* **thread targets** — functions/methods passed as ``target=`` to a
  ``Thread(...)`` call (bare names resolve to defs in the file,
  ``self.X`` to the method of the enclosing class);

and flags any mutation of a module-level mutable inside a thread
target's body — ``x[...] = …``, ``x.append/update/…(...)``, or a
``global`` rebind — unless the statement sits lexically inside a
``with <…lock…>:`` block (any context expression whose dotted name
contains "lock", e.g. ``self._lock``, ``_CACHE_LOCK``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from gansformer_tpu.analysis.engine import FileContext, Rule, register
from gansformer_tpu.analysis.jit_regions import dotted_name

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)
_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "remove", "discard", "clear", "appendleft",
             "popleft"}


def _module_mutables(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for st in tree.body:
        if isinstance(st, ast.Assign) and \
                isinstance(st.value, _MUTABLE_LITERALS):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(st, ast.AnnAssign) and st.value is not None and \
                isinstance(st.value, _MUTABLE_LITERALS) and \
                isinstance(st.target, ast.Name):
            out.add(st.target.id)
    return out


def _is_lock_expr(expr: ast.AST) -> bool:
    name = dotted_name(expr)
    if not name and isinstance(expr, ast.Call):
        name = dotted_name(expr.func)
    return "lock" in name.lower()


def _enclosing_class(node: ast.AST, ctx: FileContext):
    n = node
    while n is not None:
        if isinstance(n, ast.ClassDef):
            return n
        n = ctx.parent(n)
    return None


@register
class ThreadSharedState(Rule):
    id = "thread-shared-state"
    description = ("module-level mutable state mutated from a "
                   "threading.Thread target without holding a lock")
    hint = ("guard the mutation with the owning class's lock "
            "(with self._lock: …) or move the state onto the instance")
    node_types = (ast.Module,)

    def check(self, node: ast.Module, ctx: FileContext) -> None:
        mutables = _module_mutables(node)
        if not mutables:
            return
        for target_fn in self._thread_targets(node, ctx):
            self._scan(target_fn, mutables, False, ctx)

    # -- find thread target defs --------------------------------------------

    def _thread_targets(self, tree: ast.Module,
                        ctx: FileContext) -> List[ast.AST]:
        defs_by_name: Dict[str, List[ast.AST]] = {}
        for n in ast.walk(tree):
            if isinstance(n, _FUNC_DEFS):
                defs_by_name.setdefault(n.name, []).append(n)
        targets: List[ast.AST] = []
        seen: Set[int] = set()
        for call in ast.walk(tree):
            if not isinstance(call, ast.Call):
                continue
            name = dotted_name(call.func)
            if not name or name.split(".")[-1] != "Thread":
                continue
            for kw in call.keywords:
                if kw.arg != "target":
                    continue
                v = kw.value
                cands: List[ast.AST] = []
                if isinstance(v, ast.Name):
                    cands = defs_by_name.get(v.id, [])
                elif isinstance(v, ast.Attribute) and \
                        isinstance(v.value, ast.Name) and \
                        v.value.id == "self":
                    cls = _enclosing_class(call, ctx)
                    if cls is not None:
                        cands = [m for m in cls.body
                                 if isinstance(m, _FUNC_DEFS)
                                 and m.name == v.attr]
                for c in cands:
                    if id(c) not in seen:
                        seen.add(id(c))
                        targets.append(c)
        return targets

    # -- scan a target body, tracking lexical lock scope --------------------

    def _scan(self, node: ast.AST, mutables: Set[str], locked: bool,
              ctx: FileContext) -> None:
        for child in ast.iter_child_nodes(node):
            child_locked = locked
            if isinstance(child, (ast.With, ast.AsyncWith)) and \
                    any(_is_lock_expr(i.context_expr) for i in child.items):
                child_locked = True
            if not locked:
                self._check_stmt(child, mutables, ctx)
            self._scan(child, mutables, child_locked, ctx)

    def _check_stmt(self, node: ast.AST, mutables: Set[str],
                    ctx: FileContext) -> None:
        # x[...] = ...  /  x[...] += ...
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in mutables:
                    ctx.report(
                        self, node,
                        f"module-level mutable {t.value.id!r} written "
                        f"from a thread target without holding a lock")
            # global x; x = ...  (rebind of a module mutable)
            for t in targets:
                if isinstance(t, ast.Name) and t.id in mutables and \
                        self._declared_global(node, t.id, ctx):
                    ctx.report(
                        self, node,
                        f"module-level mutable {t.id!r} rebound from a "
                        f"thread target without holding a lock")
        # x.append(...) etc.
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in mutables:
            ctx.report(
                self, node,
                f"module-level mutable {node.func.value.id!r}."
                f"{node.func.attr}() from a thread target without "
                f"holding a lock")

    @staticmethod
    def _declared_global(node: ast.AST, name: str,
                         ctx: FileContext) -> bool:
        n = node
        while n is not None and not isinstance(n, _FUNC_DEFS):
            n = ctx.parent(n)
        if n is None:
            return False
        return any(isinstance(s, ast.Global) and name in s.names
                   for s in ast.walk(n))
