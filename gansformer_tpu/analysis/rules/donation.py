"""donation-after-use: reading a buffer after donating it to a jitted call.

``jax.jit(..., donate_argnums=…)`` hands the argument's device buffer
to XLA for in-place reuse; the Python name still points at the now-
invalid array.  Reading it after the call raises
``RuntimeError: Array has been deleted`` on real hardware — but NOT on
CPU test runs (donation is a no-op there), so this is exactly the bug
class that ships to the TPU undetected.

Scope: module-local, flow-insensitive across branches.  The shared jit
index records names bound to donating ``jax.jit`` results (including
``**dict(donate_argnums=…)`` splats and decorated defs); within each
function (and the module body), a linear statement scan marks variables
passed at donated positions and flags any later read before rebinding.
The blessed pattern — ``state, aux = step(state, …)`` — rebinds on the
same statement and never flags.
"""

from __future__ import annotations

import ast
from typing import Dict

from gansformer_tpu.analysis.engine import FileContext, Rule, register

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _flatten(stmts):
    """Statements in source order, recursing into control flow but not
    into nested function/class scopes (separate dispatch)."""
    for st in stmts:
        if isinstance(st, _FUNC_DEFS + (ast.ClassDef,)):
            continue
        yield st
        for field in ("body", "orelse", "finalbody"):
            yield from _flatten(getattr(st, field, ()) or ())
        for h in getattr(st, "handlers", ()) or ():
            yield from _flatten(h.body)


@register
class DonationAfterUse(Rule):
    id = "donation-after-use"
    description = ("argument donated to a jitted call (donate_argnums) "
                   "read again after the call site")
    hint = ("rebind the result over the donated name "
            "(state, aux = step(state, …)) or drop donate_argnums for "
            "buffers you still need")
    node_types = _FUNC_DEFS + (ast.Module,)

    def check(self, node: ast.AST, ctx: FileContext) -> None:
        donating = ctx.jit.donating
        if not donating:
            return
        body = node.body
        # donated name -> (line of the donating call)
        pending: Dict[str, int] = {}
        for st in _flatten(body):
            # order matters: reads happen before this statement's own
            # donation is recorded, and the donation before the target
            # rebinds — so ``state, aux = step(state)`` donates-then-
            # rebinds on one line and never flags.
            self._reads(st, pending, ctx)
            self._donations(st, pending, donating)
            self._rebinds(st, pending)

    # -- phase 1: reads of already-donated names ----------------------------

    def _reads(self, st: ast.stmt, pending: Dict[str, int],
               ctx: FileContext) -> None:
        if not pending:
            return
        for n in self._own_exprs(st):
            for sub in ast.walk(n):
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Load) and sub.id in pending:
                    ctx.report(
                        self, sub,
                        f"{sub.id!r} was donated to a jitted call on line "
                        f"{pending[sub.id]} and is read here — its buffer "
                        f"may already be reused (fails on TPU, silently "
                        f"passes on CPU)")

    @staticmethod
    def _own_exprs(st: ast.stmt):
        """The statement's direct expressions, not nested block bodies
        (those arrive later in the flattened order)."""
        for field, value in ast.iter_fields(st):
            if field in ("body", "orelse", "finalbody", "handlers"):
                continue
            if isinstance(value, ast.expr):
                yield value
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.expr):
                        yield v

    # -- phase 2: rebinding clears the donated mark -------------------------

    def _rebinds(self, st: ast.stmt, pending: Dict[str, int]) -> None:
        targets = []
        if isinstance(st, ast.Assign):
            targets = st.targets
        elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
            targets = [st.target]
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            targets = [st.target]
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    pending.pop(n.id, None)

    # -- phase 3: new donations from this statement -------------------------

    def _donations(self, st: ast.stmt, pending: Dict[str, int],
                   donating: Dict[str, tuple]) -> None:
        for n in self._own_exprs(st):
            for sub in ast.walk(n):
                if not (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id in donating):
                    continue
                for pos in donating[sub.func.id]:
                    if pos < len(sub.args) and \
                            isinstance(sub.args[pos], ast.Name):
                        pending[sub.args[pos].id] = sub.lineno
