"""hot-loop-sync: host syncs in a hot loop's per-iteration body.

Migrated from ``scripts/check_hot_loop.py`` (PR 2), which is now a thin
shim over this module.  The throughput discipline (PERF.md §1b) allows
exactly ONE host sync per hot loop: the sanctioned fetch span.  The
repo has two such loops, each with its own span (``HOT_LOOPS``):

* ``_train`` (train/loop.py) — the tick-boundary fetch inside
  ``with span("tick_fetch")``;
* ``_serve_dispatch`` (serve/service.py, ISSUE 10) — device fetches
  inside ``with span("serve_fetch")``.

Any other ``block_until_ready`` / ``device_get`` call in a ``while``
loop of those functions reintroduces a serial host stall per iteration
(per request batch, on the serving side).

The request tracer (obs/reqtrace.py, ISSUE 16) extends the serving hot
path: its emitter bodies (``begin`` / ``event`` / ``batch_span`` and
their private helpers) run per ticket per batch inside
``_serve_dispatch``, so a sync hidden there stalls the loop just as
surely as one written inline — but lives outside the ``while`` body
the loop scan sees.  ``TRACE_EMITTERS`` closes that hole: those
function bodies are scanned in full (no sanctioned span — a trace emit
point has no business fetching from the device at all), gated on the
reqtrace module path so an unrelated ``begin`` elsewhere stays out of
scope.

This rule complements host-sync-in-jit: the loop body is NOT a jit
region (it's the host orchestrator), so the tracer-taint rule stays
quiet there by design — this rule owns the loop-discipline half.

The legacy ``check_source``/``check_file`` entry points (same result
dict shape: ``{ok, checked, violations}``) are kept here so the script
shim and its existing callers (tests/test_device_prefetch.py) work
unchanged — including the "no while loop found in the default target"
hard failure that guards against the lint target silently moving.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from gansformer_tpu.analysis.engine import FileContext, Rule, register

BANNED = {"block_until_ready", "device_get"}
SANCTIONED_SPAN = "tick_fetch"

# hot-loop function name -> its sanctioned fetch span
HOT_LOOPS = {"_train": SANCTIONED_SPAN,
             "_serve_dispatch": "serve_fetch"}

# request-trace emitter bodies (obs/reqtrace.py) — called per ticket
# from _serve_dispatch, scanned in FULL with no sanctioned span
TRACE_EMITTERS = {"begin", "event", "batch_span",
                  "_finalize_locked", "_emit_chrome", "_flush_locked"}
# a span name no `with span(...)` call can carry: nothing is sanctioned
_NO_SPAN = "\x00no-sanctioned-span"


def _is_reqtrace_path(path: Optional[str]) -> bool:
    if not path:
        return False
    norm = path.replace(os.sep, "/")
    return norm.endswith("obs/reqtrace.py") or norm.endswith("/reqtrace.py")

_DEFAULT_TARGET = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    "train", "loop.py")


def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_sanctioned_with(node: ast.With,
                        span_name: str = SANCTIONED_SPAN) -> bool:
    """``with span("<span_name>")`` (possibly among other items)."""
    for item in node.items:
        e = item.context_expr
        if isinstance(e, ast.Call) and _call_name(e) == "span" and \
                e.args and isinstance(e.args[0], ast.Constant) and \
                e.args[0].value == span_name:
            return True
    return False


def _scan(node: ast.AST, sanctioned: bool, violations: List[dict],
          span_name: str = SANCTIONED_SPAN) -> None:
    """Recursive walk tracking whether we are under a sanctioned with."""
    for child in ast.iter_child_nodes(node):
        child_ok = sanctioned
        if isinstance(child, ast.With) and \
                _is_sanctioned_with(child, span_name):
            child_ok = True
        if isinstance(child, ast.Call):
            name = _call_name(child)
            if name in BANNED and not sanctioned:
                violations.append({"line": child.lineno,
                                   "col": child.col_offset,
                                   "call": name})
        _scan(child, child_ok, violations, span_name)


def _scan_hot_fn(fn: ast.AST, span_name: str) -> List[dict]:
    """Violations in every ``while`` loop of one hot-loop def.
    Scanning the While node covers its condition AND its body (a
    device_get in the while test would sync every iteration too)."""
    violations: List[dict] = []
    for sub in ast.walk(fn):
        if isinstance(sub, ast.While):
            _scan(sub, False, violations, span_name)
    return violations


@register
class HotLoopSync(Rule):
    id = "hot-loop-sync"
    description = ("block_until_ready/device_get in the per-iteration "
                   "while body of a hot loop (_train, _serve_dispatch) "
                   "outside its sanctioned fetch span, or anywhere in a "
                   "request-trace emitter body (obs/reqtrace.py)")
    hint = ("move the sync into the loop's sanctioned fetch span "
            "(tick_fetch / serve_fetch), or use copy_to_host_async "
            "(non-blocking); trace emitters must never touch the device")
    node_types = (ast.FunctionDef, ast.AsyncFunctionDef)

    def check(self, node: ast.AST, ctx: FileContext) -> None:
        span_name = HOT_LOOPS.get(node.name)
        if span_name is not None:
            for v in _scan_hot_fn(node, span_name):
                ctx.report(self, (v["line"], v["col"]),
                           f"{v['call']}() in the hot loop outside "
                           f"span(\"{span_name}\") — one host stall "
                           f"per iteration")
            return
        if node.name in TRACE_EMITTERS and \
                _is_reqtrace_path(getattr(ctx, "path", None)):
            violations: List[dict] = []
            _scan(node, False, violations, _NO_SPAN)
            for v in violations:
                ctx.report(self, (v["line"], v["col"]),
                           f"{v['call']}() in trace emitter "
                           f"{node.name}() — the serve dispatch loop "
                           f"calls this per ticket; a host sync here "
                           f"stalls every batch")


# -- legacy entry points (scripts/check_hot_loop.py shim) --------------------

def check_source(src: str) -> dict:
    """{ok, checked, violations} for one loop.py-shaped source string —
    the pre-framework result shape, kept for the script shim."""
    tree = ast.parse(src)
    loops: List[ast.While] = []
    violations: List[dict] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                node.name == "_train":
            for sub in ast.walk(node):
                if isinstance(sub, ast.While):
                    loops.append(sub)
    for loop in loops:
        _scan(loop, False, violations)
    return {"ok": not violations,
            "checked": len(loops),
            "violations": [{"line": v["line"], "call": v["call"]}
                           for v in violations]}


def check_file(path: str) -> dict:
    with open(path) as f:
        out = check_source(f.read())
    out["path"] = path
    if out["checked"] == 0:
        out["ok"] = False
        out["violations"] = [
            {"line": 0, "call": f"no while loop found inside _train in "
                                f"{path} — lint target moved?"}]
    return out
