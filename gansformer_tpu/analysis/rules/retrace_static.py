"""retrace-static — cache-key instability visible without tracing.

The AST companion of the dynamic ``retrace-hazard`` trace rule (ISSUE
4): the dynamic probe can only exercise entry points the harness knows
how to call; this rule catches the same bug family anywhere in the
tree, in two shapes:

* **unhashable static argument** — a call passes a list/dict/set
  display at a position (or keyword) the target's ``jax.jit(...,
  static_argnums=…/static_argnames=…)`` declared static.  jit hashes
  static arguments to build the cache key: an unhashable value raises
  at best; a freshly-built hashable-but-unstable one recompiles per
  call.
* **trace-baked mutable** — a function inside a jit region reads a
  module-level mutable (list/dict/set) that the module also *mutates*.
  The value is frozen into the jaxpr at trace time; later mutations are
  silently ignored — the "I updated the config dict but the step didn't
  change" bug.  Never-mutated module dicts (lookup tables) are de-facto
  constants and stay quiet.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from gansformer_tpu.analysis.engine import FileContext, Rule, register
from gansformer_tpu.analysis.jit_regions import is_jit_wrapper

_MUTABLE_DISPLAYS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)
_MUTATOR_METHODS = {"append", "extend", "insert", "add", "update", "pop",
                    "popitem", "remove", "discard", "clear", "setdefault"}


def _static_decl(call: ast.Call) -> Tuple[Tuple[int, ...], Tuple[str, ...]]:
    """(static positions, static names) declared on one jit(...) call."""
    nums: List[int] = []
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg in ("static_argnums", "static_argnames"):
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant):
                    if isinstance(v.value, int) and kw.arg == "static_argnums":
                        nums.append(v.value)
                    elif isinstance(v.value, str):
                        names.append(v.value)
    return tuple(nums), tuple(names)


@register
class RetraceStaticRule(Rule):
    id = "retrace-static"
    description = ("static-arg / closure cache-key instability: "
                   "unhashable value at a static_argnums position, or a "
                   "jit-region read of a mutated module-level mutable")
    hint = ("pass static args as hashable scalars/tuples; pass mutated "
            "state as explicit jit arguments instead of closing over it")
    node_types = (ast.Module,)

    def check(self, node: ast.Module, ctx: FileContext) -> None:
        self._check_static_args(node, ctx)
        self._check_baked_mutables(node, ctx)

    # -- unhashable static arguments -----------------------------------------

    def _check_static_args(self, module: ast.Module,
                           ctx: FileContext) -> None:
        # name -> (static positions, static names) for jitted callables
        declared: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {}
        for node in ast.walk(module):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call) and (
                            is_jit_wrapper(dec.func)
                            or (dec.args
                                and is_jit_wrapper(dec.args[0]))):
                        nums, names = _static_decl(dec)
                        if nums or names:
                            declared[node.name] = (nums, names)
            elif isinstance(node, ast.Call) and is_jit_wrapper(node.func):
                nums, names = _static_decl(node)
                if not (nums or names):
                    continue
                parent = ctx.parent(node)
                if isinstance(parent, ast.Assign):
                    for t in parent.targets:
                        if isinstance(t, ast.Name):
                            declared[t.id] = (nums, names)
        if not declared:
            return
        for node in ast.walk(module):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in declared):
                continue
            nums, names = declared[node.func.id]
            for i in nums:
                if i < len(node.args) and isinstance(
                        node.args[i], _MUTABLE_DISPLAYS):
                    ctx.report(self, node.args[i],
                               f"unhashable static argument at position "
                               f"{i} of jitted {node.func.id!r} — jit "
                               f"cannot key its cache on a "
                               f"list/dict/set")
            for kw in node.keywords:
                if kw.arg in names and isinstance(
                        kw.value, _MUTABLE_DISPLAYS):
                    ctx.report(self, kw.value,
                               f"unhashable static argument "
                               f"{kw.arg!r} of jitted "
                               f"{node.func.id!r} — jit cannot key its "
                               f"cache on a list/dict/set")

    # -- trace-baked mutated module-level mutables ---------------------------

    def _module_mutables(self, module: ast.Module) -> Set[str]:
        """Module-level names bound to mutable displays."""
        out: Set[str] = set()
        for stmt in module.body:
            if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, _MUTABLE_DISPLAYS):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    def _mutated_names(self, module: ast.Module,
                       candidates: Set[str]) -> Set[str]:
        """The subset of ``candidates`` the module mutates anywhere:
        mutator method calls, subscript stores/deletes, aug-assigns."""
        mutated: Set[str] = set()
        for node in ast.walk(module):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATOR_METHODS and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in candidates:
                mutated.add(node.func.value.id)
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in candidates:
                mutated.add(node.value.id)
            elif isinstance(node, ast.AugAssign):
                tgt = node.target
                if isinstance(tgt, ast.Name) and tgt.id in candidates:
                    mutated.add(tgt.id)
                elif isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id in candidates:
                    mutated.add(tgt.value.id)
        return mutated

    def _check_baked_mutables(self, module: ast.Module,
                              ctx: FileContext) -> None:
        mutables = self._module_mutables(module)
        if not mutables:
            return
        mutated = self._mutated_names(module, mutables)
        if not mutated:
            return
        for node in ast.walk(module):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not ctx.jit.is_jit(node):
                continue
            # param names shadow module globals
            args = node.args
            shadowed = {a.arg for a in (args.args + args.kwonlyargs
                                        + args.posonlyargs)}
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Load) and \
                        sub.id in mutated and sub.id not in shadowed:
                    ctx.report(self, sub,
                               f"jit-traced {node.name!r} reads module-"
                               f"level mutable {sub.id!r} (mutated "
                               f"elsewhere in this module) — the value "
                               f"is baked in at trace time; mutations "
                               f"never reach the compiled program")