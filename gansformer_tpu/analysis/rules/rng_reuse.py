"""rng-key-reuse: one PRNG key consumed by two or more calls.

JAX keys are values, not stateful generators: passing the same key to
two sampling calls silently yields *correlated* randomness (the classic
"my style-mixing latents equal my noise" bug — invisible at runtime, it
just degrades the model).  The rule tracks, per function scope:

* **key variables** — parameters whose names look like keys (``rng``,
  ``key``, ``*_rng``, ``*_key``, ``rng_*``, ``key_*``), names assigned
  from ``PRNGKey`` / ``split`` / ``fold_in`` / ``core.rng`` helpers, and
  aliases of either;
* **derivations** — passing a key to ``split`` / ``fold_in`` (and the
  ``core.rng`` wrappers) does NOT consume it; that's how new streams
  are minted;
* **consumptions** — a key appearing anywhere in the arguments of any
  other call.

Two consumptions of the same variable without an intervening rebinding
flag the second call site.  Control flow is honored: ``if``/``else``
branches are analyzed independently and merged (a consumption in each
exclusive branch does not flag); ``for``/``while`` bodies are scanned
twice so a key defined OUTSIDE the loop but consumed INSIDE it — fresh
reuse every iteration — is caught.  Intentional reuse (e.g. shared
synthesis noise across a PPL pair) gets an inline suppression with a
justification comment.
"""

from __future__ import annotations

import ast
import re
from typing import Dict

from gansformer_tpu.analysis.engine import FileContext, Rule, register
from gansformer_tpu.analysis.jit_regions import dotted_name

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_KEY_NAME = re.compile(r"^(rng|key)$|^(rng|key)_|_(rng|key)$")
# jax.random derivations (need a random-flavored prefix: a bare
# ``line.split()`` is a *string* split, not a PRNG one)
_JAX_DERIVERS = {"split", "fold_in", "PRNGKey", "key", "key_data",
                 "wrap_key_data", "clone"}
# this repo's core.rng helpers — distinctive enough to match bare
_RNG_HELPERS = {"key_for", "per_step", "per_host", "split_named"}


def _is_key_name(name: str) -> bool:
    return bool(_KEY_NAME.search(name))


def _is_derive_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if not name:
        return False
    parts = name.split(".")
    last = parts[-1]
    if last in _RNG_HELPERS:
        return True
    if last not in _JAX_DERIVERS:
        return False
    if last == "PRNGKey":
        return True
    prefix = parts[:-1]
    # jax.random.split / random.split / jr.split / bare `split` (from
    # jax.random import split); "line.split" has prefix ["line"] — no.
    return (not prefix or "random" in prefix
            or prefix[-1] in ("jr", "jrandom", "rng"))


def _is_key_source(expr: ast.AST, state: Dict[str, int]) -> bool:
    """Does this value expression produce a key?  PRNGKey/split/fold_in
    results (possibly subscripted), or an alias of a known key."""
    if isinstance(expr, ast.Call):
        return _is_derive_call(expr)
    if isinstance(expr, (ast.Subscript, ast.Starred)):
        return _is_key_source(expr.value, state)
    if isinstance(expr, ast.Name):
        return expr.id in state
    return False


def _imports_jax(tree: ast.Module) -> bool:
    """Key-looking *parameters* only seed in files that can actually
    mint JAX keys — spares 'key' dict-loop vars in pure-stdlib files."""
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            if any(a.name == "jax" or a.name.startswith("jax.")
                   for a in n.names):
                return True
        elif isinstance(n, ast.ImportFrom) and n.module and (
                n.module == "jax" or n.module.startswith("jax.")
                or n.module.endswith("core.rng")):
            return True
    return False


@register
class RngKeyReuse(Rule):
    id = "rng-key-reuse"
    description = ("a PRNG key passed to >= 2 consuming calls without an "
                   "intervening split/fold_in")
    hint = ("split the key (k1, k2 = jax.random.split(key)) or fold_in a "
            "distinct constant per consumer")
    node_types = _FUNC_DEFS

    def check(self, node: ast.AST, ctx: FileContext) -> None:
        if not hasattr(ctx, "_rng_imports_jax"):
            ctx._rng_imports_jax = _imports_jax(ctx.tree)
        state: Dict[str, int] = ({p: 0 for p in self._key_params(node)}
                                 if ctx._rng_imports_jax else {})
        self._scan_block(node.body, state, ctx)

    # -- scope setup ---------------------------------------------------------

    @staticmethod
    def _key_params(fn: ast.AST):
        a = fn.args
        params = list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
        return [p.arg for p in params
                if _is_key_name(p.arg) and p.arg != "rngs"]

    # -- control-flow-aware statement scan -----------------------------------

    def _scan_block(self, stmts, state: Dict[str, int],
                    ctx: FileContext) -> Dict[str, int]:
        for st in stmts:
            if isinstance(st, _FUNC_DEFS + (ast.ClassDef, ast.Lambda)):
                continue              # separate scope, dispatched on its own
            if isinstance(st, ast.If):
                # the condition itself can consume (jax.random.bernoulli)
                self._scan_stmt_exprs([st.test], state, ctx)
                s1 = self._scan_block(st.body, dict(state), ctx)
                s2 = self._scan_block(st.orelse, dict(state), ctx)
                # a branch that terminates (return/raise/…) contributes
                # nothing to the fall-through state
                if self._terminates(st.body):
                    s1 = dict(state)
                if st.orelse and self._terminates(st.orelse):
                    s2 = dict(state)
                state = self._merge(s1, s2)
            elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                if isinstance(st, (ast.For, ast.AsyncFor)):
                    self._scan_stmt_exprs([st.iter], state, ctx)
                    self._rebind(st.target, st.iter, state)
                # twice: the 2nd pass sees cross-iteration reuse of keys
                # bound outside the loop (keys rebound inside stay clean);
                # a while TEST re-evaluates per iteration, so it scans
                # before each body pass
                inner = dict(state)
                for _ in range(2):
                    if isinstance(st, ast.While):
                        self._scan_stmt_exprs([st.test], inner, ctx)
                    inner = self._scan_block(st.body, inner, ctx)
                state = self._merge(state, inner)
                state = self._scan_block(st.orelse, state, ctx)
            elif isinstance(st, ast.Try):
                state = self._scan_block(st.body, state, ctx)
                for h in st.handlers:
                    state = self._scan_block(h.body, state, ctx)
                state = self._scan_block(st.orelse, state, ctx)
                state = self._scan_block(st.finalbody, state, ctx)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                self._scan_stmt_exprs(
                    [i.context_expr for i in st.items], state, ctx)
                state = self._scan_block(st.body, state, ctx)
            else:
                self._process_stmt(st, state, ctx)
        return state

    @staticmethod
    def _terminates(stmts) -> bool:
        return bool(stmts) and isinstance(
            stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))

    @staticmethod
    def _merge(s1: Dict[str, int], s2: Dict[str, int]) -> Dict[str, int]:
        """Exclusive branches: a key is as used as its worst branch."""
        return {k: max(s1.get(k, 0), s2.get(k, 0))
                for k in set(s1) | set(s2)}

    # -- one linear statement ------------------------------------------------

    def _process_stmt(self, st: ast.stmt, state: Dict[str, int],
                      ctx: FileContext) -> None:
        if isinstance(st, ast.Assign):
            self._scan_stmt_exprs([st.value], state, ctx)
            for t in st.targets:
                self._rebind(t, st.value, state)
        elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
            if st.value is not None:
                self._scan_stmt_exprs([st.value], state, ctx)
                self._rebind(st.target, st.value, state)
        else:
            self._scan_stmt_exprs(
                [n for n in ast.iter_child_nodes(st)
                 if isinstance(n, ast.expr)], state, ctx)

    def _rebind(self, target: ast.AST, value: ast.AST,
                state: Dict[str, int]) -> None:
        """Assignment: a key-producing value (or any value bound to a
        key-looking name) starts a FRESH key; other values un-key the
        name.  Tuple targets of a split are all fresh keys."""
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._rebind(elt, value, state)
            return
        if not isinstance(target, ast.Name):
            return
        # provenance required: only PRNG-producing values (or aliases of
        # known keys) create tracked keys — a key-looking NAME bound to
        # e.g. np.random.RandomState is a stateful generator, legal to
        # reuse, and must not be tracked.
        if _is_key_source(value, state):
            state[target.id] = 0
        else:
            state.pop(target.id, None)

    # -- consumption counting ------------------------------------------------

    def _scan_stmt_exprs(self, exprs, state: Dict[str, int],
                         ctx: FileContext) -> None:
        for e in exprs:
            self._visit_expr(e, state, ctx)

    def _visit_expr(self, e: ast.AST, state: Dict[str, int],
                    ctx: FileContext) -> None:
        if isinstance(e, _FUNC_DEFS + (ast.Lambda,)):
            return
        if isinstance(e, ast.Call):
            derive = _is_derive_call(e)
            self._visit_expr(e.func, state, ctx)
            for arg in list(e.args) + [kw.value for kw in e.keywords]:
                if derive and isinstance(arg, ast.Name):
                    continue          # split(key)/fold_in(key, …): derives
                if derive and isinstance(arg, ast.Starred) and \
                        isinstance(arg.value, ast.Name):
                    continue
                self._visit_expr(arg, state, ctx)
            return
        if isinstance(e, ast.Name) and isinstance(e.ctx, ast.Load) and \
                e.id in state:
            if self._inside_call_args(e, ctx):
                state[e.id] += 1
                if state[e.id] == 2:
                    ctx.report(
                        self, e,
                        f"PRNG key {e.id!r} passed to a second consuming "
                        f"call without an intervening split/fold_in — "
                        f"correlated randomness")
            return
        for child in ast.iter_child_nodes(e):
            self._visit_expr(child, state, ctx)

    @staticmethod
    def _inside_call_args(name_node: ast.Name, ctx: FileContext) -> bool:
        """Only uses that hand the key to a call consume entropy (a bare
        ``return key`` or comparison does not)."""
        n = name_node
        while True:
            parent = ctx.parent(n)
            if parent is None or isinstance(parent, ast.stmt):
                return False
            if isinstance(parent, ast.Call):
                # ``key.method(...)``: the key is the callee (a stateful-
                # generator idiom), not an argument — no entropy handed over
                return n is not parent.func
            n = parent
