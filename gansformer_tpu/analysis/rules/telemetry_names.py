"""telemetry-name-convention: instrument names must be ``group/name``.

docs/observability.md documents the registry namespace: slash-separated
lowercase paths (``data/prefetch_queue_depth``, ``ckpt/write_ms``,
``metric/<name>/duration_s``) that export to Prometheus as
``group_name``.  A free-form name (``"MyCounter"``, ``"data wait"``)
still *works* — and then lands in telemetry.prom outside every dashboard
group and grep.  This rule pins the convention at review time.

Checked call sites (resolved from the file's imports so unrelated
``.counter()`` methods don't false-positive):

* ``counter/gauge/histogram`` imported bare from
  ``gansformer_tpu.obs.registry`` (or ``…obs``);
* the same attributes on a module imported as an alias
  (``from gansformer_tpu.obs import registry as telemetry``);
* the same attributes on ``get_registry()`` / ``obs.get_registry()``.

Constant names must match ``^[a-z0-9_]+(/[a-z0-9_]+)+$`` (at least one
slash: a group and a name).  f-strings are checked on their constant
fragments only (charset + at least structural plausibility); fully
dynamic names are skipped — the runtime Prometheus sanitizer and the
schema lint (telemetry_schema.py) own that half.
"""

from __future__ import annotations

import ast
import re
from typing import Set

from gansformer_tpu.analysis.engine import FileContext, Rule, register
from gansformer_tpu.analysis.jit_regions import dotted_name

_INSTRUMENTS = {"counter", "gauge", "histogram"}
_OBS_MODULES = ("gansformer_tpu.obs.registry", "gansformer_tpu.obs")
_NAME_RE = re.compile(r"^[a-z0-9_]+(/[a-z0-9_]+)+$")
_FRAGMENT_RE = re.compile(r"^[a-z0-9_/]*$")


@register
class TelemetryNameConvention(Rule):
    id = "telemetry-name-convention"
    description = ("telemetry counter/gauge/histogram names must follow "
                   "the group/name pattern from docs/observability.md")
    hint = ("use a slash-separated lowercase path, e.g. "
            "\"data/wait_ms\" or \"ckpt/save_total\"")
    node_types = (ast.Module,)

    def check(self, node: ast.Module, ctx: FileContext) -> None:
        bare, module_aliases = self._aliases(node)
        if not bare and not module_aliases:
            return
        for call in ast.walk(node):
            if isinstance(call, ast.Call) and \
                    self._is_instrument_call(call, bare, module_aliases):
                self._check_name(call, ctx)

    # -- import resolution ---------------------------------------------------

    @staticmethod
    def _aliases(tree: ast.Module):
        """(bare instrument fn names, module alias names) imported from
        the obs registry in this file."""
        bare: Set[str] = set()
        modules: Set[str] = set()
        for n in ast.walk(tree):
            if isinstance(n, ast.ImportFrom) and n.module and \
                    n.module.startswith("gansformer_tpu.obs"):
                for a in n.names:
                    local = a.asname or a.name
                    if a.name in _INSTRUMENTS:
                        bare.add(local)
                    elif a.name in ("registry", "obs"):
                        modules.add(local)
            elif isinstance(n, ast.Import):
                for a in n.names:
                    if a.name in _OBS_MODULES:
                        modules.add(a.asname or a.name.split(".")[0])
        return bare, modules

    @staticmethod
    def _is_instrument_call(call: ast.Call, bare: Set[str],
                            modules: Set[str]) -> bool:
        f = call.func
        if isinstance(f, ast.Name):
            return f.id in bare
        if isinstance(f, ast.Attribute) and f.attr in _INSTRUMENTS:
            base = dotted_name(f.value)
            if base and (base in modules
                         or base.split(".")[0] in modules):
                return True
            # get_registry().counter(...)
            if isinstance(f.value, ast.Call):
                inner = dotted_name(f.value.func)
                return bool(inner) and \
                    inner.split(".")[-1] == "get_registry"
        return False

    # -- the convention itself ----------------------------------------------

    def _check_name(self, call: ast.Call, ctx: FileContext) -> None:
        if not call.args:
            return
        arg = call.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if not _NAME_RE.match(arg.value):
                ctx.report(
                    self, arg,
                    f"telemetry name {arg.value!r} does not match the "
                    f"group/name convention "
                    f"([a-z0-9_]+(/[a-z0-9_]+)+, docs/observability.md)")
        elif isinstance(arg, ast.JoinedStr):
            frags = "".join(v.value for v in arg.values
                            if isinstance(v, ast.Constant)
                            and isinstance(v.value, str))
            if not _FRAGMENT_RE.match(frags):
                ctx.report(
                    self, arg,
                    f"telemetry f-string name has non-conforming constant "
                    f"fragments {frags!r} (want lowercase [a-z0-9_/], "
                    f"docs/observability.md)")
        # fully dynamic names: runtime sanitizer + schema lint own those
