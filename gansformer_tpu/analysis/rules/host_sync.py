"""host-sync-in-jit: host synchronization attempted inside a traced region.

Inside a function that executes under ``jax.jit``/``pjit``/``shard_map``
(per the shared jit-region resolver), flag:

* ``jax.device_get`` / ``block_until_ready`` — always an error under a
  trace (and a per-dispatch stall even where they "work");
* ``print(...)`` — runs at trace time only, silently NOT per step; the
  author almost always wanted ``jax.debug.print``;
* ``.item()`` / ``float()`` / ``int()`` / ``np.asarray`` / ``np.array``
  applied to a **tracer-tainted** expression — these concretize, raising
  ``TracerConversionError`` at best and hiding a sync at worst.

Taint = the function's parameters plus anything transitively assigned
from them (fixpoint over the function's assignments; order-insensitive,
so it over-approximates — which for a linter is the safe direction).
``float(cfg.lr)``-style trace-time constants are NOT tainted and pass;
``int(x.shape[0])``-style static-shape reads are exempted explicitly.
"""

from __future__ import annotations

import ast
from typing import Set

from gansformer_tpu.analysis.engine import FileContext, Rule, register
from gansformer_tpu.analysis.jit_regions import dotted_name

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_ALWAYS_BANNED = {"device_get", "block_until_ready"}
_NP_MODULES = {"np", "numpy", "onp"}
_STATIC_ATTRS = {"shape", "ndim", "dtype"}   # trace-time Python values


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    params = list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
    if a.vararg:
        params.append(a.vararg)
    if a.kwarg:
        params.append(a.kwarg)
    return {p.arg for p in params if p.arg not in ("self", "cls")}


def _names_in(expr: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(expr)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _target_names(target: ast.AST) -> Set[str]:
    out = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            out.add(n.id)
    return out


def _tainted_names(fn: ast.AST) -> Set[str]:
    """Params + transitive assignments from them (fixpoint)."""
    taint = _param_names(fn)
    assigns = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                assigns.append((t, node.value))
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) and node.value:
            assigns.append((node.target, node.value))
        elif isinstance(node, ast.NamedExpr):
            assigns.append((node.target, node.value))
        elif isinstance(node, ast.For):
            assigns.append((node.target, node.iter))
    changed = True
    while changed:
        changed = False
        for target, value in assigns:
            if _names_in(value) & taint:
                new = _target_names(target) - taint
                if new:
                    taint |= new
                    changed = True
    return taint


def _is_tainted(expr: ast.AST, taint: Set[str]) -> bool:
    return bool(_names_in(expr) & taint)


def _reads_static_attr(expr: ast.AST) -> bool:
    """``x.shape[0]``-style: static under a trace, a legal int() target."""
    return any(isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS
               for n in ast.walk(expr))


@register
class HostSyncInJit(Rule):
    id = "host-sync-in-jit"
    description = ("host synchronization (.item()/float()/int()/"
                   "np.asarray/device_get/block_until_ready/print) inside "
                   "a jit/pjit/shard_map region")
    hint = ("move the sync outside the jitted function, or use "
            "jax.debug.print / jnp equivalents inside the trace")
    node_types = _FUNC_DEFS

    def check(self, node: ast.AST, ctx: FileContext) -> None:
        if not ctx.jit.is_jit(node):
            return
        taint = _tainted_names(node)
        # walk this def's body only — nested defs get their own dispatch
        # (and their own in-region decision)
        stack = list(ast.iter_child_nodes(node))
        while stack:
            n = stack.pop()
            if isinstance(n, _FUNC_DEFS):
                continue
            if isinstance(n, ast.Call):
                self._check_call(n, taint, ctx)
            stack.extend(ast.iter_child_nodes(n))

    def _check_call(self, call: ast.Call, taint: Set[str],
                    ctx: FileContext) -> None:
        f = call.func
        name = dotted_name(f)
        last = name.split(".")[-1] if name else \
            (f.attr if isinstance(f, ast.Attribute) else "")
        if last in _ALWAYS_BANNED:
            ctx.report(self, call,
                       f"{last}() inside a jit region — forces a host "
                       f"sync / fails on tracers")
            return
        if name == "print":
            ctx.report(self, call,
                       "print() inside a jit region runs at trace time "
                       "only, not per step",
                       hint="use jax.debug.print for per-step output")
            return
        if isinstance(f, ast.Attribute) and f.attr == "item" \
                and not call.args and _is_tainted(f.value, taint):
            ctx.report(self, call,
                       ".item() on a traced value inside a jit region")
            return
        if name in ("float", "int") and len(call.args) == 1:
            arg = call.args[0]
            if _is_tainted(arg, taint) and not _reads_static_attr(arg):
                ctx.report(self, call,
                           f"{name}() concretizes a traced value inside "
                           f"a jit region")
            return
        if name and name.split(".")[0] in _NP_MODULES and \
                last in ("asarray", "array") and call.args and \
                _is_tainted(call.args[0], taint):
            ctx.report(self, call,
                       f"{name}() on a traced value inside a jit region "
                       f"pulls the tracer to host",
                       hint="use jnp.asarray (stays on device)")
