"""graftlint — JAX-aware static analysis for this repo (ISSUE 3).

Ordinary linters and the type checker cannot see the two classes of bug
that silently destroy TPU throughput and reproducibility: host syncs
inside a traced region, and misuse of explicit state the JAX model makes
the programmer carry (donated buffers, PRNG keys, background-thread
shared state).  This package is a small rule-based framework over one
AST walk per file:

* ``engine``       — ``Rule`` registry + visitor driver, inline
                     suppressions, per-file context.
* ``jit_regions``  — the shared resolver for "which functions run under
                     ``jax.jit``/``pjit``/``shard_map``" (decorator,
                     call wrap, or ``partial``) — used by several rules.
* ``rules/``       — one module per rule; importing ``rules`` registers
                     them all.
* ``baseline``     — checked-in allowlist so pre-existing findings don't
                     block CI while new ones do.
* ``reporters``    — text / JSON rendering.
* ``cli``          — the ``gansformer-lint`` console entry point.
* ``telemetry_schema`` — the run-dir artifact lint (events.jsonl /
                     telemetry.prom / heartbeats) migrated from
                     ``scripts/check_telemetry.py``; not AST-based, but
                     it reports through the same ``Finding`` type.
* ``learning_trend`` — the run-dir learning-evidence lint migrated from
                     ``scripts/check_learning_trend.py`` (``--run-dir
                     --learning-trend``); same ``Finding`` plumbing.
* ``trace/``       — graftcheck (ISSUE 4): jaxpr-level semantic rules
                     run against the repo's real jitted entry points —
                     retrace hazards, const bloat, silent dtype
                     promotion, sharding audit (``--trace``).

Suppression syntax (same line as the finding)::

    x = bad_thing()   # graftlint: disable=<rule-id>[,<rule-id>]

See docs/static-analysis.md for the rule catalog and workflow.
"""

from gansformer_tpu.analysis.findings import Finding  # noqa: F401
from gansformer_tpu.analysis.engine import (  # noqa: F401
    Rule, all_rules, get_rule, lint_file, lint_paths, lint_source, register,
)
