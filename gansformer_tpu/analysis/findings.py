"""``Finding`` — one rule violation at one source location.

A finding is born *new*; the driver may then mark it ``suppressed`` (an
inline ``# graftlint: disable=`` comment on its line) or ``baselined``
(matched by the checked-in baseline file).  Only new findings fail the
lint; the other two states stay visible in the JSON report so the debt
is auditable.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class Finding:
    rule: str                 # rule id, e.g. "host-sync-in-jit"
    path: str                 # as given to the driver (usually relative)
    line: int                 # 1-based
    col: int                  # 0-based (ast convention)
    message: str
    hint: str = ""            # how to fix, one line
    suppressed: bool = False  # inline # graftlint: disable=<rule>
    baselined: bool = False   # matched the checked-in baseline

    @property
    def new(self) -> bool:
        return not (self.suppressed or self.baselined)

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule, self.message)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["new"] = self.new
        return d

    def baseline_key(self, line_text: Optional[str] = None) -> str:
        """Content-addressed identity for baseline matching: rule + path
        + the *stripped text* of the flagged line, so pure line-number
        drift (edits elsewhere in the file) doesn't invalidate the
        baseline, while any edit to the flagged line itself does."""
        text = (line_text or "").strip()
        return f"{self.rule}::{self.path}::{text}"
