"""``gansformer-lint`` — run graftlint over files/directories.

Usage::

    gansformer-lint gansformer_tpu scripts            # lint the tree
    gansformer-lint --format json path/to/file.py     # machine output
    gansformer-lint --trace gansformer_tpu scripts    # AST + jaxpr rules
    gansformer-lint --trace --trace-profile full      # the whole matrix
    gansformer-lint --fix-baseline gansformer_tpu scripts
    gansformer-lint --list-rules
    gansformer-lint --run-dir results/00003-run       # artifact schema

``--trace`` adds the jaxpr-level semantic rules (ISSUE 4,
``analysis/trace/``): the repo's real jitted entry points are traced
with abstract inputs and checked for retrace hazards, const bloat,
silent dtype promotion, and sharding-vs-intent drift.  Trace findings
ride the same suppression/baseline/exit-code machinery.  When jax has
not been imported yet, the CLI forces a 2-CPU-device backend so the
sharding audit has a mesh to resolve against.

Exit codes: 0 — no new findings; 1 — new findings (or schema errors);
2 — usage error.  "New" excludes inline-suppressed findings and entries
matched by the baseline file (default: ``graftlint-baseline.json`` next
to the repo's ``gansformer_tpu`` package, i.e. the checked-in one, when
it exists; override with ``--baseline``; ``--no-baseline`` ignores it).

``--fix-baseline`` regenerates the baseline from the current tree —
sorted entries, relative paths, atomic write — so two runs on the same
tree are byte-identical and the diff of a baseline update is readable.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from gansformer_tpu.analysis import engine, reporters
from gansformer_tpu.analysis.baseline import Baseline, line_text_lookup
from gansformer_tpu.analysis.findings import Finding

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    "graftlint-baseline.json")


def _select_rules(select: Optional[str], ignore: Optional[str],
                  trace: bool = False):
    """(ast_rules, trace_rules) honoring --select/--ignore across BOTH
    registries; unknown ids are a usage error either way."""
    rules = engine.all_rules()
    from gansformer_tpu.analysis.trace.base import all_trace_rules

    trace_rules = all_trace_rules() if trace else []
    ast_ids = {r.id for r in rules}
    trace_ids = {r.id for r in all_trace_rules()}
    known = ast_ids | trace_ids
    if select:
        wanted = {r.strip() for r in select.split(",") if r.strip()}
        unknown = wanted - known
        if unknown:
            raise SystemExit(
                f"gansformer-lint: unknown rule(s): {sorted(unknown)} "
                f"(see --list-rules)")
        trace_only = wanted & (trace_ids - ast_ids)
        if trace_only and not trace:
            # a trace-only selection without --trace would walk every
            # file with ZERO rules and report a false clean pass
            raise SystemExit(
                f"gansformer-lint: rule(s) {sorted(trace_only)} are "
                f"trace rules — add --trace to run them")
        rules = [r for r in rules if r.id in wanted]
        trace_rules = [r for r in trace_rules if r.id in wanted]
    if ignore:
        dropped = {r.strip() for r in ignore.split(",") if r.strip()}
        unknown = dropped - known
        if unknown:
            raise SystemExit(
                f"gansformer-lint: unknown rule(s): {sorted(unknown)} "
                f"(see --list-rules)")
        rules = [r for r in rules if r.id not in dropped]
        trace_rules = [r for r in trace_rules if r.id not in dropped]
    return rules, trace_rules


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gansformer-lint",
        description="JAX-aware static analysis (graftlint, ISSUE 3): "
                    "tracer safety, donation, RNG reuse, thread "
                    "discipline, telemetry naming.")
    p.add_argument("paths", nargs="*",
                   help="files and/or directories to lint")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {DEFAULT_BASELINE} "
                        f"when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--fix-baseline", action="store_true",
                   help="regenerate the baseline from current findings "
                        "(deterministic: sorted, relative paths)")
    p.add_argument("--select", default=None, metavar="RULES",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--ignore", default=None, metavar="RULES",
                   help="comma-separated rule ids to skip")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--run-dir", default=None, metavar="DIR",
                   help="also schema-lint a run dir's telemetry artifacts "
                        "(events.jsonl/telemetry.prom/heartbeats)")
    p.add_argument("--learning-trend", action="store_true",
                   help="with --run-dir: also assert the run LEARNED "
                        "(fitted metric drop + finite losses; the "
                        "learning-trend rule — opt-in because smoke runs "
                        "legitimately have no metric series)")
    p.add_argument("--trace", action="store_true",
                   help="also run the jaxpr-level trace rules against the "
                        "repo's real jitted entry points (retrace hazards, "
                        "const bloat, dtype promotion, sharding audit)")
    p.add_argument("--trace-profile", choices=("structural", "fast", "full"),
                   default="fast",
                   help="trace cost/coverage: structural = tracing only "
                        "(no compiles); fast = + retrace/sharding probes "
                        "on the plain train steps; full = every rule on "
                        "every matrix entry point")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="also print suppressed/baselined findings")
    return p


def _force_virtual_devices() -> None:
    """Give the process ≥2 CPU devices for the sharding audit — only
    possible before jax initializes its backends; a no-op (with the
    audit falling back to a skip-note) when jax is already live."""
    import sys as _sys

    if "jax" in _sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()


def run_trace_findings(profile: str, trace_rules) -> List[Finding]:
    """Trace-rule findings for the CLI/selfcheck path (device setup +
    harness; see analysis/trace/harness.py for profile semantics)."""
    _force_virtual_devices()
    from gansformer_tpu.analysis.trace.harness import run_trace

    findings, _ctx = run_trace(profile, rules=trace_rules)
    return findings


def run_selfcheck(run_dir: str, trace_profile: str = "fast") -> int:
    """One-command AST + trace lint with a JSON artifact in the run dir
    (``cli/train.py --selfcheck``).  Lints the installed package tree +
    ``scripts/`` when present, applies the checked-in baseline, writes
    ``<run_dir>/graftlint.json``, and returns the number of NEW
    findings (0 = clean, training may proceed)."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    paths = [os.path.join(pkg_root, "gansformer_tpu")]
    scripts = os.path.join(pkg_root, "scripts")
    if os.path.isdir(scripts):
        paths.append(scripts)

    rules, trace_rules = _select_rules(None, None, trace=True)
    files = engine.iter_python_files(paths)
    findings: List[Finding] = []
    for path in files:
        findings.extend(engine.lint_file(path, rules=rules))
    findings.extend(run_trace_findings(trace_profile, trace_rules))
    if os.path.exists(DEFAULT_BASELINE):
        Baseline.load(DEFAULT_BASELINE).apply(findings, line_text_lookup())

    artifact = os.path.join(run_dir, "graftlint.json")
    with open(artifact, "w", encoding="utf-8") as f:
        f.write(reporters.render_json(findings, len(files)))
        f.write("\n")
    return sum(1 for f in findings if f.new)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        from gansformer_tpu.analysis.trace.base import all_trace_rules

        for cls in engine.all_rules():
            print(f"{cls.id:<26s} {cls.description}")
        for cls in all_trace_rules():
            print(f"{cls.id:<26s} [trace] {cls.description}")
        print(f"{'telemetry-schema':<26s} run-dir artifact schema "
              f"(--run-dir; scripts/check_telemetry.py shim)")
        print(f"{'learning-trend':<26s} run-dir learning evidence "
              f"(--run-dir --learning-trend; "
              f"scripts/check_learning_trend.py shim)")
        return 0

    if not args.paths and not args.run_dir and not args.trace:
        build_parser().print_usage(sys.stderr)
        print("gansformer-lint: no paths given", file=sys.stderr)
        return 2
    if args.learning_trend and not args.run_dir:
        print("gansformer-lint: --learning-trend needs --run-dir",
              file=sys.stderr)
        return 2

    try:
        rules, trace_rules = _select_rules(args.select, args.ignore,
                                           trace=args.trace)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 2

    if args.fix_baseline and (args.select or args.ignore):
        # a scoped run sees only a subset of findings; regenerating the
        # baseline from it would silently drop every other rule's entries
        print("gansformer-lint: --fix-baseline cannot be combined with "
              "--select/--ignore (it regenerates the WHOLE baseline); "
              "run it over the full rule set and lint surface",
              file=sys.stderr)
        return 2

    files = engine.iter_python_files(args.paths)
    if args.paths and not files:
        # a typo'd path must not read as a green lint over zero files
        print(f"gansformer-lint: no python files found under "
              f"{args.paths} — misspelled path?", file=sys.stderr)
        return 2
    findings: List[Finding] = []
    for path in files:
        findings.extend(engine.lint_file(path, rules=rules))

    if args.trace and trace_rules:
        # trace findings join BEFORE baseline application so they can be
        # baselined/suppressed exactly like AST findings
        findings.extend(run_trace_findings(args.trace_profile, trace_rules))

    line_text = line_text_lookup()

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)

    if args.fix_baseline:
        target = args.baseline or DEFAULT_BASELINE
        Baseline.write(target, findings, line_text)
        kept = sum(1 for f in findings if not f.suppressed)
        print(f"gansformer-lint: wrote {kept} baseline entr"
              f"{'y' if kept == 1 else 'ies'} to {target}")
        return 0

    if baseline_path and not args.no_baseline:
        Baseline.load(baseline_path).apply(findings, line_text)

    if args.run_dir:
        from gansformer_tpu.analysis.telemetry_schema import lint_run_dir

        findings.extend(lint_run_dir(args.run_dir))
        if args.learning_trend:
            from gansformer_tpu.analysis.learning_trend import (
                lint_learning_trend)

            findings.extend(lint_learning_trend(args.run_dir))

    if args.format == "json":
        print(reporters.render_json(findings, len(files)))
    else:
        print(reporters.render_text(findings, len(files),
                                    verbose=args.verbose))
    return 0 if all(not f.new for f in findings) else 1


if __name__ == "__main__":
    sys.exit(main())
