"""``gansformer-lint`` — run graftlint over files/directories.

Usage::

    gansformer-lint gansformer_tpu scripts            # lint the tree
    gansformer-lint --format json path/to/file.py     # machine output
    gansformer-lint --fix-baseline gansformer_tpu scripts
    gansformer-lint --list-rules
    gansformer-lint --run-dir results/00003-run       # artifact schema

Exit codes: 0 — no new findings; 1 — new findings (or schema errors);
2 — usage error.  "New" excludes inline-suppressed findings and entries
matched by the baseline file (default: ``graftlint-baseline.json`` next
to the repo's ``gansformer_tpu`` package, i.e. the checked-in one, when
it exists; override with ``--baseline``; ``--no-baseline`` ignores it).

``--fix-baseline`` regenerates the baseline from the current tree —
sorted entries, relative paths, atomic write — so two runs on the same
tree are byte-identical and the diff of a baseline update is readable.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from gansformer_tpu.analysis import engine, reporters
from gansformer_tpu.analysis.baseline import Baseline, line_text_lookup
from gansformer_tpu.analysis.findings import Finding

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    "graftlint-baseline.json")


def _select_rules(select: Optional[str], ignore: Optional[str]):
    rules = engine.all_rules()
    if select:
        wanted = {r.strip() for r in select.split(",") if r.strip()}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            raise SystemExit(
                f"gansformer-lint: unknown rule(s): {sorted(unknown)} "
                f"(see --list-rules)")
        rules = [r for r in rules if r.id in wanted]
    if ignore:
        dropped = {r.strip() for r in ignore.split(",") if r.strip()}
        rules = [r for r in rules if r.id not in dropped]
    return rules


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gansformer-lint",
        description="JAX-aware static analysis (graftlint, ISSUE 3): "
                    "tracer safety, donation, RNG reuse, thread "
                    "discipline, telemetry naming.")
    p.add_argument("paths", nargs="*",
                   help="files and/or directories to lint")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {DEFAULT_BASELINE} "
                        f"when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--fix-baseline", action="store_true",
                   help="regenerate the baseline from current findings "
                        "(deterministic: sorted, relative paths)")
    p.add_argument("--select", default=None, metavar="RULES",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--ignore", default=None, metavar="RULES",
                   help="comma-separated rule ids to skip")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--run-dir", default=None, metavar="DIR",
                   help="also schema-lint a run dir's telemetry artifacts "
                        "(events.jsonl/telemetry.prom/heartbeats)")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="also print suppressed/baselined findings")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for cls in engine.all_rules():
            print(f"{cls.id:<26s} {cls.description}")
        print(f"{'telemetry-schema':<26s} run-dir artifact schema "
              f"(--run-dir; scripts/check_telemetry.py shim)")
        return 0

    if not args.paths and not args.run_dir:
        build_parser().print_usage(sys.stderr)
        print("gansformer-lint: no paths given", file=sys.stderr)
        return 2

    try:
        rules = _select_rules(args.select, args.ignore)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 2

    if args.fix_baseline and (args.select or args.ignore):
        # a scoped run sees only a subset of findings; regenerating the
        # baseline from it would silently drop every other rule's entries
        print("gansformer-lint: --fix-baseline cannot be combined with "
              "--select/--ignore (it regenerates the WHOLE baseline); "
              "run it over the full rule set and lint surface",
              file=sys.stderr)
        return 2

    files = engine.iter_python_files(args.paths)
    if args.paths and not files:
        # a typo'd path must not read as a green lint over zero files
        print(f"gansformer-lint: no python files found under "
              f"{args.paths} — misspelled path?", file=sys.stderr)
        return 2
    findings: List[Finding] = []
    for path in files:
        findings.extend(engine.lint_file(path, rules=rules))

    line_text = line_text_lookup()

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)

    if args.fix_baseline:
        target = args.baseline or DEFAULT_BASELINE
        Baseline.write(target, findings, line_text)
        kept = sum(1 for f in findings if not f.suppressed)
        print(f"gansformer-lint: wrote {kept} baseline entr"
              f"{'y' if kept == 1 else 'ies'} to {target}")
        return 0

    if baseline_path and not args.no_baseline:
        Baseline.load(baseline_path).apply(findings, line_text)

    if args.run_dir:
        from gansformer_tpu.analysis.telemetry_schema import lint_run_dir

        findings.extend(lint_run_dir(args.run_dir))

    if args.format == "json":
        print(reporters.render_json(findings, len(files)))
    else:
        print(reporters.render_text(findings, len(files),
                                    verbose=args.verbose))
    return 0 if all(not f.new for f in findings) else 1


if __name__ == "__main__":
    sys.exit(main())
