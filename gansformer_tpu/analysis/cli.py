"""``gansformer-lint`` — run graftlint over files/directories.

Usage::

    gansformer-lint gansformer_tpu scripts            # lint the tree
    gansformer-lint --format json path/to/file.py     # machine output
    gansformer-lint --trace gansformer_tpu scripts    # AST + jaxpr rules
    gansformer-lint --trace --trace-profile full      # the whole matrix
    gansformer-lint --trace --json-out comms.json     # graftcomms table
    gansformer-lint --fix-baseline gansformer_tpu scripts
    gansformer-lint --list-rules
    gansformer-lint --run-dir results/00003-run       # artifact schema

``--trace`` adds the jaxpr-level semantic rules (ISSUEs 4+6,
``analysis/trace/``): the repo's real jitted entry points are traced
with abstract inputs and checked for retrace hazards, const bloat,
silent dtype promotion, and — via the graftcomms layer — sharding
contracts and collective-flow anti-patterns over the SPMD-compiled
programs.  Trace findings ride the same suppression/baseline/exit-code
machinery; ``--json-out`` additionally exports the ranked per-entry
comms-bytes table + the bytes-vs-chip-count scaling prediction.  When
jax has not been imported yet, the CLI forces a 4-CPU-device backend
so the mesh matrix has devices to resolve against (``--trace-native``
keeps the ambient backend instead — the battery's TPU capture).

Exit codes: 0 — no new findings; 1 — new findings (or schema errors);
2 — usage error.  "New" excludes inline-suppressed findings and entries
matched by the baseline file (default: ``graftlint-baseline.json`` next
to the repo's ``gansformer_tpu`` package, i.e. the checked-in one, when
it exists; override with ``--baseline``; ``--no-baseline`` ignores it).

``--fix-baseline`` regenerates the baseline from the current tree —
sorted entries, relative paths, atomic write — so two runs on the same
tree are byte-identical and the diff of a baseline update is readable.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from gansformer_tpu.analysis import engine, reporters
from gansformer_tpu.analysis.baseline import Baseline, line_text_lookup
from gansformer_tpu.analysis.findings import Finding

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    "graftlint-baseline.json")


def _select_rules(select: Optional[str], ignore: Optional[str],
                  trace: bool = False):
    """(ast_rules, trace_rules) honoring --select/--ignore across BOTH
    registries; unknown ids are a usage error either way."""
    rules = engine.all_rules()
    from gansformer_tpu.analysis.trace.base import all_trace_rules

    trace_rules = all_trace_rules() if trace else []
    aliases = engine.rule_aliases()     # retired id -> current id
    ast_ids = {r.id for r in rules}
    trace_ids = {r.id for r in all_trace_rules()}
    known = ast_ids | trace_ids | set(aliases)
    if select:
        wanted = {aliases.get(r.strip(), r.strip())
                  for r in select.split(",") if r.strip()}
        unknown = wanted - known
        if unknown:
            raise SystemExit(
                f"gansformer-lint: unknown rule(s): {sorted(unknown)} "
                f"(see --list-rules)")
        trace_only = wanted & (trace_ids - ast_ids)
        if trace_only and not trace:
            # a trace-only selection without --trace would walk every
            # file with ZERO rules and report a false clean pass
            raise SystemExit(
                f"gansformer-lint: rule(s) {sorted(trace_only)} are "
                f"trace rules — add --trace to run them")
        rules = [r for r in rules if r.id in wanted]
        trace_rules = [r for r in trace_rules if r.id in wanted]
    if ignore:
        dropped = {aliases.get(r.strip(), r.strip())
                   for r in ignore.split(",") if r.strip()}
        unknown = dropped - known
        if unknown:
            raise SystemExit(
                f"gansformer-lint: unknown rule(s): {sorted(unknown)} "
                f"(see --list-rules)")
        rules = [r for r in rules if r.id not in dropped]
        trace_rules = [r for r in trace_rules if r.id not in dropped]
    return rules, trace_rules


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gansformer-lint",
        description="JAX-aware static analysis (graftlint, ISSUE 3): "
                    "tracer safety, donation, RNG reuse, thread "
                    "discipline, telemetry naming.")
    p.add_argument("paths", nargs="*",
                   help="files and/or directories to lint")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {DEFAULT_BASELINE} "
                        f"when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--fix-baseline", action="store_true",
                   help="regenerate the baseline from current findings "
                        "(deterministic: sorted, relative paths)")
    p.add_argument("--select", default=None, metavar="RULES",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--ignore", default=None, metavar="RULES",
                   help="comma-separated rule ids to skip")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--run-dir", default=None, metavar="DIR",
                   help="also schema-lint a run dir's telemetry artifacts "
                        "(events.jsonl/telemetry.prom/heartbeats)")
    p.add_argument("--learning-trend", action="store_true",
                   help="with --run-dir: also assert the run LEARNED "
                        "(fitted metric drop + finite losses; the "
                        "learning-trend rule — opt-in because smoke runs "
                        "legitimately have no metric series)")
    p.add_argument("--trace", action="store_true",
                   help="also run the jaxpr-level trace rules against the "
                        "repo's real jitted entry points (retrace hazards, "
                        "const bloat, dtype promotion, sharding/contract/"
                        "collective audits, and the graftnum numerics "
                        "rules: fp32-island contracts, accumulation "
                        "width, unstable primitives)")
    p.add_argument("--trace-profile",
                   choices=("structural", "contracts", "fast", "full"),
                   default="fast",
                   help="trace cost/coverage: structural = tracing only "
                        "(no compiles); contracts = + the PartitionSpec "
                        "contract check on the four train steps; fast = "
                        "+ retrace/sharding/collective probes on the "
                        "train steps; full = every rule on every matrix "
                        "entry point across the 1/2/4-device mesh matrix")
    p.add_argument("--trace-native", action="store_true",
                   help="compile the trace rules on the AMBIENT jax "
                        "backend instead of forcing virtual CPU devices "
                        "— the battery uses this to capture a TPU-"
                        "compiled comms table (mesh sizes clamp to the "
                        "devices the backend exposes)")
    p.add_argument("--json-out", default=None, metavar="PATH",
                   help="with --trace: write the graftcomms artifact "
                        "(ranked per-entry comms-bytes table + the "
                        "bytes-vs-chip-count scaling prediction) to PATH "
                        "— the comms twin of bench_components.py's "
                        "--json-out FLOP attribution")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="also print suppressed/baselined findings")
    return p


def _force_virtual_devices() -> None:
    """Give the process enough CPU devices for the mesh-compiling rules
    (the 4-device member of the simulated mesh matrix) — only possible
    before jax initializes its backends; a no-op (with the audits
    falling back to skip-notes) when jax is already live."""
    import sys as _sys

    if "jax" in _sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()


def run_trace_findings(profile: str, trace_rules, native: bool = False):
    """(findings, comms_payload) for the CLI/selfcheck path — device
    setup + harness (see analysis/trace/harness.py for profile
    semantics).  ``comms_payload`` is the graftcomms attribution dict
    (ranked table + scaling prediction; empty sections when no
    mesh-compiling rule ran).  The payload distinguishes the REQUESTED
    mesh matrix from the sizes that actually COMPILED and carries the
    harness skip-notes, so a device-starved host (1-chip tunnel window,
    un-forced selfcheck process) reads as partial coverage, not as a
    clean zero-collective table."""
    if not native:
        _force_virtual_devices()
    from gansformer_tpu.analysis.trace.collective_flow import (
        ranked_comms_table, scaling_report)
    from gansformer_tpu.analysis.trace.harness import run_trace
    from gansformer_tpu.utils.hostenv import enable_compile_cache

    enable_compile_cache()    # the contract compiles are cache-keyed by
    # HLO: pre-commit / selfcheck re-runs hit the persistent cache
    findings, ctx = run_trace(profile, rules=trace_rules)
    payload = {
        "comms": ranked_comms_table(ctx.comms),
        "scaling_bytes_per_device": scaling_report(ctx.comms),
        "trace_profile": profile,
        "mesh_sizes_requested": list(ctx.mesh_sizes),
        "mesh_sizes_compiled": sorted(ctx.meshes_compiled),
        "notes": list(ctx.notes),
        # graftnum (ISSUE 19): per-entry fp32-island audit records —
        # the positive "the declared islands compute in fp32 in the
        # compiled programs" claim, entry by entry
        "numerics": list(ctx.numerics),
    }
    return findings, payload


def run_selfcheck(run_dir: str, trace_profile: str = "contracts") -> int:
    """One-command AST + trace lint with a JSON artifact in the run dir
    (``cli/train.py --selfcheck``).  Lints the installed package tree +
    ``scripts/`` when present, applies the checked-in baseline, writes
    ``<run_dir>/graftlint.json``, and returns the number of NEW
    findings (0 = clean, training may proceed).  The default trace
    profile is ``contracts``: the structural rules plus the
    PartitionSpec-contract check on the four train-step programs — a
    mis-partitioned step aborts before it burns accelerator hours.
    Runs NATIVE (no CPU-device forcing): selfcheck executes inside the
    training process, whose backend is already configured."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    paths = [os.path.join(pkg_root, "gansformer_tpu")]
    scripts = os.path.join(pkg_root, "scripts")
    if os.path.isdir(scripts):
        paths.append(scripts)

    rules, trace_rules = _select_rules(None, None, trace=True)
    files = engine.iter_python_files(paths)
    findings: List[Finding] = []
    for path in files:
        findings.extend(engine.lint_file(path, rules=rules))
    trace_findings, comms = run_trace_findings(trace_profile, trace_rules,
                                               native=True)
    findings.extend(trace_findings)
    if os.path.exists(DEFAULT_BASELINE):
        Baseline.load(DEFAULT_BASELINE).apply(findings, line_text_lookup())

    from gansformer_tpu.analysis.concurrency.thread_model import (
        summarize_paths)

    extra = dict(comms)
    extra["thread_model"] = summarize_paths(files, root=pkg_root)
    artifact = os.path.join(run_dir, "graftlint.json")
    with open(artifact, "w", encoding="utf-8") as f:
        f.write(reporters.render_json(findings, len(files), extra=extra))
        f.write("\n")
    return sum(1 for f in findings if f.new)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        from gansformer_tpu.analysis.trace.base import all_trace_rules

        for cls in engine.all_rules():
            print(f"{cls.id:<26s} {cls.description}")
        for old, cur in sorted(engine.rule_aliases().items()):
            print(f"{old:<26s} DEPRECATED alias of {cur} (kept so "
                  f"existing disable= comments and baseline keys "
                  f"keep working)")
        for cls in all_trace_rules():
            print(f"{cls.id:<26s} [trace] {cls.description}")
        print(f"{'telemetry-schema':<26s} run-dir artifact schema "
              f"(--run-dir; scripts/check_telemetry.py shim)")
        print(f"{'learning-trend':<26s} run-dir learning evidence "
              f"(--run-dir --learning-trend; "
              f"scripts/check_learning_trend.py shim)")
        return 0

    if not args.paths and not args.run_dir and not args.trace:
        build_parser().print_usage(sys.stderr)
        print("gansformer-lint: no paths given", file=sys.stderr)
        return 2
    if args.learning_trend and not args.run_dir:
        print("gansformer-lint: --learning-trend needs --run-dir",
              file=sys.stderr)
        return 2
    if (args.json_out or args.trace_native) and not args.trace:
        print("gansformer-lint: --json-out/--trace-native need --trace "
              "(the comms table comes from the compiled trace programs)",
              file=sys.stderr)
        return 2

    try:
        rules, trace_rules = _select_rules(args.select, args.ignore,
                                           trace=args.trace)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 2

    if args.fix_baseline and (args.select or args.ignore):
        # a scoped run sees only a subset of findings; regenerating the
        # baseline from it would silently drop every other rule's entries
        print("gansformer-lint: --fix-baseline cannot be combined with "
              "--select/--ignore (it regenerates the WHOLE baseline); "
              "run it over the full rule set and lint surface",
              file=sys.stderr)
        return 2

    files = engine.iter_python_files(args.paths)
    if args.paths and not files:
        # a typo'd path must not read as a green lint over zero files
        print(f"gansformer-lint: no python files found under "
              f"{args.paths} — misspelled path?", file=sys.stderr)
        return 2
    findings: List[Finding] = []
    for path in files:
        findings.extend(engine.lint_file(path, rules=rules))

    comms_payload = None
    if args.trace:
        if trace_rules:
            # trace findings join BEFORE baseline application so they can
            # be baselined/suppressed exactly like AST findings
            trace_findings, comms_payload = run_trace_findings(
                args.trace_profile, trace_rules, native=args.trace_native)
            findings.extend(trace_findings)
        else:
            # --ignore filtered every trace rule away: the artifact must
            # still exist (and say why it's empty) — a consumer finding
            # no file after a green exit is worse than an empty table
            comms_payload = {
                "comms": [], "scaling_bytes_per_device": {},
                "trace_profile": args.trace_profile,
                "mesh_sizes_requested": [], "mesh_sizes_compiled": [],
                "notes": ["no trace rules selected"]}
        if args.json_out:
            import json as _json

            with open(args.json_out, "w", encoding="utf-8") as f:
                _json.dump({"version": 1, **comms_payload}, f, indent=1,
                           sort_keys=True)
                f.write("\n")

    line_text = line_text_lookup()

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)

    if args.fix_baseline:
        target = args.baseline or DEFAULT_BASELINE
        Baseline.write(target, findings, line_text)
        kept = sum(1 for f in findings if not f.suppressed)
        print(f"gansformer-lint: wrote {kept} baseline entr"
              f"{'y' if kept == 1 else 'ies'} to {target}")
        return 0

    if baseline_path and not args.no_baseline:
        Baseline.load(baseline_path).apply(findings, line_text)

    if args.run_dir:
        from gansformer_tpu.analysis.telemetry_schema import lint_run_dir

        findings.extend(lint_run_dir(args.run_dir))
        if args.learning_trend:
            from gansformer_tpu.analysis.learning_trend import (
                lint_learning_trend)

            findings.extend(lint_learning_trend(args.run_dir))

    if args.format == "json":
        from gansformer_tpu.analysis.concurrency.thread_model import (
            summarize_paths)

        # the thread-model summary rides every JSON report (threads
        # discovered, locks, entry-point mapping, signal handlers) —
        # the doctor / future elasticity work consume it
        extra = dict(comms_payload or {})
        extra["thread_model"] = summarize_paths(files, root=os.getcwd())
        print(reporters.render_json(findings, len(files), extra=extra))
    else:
        print(reporters.render_text(findings, len(files),
                                    verbose=args.verbose))
    return 0 if all(not f.new for f in findings) else 1


if __name__ == "__main__":
    sys.exit(main())
