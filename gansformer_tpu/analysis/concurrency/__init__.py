"""Concurrency static analysis over the threaded runtime (ISSUE 18).

The repo runs a real threaded runtime beside the jitted hot path —
``DevicePrefetcher``/``PrefetchIterator`` producer threads,
``SingleSlotWriter``/``LoopWorker`` background writers, the serving
dispatcher + supervisor pair, and SIGTERM/drain handlers.  This package
is the concurrency twin of ``jit_regions.py``: a shared **thread-model
resolver** (``thread_model.ThreadModel``, reachable per file as
``ctx.threads``) maps every ``threading.Thread`` / ``LoopWorker``
construction and ``.submit()`` dispatch to its target function (bare
name, ``self.method``, lambda, ``functools.partial``), computes the set
of functions reachable from thread entry points, and records every
``Lock``/``RLock``/``Condition`` with its acquisition sites.

Five rules ride on top (one module per rule, catalog in
docs/static-analysis.md):

* ``lock_order``         — lock-order-inversion
* ``shared_state``       — unguarded-shared-attribute (retires the old
                           module-literal-only ``thread-shared-state``
                           rule; the legacy id is kept as an alias)
* ``lifecycle``          — thread-lifecycle
* ``signal_safety``      — signal-handler-safety
* ``condition_protocol`` — condition-protocol

Importing this package registers all five into the engine registry, so
they run under ``gansformer-lint``, pre-commit, and ``--selfcheck``
exactly like the AST rules in ``analysis/rules/``.  Everything here is
pure-AST: no jax import, safe for the fast pre-commit hook.
"""

from gansformer_tpu.analysis.concurrency import (  # noqa: F401  (registers)
    condition_protocol,
    lifecycle,
    lock_order,
    shared_state,
    signal_safety,
)
