"""Shared thread-model resolver (the concurrency twin of jit_regions).

Answers, per module, the questions every concurrency rule needs:

* **which threads exist** — every ``threading.Thread(target=…)`` and
  ``LoopWorker(fn, …)`` construction, plus every ``X.submit(fn)``
  dispatch onto a background executor (``SingleSlotWriter`` and
  anything with the same shape), with the construction's binding
  (``self._thread = …`` / ``t = …`` / fire-and-forget) and its
  ``daemon`` flag;
* **what runs on them** — each target resolved to its definition(s):
  bare name module-wide, ``self.method`` to the enclosing class's
  method, ``lambda`` to the lambda node itself, and one
  ``functools.partial(f, …)`` layer; membership then propagates
  transitively exactly like the jit-region index — a function
  referenced by bare name or as ``self.method`` from thread-entered
  code is thread-reachable too;
* **which locks exist** — assignments of ``threading.Lock`` / ``RLock``
  / ``Condition`` / ``Semaphore`` results, keyed ``(class, attr)`` for
  ``self._lock = …`` and ``("", name)`` for module-level locks, plus
  thread-safe primitives (``Event``, ``queue.Queue``) the shared-state
  rule must NOT flag;
* **which signal handlers are installed** — ``signal.signal(SIG, h)``
  registrations with ``h`` resolved like a thread target.

Known limits (documented in docs/static-analysis.md): resolution is
name-based and module-local — a target held by a non-``self`` receiver
(``srv.serve_forever``) or imported from another module is recorded but
unresolved, and cross-instance aliasing (two ``Ticket`` objects' locks)
collapses onto one ``(class, attr)`` key, which is exactly what the
lock-order rule wants for self-deadlock shapes and an over-approximation
everywhere else.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from gansformer_tpu.analysis.jit_regions import dotted_name

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

# constructor last-name -> lock kind
LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition",
              "Semaphore": "semaphore", "BoundedSemaphore": "semaphore"}
# thread-safe primitives: never "unguarded shared state"
SAFE_CTORS = {"Event", "Queue", "SimpleQueue", "LifoQueue",
              "PriorityQueue", "Barrier", "local"}
# reentrant-safe lock kinds (self-reacquisition is legal)
REENTRANT_KINDS = {"rlock"}

# LockKey: ("" or class name, attribute/variable name)
LockKey = Tuple[str, str]


def lockish_name(name: str) -> bool:
    """Heuristic for lock objects the module did not construct itself
    (a lock passed in as a parameter, e.g. obs/registry instruments):
    the repo's naming convention makes these recognizable."""
    last = name.lower()
    return ("lock" in last or "cond" in last or last in ("_cv", "cv")
            or "semaphore" in last or "mutex" in last)


@dataclasses.dataclass
class ThreadSite:
    kind: str                       # "Thread" | "LoopWorker" | "submit"
    node: ast.Call                  # the construction / dispatch call
    target_desc: str                # human-readable target expression
    targets: Tuple[ast.AST, ...]    # resolved defs / lambda nodes
    daemon: Optional[bool]          # the daemon= kwarg, when constant
    binding: Optional[Tuple[str, str, str]]  # ("attr",cls,name)|("name","",n)


@dataclasses.dataclass
class LockSite:
    key: LockKey
    kind: str                       # lock | rlock | condition | semaphore
    node: ast.AST                   # the constructing assignment


@dataclasses.dataclass
class HandlerSite:
    node: ast.Call                  # the signal.signal(...) call
    target_desc: str
    targets: Tuple[ast.AST, ...]


def _describe(expr: ast.AST) -> str:
    name = dotted_name(expr)
    if name:
        return name
    if isinstance(expr, ast.Lambda):
        return "<lambda>"
    if isinstance(expr, ast.Call):
        inner = dotted_name(expr.func)
        return f"{inner}(...)" if inner else "<call>"
    return f"<{type(expr).__name__}>"


class ThreadModel:
    """Per-module thread/lock/handler index (built once, shared across
    the concurrency rules via ``ctx.threads``)."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self._parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[id(child)] = parent
        # bare-name def index: module-level and nested (closure) defs.
        # Direct class-body methods are excluded — a bare name never
        # reaches them (they need a receiver), and a method named after
        # a builtin (Gauge.max) must not capture calls to that builtin.
        self._defs_by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, _FUNC_DEFS) and \
                    not isinstance(self._parents.get(id(node)),
                                   ast.ClassDef):
                self._defs_by_name.setdefault(node.name, []).append(node)
        # class name -> {method name -> [def nodes]} (direct body only)
        self._methods: Dict[str, Dict[str, List[ast.AST]]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                table = self._methods.setdefault(node.name, {})
                for m in node.body:
                    if isinstance(m, _FUNC_DEFS):
                        table.setdefault(m.name, []).append(m)

        self.locks: Dict[LockKey, LockSite] = {}
        self.safe_keys: Set[LockKey] = set()
        self._collect_locks()

        self.thread_sites: List[ThreadSite] = []
        self.handlers: List[HandlerSite] = []
        self._collect_sites()

        self._entry_ids: Set[int] = set()
        self._reachable_ids: Set[int] = set()
        self._propagate([t for s in self.thread_sites for t in s.targets])

    # -- tree helpers --------------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        n = self.parent(node)
        while n is not None:
            if isinstance(n, ast.ClassDef):
                return n
            n = self.parent(n)
        return None

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        n = self.parent(node)
        while n is not None:
            if isinstance(n, _FUNC_DEFS + (ast.Lambda,)):
                return n
            if isinstance(n, ast.ClassDef):
                return None
            n = self.parent(n)
        return None

    def qualname(self, fn: ast.AST) -> str:
        if isinstance(fn, ast.Lambda):
            base = "<lambda>"
        else:
            base = fn.name
        cls = self.enclosing_class(fn)
        return f"{cls.name}.{base}" if cls is not None else base

    # -- target / lock resolution -------------------------------------------

    def resolve_callable(self, expr: ast.AST,
                         at: ast.AST) -> Tuple[ast.AST, ...]:
        """Defs/lambdas an expression used as a callable refers to."""
        if isinstance(expr, ast.Name):
            return tuple(self._defs_by_name.get(expr.id, ()))
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            cls = self.enclosing_class(at)
            if cls is not None:
                return tuple(self._methods.get(cls.name, {})
                             .get(expr.attr, ()))
            return ()
        if isinstance(expr, ast.Lambda):
            return (expr,)
        if isinstance(expr, ast.Call):
            name = dotted_name(expr.func)
            if name and name.split(".")[-1] == "partial" and expr.args:
                return self.resolve_callable(expr.args[0], at)
        return ()

    def lock_key(self, expr: ast.AST,
                 at: ast.AST) -> Optional[LockKey]:
        """The canonical key of a lock-valued expression, or None when
        the expression is not recognizably a lock.  Recorded
        constructions match exactly; un-constructed names fall back to
        the naming heuristic (a lock received as a parameter)."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            cls = self.enclosing_class(at)
            key = (cls.name if cls is not None else "", expr.attr)
            if key in self.locks or lockish_name(expr.attr):
                return key
            return None
        name = dotted_name(expr)
        if name and "." not in name:
            key = ("", name)
            if key in self.locks or lockish_name(name):
                return key
        return None

    def lock_kind(self, key: LockKey) -> str:
        site = self.locks.get(key)
        return site.kind if site is not None else "lock"

    def held_locks(self, node: ast.AST) -> List[LockKey]:
        """Lock keys lexically held at ``node`` (enclosing ``with``
        statements whose context expressions are locks), innermost
        last."""
        chain: List[LockKey] = []
        n = self.parent(node)
        child: ast.AST = node
        while n is not None:
            # a node inside the context expression itself (child is the
            # withitem, not a body statement) does not yet hold the lock
            if isinstance(n, (ast.With, ast.AsyncWith)) and \
                    not isinstance(child, ast.withitem):
                for item in n.items:
                    key = self.lock_key(item.context_expr, n)
                    if key is not None:
                        chain.append(key)
            child, n = n, self.parent(n)
        chain.reverse()
        return chain

    def acquisitions(self, fn: ast.AST,
                     transitive: bool = False) -> Set[LockKey]:
        """Lock keys ``fn`` acquires — lexical ``with`` items and
        ``.acquire()`` calls in its own body (nested defs excluded);
        ``transitive`` adds everything reachable through resolvable
        in-module calls."""
        out: Set[LockKey] = set()
        seen: Set[int] = set()
        work = [fn]
        while work:
            cur = work.pop()
            if id(cur) in seen:
                continue
            seen.add(id(cur))
            for node in self._own_body(cur):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        key = self.lock_key(item.context_expr, node)
                        if key is not None:
                            out.add(key)
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "acquire":
                    key = self.lock_key(node.func.value, node)
                    if key is not None:
                        out.add(key)
                elif transitive and isinstance(node, ast.Call):
                    work.extend(self.resolve_callable(node.func, node))
        return out

    # -- thread reachability -------------------------------------------------

    def is_entry(self, fn: ast.AST) -> bool:
        """Is this def/lambda a direct thread target?"""
        return id(fn) in self._entry_ids

    def is_thread_reachable(self, fn: ast.AST) -> bool:
        """Entry, or transitively referenced from one."""
        return id(fn) in self._reachable_ids

    def _own_body(self, fn: ast.AST):
        """Nodes of a def/lambda body, nested function bodies excluded
        (they run on their own call, and propagate on their own turn)."""
        roots = [fn.body] if isinstance(fn, ast.Lambda) else list(fn.body)
        stack = list(roots)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, _FUNC_DEFS + (ast.Lambda,)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _refs(self, fn: ast.AST) -> Tuple[Set[str], Set[str]]:
        """(bare names loaded, attribute names on ``self``) in the
        def's own body."""
        names: Set[str] = set()
        self_attrs: Set[str] = set()
        for node in self._own_body(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                names.add(node.id)
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                self_attrs.add(node.attr)
        return names, self_attrs

    def _propagate(self, entries: Sequence[ast.AST]) -> None:
        self._entry_ids = {id(e) for e in entries}
        work = list(entries)
        while work:
            fn = work.pop()
            if id(fn) in self._reachable_ids:
                continue
            self._reachable_ids.add(id(fn))
            names, self_attrs = self._refs(fn)
            targets: List[ast.AST] = []
            for name in names:
                targets.extend(self._defs_by_name.get(name, ()))
            cls = self.enclosing_class(fn)
            if cls is not None:
                table = self._methods.get(cls.name, {})
                for attr in self_attrs:
                    targets.extend(table.get(attr, ()))
            for t in targets:
                if id(t) not in self._reachable_ids:
                    work.append(t)

    # -- collection ----------------------------------------------------------

    def _collect_locks(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            if not isinstance(value, ast.Call):
                continue
            ctor = dotted_name(value.func).split(".")[-1]
            for t in targets:
                key: Optional[LockKey] = None
                if isinstance(t, ast.Name):
                    key = ("", t.id)
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    cls = self.enclosing_class(node)
                    key = (cls.name if cls is not None else "", t.attr)
                if key is None:
                    continue
                if ctor in LOCK_CTORS:
                    self.locks.setdefault(
                        key, LockSite(key, LOCK_CTORS[ctor], node))
                elif ctor in SAFE_CTORS:
                    self.safe_keys.add(key)

    def _thread_target_expr(self, call: ast.Call,
                            kind: str) -> Optional[ast.AST]:
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
        if kind in ("LoopWorker", "submit") and call.args:
            return call.args[0]
        return None

    def _binding_of(self, call: ast.Call):
        """('attr', class, name) / ('name', '', name) for constructions
        assigned somewhere — following ``.start()`` chains like
        ``self._w = LoopWorker(...).start()`` — else None."""
        node: ast.AST = call
        p = self.parent(node)
        while p is not None and (
                (isinstance(p, ast.Attribute) and p.value is node)
                or (isinstance(p, ast.Call) and p.func is node)):
            node, p = p, self.parent(p)
        if isinstance(p, ast.Assign) and p.value is node:
            t = p.targets[0]
            if isinstance(t, ast.Name):
                return ("name", "", t.id)
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                cls = self.enclosing_class(p)
                return ("attr", cls.name if cls is not None else "", t.attr)
        return None

    def _collect_sites(self) -> None:
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            name = dotted_name(call.func)
            last = name.split(".")[-1] if name else ""
            if name == "signal.signal" and len(call.args) >= 2:
                expr = call.args[1]
                self.handlers.append(HandlerSite(
                    call, _describe(expr),
                    self.resolve_callable(expr, call)))
                continue
            kind = None
            if last == "Thread":
                kind = "Thread"
            elif last == "LoopWorker":
                kind = "LoopWorker"
            elif isinstance(call.func, ast.Attribute) and \
                    call.func.attr == "submit":
                kind = "submit"
            if kind is None:
                continue
            expr = self._thread_target_expr(call, kind)
            if expr is None:
                continue
            targets = self.resolve_callable(expr, call)
            if kind == "submit" and not targets:
                # an unresolvable .submit() is some other API (e.g. a
                # futures executor over imported fns) — recording it
                # would only add noise with zero reachable code
                continue
            daemon: Optional[bool] = None
            for kw in call.keywords:
                if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                    daemon = bool(kw.value.value)
            self.thread_sites.append(ThreadSite(
                kind, call, _describe(expr), targets, daemon,
                self._binding_of(call)))

    # -- export ---------------------------------------------------------------

    def summary(self) -> dict:
        """JSON-ready per-module summary (threads discovered, locks,
        entry-point mapping, signal handlers) — the ``--format json``
        thread_model section the doctor and elasticity work consume."""
        threads = []
        for s in self.thread_sites:
            threads.append({
                "kind": s.kind, "line": s.node.lineno,
                "target": s.target_desc,
                "resolved": sorted(self.qualname(t) for t in s.targets),
                "daemon": s.daemon,
                "bound_to": (f"self.{s.binding[2]}"
                             if s.binding and s.binding[0] == "attr"
                             else s.binding[2] if s.binding else None),
            })
        locks = [{"name": key[1], "class": key[0] or None,
                  "kind": site.kind, "line": site.node.lineno}
                 for key, site in sorted(self.locks.items())]
        handlers = [{"line": h.node.lineno, "handler": h.target_desc,
                     "resolved": sorted(self.qualname(t)
                                        for t in h.targets)}
                    for h in self.handlers]
        reachable = sorted({self.qualname(t) for t in ast.walk(self.tree)
                            if isinstance(t, _FUNC_DEFS)
                            and self.is_thread_reachable(t)})
        return {"threads": threads, "locks": locks,
                "signal_handlers": handlers,
                "thread_reachable": reachable}


def summarize_paths(paths: Sequence[str], root: str = ".") -> dict:
    """Aggregate thread-model summaries over ``paths`` (python files) —
    files without threads/locks/handlers are elided to keep the
    artifact small; unparseable files are skipped (the lint run itself
    reports the parse error)."""
    import os

    files = []
    totals = {"threads": 0, "locks": 0, "signal_handlers": 0}
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            continue
        model = ThreadModel(tree)
        s = model.summary()
        if not (s["threads"] or s["locks"] or s["signal_handlers"]):
            continue
        try:
            rel = os.path.relpath(os.path.abspath(path),
                                  os.path.abspath(root))
        except ValueError:
            rel = path
        files.append({"path": rel.replace(os.sep, "/"), **s})
        totals["threads"] += len(s["threads"])
        totals["locks"] += len(s["locks"])
        totals["signal_handlers"] += len(s["signal_handlers"])
    return {"files": files,
            "totals": {**totals, "files_with_threads": len(files)}}
