"""thread-lifecycle: every started thread must be joinable on teardown.

The ``finally``-drain discipline PRs 12/13 hand-enforced, checked:

* a thread bound to ``self._x`` must have ``self._x.join(…)`` /
  ``.close(…)`` / ``.wait(…)`` somewhere in its class (aliases like
  ``t = self._x; t.join(…)`` resolve) — otherwise shutdown abandons it
  mid-write;
* a thread bound to a local name must be joined inside a ``finally``
  (or used as a context manager) in the same function — a join on the
  happy path only leaks the thread on every exception exit;
* a fire-and-forget construction (``Thread(...).start()`` with no
  binding) must be ``daemon=True`` — a non-daemon orphan blocks
  interpreter exit forever;
* a daemon thread whose (resolved) target opens external resources —
  ``open``/``tempfile.*``/``socket.socket`` in its direct body — is
  flagged: daemons are killed mid-operation at interpreter exit,
  leaking fds and half-written files.

``.submit()`` dispatches are exempt: the executor object owns the
thread, and its own ``Thread`` construction is checked where the
executor class is defined (``SingleSlotWriter`` passes via the
``t = self._thread; t.join()`` alias path).
"""

from __future__ import annotations

import ast
from typing import Dict, Optional, Set

from gansformer_tpu.analysis.engine import FileContext, Rule, register
from gansformer_tpu.analysis.jit_regions import dotted_name

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_JOINERS = {"join", "close", "wait"}
_RESOURCE_CALLS = {"open", "socket.socket", "mkdtemp", "mkstemp",
                   "NamedTemporaryFile", "TemporaryDirectory"}


@register
class ThreadLifecycle(Rule):
    id = "thread-lifecycle"
    description = ("started thread without a join/close on the teardown "
                   "path, non-daemon fire-and-forget, or a daemon "
                   "owning fds/tempdirs")
    hint = ("bind the thread and join it in close()/a finally block; "
            "fire-and-forget threads must be daemon=True and must not "
            "own external resources")
    node_types = (ast.Module,)

    def check(self, node: ast.Module, ctx: FileContext) -> None:
        tm = ctx.threads
        for site in tm.thread_sites:
            if site.kind == "submit":
                continue
            if site.binding is None:
                if site.daemon is not True:
                    ctx.report(
                        self, site.node,
                        "fire-and-forget thread without daemon=True — "
                        "an orphaned non-daemon thread blocks "
                        "interpreter exit forever")
            elif site.binding[0] == "attr":
                cls = tm.enclosing_class(site.node)
                if cls is not None and not self._attr_joined(
                        cls, site.binding[2]):
                    ctx.report(
                        self, site.node,
                        f"thread bound to self.{site.binding[2]} is "
                        f"never joined/closed in {cls.name} — teardown "
                        f"abandons it mid-write")
            else:
                self._check_local(site, ctx, tm)
            if site.daemon:
                self._check_daemon_resources(site, ctx, tm)

    # -- self-attribute bindings ---------------------------------------------

    @staticmethod
    def _attr_joined(cls: ast.ClassDef, attr: str) -> bool:
        aliases: Dict[str, str] = {}
        for n in ast.walk(cls):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name) and \
                    isinstance(n.value, ast.Attribute) and \
                    isinstance(n.value.value, ast.Name) and \
                    n.value.value.id == "self":
                aliases[n.targets[0].id] = n.value.attr
        for n in ast.walk(cls):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _JOINERS):
                continue
            recv = n.func.value
            if isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self" and recv.attr == attr:
                return True
            if isinstance(recv, ast.Name) and \
                    aliases.get(recv.id) == attr:
                return True
        return False

    # -- local-name bindings -------------------------------------------------

    def _check_local(self, site, ctx: FileContext, tm) -> None:
        name = site.binding[2]
        fn = tm.enclosing_function(site.node)
        scope = fn if fn is not None else ctx.tree
        join_call: Optional[ast.AST] = None
        for n in ast.walk(scope):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in _JOINERS and \
                    isinstance(n.func.value, ast.Name) and \
                    n.func.value.id == name:
                join_call = n
                break
            if isinstance(n, (ast.With, ast.AsyncWith)) and any(
                    isinstance(i.context_expr, ast.Name)
                    and i.context_expr.id == name for i in n.items):
                return   # context-managed: __exit__ is the join
        if join_call is None:
            ctx.report(
                self, site.node,
                f"thread bound to {name!r} is never joined in its "
                f"scope — every exit path leaks the thread")
        elif not self._in_finally(join_call, tm):
            ctx.report(
                self, site.node,
                f"thread {name!r} is joined only on the happy path — "
                f"move the join into a finally block so exception "
                f"exits drain it too")

    @staticmethod
    def _in_finally(node: ast.AST, tm) -> bool:
        child, n = node, tm.parent(node)
        while n is not None:
            if isinstance(n, ast.Try) and any(
                    child is s or any(child is d for d in ast.walk(s))
                    for s in n.finalbody):
                return True
            child, n = n, tm.parent(n)
        return False

    # -- daemon resource ownership -------------------------------------------

    def _check_daemon_resources(self, site, ctx: FileContext, tm) -> None:
        seen: Set[str] = set()
        for target in site.targets:
            for n in tm._own_body(target):
                if not isinstance(n, ast.Call):
                    continue
                name = dotted_name(n.func)
                last = name.split(".")[-1] if name else ""
                if name in _RESOURCE_CALLS or last in _RESOURCE_CALLS or \
                        (name or "").startswith("tempfile."):
                    what = name or last
                    if what in seen:
                        continue
                    seen.add(what)
                    ctx.report(
                        self, n,
                        f"daemon thread target "
                        f"{tm.qualname(target)!r} owns an external "
                        f"resource via {what}() — daemons die "
                        f"mid-operation at interpreter exit, leaking "
                        f"fds / half-written files")
