"""unguarded-shared-attribute: cross-thread state without a common lock.

Subsumes (and retires) the old module-literal-only ``thread-shared-state``
rule.  Two families of findings, both scoped to modules that actually
construct threads (``ctx.threads``):

* **module-level mutables** — the legacy behaviour, now with transitive
  thread-reachability: a module dict/list/set mutated from any function
  reachable from a thread entry point without a lock held;
* **instance attributes** — inside a class that constructs threads or
  has thread-reachable methods, an attribute with inconsistent lock
  discipline: an unlocked read-modify-write (``self.x += 1``,
  ``self.d[k] = v``, ``self.l.append(…)``) of an attribute shared
  across functions, or an unlocked write to an attribute that is
  lock-guarded elsewhere (the torn-publish shape: ``_pop_batch`` writes
  ``_busy_since`` under ``_cv`` while the supervisor clears it bare).

Sanctioned idioms (never flagged — the allowlist the hint points at):

* **single-writer publish / monotonic flag** — a plain ``self.x = v``
  with no read-modify-write and no locked access anywhere
  (``self._error = e`` from a producer thread, ``self._finished =
  True``): one atomic store, readers tolerate staleness by design;
* **unlocked reads** — racy reads of monotonic state are the reader's
  explicit choice; flagging them would bury the writes that tear;
* **thread-safe primitives** — attributes holding ``Event`` / ``Queue``
  / locks themselves;
* **``__init__`` stores** — construction happens-before ``start()``.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set

from gansformer_tpu.analysis.engine import FileContext, Rule, register

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)
_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "remove", "discard", "clear", "appendleft",
             "popleft"}


def _module_mutables(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for st in tree.body:
        if isinstance(st, ast.Assign) and \
                isinstance(st.value, _MUTABLE_LITERALS):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(st, ast.AnnAssign) and st.value is not None and \
                isinstance(st.value, _MUTABLE_LITERALS) and \
                isinstance(st.target, ast.Name):
            out.add(st.target.id)
    return out


def _is_self_attr(expr: ast.AST) -> bool:
    return (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self")


def _reads_attr(expr: ast.AST, attr: str) -> bool:
    return any(_is_self_attr(n) and n.attr == attr
               and isinstance(n.ctx, ast.Load)
               for n in ast.walk(expr))


@dataclasses.dataclass
class _Access:
    attr: str
    kind: str            # "read" | "write" | "rmw" | "mutate"
    node: ast.AST
    owner: Optional[ast.AST]
    locked: bool
    in_init: bool


@register
class UnguardedSharedAttribute(Rule):
    id = "unguarded-shared-attribute"
    aliases = ("thread-shared-state",)
    description = ("state shared across threads written without the lock "
                   "that guards it elsewhere (absorbs thread-shared-state)")
    hint = ("guard the write with the lock the other accesses hold "
            "(with self._lock: …); a plain single-writer publish of an "
            "immutable value is sanctioned — read-modify-writes and "
            "container mutations are not")
    node_types = (ast.Module,)

    def check(self, node: ast.Module, ctx: FileContext) -> None:
        tm = ctx.threads
        if not tm.thread_sites:
            return
        self._check_module_mutables(node, ctx, tm)
        for cls in ast.walk(node):
            if isinstance(cls, ast.ClassDef):
                self._check_class(cls, ctx, tm)

    # -- module-level mutables (legacy thread-shared-state scope) -----------

    def _check_module_mutables(self, tree: ast.Module, ctx: FileContext,
                               tm) -> None:
        mutables = _module_mutables(tree)
        if not mutables:
            return
        for fn in ast.walk(tree):
            if not isinstance(fn, _FUNC_DEFS + (ast.Lambda,)):
                continue
            if not tm.is_thread_reachable(fn):
                continue
            for node in tm._own_body(fn):
                if tm.held_locks(node):
                    continue
                self._check_global_stmt(node, mutables, fn, ctx)

    def _check_global_stmt(self, node: ast.AST, mutables: Set[str],
                           fn: ast.AST, ctx: FileContext) -> None:
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in mutables:
                    ctx.report(
                        self, node,
                        f"module-level mutable {t.value.id!r} written "
                        f"from thread-reachable code without holding "
                        f"a lock")
                elif isinstance(t, ast.Name) and t.id in mutables and \
                        self._declared_global(fn, t.id):
                    ctx.report(
                        self, node,
                        f"module-level mutable {t.id!r} rebound from "
                        f"thread-reachable code without holding a lock")
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in mutables:
            ctx.report(
                self, node,
                f"module-level mutable {node.func.value.id!r}."
                f"{node.func.attr}() from thread-reachable code without "
                f"holding a lock")

    @staticmethod
    def _declared_global(fn: ast.AST, name: str) -> bool:
        if isinstance(fn, ast.Lambda):
            return False
        return any(isinstance(s, ast.Global) and name in s.names
                   for s in ast.walk(fn))

    # -- instance attributes -------------------------------------------------

    def _check_class(self, cls: ast.ClassDef, ctx: FileContext,
                     tm) -> None:
        in_scope = any(
            tm.enclosing_class(site.node) is cls
            for site in tm.thread_sites) or any(
            isinstance(m, _FUNC_DEFS) and tm.is_thread_reachable(m)
            for m in cls.body)
        if not in_scope:
            return
        accesses = self._collect_accesses(cls, tm)
        by_attr: Dict[str, List[_Access]] = {}
        for a in accesses:
            by_attr.setdefault(a.attr, []).append(a)
        for attr, accs in sorted(by_attr.items()):
            key = (cls.name, attr)
            if key in tm.locks or key in tm.safe_keys:
                continue     # locks/Events/Queues are their own guards
            live = [a for a in accs if not a.in_init]
            owners = {id(a.owner) for a in live if a.owner is not None}
            shared = (len(owners) >= 2 and any(
                a.owner is not None and tm.is_thread_reachable(a.owner)
                for a in live))
            has_locked = any(a.locked for a in accs)
            for a in live:
                if a.locked or a.kind == "read":
                    continue
                if a.kind in ("rmw", "mutate") and shared:
                    what = ("read-modify-write of"
                            if a.kind == "rmw" else "mutation of")
                    ctx.report(
                        self, a.node,
                        f"unlocked {what} shared attribute "
                        f"'self.{attr}' in {cls.name} — compound "
                        f"updates tear across threads")
                elif has_locked:
                    ctx.report(
                        self, a.node,
                        f"unlocked write to 'self.{attr}' in "
                        f"{cls.name}, which is lock-guarded elsewhere "
                        f"— inconsistent discipline hides a torn "
                        f"publish")

    def _collect_accesses(self, cls: ast.ClassDef, tm) -> List[_Access]:
        out: List[_Access] = []

        def add(attr: str, kind: str, node: ast.AST) -> None:
            owner = self._owner(node, tm)
            if owner is None or tm.enclosing_class(owner) is not cls:
                return   # class-body defaults / an inner class's code
            in_init = (isinstance(owner, _FUNC_DEFS)
                       and owner.name == "__init__"
                       and not tm.is_entry(owner))
            out.append(_Access(attr, kind, node, owner,
                               bool(tm.held_locks(node)), in_init))

        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                for t in self._flat_targets(node.targets):
                    if _is_self_attr(t):
                        kind = "rmw" if _reads_attr(node.value, t.attr) \
                            else "write"
                        add(t.attr, kind, node)
                    elif isinstance(t, ast.Subscript) and \
                            _is_self_attr(t.value):
                        add(t.value.attr, "mutate", node)
            elif isinstance(node, ast.AugAssign):
                if _is_self_attr(node.target):
                    add(node.target.attr, "rmw", node)
                elif isinstance(node.target, ast.Subscript) and \
                        _is_self_attr(node.target.value):
                    add(node.target.value.attr, "mutate", node)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS and \
                    _is_self_attr(node.func.value):
                add(node.func.value.attr, "mutate", node)
            elif _is_self_attr(node) and isinstance(node.ctx, ast.Load):
                add(node.attr, "read", node)
        return out

    @staticmethod
    def _flat_targets(targets: List[ast.AST]) -> List[ast.AST]:
        out: List[ast.AST] = []
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                out.extend(t.elts)
            else:
                out.append(t)
        return out

    @staticmethod
    def _owner(node: ast.AST, tm) -> Optional[ast.AST]:
        """The function whose execution context an access runs in:
        nested helpers collapse into their enclosing method (they are
        called synchronously) — unless the nested function is itself a
        thread entry (a ``_produce`` closure target), which anchors its
        own context."""
        fn = tm.enclosing_function(node)
        if fn is None:
            return None
        while not tm.is_entry(fn):
            up = tm.enclosing_function(fn)
            if up is None:
                break
            fn = up
        return fn
