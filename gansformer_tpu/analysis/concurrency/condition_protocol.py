"""condition-protocol: Condition.wait/notify used off-protocol.

``threading.Condition`` has exactly one correct shape::

    with cv:                      # notify side
        state_change()
        cv.notify_all()

    with cv:                      # wait side
        while not predicate():    # re-check: spurious + missed wakeups
            cv.wait(timeout)

Flagged, for objects the resolver saw constructed as
``threading.Condition()`` (an ``Event.wait`` or ``Thread.join`` never
matches):

* ``cv.wait(…)`` not lexically inside ``with cv:`` — waiting without
  the lock raises at runtime only on the unlucky interleaving;
* ``cv.wait(…)`` with no enclosing ``while`` between it and the
  ``with`` — an ``if``-guarded (or unguarded) wait misses wakeups that
  land before the wait and trusts every spurious wakeup
  (``wait_for`` is exempt: the predicate loop is built in);
* ``cv.notify()`` / ``notify_all()`` outside ``with cv:`` — legal-ish
  in CPython but a lost-wakeup race against the waiter's predicate
  check.
"""

from __future__ import annotations

import ast

from gansformer_tpu.analysis.engine import FileContext, Rule, register

_WAITS = {"wait", "wait_for"}
_NOTIFIES = {"notify", "notify_all"}


@register
class ConditionProtocol(Rule):
    id = "condition-protocol"
    description = ("Condition.wait outside a while-predicate loop / "
                   "with-block, or notify outside the owning lock")
    hint = ("wrap: `with cv:` + `while not predicate(): cv.wait()`; "
            "notify under the same `with cv:` that changed the "
            "predicate state")
    node_types = (ast.Module,)

    def check(self, node: ast.Module, ctx: FileContext) -> None:
        tm = ctx.threads
        conditions = {k for k, site in tm.locks.items()
                      if site.kind == "condition"}
        if not conditions:
            return
        for call in ast.walk(node):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in (_WAITS | _NOTIFIES)):
                continue
            key = tm.lock_key(call.func.value, call)
            if key is None or key not in conditions:
                continue
            in_with, in_while = self._context(call, key, tm)
            name = key[1]
            if call.func.attr in _NOTIFIES:
                if not in_with:
                    ctx.report(
                        self, call,
                        f"{name}.{call.func.attr}() outside `with "
                        f"{name}:` — racing the waiter's predicate "
                        f"check loses wakeups")
            else:
                if not in_with:
                    ctx.report(
                        self, call,
                        f"{name}.wait() outside `with {name}:` — "
                        f"Condition.wait requires the lock held")
                elif call.func.attr == "wait" and not in_while:
                    ctx.report(
                        self, call,
                        f"{name}.wait() not inside a while-predicate "
                        f"loop — spurious and early wakeups break an "
                        f"if-guarded wait; loop on the predicate (or "
                        f"use wait_for)")

    @staticmethod
    def _context(call: ast.Call, key, tm):
        """(inside `with key:`, a While sits between wait and the with)."""
        in_while = False
        n = tm.parent(call)
        while n is not None:
            if isinstance(n, ast.While):
                in_while = True
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    if tm.lock_key(item.context_expr, n) == key:
                        return True, in_while
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                break   # the lock cannot be lexically held across defs
            n = tm.parent(n)
        return False, in_while
