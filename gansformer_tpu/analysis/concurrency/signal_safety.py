"""signal-handler-safety: handlers must stay async-signal-safe-ish.

CPython runs signal handlers on the MAIN thread at an arbitrary
bytecode boundary — possibly while that very thread holds a lock the
handler wants (the non-reentrant deadlock ``GenerationService``
documents in ``install_signal_drain``), possibly mid-allocation.  So a
function registered via ``signal.signal`` (directly, or inside an
installer like ``install_signal_drain``) must limit itself to the
sanctioned idiom: set a flag, ``os.write``/``os.kill``, poke a
subprocess, or hand the real work to a separate thread
(``threading.Thread(target=…).start()`` — the drain-thread pattern).

Flagged inside a resolved handler (transitively through in-module
calls; thread *targets* constructed by the handler are exempt — they
run elsewhere, which is the point):

* acquiring any lock (``with lock:`` / ``.acquire()``);
* calling into jax (``jax.*``/``jnp.*`` — allocation, device sync);
* non-reentrant / blocking IO: ``print``, ``open``, ``logging.*``,
  ``time.sleep``, and blocking ``.join(…)``/``.wait(…)`` calls.

Handlers that cannot be resolved (a name imported from elsewhere, the
restore path ``signal.signal(sig, old_handler)``) are skipped — the
rule checks definitions it can see, the resolver summary records the
registration either way.
"""

from __future__ import annotations

import ast
from typing import Set

from gansformer_tpu.analysis.engine import FileContext, Rule, register
from gansformer_tpu.analysis.jit_regions import dotted_name

_BLOCKING = {"print", "open", "time.sleep"}
_BLOCKING_ATTRS = {"join", "wait"}


@register
class SignalHandlerSafety(Rule):
    id = "signal-handler-safety"
    description = ("signal handler acquires a lock, calls into jax, or "
                   "performs non-reentrant IO")
    hint = ("a handler may only set flags, os.write/os.kill, poke a "
            "subprocess, or defer to a thread "
            "(threading.Thread(target=…).start()) — it interrupts the "
            "main thread at an arbitrary bytecode boundary, possibly "
            "while a lock it wants is already held")
    node_types = (ast.Module,)

    def check(self, node: ast.Module, ctx: FileContext) -> None:
        tm = ctx.threads
        for handler in tm.handlers:
            for target in handler.targets:
                self._scan(target, handler.target_desc, ctx, tm)

    def _scan(self, root: ast.AST, hname: str, ctx: FileContext,
              tm) -> None:
        seen: Set[int] = set()
        work = [root]
        while work:
            fn = work.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            for n in tm._own_body(fn):
                if isinstance(n, (ast.With, ast.AsyncWith)):
                    for item in n.items:
                        key = tm.lock_key(item.context_expr, n)
                        if key is not None:
                            ctx.report(
                                self, n,
                                f"signal handler {hname!r} acquires "
                                f"lock {key[1]!r} — the interrupted "
                                f"main thread may already hold it "
                                f"(non-reentrant deadlock)")
                elif isinstance(n, ast.Call):
                    self._check_call(n, hname, ctx, tm)
                    # follow in-module callees: the violation may hide
                    # one helper down (thread targets are ARGS, not
                    # Call.func — never followed, by construction)
                    work.extend(tm.resolve_callable(n.func, n))

    def _check_call(self, call: ast.Call, hname: str, ctx: FileContext,
                    tm) -> None:
        if isinstance(call.func, ast.Attribute):
            if call.func.attr == "acquire":
                key = tm.lock_key(call.func.value, call)
                if key is not None:
                    ctx.report(
                        self, call,
                        f"signal handler {hname!r} acquires lock "
                        f"{key[1]!r} — the interrupted main thread may "
                        f"already hold it (non-reentrant deadlock)")
                    return
            if call.func.attr in _BLOCKING_ATTRS:
                name = dotted_name(call.func.value) or "<expr>"
                ctx.report(
                    self, call,
                    f"signal handler {hname!r} blocks on "
                    f"{name}.{call.func.attr}() — a handler must "
                    f"return promptly; defer the wait to a drain "
                    f"thread")
                return
        name = dotted_name(call.func)
        if not name:
            return
        root = name.split(".")[0]
        if root in ("jax", "jnp"):
            ctx.report(
                self, call,
                f"signal handler {hname!r} calls {name}() — jax "
                f"allocation/dispatch inside a handler can deadlock "
                f"the runtime it interrupted")
        elif name in _BLOCKING or root == "logging":
            ctx.report(
                self, call,
                f"signal handler {hname!r} performs non-reentrant IO "
                f"via {name}() — use os.write or set a flag and "
                f"handle it on the main loop")
