"""lock-order-inversion: cyclic lock acquisition orders.

Builds the per-module lock-ordering graph: an edge A→B whenever B is
acquired while A is held — lexically (``with a: … with b:``) or through
a call made under A to an in-module function that (transitively)
acquires B.  A cycle in that graph is the classic ABBA deadlock shape:
two threads entering from opposite ends block forever, and nothing
short of production load exercises both interleavings.

A self-edge A→A (re-acquiring a lock already held, via a helper called
under the lock) is reported too unless the lock is an ``RLock`` —
``threading.Lock`` and ``Condition`` are non-reentrant, so the "cycle"
is a single-thread self-deadlock, the service/supervisor-vs-dispatcher
shape the serving stack dodges by calling ticket callbacks outside
``_cv``.

Lock identity is the resolver's ``(class, attr)`` key — per-instance
locks of one class collapse together, which over-approximates exactly
the way a lock-ORDER discipline should: order must hold per lock
*role*, not per object.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from gansformer_tpu.analysis.engine import FileContext, Rule, register
from gansformer_tpu.analysis.concurrency.thread_model import (
    REENTRANT_KINDS, LockKey)


def _fmt(key: LockKey) -> str:
    cls, name = key
    return f"{cls}.{name}" if cls else name


@register
class LockOrderInversion(Rule):
    id = "lock-order-inversion"
    description = ("cyclic lock-acquisition order (ABBA deadlock) or "
                   "re-acquisition of a non-reentrant lock")
    hint = ("acquire locks in one global order everywhere, or narrow "
            "the outer critical section so the call happens after "
            "release (the serve stack resolves tickets OUTSIDE _cv "
            "for exactly this reason)")
    node_types = (ast.Module,)

    def check(self, node: ast.Module, ctx: FileContext) -> None:
        tm = ctx.threads
        if not tm.locks and not tm.thread_sites:
            return
        edges: Dict[Tuple[LockKey, LockKey], ast.AST] = {}

        def add_edge(a: LockKey, b: LockKey, site: ast.AST) -> None:
            if a == b and tm.lock_kind(a) in REENTRANT_KINDS:
                return
            edges.setdefault((a, b), site)

        for n in ast.walk(node):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                held = tm.held_locks(n)
                for item in n.items:
                    key = tm.lock_key(item.context_expr, n)
                    if key is None:
                        continue
                    for a in held:
                        add_edge(a, key, n)
            elif isinstance(n, ast.Call):
                if isinstance(n.func, ast.Attribute) and \
                        n.func.attr == "acquire":
                    key = tm.lock_key(n.func.value, n)
                    if key is not None:
                        for a in tm.held_locks(n):
                            add_edge(a, key, n)
                    continue
                held = tm.held_locks(n)
                if not held:
                    continue
                callees = tm.resolve_callable(n.func, n)
                for callee in callees:
                    for b in tm.acquisitions(callee, transitive=True):
                        for a in held:
                            add_edge(a, b, n)

        # self-edges are immediate single-thread deadlocks
        for (a, b), site in sorted(
                edges.items(), key=lambda kv: kv[1].lineno):
            if a == b:
                ctx.report(
                    self, site,
                    f"non-reentrant lock {_fmt(a)!r} re-acquired while "
                    f"already held (single-thread self-deadlock)")

        adj: Dict[LockKey, Set[LockKey]] = {}
        for (a, b) in edges:
            if a != b:
                adj.setdefault(a, set()).add(b)
        for (a, b), site in sorted(
                edges.items(), key=lambda kv: kv[1].lineno):
            if a != b and self._reaches(adj, b, a):
                ctx.report(
                    self, site,
                    f"lock-order inversion: {_fmt(b)!r} acquired while "
                    f"holding {_fmt(a)!r}, but the reverse order exists "
                    f"elsewhere in this module (ABBA deadlock)")

    @staticmethod
    def _reaches(adj: Dict[LockKey, Set[LockKey]],
                 src: LockKey, dst: LockKey) -> bool:
        seen: Set[LockKey] = set()
        work: List[LockKey] = [src]
        while work:
            cur = work.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            work.extend(adj.get(cur, ()))
        return False
