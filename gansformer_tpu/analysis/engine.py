"""Rule registry + visitor driver: ONE ast walk per file.

A ``Rule`` subscribes to ast node types; the driver parses each file
once, builds parent links, and dispatches every node (in source order)
to the rules subscribed to its type.  Whole-function/whole-module rules
simply subscribe to ``ast.FunctionDef`` / ``ast.Module`` and walk their
own subtree — the engine guarantees each node is offered exactly once
per rule, so a rule never double-reports.

The per-file ``FileContext`` carries everything rules share: source
lines, parent links, the lazily-built jit-region index
(``jit_regions.py``), and ``report()`` — which applies inline
suppressions (``# graftlint: disable=<rule>``) and de-duplicates.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Type

from gansformer_tpu.analysis.findings import Finding

_RULE_LIST = r"([A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*)"
_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=" + _RULE_LIST)
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*graftlint:\s*disable-file=" + _RULE_LIST)


class Rule:
    """Base class.  Subclasses set ``id``/``description``/``hint`` and
    ``node_types`` (the ast classes they subscribe to), and implement
    ``check(node, ctx)`` calling ``ctx.report(self, node, message)``.
    ``aliases`` lists RETIRED ids this rule subsumes: old
    ``# graftlint: disable=`` comments, baseline keys, and
    ``--select``/``--ignore`` spellings keep working through them."""

    id: str = ""
    description: str = ""
    hint: str = ""
    node_types: Sequence[type] = ()
    aliases: Sequence[str] = ()

    def check(self, node: ast.AST, ctx: "FileContext") -> None:
        raise NotImplementedError


_REGISTRY: Dict[str, Type[Rule]] = {}
_ALIASES: Dict[str, str] = {}     # retired id -> current rule id


def register(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator adding a rule to the global registry."""
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if _REGISTRY.get(cls.id, cls) is not cls:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    for alias in cls.aliases:
        if alias in _REGISTRY or _ALIASES.get(alias, cls.id) != cls.id:
            raise ValueError(f"alias {alias!r} of {cls.id!r} collides "
                             f"with an existing rule id/alias")
        _ALIASES[alias] = cls.id
    _REGISTRY[cls.id] = cls
    return cls


def _import_rule_packages() -> None:
    import gansformer_tpu.analysis.concurrency  # noqa: F401  (registers)
    import gansformer_tpu.analysis.numerics  # noqa: F401  (registers)
    import gansformer_tpu.analysis.rules  # noqa: F401  (registers)


def all_rules() -> List[Type[Rule]]:
    """Every registered rule class, importing the bundled rule sets on
    first use (rules register at import time)."""
    _import_rule_packages()
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(rule_id: str) -> Type[Rule]:
    """Look up a rule by id — retired aliases resolve to their
    successor (``thread-shared-state`` → unguarded-shared-attribute)."""
    _import_rule_packages()
    return _REGISTRY[_ALIASES.get(rule_id, rule_id)]


def rule_aliases() -> Dict[str, str]:
    """{retired id: current id} for every registered alias."""
    _import_rule_packages()
    return dict(_ALIASES)


def legacy_ids(rule_id: str) -> List[str]:
    """Retired ids that now map to ``rule_id`` (for baseline-key
    compatibility: an old baseline entry keyed by the retired id still
    absolves the successor rule's finding on the same line)."""
    return sorted(a for a, cur in _ALIASES.items() if cur == rule_id)


def _parse_suppressions(lines: Sequence[str]):
    """(per-line {lineno: set(rule ids)}, file-level set).  'all' means
    every rule.  Comment-shaped text inside string literals can false-
    positive here; that costs an unnecessary suppression, never a missed
    finding on another line."""
    per_line: Dict[int, Set[str]] = {}
    whole_file: Set[str] = set()
    for i, text in enumerate(lines, 1):
        m = _SUPPRESS_FILE_RE.search(text)
        if m:
            whole_file |= {r.strip() for r in m.group(1).split(",") if r.strip()}
        m = _SUPPRESS_RE.search(text)
        if m:
            per_line[i] = {r.strip() for r in m.group(1).split(",")
                           if r.strip()}
    return per_line, whole_file


class FileContext:
    """Everything the rules share while one file is being checked."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.findings: List[Finding] = []
        self._suppress, self._suppress_file = _parse_suppressions(self.lines)
        self._parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[id(child)] = parent
        self._jit = None
        self._threads = None
        self._seen: Set[tuple] = set()

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    @property
    def jit(self):
        """Lazily-built jit-region index (shared across rules)."""
        if self._jit is None:
            from gansformer_tpu.analysis.jit_regions import JitIndex

            self._jit = JitIndex(self.tree)
        return self._jit

    @property
    def threads(self):
        """Lazily-built thread-model index (shared across the
        concurrency rules — analysis/concurrency/thread_model.py)."""
        if self._threads is None:
            from gansformer_tpu.analysis.concurrency.thread_model import (
                ThreadModel)

            self._threads = ThreadModel(self.tree)
        return self._threads

    def line_text(self, line: int) -> str:
        return self.lines[line - 1] if 0 < line <= len(self.lines) else ""

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        on_line = self._suppress.get(line, ())
        return (rule_id in on_line or "all" in on_line
                or rule_id in self._suppress_file
                or "all" in self._suppress_file)

    def report(self, rule: Rule, node, message: str,
               hint: Optional[str] = None) -> Optional[Finding]:
        """File a finding at ``node`` (an ast node, or an (line, col)
        pair for non-AST locations).  Returns None on duplicates."""
        if isinstance(node, tuple):
            line, col = node
        else:
            line, col = node.lineno, node.col_offset
        key = (rule.id, line, col, message)
        if key in self._seen:
            return None
        self._seen.add(key)
        suppressed = any(self.is_suppressed(rid, line)
                         for rid in (rule.id, *rule.aliases))
        f = Finding(rule=rule.id, path=self.path, line=line, col=col,
                    message=message,
                    hint=rule.hint if hint is None else hint,
                    suppressed=suppressed)
        self.findings.append(f)
        return f


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Iterable[Type[Rule]]] = None) -> List[Finding]:
    """Run ``rules`` (default: all registered) over one source string."""
    rule_classes = list(rules) if rules is not None else all_rules()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(rule="parse-error", path=path, line=e.lineno or 0,
                        col=e.offset or 0, message=f"syntax error: {e.msg}")]
    ctx = FileContext(path, source, tree)
    instances = [cls() for cls in rule_classes]
    # subscription table: ast type -> rules wanting it
    by_type: Dict[type, List[Rule]] = {}
    for r in instances:
        for t in r.node_types:
            by_type.setdefault(t, []).append(r)
    for node in ast.walk(tree):
        for r in by_type.get(type(node), ()):
            r.check(node, ctx)
    ctx.findings.sort(key=Finding.sort_key)
    return ctx.findings


def lint_file(path: str,
              rules: Optional[Iterable[Type[Rule]]] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path=path, rules=rules)


def iter_python_files(paths: Iterable[str]) -> List[str]:
    """Expand files/dirs into a sorted, de-duplicated list of .py files
    (skipping __pycache__ and dot-directories) — deterministic order so
    reports and baselines are stable."""
    out: Set[str] = set()
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for name in files:
                    if name.endswith(".py"):
                        out.add(os.path.join(root, name))
        elif p.endswith(".py"):
            out.add(p)
    return sorted(out)


def lint_paths(paths: Iterable[str],
               rules: Optional[Iterable[Type[Rule]]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(lint_file(path, rules=rules))
    return findings
