"""Shared jit-region resolver.

Answers, per module, the question several rules need: *which function
definitions execute under a JAX trace* (``jax.jit`` / ``pjit`` /
``shard_map``), reached via

* decorator — ``@jax.jit``, ``@jit``, ``@functools.partial(jax.jit, …)``,
  ``@shard_map(…)``;
* call wrap — ``f2 = jax.jit(f)`` or any ``jax.jit(f, …)`` appearing as
  an expression (e.g. field values in a dataclass constructor);
* partial — ``jax.jit(functools.partial(f, flag=True), …)``;
* lambda wrap — ``step = jax.jit(lambda s, b: _step(s, b))``: the lambda
  body runs under the trace, so every function it references by name is
  seeded into the region (the lambda itself has no def to mark).

Membership then propagates transitively: a function *referenced by
name* from an in-region function is in the region too — plain calls,
and references passed to higher-order tracers (``jax.lax.scan``,
``value_and_grad``, …) alike.  Name→def resolution is by bare name
module-wide (an over-approximation; precision costs nothing here since
a false in-region marking only matters if the function also contains a
host sync, which an inline suppression can then document).

Also collected while walking: **donation info** — names bound to
``jax.jit(..., donate_argnums=…)`` results and decorated defs with
donated parameters, consumed by the donation-after-use rule.  A
``**kwargs`` splat is resolved one level through module/function-scope
``name = dict(donate_argnums=…)`` assignments (the idiom train/steps.py
uses).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

_JIT_NAMES = {"jit", "pjit", "shard_map"}
_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def dotted_name(expr: ast.AST) -> str:
    """'jax.jit' for Attribute/Name chains; '' when not a plain chain."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return ""


def is_jit_wrapper(expr: ast.AST) -> bool:
    """Does this expression name jit/pjit/shard_map?"""
    name = dotted_name(expr)
    return bool(name) and name.split(".")[-1] in _JIT_NAMES


def _is_partial(expr: ast.AST) -> bool:
    name = dotted_name(expr)
    return bool(name) and name.split(".")[-1] == "partial"


def _donate_positions(v: ast.AST) -> Tuple[int, ...]:
    if isinstance(v, ast.Constant) and isinstance(v.value, int):
        return (v.value,)
    if isinstance(v, (ast.Tuple, ast.List)):
        out = []
        for e in v.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


class JitIndex:
    """Per-module jit-region + donation index (built once, shared)."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self._defs_by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, _FUNC_DEFS):
                self._defs_by_name.setdefault(node.name, []).append(node)
        # name -> donated call-site positions for calls to that name
        self.donating: Dict[str, Tuple[int, ...]] = {}
        self._dict_kwargs: Dict[str, Tuple[int, ...]] = {}
        self._collect_dict_kwargs()
        seeds = self._collect_seeds()
        self._region_ids: Set[int] = set()
        self._propagate(seeds)

    # -- queries -------------------------------------------------------------

    def is_jit(self, func_def: ast.AST) -> bool:
        """Is this FunctionDef (transitively) inside a jit region?"""
        return id(func_def) in self._region_ids

    @property
    def jit_functions(self) -> Set[int]:
        return self._region_ids

    # -- seed collection -----------------------------------------------------

    def _collect_dict_kwargs(self) -> None:
        """``donate_state = dict(donate_argnums=(0,))`` assignments, so a
        later ``jax.jit(f, **donate_state)`` resolves its donation."""
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            v = node.value
            if isinstance(v, ast.Call) and dotted_name(v.func) == "dict":
                for kw in v.keywords:
                    if kw.arg == "donate_argnums":
                        self._dict_kwargs[node.targets[0].id] = \
                            _donate_positions(kw.value)
            elif isinstance(v, ast.Dict):
                for k, val in zip(v.keys, v.values):
                    if isinstance(k, ast.Constant) and \
                            k.value == "donate_argnums":
                        self._dict_kwargs[node.targets[0].id] = \
                            _donate_positions(val)

    def _jit_call_donations(self, call: ast.Call) -> Tuple[int, ...]:
        pos: Tuple[int, ...] = ()
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                pos += _donate_positions(kw.value)
            elif kw.arg is None and isinstance(kw.value, ast.Name):
                pos += self._dict_kwargs.get(kw.value.id, ())
        return pos

    def _wrapped_def(self, expr: ast.AST) -> Optional[str]:
        """The bare name of the function a jit(...) first argument refers
        to — directly, or through one ``partial(f, ...)`` layer."""
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Call) and _is_partial(expr.func) and \
                expr.args and isinstance(expr.args[0], ast.Name):
            return expr.args[0].id
        return None

    def _collect_seeds(self) -> List[ast.AST]:
        seeds: List[ast.AST] = []
        for node in ast.walk(self.tree):
            if isinstance(node, _FUNC_DEFS):
                for dec in node.decorator_list:
                    if self._decorator_is_jit(dec):
                        seeds.append(node)
                        if isinstance(dec, ast.Call):
                            pos = self._jit_call_donations(dec)
                            if pos:
                                self.donating[node.name] = pos
                        break
            elif isinstance(node, ast.Call) and is_jit_wrapper(node.func):
                if node.args:
                    resolved = False
                    name = self._wrapped_def(node.args[0])
                    if name:
                        seeds.extend(self._defs_by_name.get(name, ()))
                        resolved = True
                    elif isinstance(node.args[0], ast.Lambda):
                        # jax.jit(lambda s, b: _step(s, b)) — the lambda
                        # body is the region; seed what it references
                        for ref in self._lambda_refs(node.args[0]):
                            seeds.extend(self._defs_by_name.get(ref, ()))
                        resolved = True
                    if resolved:
                        pos = self._jit_call_donations(node)
                        if pos:
                            # the jit result donates; record under the
                            # name(s) it is assigned to
                            for tgt in self._assign_targets_of(node):
                                self.donating[tgt] = pos
        return seeds

    @staticmethod
    def _lambda_refs(lam: ast.Lambda) -> Set[str]:
        """Bare names the lambda body loads, minus its own parameters."""
        params = {a.arg for a in (lam.args.args + lam.args.kwonlyargs
                                  + lam.args.posonlyargs)}
        for extra in (lam.args.vararg, lam.args.kwarg):
            if extra is not None:
                params.add(extra.arg)
        return {n.id for n in ast.walk(lam.body)
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                and n.id not in params}

    def _decorator_is_jit(self, dec: ast.AST) -> bool:
        if is_jit_wrapper(dec):
            return True
        if isinstance(dec, ast.Call):
            if is_jit_wrapper(dec.func):
                return True
            # @functools.partial(jax.jit, static_argnames=...)
            if _is_partial(dec.func) and dec.args and \
                    is_jit_wrapper(dec.args[0]):
                return True
        return False

    def _assign_targets_of(self, call: ast.Call) -> List[str]:
        """Names an ``X = jax.jit(...)`` call is directly assigned to.
        Uses a parent scan over Assign nodes (cheap; runs per jit call)."""
        out = []
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and node.value is call:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.append(t.id)
        return out

    # -- propagation ---------------------------------------------------------

    def _references(self, func_def: ast.AST) -> Set[str]:
        """Bare names referenced in the def's own body — nested function
        *bodies* excluded (they propagate on their own turn when marked)."""
        names: Set[str] = set()
        stack = list(ast.iter_child_nodes(func_def))
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNC_DEFS):
                continue
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load):
                names.add(node.id)
            stack.extend(ast.iter_child_nodes(node))
        return names

    def _propagate(self, seeds: List[ast.AST]) -> None:
        work = list(seeds)
        while work:
            fn = work.pop()
            if id(fn) in self._region_ids:
                continue
            self._region_ids.add(id(fn))
            for name in self._references(fn):
                for target in self._defs_by_name.get(name, ()):
                    if id(target) not in self._region_ids:
                        work.append(target)
