"""Split generation programs — the serving half of the generator.

The train-side sampler (``train/steps.py _sample``) is ONE jitted
program: mapping + truncation + synthesis, ψ a *static* argument (a new
executable per ψ value) built from a full ``TrainState`` (G **and** D
and both optimizers).  A service wants the opposite of all three
choices, so this module splits the generator at the mapping/synthesis
boundary (the compiler-first cached-intermediate shape of arxiv
2603.09555, PAPERS.md):

* ``map_seeds``  — ``(params, seeds[B]) → ws``: per-row latent draw
  (z_i is a pure function of seed_i — the cache key IS the content
  address) + mapping network.  Row-independent, so bucket padding
  leaves the real rows bit-identical (held by tests/test_serve.py).
* ``map_z``      — ``(params, z) → ws``: explicit-latent flavor for
  interpolation / parity with the training sampler.
* ``synthesize`` — ``(params, w_avg, ws, psi[B], rng, tags[B]) → imgs``:
  truncation + synthesis.  ψ rides as a TRACED per-row vector, so ONE
  executable covers every ψ (and mixed-ψ batches); keeping truncation
  here — not in the map programs — makes the w-cache ψ-independent:
  one cached mapping serves every truncation setting.  ``tags`` are
  per-row noise identities (the service passes each request's seed) so
  a row's noise never depends on batch composition, dispatch order, or
  which replica served it (ISSUE 20).

``ServePrograms`` AOT-lowers each (kind, batch-bucket) pair to a
``Compiled`` executable, warm-starting from the serialized-executable
manifest (``serve/warmstart.py``) when a valid entry exists — a cold
process start with a populated manifest compiles ZERO programs.
Telemetry: ``serve/compiles_total``, ``serve/compile_ms``,
``serve/map_dispatch_total``, ``serve/synth_dispatch_total``.

``load_generator`` is the matching checkpoint surface: the G-only
partial restore (``checkpoint.restore_selected`` over an ABSTRACT
template) that reads ``ema_params`` + ``w_avg`` and never initializes
the discriminator or the optimizers.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from types import SimpleNamespace
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

from gansformer_tpu.core.config import ExperimentConfig
from gansformer_tpu.obs import registry as telemetry

DEFAULT_BUCKETS = (1, 2, 4, 8)

# The serving precision axis (ISSUE 20) — synth-program only:
#   f32   — reference: model dtype as trained (the fidelity anchor)
#   bf16  — bfloat16 activations, f32 weights (the declared fp32
#           islands — instance-norm, attention-lse, demodulation —
#           stay f32 inside the bf16 program)
#   int8w — bf16 activations + int8 weight-only kernels with
#           per-output-channel scales (serve/quant.py), dequantized in
#           the shared kernel-prep seam (ops.resolve_weight)
SERVE_PRECISIONS = ("f32", "bf16", "int8w")

# Serving programs a warm start pre-builds by default.  ``map_z`` is the
# explicit-latent flavor only the generate CLI's interpolation path
# needs — it compiles (and manifests) on first use instead.
WARM_KINDS = ("map_seeds", "synthesize")


@dataclasses.dataclass(frozen=True)
class GeneratorBundle:
    """Everything generation needs — and nothing else."""

    cfg: ExperimentConfig
    ema_params: Any                  # the Gs tree (EMA generator)
    w_avg: Any                       # [w_dim] truncation anchor


def sorted_buckets(buckets: Iterable[int]) -> Tuple[int, ...]:
    out = tuple(sorted({int(b) for b in buckets}))
    if not out or out[0] < 1:
        raise ValueError(f"batch buckets must be positive ints, got "
                        f"{buckets!r}")
    return out


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket ≥ n; requests beyond the largest bucket are the
    caller's job to chunk (the service pops at most max-bucket rows)."""
    if n < 1:
        raise ValueError(f"bucket_for: need n >= 1, got {n}")
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(f"batch of {n} exceeds the largest bucket "
                     f"{buckets[-1]} — chunk the request batch first")


def generator_fns(cfg: ExperimentConfig) -> SimpleNamespace:
    """The three pure program bodies (named for device-time
    attribution: the profiler labels HloModules after ``__name__``)."""
    import jax
    import jax.numpy as jnp

    from gansformer_tpu.models.generator import Generator

    m = cfg.model
    G = Generator(m)

    def serve_map_seeds(params, seeds, label=None):
        def one(seed):
            return jax.random.normal(
                jax.random.PRNGKey(seed), (m.num_ws, m.latent_dim),
                jnp.float32)

        z = jax.vmap(one)(seeds)
        return G.apply({"params": params}, z, label, method=Generator.map)

    def serve_map_z(params, z, label=None):
        return G.apply({"params": params}, z, label, method=Generator.map)

    def serve_synth(params, w_avg, ws, psi, rng, tags):
        # per-row traced ψ: ws' = w̄ + ψ·(ws − w̄) — the truncation
        # trick with the EMA anchor, applied HERE (not at mapping time)
        # so cached w rows stay valid for every ψ
        wa = w_avg[None, None, :]
        ws = wa + psi[:, None, None].astype(ws.dtype) * (ws - wa)

        # Per-row noise keys via vmap, NOT one batch-shaped draw: a
        # single key over a [B,H,W,1] draw makes row i's noise depend
        # on B (threefry counters pair across the whole array), which
        # would break the bucketed-padding parity contract — a padded
        # batch must produce bit-identical prefix rows
        # (tests/test_serve.py).  vmap keeps the batched lowering.
        #
        # ``tags`` [B]uint32 are per-row noise identities folded into
        # ``rng``.  The service passes each request's seed, so a row's
        # noise is a pure function of the request — never of which
        # batch, dispatcher, or replica happened to serve it (the
        # 1-vs-N replica determinism contract, ISSUE 20).  Direct
        # callers default to arange(B), which is row-position-only and
        # keeps the padding-parity contract on its own.
        def one(ws_row, key):
            return G.apply({"params": params}, ws_row[None],
                           rngs={"noise": key},
                           method=Generator.synthesize)[0]

        keys = jax.vmap(jax.random.fold_in, (None, 0))(
            rng, tags.astype(jnp.uint32))
        return jax.vmap(one, (0, 0))(ws, keys)

    serve_map_seeds.__name__ = "serve_map_seeds"
    serve_map_z.__name__ = "serve_map_z"
    serve_synth.__name__ = "serve_synth"
    return SimpleNamespace(map_seeds=serve_map_seeds, map_z=serve_map_z,
                           synthesize=serve_synth)


class ServePrograms:
    """AOT-compiled (kind × batch-bucket) generation executables with
    manifest warm start.

    Params are ARGUMENTS, not closure constants: the executables are
    weight-agnostic, so one manifest serves every checkpoint of an
    architecture and a weight refresh never recompiles anything.
    """

    def __init__(self, bundle: GeneratorBundle,
                 buckets: Iterable[int] = DEFAULT_BUCKETS,
                 manifest_dir: Optional[str] = None,
                 warm_start: bool = True,
                 serve_precision: str = "f32",
                 device: Optional[Any] = None):
        if serve_precision not in SERVE_PRECISIONS:
            raise ValueError(f"serve_precision must be one of "
                             f"{SERVE_PRECISIONS}, got {serve_precision!r}")
        self.bundle = bundle
        self.buckets = sorted_buckets(buckets)
        self.manifest_dir = manifest_dir
        self.warm_start_enabled = warm_start and manifest_dir is not None
        self.serve_precision = serve_precision
        # Replica-per-device placement (ISSUE 20): ``device`` pins THIS
        # instance's params and executables to one device; the manifest
        # fingerprint carries the ordinal so replica i's serialized
        # executables never warm-start replica j.
        self.device = device
        self.device_ordinal = int(device.id) if device is not None else 0
        self._fns = generator_fns(bundle.cfg)
        self._synth_fn = self._fns.synthesize
        self._map_params = bundle.ema_params
        self._synth_params = bundle.ema_params
        self._w_avg = bundle.w_avg
        if serve_precision != "f32":
            # The precision axis applies to the SYNTH split program
            # only: the mapping half stays f32 on the original tree so
            # one w-cache entry (and one map manifest) serves every
            # precision — truncation happens inside synth, so cached w
            # rows are precision-agnostic by construction.
            import dataclasses as _dc
            synth_cfg = _dc.replace(
                bundle.cfg, model=_dc.replace(bundle.cfg.model,
                                              dtype="bfloat16"))
            self._synth_fn = generator_fns(synth_cfg).synthesize
            if serve_precision == "int8w":
                from gansformer_tpu.serve.quant import quantize_params

                self._synth_params = quantize_params(bundle.ema_params)
        if device is not None:
            import jax

            put = lambda t: jax.device_put(t, device)  # noqa: E731
            self._map_params = put(self._map_params)
            self._synth_params = (self._map_params
                                  if self._synth_params is bundle.ema_params
                                  else put(self._synth_params))
            self._w_avg = put(self._w_avg)
        self._compiled: Dict[Tuple[str, int], Any] = {}
        # THIS instance's manifest traffic (the global counters span
        # every service a process ever ran — health() needs its own)
        self.warm_hits = 0
        self.manifest_stale = 0
        self._model_json = json.dumps(
            dataclasses.asdict(bundle.cfg.model), sort_keys=True)
        # explicit zeros for the schema lint (see serve/service.py)
        telemetry.counter("serve/map_dispatch_total")
        telemetry.counter("serve/synth_dispatch_total")
        telemetry.counter("serve/compiles_total")

    # -- shapes --------------------------------------------------------------

    def _abs(self, shape, dtype) -> Any:
        """ShapeDtypeStruct, pinned to this replica's device when one
        is set — the AOT compile then bakes the placement in, so
        dispatch never pays a cross-device transfer."""
        import jax

        if self.device is None:
            return jax.ShapeDtypeStruct(shape, dtype)
        from jax.sharding import SingleDeviceSharding

        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=SingleDeviceSharding(self.device))

    def _abstract_args(self, kind: str, bucket: int) -> Tuple[Any, ...]:
        import jax

        m = self.bundle.cfg.model
        params = (self._synth_params if kind == "synthesize"
                  else self._map_params)
        params_abs = jax.tree_util.tree_map(
            lambda l: self._abs(l.shape, l.dtype), params)
        label_abs = ((self._abs((bucket, m.label_dim), np.float32),)
                     if m.label_dim else ())
        if kind == "map_seeds":
            return (params_abs,
                    self._abs((bucket,), np.int32)) + label_abs
        if kind == "map_z":
            return (params_abs,
                    self._abs((bucket, m.num_ws, m.latent_dim),
                              np.float32)) + label_abs
        if kind == "synthesize":
            return (params_abs,
                    self._abs((m.w_dim,), np.float32),
                    self._abs((bucket, m.num_ws, m.w_dim), np.float32),
                    self._abs((bucket,), np.float32),
                    self._abs((2,), np.uint32),
                    self._abs((bucket,), np.uint32))
        raise KeyError(f"unknown serve program kind {kind!r}")

    # -- compile / warm start ------------------------------------------------

    def is_compiled(self, kind: str, bucket: int) -> bool:
        """Whether this (kind, bucket) executable is already
        materialized — the serving hang watchdog widens its budget for
        batches that will pay a lazy cold compile."""
        return (kind, bucket) in self._compiled

    def _get(self, kind: str, bucket: int) -> Any:
        import jax

        from gansformer_tpu.serve import warmstart

        ck = (kind, bucket)
        if ck in self._compiled:
            return self._compiled[ck]
        # The precision axis is synth-only (map always runs f32 on the
        # original tree), so map manifest entries stay shared across
        # precisions; the ordinal suffix keeps replica manifests
        # side-by-side in one dir.  Defaults keep the PR-13 key names.
        prec = self.serve_precision if kind == "synthesize" else "f32"
        key = f"{kind}_b{bucket}"
        if prec != "f32":
            key += f"_{prec}"
        if self.device_ordinal:
            key += f"_d{self.device_ordinal}"
        fp = warmstart.fingerprint(self._model_json, kind, bucket,
                                   serve_precision=prec,
                                   device_ordinal=self.device_ordinal)
        if self.warm_start_enabled:
            stale0 = telemetry.counter("serve/manifest_stale_total").value
            compiled = warmstart.load_executable(self.manifest_dir, key, fp)
            self.manifest_stale += int(telemetry.counter(
                "serve/manifest_stale_total").value - stale0)
            if compiled is not None:
                self.warm_hits += 1
                self._compiled[ck] = compiled
                return compiled
        fn = (self._synth_fn if kind == "synthesize"
              else getattr(self._fns, kind))
        t0 = time.perf_counter()
        compiled = self._compile(jax.jit(fn), kind, bucket)
        telemetry.counter("serve/compiles_total").inc()
        telemetry.histogram("serve/compile_ms").observe(
            (time.perf_counter() - t0) * 1000.0)
        if self.warm_start_enabled:
            warmstart.save_executable(self.manifest_dir, key, compiled, fp)
        self._compiled[ck] = compiled
        return compiled

    def _compile(self, jitted: Any, kind: str, bucket: int) -> Any:
        """One AOT compile, with the persistent XLA disk cache DISABLED
        when the result is destined for the manifest: an executable that
        was a disk-cache *hit* deserializes against runtime-generated
        symbol names that no longer exist ("Symbols not found" from
        ``serialize_executable`` round-trips — reproduced on jax 0.4.37
        CPU), so a manifest written from cache hits silently loses its
        warm start.  Unsetting ``jax_compilation_cache_dir`` is the
        lever that works (``jax_enable_compilation_cache=False`` does
        NOT gate this path on 0.4.37 — entries still read/write); the
        save path additionally verifies every blob round-trips before
        the manifest records it (``warmstart.save_executable``).  The
        manifest supersedes the XLA cache for serving anyway — both
        layers caching the same program buys nothing."""
        import jax

        args = self._abstract_args(kind, bucket)
        if not self.warm_start_enabled:
            return jitted.lower(*args).compile()
        try:
            from jax._src import compilation_cache as cc
        except ImportError:            # layout drift in a future jax
            cc = None
        prev = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", None)
        if cc is not None:
            cc.reset_cache()   # the module LATCHES the dir at first use
        try:
            return jitted.lower(*args).compile()
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
            if cc is not None:
                cc.reset_cache()       # re-latch from the restored dir

    def warm_start(self, kinds: Sequence[str] = WARM_KINDS) -> Dict[str, Any]:
        """Materialize every (kind, bucket) executable — from the
        manifest when valid, compiling (and re-serializing) otherwise.
        Returns {loaded, compiled, seconds}."""
        before_hits = telemetry.counter("serve/warm_hits_total").value
        before_compiles = telemetry.counter("serve/compiles_total").value
        t0 = time.perf_counter()
        for kind in kinds:
            for bucket in self.buckets:
                self._get(kind, bucket)
        return {
            "loaded": int(telemetry.counter("serve/warm_hits_total").value
                          - before_hits),
            "compiled": int(telemetry.counter("serve/compiles_total").value
                            - before_compiles),
            "seconds": time.perf_counter() - t0,
        }

    # -- dispatch ------------------------------------------------------------

    def _label_args(self, bucket: int, label) -> Tuple[Any, ...]:
        if not self.bundle.cfg.model.label_dim:
            if label is not None:
                raise ValueError("label passed to an unconditional model")
            return ()
        if label is None:
            raise ValueError(
                f"model has label_dim={self.bundle.cfg.model.label_dim}; "
                f"requests must carry a label vector")
        label = np.asarray(label, np.float32)
        if label.shape != (bucket, self.bundle.cfg.model.label_dim):
            raise ValueError(f"label shape {label.shape} != "
                             f"({bucket}, "
                             f"{self.bundle.cfg.model.label_dim})")
        return (label,)

    def map_seeds(self, seeds: np.ndarray, label=None):
        """seeds [bucket]int32 → ws [bucket, num_ws, w_dim] (device)."""
        seeds = np.ascontiguousarray(seeds, np.int32)
        bucket = bucket_for(len(seeds), self.buckets)
        if len(seeds) != bucket:
            raise ValueError(f"map_seeds takes a full bucket "
                             f"({self.buckets}); pad {len(seeds)} rows "
                             f"to {bucket} first")
        telemetry.counter("serve/map_dispatch_total").inc()
        return self._get("map_seeds", bucket)(
            self._map_params, seeds,
            *self._label_args(bucket, label))

    def map_z(self, z: np.ndarray, label=None):
        z = np.ascontiguousarray(z, np.float32)
        bucket = bucket_for(z.shape[0], self.buckets)
        if z.shape[0] != bucket:
            raise ValueError(f"map_z takes a full bucket "
                             f"({self.buckets}); pad {z.shape[0]} rows "
                             f"to {bucket} first")
        telemetry.counter("serve/map_dispatch_total").inc()
        return self._get("map_z", bucket)(
            self._map_params, z, *self._label_args(bucket, label))

    def synthesize(self, ws, psi, rng, tags=None):
        """ws [bucket, num_ws, w_dim], psi [bucket]f32, rng (2,)uint32,
        tags [bucket]uint32 (per-row noise identities; default: row
        positions) → imgs [bucket, R, R, C] (device, unfetched)."""
        ws = np.ascontiguousarray(ws, np.float32) \
            if isinstance(ws, np.ndarray) else ws
        psi = np.ascontiguousarray(psi, np.float32)
        bucket = bucket_for(psi.shape[0], self.buckets)
        if psi.shape[0] != bucket or ws.shape[0] != bucket:
            raise ValueError(f"synthesize takes a full bucket "
                             f"({self.buckets}); pad "
                             f"{psi.shape[0]}/{ws.shape[0]} rows to "
                             f"{bucket} first")
        if tags is None:
            tags = np.arange(bucket, dtype=np.uint32)
        tags = np.ascontiguousarray(tags, np.uint32)
        if tags.shape != (bucket,):
            raise ValueError(f"tags shape {tags.shape} != ({bucket},)")
        telemetry.counter("serve/synth_dispatch_total").inc()
        return self._get("synthesize", bucket)(
            self._synth_params, self._w_avg, ws, psi, rng, tags)


# -- checkpoint surface ------------------------------------------------------

def _is_generator_leaf(path) -> bool:
    from gansformer_tpu.parallel.contracts import key_str

    return key_str(path[0]) in ("ema_params", "w_avg") if path else False


def load_generator(run_dir: str,
                   cfg: Optional[ExperimentConfig] = None,
                   step: Optional[int] = None) -> GeneratorBundle:
    """G-only checkpoint load: ``ema_params`` + ``w_avg`` from
    ``<run_dir>/checkpoints`` against an ABSTRACT template — the
    discriminator and both optimizer states are never initialized,
    never read, never put on device (the cost lands in the
    ``serve/restore_ms`` gauge; tests/test_serve.py compares it against
    the full init+restore path).  Legacy Orbax checkpoints (no
    ``state.npz``) fall back to the full concrete restore."""
    import jax

    from gansformer_tpu.train import checkpoint as ckpt
    from gansformer_tpu.train.state import create_train_state

    if cfg is None:
        with open(os.path.join(run_dir, "config.json")) as f:
            cfg = ExperimentConfig.from_json(f.read())
    ckpt_dir = os.path.join(run_dir, "checkpoints")
    t0 = time.perf_counter()
    template = jax.eval_shape(lambda k: create_train_state(cfg, k),
                              jax.random.PRNGKey(0))
    try:
        partial = ckpt.restore_selected(ckpt_dir, template,
                                        _is_generator_leaf, step=step)
    except FileNotFoundError as e:
        if "Orbax" not in str(e) and "pre-npz" not in str(e):
            raise
        # legacy step dir: pay the full init+restore once
        full_template = create_train_state(cfg, jax.random.PRNGKey(0))
        partial = ckpt.restore(ckpt_dir, full_template, step=step)
    telemetry.gauge("serve/restore_ms").set(
        (time.perf_counter() - t0) * 1000.0)
    return GeneratorBundle(cfg=cfg, ema_params=partial.ema_params,
                           w_avg=partial.w_avg)


def init_generator(cfg: ExperimentConfig, seed: int = 0) -> GeneratorBundle:
    """Randomly-initialized G-only bundle (no checkpoint) — the
    load-test / battery path, where serving PERFORMANCE is measured on
    the real architecture without needing trained weights."""
    import jax
    import jax.numpy as jnp

    from gansformer_tpu.models.generator import Generator

    m = cfg.model
    G = Generator(m)
    k_g, k_noise = jax.random.split(jax.random.PRNGKey(seed))
    z = jnp.zeros((2, m.num_ws, m.latent_dim), jnp.float32)
    label = jnp.zeros((2, m.label_dim), jnp.float32) if m.label_dim \
        else None
    g_vars = G.init({"params": k_g, "noise": k_noise}, z, label=label)
    return GeneratorBundle(cfg=cfg, ema_params=g_vars["params"],
                           w_avg=jnp.zeros((m.w_dim,), jnp.float32))
