"""Continuous-batching generation service — the request front end.

One dispatcher thread (``utils/background.LoopWorker``) runs
``_serve_dispatch``: pop whatever is queued (up to the largest compiled
bucket, waiting ``max_fill_wait_ms`` after the first arrival to improve
fill), resolve each request's w row — LRU cache hit or a bucketed
``map_seeds`` dispatch for the misses — pad to the next bucket, run the
ψ-vectorized synthesis executable, fetch, slice, fulfill tickets.  An
all-miss batch (cold-seed traffic) keeps ws ON DEVICE between the two
programs — the cache-fill fetch rides after the synthesis dispatch, so
the host copy overlaps the synth compute instead of serializing
map → host → synth.
Continuous batching: the queue drains whenever the device is free; a
batch is NEVER held for stragglers beyond the fill wait, and oversize
backlogs chunk at the max bucket per iteration.

Robustness floor (ISSUE 13) — the serving half of the availability
story the supervisor (ISSUE 12) started for training:

* **Admission control** — the queue is BOUNDED (``max_queue_depth``);
  an over-depth ``submit`` raises a typed ``Overloaded`` immediately
  (``serve/shed_total``) instead of queueing unboundedly.  Under
  overload the service degrades predictably: p50/p99 stay meaningful
  because the queue can't grow past the bound.
* **Deadlines** — per-request (``submit(deadline_s=…)`` or the
  service-wide ``default_deadline_s``); an expired ticket is dropped at
  pop time BEFORE dispatch (never padded into a bucket) and resolved
  with a typed ``Expired`` error (``serve/expired_total``).  A client
  whose ``result(timeout)`` raised marks its ticket CANCELLED, so the
  dispatcher skips the orphaned work too (``serve/cancelled_total``).
* **Self-healing dispatch** — a supervisor thread restarts a crashed
  (or hung: ``hang_after_s``) dispatcher under progress-reset bounded
  backoff (the exit-classification/backoff shape of
  ``supervise/supervisor.py`` at serving time scale), failing the
  in-flight batch instead of hanging it; after ``max_dispatcher_restarts``
  back-to-back deaths the CIRCUIT BREAKER trips — queued tickets fail
  with ``ServiceUnhealthy``, new submits are refused, ``health()``
  reports unhealthy.
* **Bucket quarantine** — ``quarantine_after`` consecutive synthesis
  failures on one batch bucket quarantine it; later batches route to
  the next-larger bucket (the largest bucket is never quarantined —
  there must always be a route).
* **Graceful drain** — ``close()`` (and the SIGTERM hook
  ``install_signal_drain``) stops admitting, serves what's queued
  within the grace window, then fails the rest with ``ServiceClosed``;
  ``serve/queue_depth_now`` returns to 0 and no service thread leaks.
* **Fault injection** — ``supervise/faults.py`` code points
  ``serve_dispatch`` / ``serve_map`` / ``serve_fetch`` /
  ``serve_fulfill`` (coords: monotonic ``batch``, plus ``n``/``bucket``)
  so every recovery path above is deterministically exercised by tier-1
  tests and ``scripts/loadtest_serve.py --chaos``.

The dispatch loop is under the ``hot-loop-sync`` lint discipline
(analysis/rules/hot_loop.py): the only host syncs in the ``while`` body
live inside ``with span("serve_fetch")`` — the serving twin of the
train loop's ``tick_fetch`` contract, so a future edit that sneaks a
hidden ``block_until_ready`` into the dispatch path fails tier-1.

SLO telemetry (obs/registry → ``telemetry.prom``):
``serve/queue_depth`` histogram+gauge, ``serve/batch_fill`` histogram
(rows/bucket), ``serve/e2e_ms`` histogram (submit→ready),
``serve/batch_ms`` histogram (dispatch+fetch), counters
``serve/requests_total`` / ``serve/images_total`` /
``serve/map_dispatch_total`` / ``serve/synth_dispatch_total`` and the
w-cache pair, plus the robustness family: ``serve/shed_total``,
``serve/expired_total``, ``serve/cancelled_total``,
``serve/dispatcher_restarts_total``, ``serve/bucket_quarantined_total``,
gauges ``serve/health_state`` (0 ready / 1 degraded / 2 unhealthy /
3 closed-cleanly), ``serve/dispatcher_alive``, ``serve/queue_bound``,
and the LoopWorker's ``serve/dispatch_heartbeat``.

Request tracing (ISSUE 16): every ``Ticket`` carries a request ID
(``obs/reqtrace``) and a lifecycle event stream — submitted/admitted at
submit, popped/batched/wcache_hit/map_dispatch/synth/fetch along the
dispatch path, and a terminal fulfilled/shed/expired/cancelled/failed
with a cause.  ``Ticket._resolve`` is the one-shot funnel every
outcome passes through, so terminal coverage is structural; the shed
and refused-submit paths emit their terminals at the raise site.  The
emit points are host-side dict appends only (the hot-loop-sync rule
scans the emitter bodies), the ``serve/e2e_ms`` / ``serve/batch_ms``
histograms carry the max-latency request ID as a prom exemplar, and
each batch emits a ``serve_batch`` span listing the request IDs it
carried (the batch→trace causal link in events.jsonl).
"""

from __future__ import annotations

import signal
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set

import numpy as np

from gansformer_tpu.obs import registry as telemetry
from gansformer_tpu.obs import reqtrace
from gansformer_tpu.obs.spans import span
from gansformer_tpu.serve.cache import WCache, wcache_key
from gansformer_tpu.serve.programs import ServePrograms
from gansformer_tpu.supervise import faults
from gansformer_tpu.utils.background import LoopWorker

HEALTH_READY, HEALTH_DEGRADED, HEALTH_UNHEALTHY, HEALTH_CLOSED = \
    0, 1, 2, 3
# keep in sync with analysis/telemetry_schema.SERVE_HEALTH_NAMES (the
# CLI graders' shared copy) — mirrored here so the serving hot path
# does not import the analysis package
_HEALTH_NAMES = {HEALTH_READY: "ready", HEALTH_DEGRADED: "degraded",
                 HEALTH_UNHEALTHY: "unhealthy",
                 HEALTH_CLOSED: "closed"}


class ServeError(RuntimeError):
    """Base of the typed serving outcomes; ``Ticket.result`` raises
    these DIRECTLY (not wrapped) so callers can catch by class."""


class Overloaded(ServeError):
    """Admission queue at its bound — the request was shed at submit."""


class Expired(ServeError):
    """The request's deadline passed before dispatch."""


class Cancelled(ServeError):
    """The client abandoned the ticket (``cancel()`` / result timeout)."""


class ServiceUnhealthy(ServeError):
    """Circuit breaker open (dispatcher restart budget exhausted)."""


class ServiceClosed(ServeError):
    """The service closed/drained before this ticket could be served."""


class Ticket:
    """One submitted request; ``result()`` blocks until fulfilled.

    Terminal states: ``done`` (image), ``failed`` (error), ``cancelled``
    (client abandoned).  Transitions are one-shot — a late ``_fulfill``
    against a cancelled ticket is a no-op, so the cancel/dispatch race
    is benign by construction."""

    __slots__ = ("seed", "psi", "label", "t_submit", "t_done", "deadline",
                 "rid", "_event", "_image", "_error", "_state", "_lock")

    def __init__(self, seed: int, psi: float, label,
                 deadline_s: Optional[float] = None):
        self.seed = int(seed)
        self.psi = float(psi)
        self.label = label
        # request ID + the "submitted" trace event (obs/reqtrace);
        # None while tracing is disabled — every later emit no-ops
        self.rid = reqtrace.get_reqtracer().begin(seed=int(seed),
                                                  psi=float(psi))
        self.t_submit = time.perf_counter()
        self.t_done: Optional[float] = None
        self.deadline = (None if deadline_s is None
                         else self.t_submit + float(deadline_s))
        self._event = threading.Event()
        self._image: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        self._state = "pending"
        self._lock = threading.Lock()

    @property
    def state(self) -> str:
        return self._state

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (time.perf_counter() if now is None else now) > self.deadline

    def _resolve(self, state: str, image=None, error=None) -> bool:
        with self._lock:
            if self._state != "pending":
                return False
            self._state = state
            self._image, self._error = image, error
            self.t_done = time.perf_counter()
        if state == "done":
            # exemplar: the request ID rides the histogram's max, so a
            # p99 outlier in telemetry.prom resolves to its timeline
            telemetry.histogram("serve/e2e_ms").observe(
                (self.t_done - self.t_submit) * 1000.0,
                exemplar=self.rid)
        # terminal trace event with the typed cause — _resolve is the
        # one-shot funnel every outcome passes through, so terminal
        # coverage is structural, not per-call-site
        rt = reqtrace.get_reqtracer()
        if state == "done":
            rt.event(self.rid, "fulfilled")
        elif state == "cancelled":
            rt.event(self.rid, "cancelled", cause="client_cancelled")
        elif isinstance(error, Expired):
            rt.event(self.rid, "expired", cause="deadline")
        else:
            rt.event(self.rid, "failed",
                     cause=(type(error).__name__
                            if error is not None else None))
        self._event.set()
        return True

    def _fulfill(self, image: np.ndarray) -> bool:
        return self._resolve("done", image=image)

    def _fail(self, err: BaseException) -> bool:
        return self._resolve("failed", error=err)

    def cancel(self) -> bool:
        """Abandon the request: a cancelled ticket is skipped at pop
        time, so the dispatcher never computes work nobody will read.
        Returns False when the ticket already reached a terminal
        state."""
        return self._resolve(
            "cancelled",
            error=Cancelled(f"request (seed={self.seed}) cancelled"))

    @property
    def latency_ms(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1000.0

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            # Orphaned-work fix: the client is giving up NOW — mark the
            # ticket cancelled so the dispatcher skips it instead of
            # synthesizing an image nobody will fetch.  A cancel that
            # LOSES the race (the ticket reached a terminal state in
            # the window after the wait deadline) delivers the real
            # outcome below instead of a spurious TimeoutError.
            if self.cancel():
                raise TimeoutError(
                    f"request (seed={self.seed}) not served in "
                    f"{timeout}s")
            self._event.wait(1.0)   # _resolve sets the event imminently
        if self._error is not None:
            if isinstance(self._error, ServeError):
                raise self._error
            raise RuntimeError("generation request failed") from self._error
        return self._image


class GenerationService:
    """Front a ``ServePrograms`` with a continuous-batching queue under
    the ISSUE 13 robustness floor (bounded admission, deadlines,
    supervised dispatch, health states, graceful drain)."""

    def __init__(self, programs: ServePrograms,
                 max_fill_wait_ms: float = 2.0,
                 wcache_capacity: int = 4096,
                 noise_seed: int = 0,
                 max_queue_depth: int = 256,
                 default_deadline_s: Optional[float] = None,
                 max_dispatcher_restarts: int = 3,
                 restart_backoff_base_s: float = 0.05,
                 restart_backoff_max_s: float = 2.0,
                 hang_after_s: Optional[float] = 300.0,
                 hang_startup_grace_s: float = 1800.0,
                 quarantine_after: int = 2,
                 replica_id: Optional[int] = None):
        if max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1, got "
                             f"{max_queue_depth}")
        # Replica member mode (serve/replicas.ReplicaSet, ISSUE 20):
        # the instance LIVENESS gauges move to serve/replica<i>/... so
        # N members never fight over one global gauge — the fleet-level
        # serve/health_state, serve/dispatcher_alive (any-alive),
        # serve/queue_depth_now (sum) and serve/queue_bound are owned
        # by the ReplicaSet.  Counters and the shared histograms stay
        # global (they sum correctly across members); dispatch
        # additionally attributes images/fill/latency per replica.
        self.replica_id = replica_id
        if replica_id is None:
            self._g = lambda name: name
        else:
            pfx = f"serve/replica{int(replica_id)}/"
            self._g = lambda name: pfx + name[len("serve/"):]
        self.programs = programs
        self._max_bucket = programs.buckets[-1]
        self._fill_wait_s = max(0.0, max_fill_wait_ms) / 1000.0
        self.wcache = WCache(wcache_capacity)
        self._noise_seed = int(noise_seed)
        self._batches = 0
        self._pending: "deque[Ticket]" = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._drain_failed = False
        self._tripped = False
        self._trip_cause: Optional[BaseException] = None
        self._max_queue_depth = int(max_queue_depth)
        self._default_deadline_s = default_deadline_s
        self._max_restarts = int(max_dispatcher_restarts)
        self._backoff_base_s = float(restart_backoff_base_s)
        self._backoff_max_s = float(restart_backoff_max_s)
        self._hang_after_s = hang_after_s
        self._hang_startup_grace_s = float(hang_startup_grace_s)
        self._quarantine_after = int(quarantine_after)
        self._quarantined: Set[int] = set()
        self._bucket_fails: Dict[int, int] = {}
        self._restarts = 0
        self._deaths_in_row = 0
        self._fulfilled = 0
        self._fulfilled_at_restart = 0
        self._inflight: List[Ticket] = []
        self._busy_since: Optional[float] = None
        self._busy_cold = False     # current batch pays a lazy compile
        self._poll_s = 0.05
        # Dispatcher generation: bumped (under _cv) on every restart /
        # breaker trip, so an ABANDONED-as-hung worker that later wakes
        # retires at its next pop instead of racing the replacement.
        self._gen = 0
        # materialize every SLO + robustness family up front so an idle
        # (or all-hit / all-miss / never-overloaded) service still
        # exports explicit zeros — the serve-family schema lint reads
        # absence as rotted wiring
        for name in ("serve/queue_depth", "serve/batch_fill",
                     "serve/e2e_ms", "serve/batch_ms"):
            telemetry.histogram(name)
        for name in ("serve/requests_total", "serve/images_total",
                     "serve/shed_total", "serve/expired_total",
                     "serve/cancelled_total",
                     "serve/dispatcher_restarts_total",
                     "serve/bucket_quarantined_total",
                     # request tracing (obs/reqtrace): materialized here
                     # so a serving prom always answers "is tracing
                     # wired?" explicitly
                     "reqtrace/requests_total", "reqtrace/events_total",
                     "reqtrace/terminal_total", "reqtrace/dropped_total",
                     "reqtrace/ledger_rows_total",
                     "reqtrace/ledger_dropped_total"):
            telemetry.counter(name)
        telemetry.gauge("reqtrace/enabled").set(
            1.0 if reqtrace.get_reqtracer().enabled else 0.0)
        if replica_id is not None:
            # replica-member instruments: materialized up front so an
            # idle replica still exports explicit zeros (the fleet
            # schema check reads absence as rotted wiring)
            telemetry.counter(self._g("serve/images_total"))
            telemetry.histogram(self._g("serve/batch_ms"))
            telemetry.histogram(self._g("serve/batch_fill"))
        telemetry.gauge(self._g("serve/queue_bound")).set(self._max_queue_depth)
        telemetry.gauge(self._g("serve/health_state")).set(HEALTH_READY)
        telemetry.gauge(self._g("serve/queue_depth_now")).set(0)
        self._worker = LoopWorker(self._serve_dispatch,
                                  "serve/dispatch").start()
        telemetry.gauge(self._g("serve/dispatcher_alive")).set(1)
        self._monitor = threading.Thread(target=self._supervise_dispatch,
                                         name="serve-supervisor",
                                         daemon=True)
        self._monitor.start()

    # -- producer side -------------------------------------------------------

    def submit(self, seed: int, psi: float = 0.7, label=None,
               deadline_s: Optional[float] = None) -> Ticket:
        """Admit one request.  Typed refusals: ``ServiceClosed`` when
        closed/draining, ``ServiceUnhealthy`` when the breaker is open,
        ``Overloaded`` (counted in ``serve/shed_total``) when the
        admission queue is at its bound."""
        t = Ticket(seed, psi, label,
                   deadline_s if deadline_s is not None
                   else self._default_deadline_s)
        shed = False
        rt = reqtrace.get_reqtracer()
        dropped: List[Ticket] = []
        with self._cv:
            if self._stop:
                rt.event(t.rid, "failed", cause="ServiceClosed")
                raise ServiceClosed("service is closed")
            if self._tripped:
                rt.event(t.rid, "failed", cause="ServiceUnhealthy")
                raise ServiceUnhealthy(
                    f"circuit breaker open after {self._restarts} "
                    f"dispatcher restart(s): "
                    f"{self._trip_cause}") from self._trip_cause
            if len(self._pending) >= self._max_queue_depth:
                # compact DEAD tickets (cancelled / already expired)
                # before shedding: slots held by abandoned work — e.g.
                # clients that timed out against a wedged dispatcher —
                # must not shed live traffic as phantom load
                now = time.perf_counter()
                keep: "deque[Ticket]" = deque()
                for t2 in self._pending:
                    if t2.state == "cancelled" or t2.expired(now):
                        dropped.append(t2)
                    else:
                        keep.append(t2)
                self._pending = keep
            if len(self._pending) >= self._max_queue_depth:
                shed = True
            else:
                self._pending.append(t)
                rt.event(t.rid, "admitted", depth=len(self._pending))
                telemetry.gauge(self._g("serve/queue_depth_now")).set(
                    len(self._pending))
                self._cv.notify()
        self._settle_dropped(dropped)
        if shed:
            telemetry.counter("serve/shed_total").inc()
            rt.event(t.rid, "shed", cause="overloaded")
            raise Overloaded(
                f"admission queue at its bound "
                f"({self._max_queue_depth}) — request shed")
        telemetry.counter("serve/requests_total").inc()
        return t

    def _settle_dropped(self, dropped: List[Ticket]) -> None:
        """Resolve+count tickets discarded BEFORE dispatch (queue
        compaction at submit, or the pop-time skip) — cancelled ones
        are already resolved, expired ones fail typed here."""
        for t in dropped:
            if t.state == "cancelled":
                telemetry.counter("serve/cancelled_total").inc()
            else:
                telemetry.counter("serve/expired_total").inc()
                t._fail(Expired(
                    f"request (seed={t.seed}) deadline passed "
                    f"before dispatch"))

    def load(self) -> int:
        """Router signal (serve/replicas): queued + in-flight tickets —
        the work this replica would have to finish before a newly
        assigned request runs."""
        with self._cv:
            return len(self._pending) + len(self._inflight)

    def accepting(self) -> bool:
        """True iff ``submit`` would not refuse outright (not closed,
        breaker not open).  Queue saturation is NOT checked here — the
        router prefers a deep healthy queue over a tripped replica."""
        with self._cv:
            return not self._stop and not self._tripped

    def health(self) -> dict:
        """Point-in-time health snapshot: ``ready`` / ``degraded`` /
        ``unhealthy`` / ``closed`` (clean shutdown) with reasons, also
        exported as the ``serve/health_state`` gauge (0/1/2/3)."""
        with self._cv:
            depth = len(self._pending)
            stop, tripped = self._stop, self._tripped
            restarts = self._restarts
            quarantined = sorted(self._quarantined)
        alive = self._worker.alive
        reasons: List[str] = []
        if tripped:
            state = HEALTH_UNHEALTHY
            reasons.append(f"circuit breaker open after {restarts} "
                           f"dispatcher restart(s)")
        elif stop and self._drain_failed:
            state = HEALTH_UNHEALTHY
            reasons.append("drain failed: tickets were still "
                           "queued/in-flight past the grace window")
        elif stop:
            # a CLEAN close is not a failure — the exported gauge must
            # not read as a tripped breaker to the doctor/healthcheck
            state = HEALTH_CLOSED
            reasons.append("service closed/draining")
        else:
            state = HEALTH_READY
            if depth >= self._max_queue_depth:
                reasons.append(f"admission queue saturated "
                               f"({depth}/{self._max_queue_depth})")
            if restarts > 0:
                reasons.append(f"dispatcher restarted {restarts} time(s) "
                               f"(budget {self._max_restarts})")
            if not alive:
                reasons.append("dispatcher down (restart pending)")
            if quarantined:
                reasons.append(f"bucket(s) {quarantined} quarantined")
            # per-instance counts (ServePrograms tracks its own): the
            # process-global counters span every service ever run here
            stale = self.programs.manifest_stale
            hits = self.programs.warm_hits
            if stale + hits > 0 and stale / (stale + hits) > 0.5:
                reasons.append(
                    f"warm-start fallback rate "
                    f"{stale / (stale + hits):.0%} — the manifest is "
                    f"mostly stale (recompiling at serve time)")
            if reasons:
                state = HEALTH_DEGRADED
        telemetry.gauge(self._g("serve/health_state")).set(state)
        telemetry.gauge(self._g("serve/queue_depth_now")).set(depth)
        return {"state": _HEALTH_NAMES[state], "state_code": state,
                "replica_id": self.replica_id,
                "reasons": reasons, "queue_depth": depth,
                "queue_bound": self._max_queue_depth,
                "dispatcher_alive": alive,
                "dispatcher_restarts": restarts,
                "quarantined_buckets": quarantined,
                "shed_total": telemetry.counter("serve/shed_total").value,
                "expired_total":
                    telemetry.counter("serve/expired_total").value,
                "cancelled_total":
                    telemetry.counter("serve/cancelled_total").value}

    def install_signal_drain(self, grace_s: float = 30.0) -> bool:
        """SIGTERM → graceful drain (main thread only; returns whether
        the handler was installed).  The handler mirrors the training
        loop's preemption discipline: stop admitting, serve the queue
        within the grace window, fail the rest."""
        if threading.current_thread() is not threading.main_thread():
            return False

        def _on_term(signum, frame):
            # Drain on a SEPARATE thread: the handler runs on the main
            # thread at an arbitrary bytecode boundary, possibly while
            # that thread already holds _cv (mid-submit) — close()
            # inline would deadlock on the non-reentrant lock.  The
            # drain thread just blocks until the interrupted frame
            # releases it.
            threading.Thread(target=self.close,
                             kwargs={"timeout": grace_s},
                             name="serve-sigterm-drain",
                             daemon=True).start()

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            return False
        return True

    def close(self, timeout: float = 30.0) -> None:
        """Graceful drain: stop admitting, serve what's queued within
        the grace window, then fail every leftover (queued or in-flight)
        with a typed ``ServiceClosed`` — the finally-path guarantees no
        ticket is left blocked even when the dispatcher died between
        submit and close."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        try:
            deadline = time.monotonic() + max(0.0, timeout)
            self._monitor.join(timeout)
            self._worker.join(max(0.0, deadline - time.monotonic()))
        finally:
            with self._cv:
                leftovers = list(self._pending)
                self._pending.clear()
                telemetry.gauge(self._g("serve/queue_depth_now")).set(0)
            # dead tickets swept at drain still count as dropped-before-
            # dispatch (and expired ones resolve with the typed Expired),
            # exactly as a pop would have counted them
            now = time.perf_counter()
            dead = [t for t in leftovers
                    if t.state == "cancelled" or t.expired(now)]
            self._settle_dropped(dead)
            failed = 0
            for t in leftovers:
                failed += t._fail(ServiceClosed(
                    "service closed with request queued"))
            if self._worker.alive:
                # the dispatcher is wedged past the grace window: its
                # batch is being failed below, so supersede its
                # generation — when it finally unblocks it must not
                # count images nobody received
                with self._cv:
                    self._gen += 1
                    self._cv.notify_all()
            failed += self._fail_inflight(ServiceClosed(
                "service closed mid-batch (dispatcher did not drain "
                "within the grace window)"))
            telemetry.gauge(self._g("serve/dispatcher_alive")).set(
                1.0 if self._worker.alive else 0.0)
            if failed:
                self._drain_failed = True
                telemetry.gauge(self._g("serve/health_state")).set(HEALTH_UNHEALTHY)
            elif not self._tripped:
                # a clean drain exports as closed (3) even when the
                # caller never polls health() again
                telemetry.gauge(self._g("serve/health_state")).set(HEALTH_CLOSED)

    def __enter__(self) -> "GenerationService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- dispatcher supervision (monitor thread) -----------------------------

    def _fail_inflight(self, err: BaseException) -> int:
        """Resolve whatever the dispatcher had popped but not fulfilled
        (idempotent — tickets already resolved are untouched)."""
        with self._cv:
            batch = list(self._inflight)
            self._inflight = []
        failed = 0
        for t in batch:
            if t._fail(err):
                failed += 1
            elif t.state == "cancelled":
                # cancelled while in flight, batch never fulfilled:
                # the cancel still counts (pop-time never saw it)
                telemetry.counter("serve/cancelled_total").inc()
        return failed

    def _trip_breaker(self, cause: BaseException) -> None:
        with self._cv:
            self._tripped = True
            self._trip_cause = cause
            self._gen += 1
            leftovers = list(self._pending)
            self._pending.clear()
            telemetry.gauge(self._g("serve/queue_depth_now")).set(0)
            self._cv.notify_all()
        now = time.perf_counter()
        self._settle_dropped([t for t in leftovers
                              if t.state == "cancelled"
                              or t.expired(now)])
        for t in leftovers:
            t._fail(ServiceUnhealthy(
                f"circuit breaker open after {self._restarts} dispatcher "
                f"restart(s): {cause}"))
        telemetry.gauge(self._g("serve/health_state")).set(HEALTH_UNHEALTHY)
        telemetry.gauge(self._g("serve/dispatcher_alive")).set(0)

    def _supervise_dispatch(self) -> None:
        """The serving twin of ``supervise/supervisor.py``: wait for the
        dispatcher to die (crash, or hang past ``hang_after_s`` on one
        batch), fail its in-flight tickets, and restart it under
        progress-reset bounded backoff; exhaustion trips the circuit
        breaker."""
        while True:
            worker = self._worker
            hung = False
            while True:
                worker.join(self._poll_s)
                if not worker.alive:
                    break
                busy = self._busy_since
                # lazy per-bucket compiles may legitimately hold one
                # batch for minutes (the supervisor.py startup-grace
                # shape) — judging them with the steady-state budget
                # would abandon a healthy dispatcher mid-compile and
                # walk the breaker.  Graced: the window before the
                # first fulfilled batch, and any batch whose bucket
                # executable is not materialized yet.
                cold = self._fulfilled == 0 or self._busy_cold
                budget = (max(self._hang_after_s or 0.0,
                              self._hang_startup_grace_s)
                          if cold else self._hang_after_s)
                if self._hang_after_s is not None and busy is not None \
                        and time.monotonic() - busy > budget:
                    hung = True
                    break
            if hung:
                err: BaseException = ServiceUnhealthy(
                    f"dispatcher hung: one batch busy for more than "
                    f"{self._hang_after_s:.0f}s — abandoning the thread")
            else:
                err = worker.error
                if err is None:
                    return           # clean exit: stop-drain completed
            with self._cv:
                # supersede the dead/hung generation BEFORE failing its
                # batch: a falsely-abandoned worker that wakes up later
                # retires at its next pop instead of double-dispatching
                self._gen += 1
                self._cv.notify_all()
            self._fail_inflight(err)
            with self._cv:
                # every other write of this pair goes through _cv (the
                # pop/_finish_batch paths); an unlocked reset here let
                # the hang detector sample a half-reset pair and
                # re-flag an already-abandoned worker as hung
                self._busy_since = None
                self._busy_cold = False
            telemetry.gauge(self._g("serve/dispatcher_alive")).set(0)
            # Progress resets the escalation (the supervisor.py shape):
            # a dispatcher that served batches between deaths restarts
            # eagerly forever; only BACK-TO-BACK no-progress deaths
            # count against the budget and escalate the backoff.  Every
            # death counts itself, so a zero budget means "never
            # restart".  Progress = FULFILLED batches — counting popped
            # batches would let a permanently-broken device reset the
            # breaker by crashing one dispatch attempt at a time.
            progress = self._fulfilled > self._fulfilled_at_restart
            self._deaths_in_row = 1 if progress \
                else self._deaths_in_row + 1
            self._fulfilled_at_restart = self._fulfilled
            if self._deaths_in_row > self._max_restarts:
                self._trip_breaker(err)
                return
            delay = min(self._backoff_max_s,
                        self._backoff_base_s
                        * (2 ** (self._deaths_in_row - 1)))
            deadline = time.monotonic() + delay
            while time.monotonic() < deadline:
                with self._cv:
                    if self._stop and not self._pending:
                        return   # nothing left to drain: stay down
                time.sleep(min(self._poll_s,
                               max(0.0, deadline - time.monotonic())))
            # counted HERE, after the trip check AND the stay-down
            # exit: a restart is a REPLACEMENT WORKER, nothing less
            # (under _cv: health() reads it from request threads, and
            # an unlocked += tears against them)
            with self._cv:
                self._restarts += 1
            telemetry.counter("serve/dispatcher_restarts_total").inc()
            self._worker = LoopWorker(self._serve_dispatch,
                                      "serve/dispatch").start()
            telemetry.gauge(self._g("serve/dispatcher_alive")).set(1)
            telemetry.gauge(self._g("serve/health_state")).set(HEALTH_DEGRADED)

    # -- consumer side (dispatcher thread) -----------------------------------

    def _select_bucket(self, n: int) -> int:
        """Smallest NON-QUARANTINED bucket ≥ n; the largest bucket is
        the route of last resort (never effectively quarantined)."""
        for b in self.programs.buckets:
            if b >= n and b not in self._quarantined:
                return b
        return self._max_bucket

    def _note_bucket_failure(self, bucket: int) -> None:
        # mutations under _cv: health() snapshots these sets from other
        # threads, and an unlocked add() mid-sorted() would crash the
        # liveness probe
        with self._cv:
            fails = self._bucket_fails.get(bucket, 0) + 1
            self._bucket_fails[bucket] = fails
            quarantine = (fails >= self._quarantine_after
                          and bucket != self._max_bucket
                          and bucket not in self._quarantined)
            if quarantine:
                self._quarantined.add(bucket)
        if quarantine:
            telemetry.counter("serve/bucket_quarantined_total").inc()

    def _pop_batch(self, gen: int) -> Optional[List[Ticket]]:
        """Up to max-bucket ADMISSIBLE queued tickets; None on shutdown
        or when this dispatcher generation was superseded.  Cancelled
        tickets are skipped (``serve/cancelled_total``) and expired
        ones resolved with ``Expired`` (``serve/expired_total``) HERE —
        before dispatch, so dead work is never padded into a bucket.
        After the first arrival, waits at most ``max_fill_wait_ms`` for
        the batch to fill — continuous batching, not fixed-size
        batching."""
        while True:
            with self._cv:
                while not self._pending and not self._stop and \
                        gen == self._gen:
                    self._cv.wait(0.25)
                if gen != self._gen:
                    return None            # superseded after a hang
                if not self._pending:
                    return None            # stopped and drained
                if self._fill_wait_s > 0 and \
                        len(self._pending) < self._max_bucket:
                    deadline = time.monotonic() + self._fill_wait_s
                    while len(self._pending) < self._max_bucket and \
                            not self._stop and gen == self._gen:
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        self._cv.wait(left)
                    if gen != self._gen:
                        return None
                depth = len(self._pending)
                batch: List[Ticket] = []
                dropped: List[Ticket] = []
                now = time.perf_counter()
                while self._pending and len(batch) < self._max_bucket:
                    t = self._pending.popleft()
                    if t.state == "cancelled" or t.expired(now):
                        dropped.append(t)
                    else:
                        batch.append(t)
                telemetry.histogram("serve/queue_depth").observe(depth)
                telemetry.gauge(self._g("serve/queue_depth_now")).set(
                    len(self._pending))
                if batch:
                    self._inflight = list(batch)
                    self._busy_since = time.monotonic()
            self._settle_dropped(dropped)
            if batch:
                rt = reqtrace.get_reqtracer()
                for t in batch:
                    rt.event(t.rid, "popped", depth=depth)
                return batch
            # everything popped was dead — go back to waiting

    def _finish_batch(self, gen: int) -> None:
        with self._cv:
            if gen == self._gen:
                self._inflight = []
                self._busy_since = None
                self._busy_cold = False

    def _serve_dispatch(self) -> None:
        """The dispatch hot loop (hot-loop-sync discipline: device
        fetches only inside ``span("serve_fetch")``)."""
        import jax

        programs, cache = self.programs, self.wcache
        rt = reqtrace.get_reqtracer()
        gen = self._gen
        label_dim = programs.bundle.cfg.model.label_dim
        while True:
            batch = self._pop_batch(gen)
            if batch is None:
                return
            self._worker.beat()
            t0 = time.perf_counter()
            # the bucket whose executable is CURRENTLY dispatching —
            # failure attribution for quarantine (map_misses points it
            # at the mapping bucket while that program runs)
            fail_bucket = None
            try:
                n = len(batch)
                self._batches += 1
                faults.fire("serve_dispatch", batch=self._batches, n=n)
                bucket = self._select_bucket(n)
                fail_bucket = bucket
                telemetry.histogram("serve/batch_fill").observe(n / bucket)
                for t in batch:
                    rt.event(t.rid, "batched", batch=self._batches,
                             bucket=bucket)
                rows: List[Optional[np.ndarray]] = [None] * n
                miss: List[int] = []
                for i, t in enumerate(batch):
                    row = cache.get(wcache_key(t.seed, t.label))
                    if row is None:
                        miss.append(i)
                    else:
                        rows[i] = row
                        rt.event(t.rid, "wcache_hit")
                # a batch that will pay a lazy cold compile gets the
                # hang watchdog's startup grace, not the steady budget
                cold = (
                    not programs.is_compiled("synthesize", bucket)
                    or bool(miss) and not programs.is_compiled(
                        "map_seeds", self._select_bucket(len(miss))))
                with self._cv:
                    # publish under _cv: the supervisor samples
                    # (_busy_since, _busy_cold) as a pair, and an
                    # unlocked write here could pair a fresh cold flag
                    # with the PREVIOUS batch's start time
                    self._busy_cold = cold
                psi = np.full((bucket,), 1.0, np.float32)
                psi[:n] = [t.psi for t in batch]
                # Noise identity rides the REQUEST (its seed), not the
                # batch counter: serve_synth folds tags[i] into the rng
                # per row, so an image is a pure function of
                # (seed, psi, noise_seed) no matter which batch,
                # replica, or restart served it — replica placement
                # must never enter the rng path (ISSUE 20; pinned by
                # the 1-vs-N determinism test).  Padding rows repeat
                # the last real tag, mirroring the ws padding.
                noise = np.array([self._noise_seed, 0], np.uint32)
                tags = np.full((bucket,), batch[-1].seed & 0xFFFFFFFF,
                               np.uint32)
                tags[:n] = [t.seed & 0xFFFFFFFF for t in batch]

                def map_misses():
                    nonlocal fail_bucket
                    faults.fire("serve_map", batch=self._batches,
                                n=len(miss))
                    mb = self._select_bucket(len(miss))
                    fail_bucket = mb
                    for i in miss:
                        rt.event(batch[i].rid, "map_dispatch", bucket=mb)
                    seeds = np.full((mb,), batch[miss[-1]].seed, np.int32)
                    seeds[:len(miss)] = [batch[i].seed for i in miss]
                    mlabel = None
                    if label_dim:
                        mlabel = np.zeros((mb, label_dim), np.float32)
                        for j, i in enumerate(miss):
                            mlabel[j] = batch[i].label
                    out = programs.map_seeds(seeds, mlabel)
                    fail_bucket = bucket   # mapping dispatched fine
                    return out

                def cache_fill(ws_host):
                    for j, i in enumerate(miss):
                        cache.put(wcache_key(batch[i].seed,
                                             batch[i].label), ws_host[j])

                if len(miss) == n:
                    # all-miss (the cold-seed traffic the first-image
                    # story cares about): ws stays ON DEVICE between
                    # the two programs — no host round-trip before
                    # synthesis; the cache fill rides a fetch that
                    # happens AFTER the synth dispatch, overlapping
                    # the copy with the synthesis compute.  miss
                    # bucket == synth bucket here (same n).
                    ws_dev = map_misses()
                    imgs_dev = programs.synthesize(ws_dev, psi, noise,
                                                   tags)
                    for t in batch:
                        rt.event(t.rid, "synth", bucket=bucket)
                    with span("serve_fetch"):
                        faults.fire("serve_fetch", batch=self._batches)
                        cache_fill(np.asarray(jax.device_get(ws_dev)))
                else:
                    if miss:
                        ws_dev = map_misses()
                        with span("serve_fetch"):
                            ws_miss = np.asarray(jax.device_get(ws_dev))
                        cache_fill(ws_miss)
                        for j, i in enumerate(miss):
                            rows[i] = ws_miss[j]
                    # pad to the synthesis bucket by repeating the last
                    # real row (row-independence keeps the prefix
                    # bit-identical)
                    ws = np.stack(rows + [rows[-1]] * (bucket - n))
                    imgs_dev = programs.synthesize(ws, psi, noise, tags)
                    for t in batch:
                        rt.event(t.rid, "synth", bucket=bucket)
                with span("serve_fetch"):
                    faults.fire("serve_fetch", batch=self._batches)
                    imgs = np.asarray(jax.device_get(imgs_dev))
                for t in batch:
                    rt.event(t.rid, "fetch")
                if gen != self._gen:
                    # superseded mid-batch (hang verdict): the
                    # supervisor already failed these tickets — don't
                    # count images nobody received
                    return
                faults.fire("serve_fulfill", batch=self._batches, n=n)
                delivered = 0
                for i, t in enumerate(batch):
                    if t._fulfill(imgs[i]):
                        delivered += 1
                    elif t.state == "cancelled":
                        # cancelled while in flight: computed but not
                        # delivered — count the cancel, not an image
                        telemetry.counter("serve/cancelled_total").inc()
                with self._cv:
                    # _fulfilled is the supervisor's progress signal
                    # and the watchdog's cold-start gate; keep the
                    # compound += under _cv with the rest of the batch
                    # bookkeeping so those readers never see a torn
                    # update
                    self._fulfilled += 1
                    # this batch proved both executables it used —
                    # reset their consecutive-failure counts
                    self._bucket_fails.pop(bucket, None)
                    if miss:
                        self._bucket_fails.pop(
                            self._select_bucket(len(miss)), None)
                telemetry.counter("serve/images_total").inc(delivered)
                batch_s = time.perf_counter() - t0
                telemetry.histogram("serve/batch_ms").observe(
                    batch_s * 1000.0, exemplar=batch[0].rid)
                if self.replica_id is not None:
                    # per-replica attribution (globals above keep
                    # moving — they are the fleet sums the schema lint
                    # and the doctor read)
                    telemetry.counter(
                        self._g("serve/images_total")).inc(delivered)
                    telemetry.histogram(
                        self._g("serve/batch_ms")).observe(batch_s * 1000.0)
                    telemetry.histogram(
                        self._g("serve/batch_fill")).observe(n / bucket)
                # the batch→requests causal link in events.jsonl
                rt.batch_span(self._batches, bucket,
                              [t.rid for t in batch], t0, batch_s)
                self._finish_batch(gen)
            except BaseException as e:
                # Attribution is exact for executables that raise at
                # call time (the observed poisoned-program mode); an
                # async device error surfacing at the later fetch is
                # charged to the synthesis bucket.  A SUPERSEDED worker
                # (abandoned as hung, then woke into an error) charges
                # nothing — its verdict belongs to a dead generation.
                if fail_bucket is not None and gen == self._gen:
                    self._note_bucket_failure(fail_bucket)
                for t in batch:
                    if not t._fail(e) and t.state == "cancelled":
                        # as in _fail_inflight: an in-flight cancel on
                        # a failed batch still counts
                        telemetry.counter("serve/cancelled_total").inc()
                self._finish_batch(gen)
                raise   # LoopWorker stores it; the supervisor restarts
