"""Continuous-batching generation service — the request front end.

One dispatcher thread (``utils/background.LoopWorker``) runs
``_serve_dispatch``: pop whatever is queued (up to the largest compiled
bucket, waiting ``max_fill_wait_ms`` after the first arrival to improve
fill), resolve each request's w row — LRU cache hit or a bucketed
``map_seeds`` dispatch for the misses — pad to the next bucket, run the
ψ-vectorized synthesis executable, fetch, slice, fulfill tickets.  An
all-miss batch (cold-seed traffic) keeps ws ON DEVICE between the two
programs — the cache-fill fetch rides after the synthesis dispatch, so
the host copy overlaps the synth compute instead of serializing
map → host → synth.
Continuous batching: the queue drains whenever the device is free; a
batch is NEVER held for stragglers beyond the fill wait, and oversize
backlogs chunk at the max bucket per iteration.

The dispatch loop is under the ``hot-loop-sync`` lint discipline
(analysis/rules/hot_loop.py): the only host syncs in the ``while`` body
live inside ``with span("serve_fetch")`` — the serving twin of the
train loop's ``tick_fetch`` contract, so a future edit that sneaks a
hidden ``block_until_ready`` into the dispatch path fails tier-1.

SLO telemetry (obs/registry → ``telemetry.prom``):
``serve/queue_depth`` histogram+gauge, ``serve/batch_fill`` histogram
(rows/bucket), ``serve/e2e_ms`` histogram (submit→ready),
``serve/batch_ms`` histogram (dispatch+fetch), counters
``serve/requests_total`` / ``serve/images_total`` /
``serve/map_dispatch_total`` / ``serve/synth_dispatch_total`` and the
w-cache pair, plus the LoopWorker's ``serve/dispatch_heartbeat``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

import numpy as np

from gansformer_tpu.obs import registry as telemetry
from gansformer_tpu.obs.spans import span
from gansformer_tpu.serve.cache import WCache, wcache_key
from gansformer_tpu.serve.programs import ServePrograms, bucket_for
from gansformer_tpu.utils.background import LoopWorker


class Ticket:
    """One submitted request; ``result()`` blocks until fulfilled."""

    __slots__ = ("seed", "psi", "label", "t_submit", "t_done",
                 "_event", "_image", "_error")

    def __init__(self, seed: int, psi: float, label):
        self.seed = int(seed)
        self.psi = float(psi)
        self.label = label
        self.t_submit = time.perf_counter()
        self.t_done: Optional[float] = None
        self._event = threading.Event()
        self._image: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None

    def _fulfill(self, image: np.ndarray) -> None:
        self._image = image
        self.t_done = time.perf_counter()
        telemetry.histogram("serve/e2e_ms").observe(
            (self.t_done - self.t_submit) * 1000.0)
        self._event.set()

    def _fail(self, err: BaseException) -> None:
        self._error = err
        self.t_done = time.perf_counter()
        self._event.set()

    @property
    def latency_ms(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1000.0

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request (seed={self.seed}) not served in {timeout}s")
        if self._error is not None:
            raise RuntimeError("generation request failed") from self._error
        return self._image


class GenerationService:
    """Front a ``ServePrograms`` with a continuous-batching queue."""

    def __init__(self, programs: ServePrograms,
                 max_fill_wait_ms: float = 2.0,
                 wcache_capacity: int = 4096,
                 noise_seed: int = 0):
        self.programs = programs
        self._max_bucket = programs.buckets[-1]
        self._fill_wait_s = max(0.0, max_fill_wait_ms) / 1000.0
        self.wcache = WCache(wcache_capacity)
        self._noise_seed = int(noise_seed)
        self._batches = 0
        self._pending: "deque[Ticket]" = deque()
        self._cv = threading.Condition()
        self._stop = False
        # materialize every SLO family up front so an idle (or
        # all-hit / all-miss) service still exports explicit zeros —
        # the serve-family schema lint reads absence as rotted wiring
        for name in ("serve/queue_depth", "serve/batch_fill",
                     "serve/e2e_ms", "serve/batch_ms"):
            telemetry.histogram(name)
        for name in ("serve/requests_total", "serve/images_total"):
            telemetry.counter(name)
        self._worker = LoopWorker(self._serve_dispatch,
                                  "serve/dispatch").start()

    # -- producer side -------------------------------------------------------

    def submit(self, seed: int, psi: float = 0.7, label=None) -> Ticket:
        self._worker.poll()            # surface a dead dispatcher HERE
        t = Ticket(seed, psi, label)
        with self._cv:
            if self._stop:
                raise RuntimeError("service is closed")
            self._pending.append(t)
            telemetry.gauge("serve/queue_depth_now").set(len(self._pending))
            self._cv.notify()
        telemetry.counter("serve/requests_total").inc()
        return t

    def close(self, timeout: float = 30.0) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._worker.join(timeout)
        with self._cv:
            leftovers = list(self._pending)
            self._pending.clear()
        for t in leftovers:
            t._fail(RuntimeError("service closed with request queued"))

    def __enter__(self) -> "GenerationService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- consumer side (dispatcher thread) -----------------------------------

    def _pop_batch(self) -> Optional[List[Ticket]]:
        """Up to max-bucket queued tickets; None on shutdown.  After the
        first arrival, waits at most ``max_fill_wait_ms`` for the batch
        to fill — continuous batching, not fixed-size batching."""
        with self._cv:
            while not self._pending and not self._stop:
                self._cv.wait(0.25)
            if not self._pending:
                return None            # stopped and drained
            if self._fill_wait_s > 0 and \
                    len(self._pending) < self._max_bucket:
                deadline = time.monotonic() + self._fill_wait_s
                while len(self._pending) < self._max_bucket and \
                        not self._stop:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cv.wait(left)
            depth = len(self._pending)
            take = min(depth, self._max_bucket)
            batch = [self._pending.popleft() for _ in range(take)]
            telemetry.histogram("serve/queue_depth").observe(depth)
            telemetry.gauge("serve/queue_depth_now").set(len(self._pending))
        return batch

    def _serve_dispatch(self) -> None:
        """The dispatch hot loop (hot-loop-sync discipline: device
        fetches only inside ``span("serve_fetch")``)."""
        import jax

        programs, cache = self.programs, self.wcache
        buckets = programs.buckets
        label_dim = programs.bundle.cfg.model.label_dim
        while True:
            batch = self._pop_batch()
            if batch is None:
                return
            self._worker.beat()
            t0 = time.perf_counter()
            try:
                n = len(batch)
                bucket = bucket_for(n, buckets)
                telemetry.histogram("serve/batch_fill").observe(n / bucket)
                rows: List[Optional[np.ndarray]] = [None] * n
                miss: List[int] = []
                for i, t in enumerate(batch):
                    row = cache.get(wcache_key(t.seed, t.label))
                    if row is None:
                        miss.append(i)
                    else:
                        rows[i] = row
                psi = np.full((bucket,), 1.0, np.float32)
                psi[:n] = [t.psi for t in batch]
                self._batches += 1
                noise = np.array([self._noise_seed, self._batches],
                                 np.uint32)

                def map_misses():
                    mb = bucket_for(len(miss), buckets)
                    seeds = np.full((mb,), batch[miss[-1]].seed, np.int32)
                    seeds[:len(miss)] = [batch[i].seed for i in miss]
                    mlabel = None
                    if label_dim:
                        mlabel = np.zeros((mb, label_dim), np.float32)
                        for j, i in enumerate(miss):
                            mlabel[j] = batch[i].label
                    return programs.map_seeds(seeds, mlabel)

                def cache_fill(ws_host):
                    for j, i in enumerate(miss):
                        cache.put(wcache_key(batch[i].seed,
                                             batch[i].label), ws_host[j])

                if len(miss) == n:
                    # all-miss (the cold-seed traffic the first-image
                    # story cares about): ws stays ON DEVICE between
                    # the two programs — no host round-trip before
                    # synthesis; the cache fill rides a fetch that
                    # happens AFTER the synth dispatch, overlapping
                    # the copy with the synthesis compute.  miss
                    # bucket == synth bucket here (same n).
                    ws_dev = map_misses()
                    imgs_dev = programs.synthesize(ws_dev, psi, noise)
                    with span("serve_fetch"):
                        cache_fill(np.asarray(jax.device_get(ws_dev)))
                else:
                    if miss:
                        ws_dev = map_misses()
                        with span("serve_fetch"):
                            ws_miss = np.asarray(jax.device_get(ws_dev))
                        cache_fill(ws_miss)
                        for j, i in enumerate(miss):
                            rows[i] = ws_miss[j]
                    # pad to the synthesis bucket by repeating the last
                    # real row (row-independence keeps the prefix
                    # bit-identical)
                    ws = np.stack(rows + [rows[-1]] * (bucket - n))
                    imgs_dev = programs.synthesize(ws, psi, noise)
                with span("serve_fetch"):
                    imgs = np.asarray(jax.device_get(imgs_dev))
                for i, t in enumerate(batch):
                    t._fulfill(imgs[i])
                telemetry.counter("serve/images_total").inc(n)
                telemetry.histogram("serve/batch_ms").observe(
                    (time.perf_counter() - t0) * 1000.0)
            except BaseException as e:
                for t in batch:
                    t._fail(e)
                raise   # sticky on the LoopWorker; submitters see poll()
