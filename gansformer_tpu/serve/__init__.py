"""Serving subsystem — AOT-compiled generation as a service (ISSUE 10).

Four layers, bottom-up:

* ``warmstart`` — serialized-executable manifest next to the persistent
  XLA compile cache: a cold process deserializes instead of compiling.
* ``programs``  — the generator split at the mapping/synthesis boundary
  (``map_seeds`` / ``map_z`` / ``synthesize``, ψ traced per-row) AOT-
  compiled per batch bucket, plus the G-only checkpoint surface
  (``load_generator`` — no discriminator, no optimizer state).
* ``cache``     — LRU w-cache keyed by (seed, label): repeat /
  interpolation / style-mix traffic skips the mapping network.
* ``service``   — continuous-batching request queue + dispatcher thread
  with queue-depth / batch-fill / latency SLO telemetry, under the
  ISSUE 13 robustness floor: bounded admission (``Overloaded``),
  per-request deadlines (``Expired``), client-cancel (``Cancelled``),
  supervised dispatcher restart with a circuit breaker
  (``ServiceUnhealthy``), bucket quarantine, ``health()`` states, and
  graceful drain (``ServiceClosed``).
* ``quant``     — the ``serve_precision`` axis (f32 | bf16 | int8w):
  int8 weight-only quantization with per-output-channel scales, plus
  the cost/fidelity A/B reports (ISSUE 20).
* ``replicas``  — replica-per-device placement (``ReplicaSet``):
  least-loaded routing across device-pinned members, fleet health,
  and the optional autoscaler controller (ISSUE 20).

``cli/serve.py`` (``gansformer-serve``) and
``scripts/loadtest_serve.py`` sit on top; ``docs/serving.md`` is the
operator guide.
"""

from gansformer_tpu.serve.cache import WCache, wcache_key  # noqa: F401
from gansformer_tpu.serve.programs import (  # noqa: F401
    DEFAULT_BUCKETS, SERVE_PRECISIONS, GeneratorBundle, ServePrograms,
    bucket_for, generator_fns, init_generator, load_generator)
from gansformer_tpu.serve.quant import (  # noqa: F401
    FIDELITY_TOLERANCES, cost_report, fidelity_report, quantize_params)
from gansformer_tpu.serve.replicas import Replica, ReplicaSet  # noqa: F401
from gansformer_tpu.serve.service import (  # noqa: F401
    Cancelled, Expired, GenerationService, Overloaded, ServeError,
    ServiceClosed, ServiceUnhealthy, Ticket)
from gansformer_tpu.serve.warmstart import (  # noqa: F401
    default_manifest_dir)
