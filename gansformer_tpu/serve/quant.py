"""int8 weight-only quantization for the serving synth program
(``serve_precision='int8w'``, ISSUE 20).

Scheme — the weight-only recipe the serving-throughput literature
converged on (weights are the bandwidth, activations are the accuracy):

* Every equalized-LR kernel (the ``"w"`` params of EqualDense /
  EqualConv / ModulatedConv — ndim 2 or 4) is stored as int8 codes plus
  a **per-output-channel** fp32 scale over the LAST axis:
  ``scale_c = max|w[..., c]| / 127``, ``q = round(w / scale)``.
  Per-channel (not per-tensor) because the equalized-LR parametrization
  keeps channels at unit variance only in expectation — individual
  output channels drift an order of magnitude apart during training,
  and a per-tensor scale would burn most of the 8-bit range on the
  loudest channel.
* Everything else (biases, ``noise_strength``, the attention tables
  ``pos_emb``/``d_queries``, the learned ``const`` input, gates) stays
  fp32: these are O(channels) not O(channels²) — quantizing them saves
  nothing and costs fidelity.
* Dequantization happens in ``ops.resolve_weight`` — the kernel-prep
  seam every equalized-LR layer already routes through — as an fp32
  island (``int8w-dequant`` in ``analysis/numerics/contracts.py``), so
  the XLA composites and the Pallas modconv kernels both consume the
  same dequantized weights with no per-backend code.

Scales are recomputed **deterministically at bundle load** (pure
numpy, no rng), so two replicas — or a cold restart — always derive
bit-identical quantized trees from the same checkpoint; only the
compiled executables ride the warm-start manifest, fingerprinted with
``serve_precision`` so an int8w blob can never warm-start a f32
service (serve/warmstart.py).

The A/B half (`cost_report`, `fidelity_report`) measures what the
quantization bought and what it cost: AOT ``memory_analysis`` /
``cost_analysis`` deltas per image, and output error against the f32
reference at the declared tolerances below.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

# Declared fidelity tolerances per serve_precision: max |out - ref|
# normalized by the f32 reference's dynamic range (max|ref|), over the
# bucketed-parity fixtures.  f32 is the reference (exact); bf16 loses
# activation mantissa only (weights and the declared islands stay f32);
# int8w adds ~0.4% per-weight rounding error that accumulates through
# the synthesis depth.  Exceeding these is a regression, not noise —
# they carry 2-3x headroom over measured tiny-config error.
FIDELITY_TOLERANCES: Dict[str, float] = {
    "f32": 0.0,
    "bf16": 0.05,
    "int8w": 0.20,
}


def _leaf_name(path) -> str:
    last = path[-1]
    return str(getattr(last, "key", getattr(last, "name", last)))


def is_kernel(path, leaf) -> bool:
    """The quantization predicate: exactly the equalized-LR kernels.
    All three layer classes name their kernel ``"w"`` with ndim 2
    (dense [fan_in, out]) or 4 (conv [kh, kw, cin, cout]); everything
    else under that name check — ``b``, ``pos_emb``, ``d_queries``,
    ``const``, gates, ``noise_strength`` — fails one of the two
    conditions."""
    return _leaf_name(path) == "w" and getattr(leaf, "ndim", 0) in (2, 4)


def quantize_leaf(w: np.ndarray):
    """One kernel → QuantizedWeight(q int8 same-shape, scale fp32
    per-output-channel over the last axis, keepdims)."""
    from gansformer_tpu.ops import QuantizedWeight

    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)), keepdims=True)
    scale = (amax / 127.0).astype(np.float32)
    # all-zero channels (possible at init): scale 1 keeps dequant exact
    scale = np.where(scale > 0.0, scale, np.float32(1.0)).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return QuantizedWeight(q, scale)


def quantize_params(params: Any) -> Any:
    """The full params tree with every equalized-LR kernel replaced by
    a ``QuantizedWeight`` leaf.  Deterministic (pure numpy) — replicas
    quantizing the same checkpoint agree bit-for-bit."""
    import jax

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: (quantize_leaf(leaf) if is_kernel(path, leaf)
                            else leaf),
        params)


def param_tree_bytes(params: Any) -> int:
    """Host-side truth: total bytes of the params-tree leaves (a
    QuantizedWeight contributes its int8 codes plus its fp32 scales)."""
    import jax

    return int(sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
                   for l in jax.tree_util.tree_leaves(params)))


# -- A/B reports -------------------------------------------------------------

def _synth_compiled(bundle, precision: str, bucket: int):
    from gansformer_tpu.serve.programs import ServePrograms

    p = ServePrograms(bundle, buckets=(bucket,), manifest_dir=None,
                      warm_start=False, serve_precision=precision)
    return p, p._get("synthesize", bucket)


def _memory_stats(compiled) -> Dict[str, Optional[float]]:
    try:
        ma = compiled.memory_analysis()
        return {"argument_bytes": float(ma.argument_size_in_bytes),
                "output_bytes": float(ma.output_size_in_bytes),
                "temp_bytes": float(ma.temp_size_in_bytes)}
    except Exception:
        return {"argument_bytes": None, "output_bytes": None,
                "temp_bytes": None}


def cost_report(bundle, bucket: int = 4,
                precisions: Sequence[str] = ("f32", "bf16", "int8w")
                ) -> Dict[str, Any]:
    """AOT cost A/B across the precision axis at one bucket: FLOPs and
    bytes per image from the compiled executables (deterministic on
    CPU — XLA cost analysis over the partitioned module, no runtime
    sampling), plus the host-side params-tree bytes.

    ``param_bytes_per_image`` reads the compiled ARGUMENT bytes: jax
    DCEs unused flat inputs at trace time, so the synth executable's
    argument set is exactly the synthesis-reachable params plus the
    O(bucket) request rows — the bytes a weight-stationary serving
    floor actually holds per replica.
    """
    from gansformer_tpu.utils.benchcheck import flops_of

    out: Dict[str, Any] = {"bucket": int(bucket), "per_precision": {}}
    for prec in precisions:
        p, compiled = _synth_compiled(bundle, prec, bucket)
        mem = _memory_stats(compiled)
        flops = flops_of(compiled)
        arg_b = mem["argument_bytes"]
        # request-row bytes (w_avg, ws, psi, rng, tags — everything
        # that is NOT weights) come off the top: the headline is
        # PARAMETER bytes, the weight traffic a replica re-reads per
        # dispatched image
        req_b = sum(int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
                    for a in p._abstract_args("synthesize", bucket)[1:])
        param_b = (arg_b - req_b) if arg_b else None
        rec = {
            "flops_per_image": (flops / bucket) if flops else None,
            "argument_bytes": arg_b,
            "request_bytes": float(req_b),
            "param_bytes_per_image":
                (param_b / bucket) if param_b else None,
            "output_bytes_per_image":
                (mem["output_bytes"] / bucket) if mem["output_bytes"]
                else None,
            "temp_bytes": mem["temp_bytes"],
            "params_tree_bytes": param_tree_bytes(p._synth_params),
        }
        out["per_precision"][prec] = rec
    f32 = out["per_precision"].get("f32", {})
    for prec in precisions:
        if prec == "f32":
            continue
        rec = out["per_precision"][prec]
        for num, den, key in (
                (f32.get("param_bytes_per_image"),
                 rec.get("param_bytes_per_image"), "param_bytes_ratio"),
                (f32.get("params_tree_bytes"),
                 rec.get("params_tree_bytes"), "tree_bytes_ratio"),
                (f32.get("flops_per_image"),
                 rec.get("flops_per_image"), "flops_ratio")):
            rec[f"{key}_vs_f32"] = (num / den) if num and den else None
    return out


def fidelity_report(bundle, precision: str, bucket: int = 4,
                    seeds: Optional[Sequence[int]] = None,
                    psi: float = 0.7,
                    tolerance: Optional[float] = None) -> Dict[str, Any]:
    """Output error of a precision variant against the f32 reference on
    the bucketed-parity fixtures: both programs synthesize the SAME
    cached w rows (mapping always runs f32), same ψ, same noise tags —
    the only delta is the synth program's precision.  ``rel_err`` is
    max |out - ref| / max|ref|; ``ok`` grades it against the declared
    tolerance."""
    if tolerance is None:
        tolerance = FIDELITY_TOLERANCES[precision]
    if seeds is None:
        seeds = list(range(1, bucket + 1))
    seeds = np.asarray(seeds, np.int32)
    if len(seeds) != bucket:
        raise ValueError(f"need exactly {bucket} seeds, got {len(seeds)}")
    ref_p, _ = _synth_compiled(bundle, "f32", bucket)
    var_p, _ = _synth_compiled(bundle, precision, bucket)
    ws = np.asarray(ref_p.map_seeds(seeds))
    psis = np.full((bucket,), psi, np.float32)
    rng = np.array([7, 11], np.uint32)
    tags = seeds.astype(np.uint32)
    ref = np.asarray(ref_p.synthesize(ws, psis, rng, tags),
                     np.float32)
    out = np.asarray(var_p.synthesize(ws, psis, rng, tags),
                     np.float32)
    denom = float(np.max(np.abs(ref))) or 1.0
    abs_err = float(np.max(np.abs(out - ref)))
    rel_err = abs_err / denom
    return {
        "precision": precision,
        "bucket": int(bucket),
        "psi": float(psi),
        "max_abs_err": abs_err,
        "ref_dynamic_range": denom,
        "rel_err": rel_err,
        "tolerance": float(tolerance),
        "ok": bool(rel_err <= tolerance),
    }
