"""LRU w-cache — mapped latents by content address.

``serve_map_seeds`` makes z_i a pure function of (seed_i, label_i), so
the mapping output ``ws`` row is fully determined by that pair: the
request key IS the content address.  Caching the POST-mapping,
PRE-truncation row means

* repeat-seed traffic skips the mapping network entirely (the
  acceptance counter: ``serve/map_dispatch_total`` stays flat on the
  hit path);
* every ψ reuses the same cached row — truncation lives in the
  synthesis program (``serve/programs.py``), so a popular seed served
  at ψ=0.5 and ψ=1.0 is ONE mapping;
* interpolation / style-mix endpoints resolve from the cache too (they
  are w-space operations over already-mapped rows).

Rows are small host arrays ([num_ws, w_dim] f32 — ~35 KB at the
flagship width), so the default 4096-entry capacity is ~140 MB of host
RAM, nothing near HBM.  Telemetry: ``serve/wcache_hits_total``,
``serve/wcache_misses_total``, ``serve/wcache_size`` gauge.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

from gansformer_tpu.obs import registry as telemetry


def wcache_key(seed: int, label: Optional[np.ndarray]) -> Tuple:
    """(seed, label-bytes) — the content address of one mapped row."""
    if label is None:
        return (int(seed), None)
    return (int(seed), np.ascontiguousarray(label, np.float32).tobytes())


class WCache:
    """Thread-safe LRU of mapped-latent rows."""

    def __init__(self, capacity: int = 4096):
        if capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._rows: "OrderedDict[Tuple, np.ndarray]" = OrderedDict()
        # materialize the family at construction (the compile-listener
        # explicit-zero pattern): all-miss or idle traffic must still
        # export serve_wcache_hits_total 0, or the schema lint can't
        # tell "no hits yet" from "the wiring rotted"
        telemetry.counter("serve/wcache_hits_total")
        telemetry.counter("serve/wcache_misses_total")
        telemetry.gauge("serve/wcache_size").set(0)

    def get(self, key: Tuple) -> Optional[np.ndarray]:
        with self._lock:
            row = self._rows.get(key)
            if row is not None:
                self._rows.move_to_end(key)
        telemetry.counter("serve/wcache_hits_total" if row is not None
                          else "serve/wcache_misses_total").inc()
        return row

    def put(self, key: Tuple, row: np.ndarray) -> None:
        if self.capacity == 0:
            return
        with self._lock:
            self._rows[key] = row
            self._rows.move_to_end(key)
            while len(self._rows) > self.capacity:
                self._rows.popitem(last=False)
            telemetry.gauge("serve/wcache_size").set(len(self._rows))

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)
