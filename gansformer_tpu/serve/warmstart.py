"""Serialized-executable warm-start manifest — seconds, not minutes.

The persistent XLA compile cache (``utils/hostenv.enable_compile_cache``)
already turns a *re-compile* into a disk hit, but a cold serving process
still pays tracing + lowering + cache lookup per program — and a cache
miss (new jaxlib, evicted entry) silently costs the full 30–100 s
compile (BENCH_TPU_MEASURED ``compile_s``).  This module removes the
guesswork: every AOT-compiled serving executable is **serialized to
disk** (``jax.experimental.serialize_executable``) next to an explicit
``manifest.json`` that records exactly what the bytes are valid for —
jax version, backend platform/device kind/count, model architecture,
program kind, and batch bucket.  A warm process start is then

    load manifest → fingerprint match → deserialize → serve

with ZERO compiles (asserted by the warm-start regression test via the
``compile/compiles_total`` registry counter).  Any mismatch — stale
fingerprint, torn file, checksum drift, deserialization error — falls
back to recompile-and-rewrite instead of crashing: the manifest is an
accelerator, never a correctness dependency.

One sharp edge, handled in ``ServePrograms._compile``: an executable
that was an XLA *disk-cache hit* serializes into a blob that later
fails to deserialize ("Symbols not found" — the cached binary refers to
runtime-generated symbols of the process that wrote it), so compiles
destined for this manifest run with the persistent XLA cache disabled.
The manifest supersedes the disk cache for serving; the disk cache
still accelerates every non-serving entry point.

Layout (``manifest_dir``, default ``.jax_compile_cache/serve/``)::

    manifest.json                     {"version": 1, "entries": {key: …}}
    <key>.bin                         pickle of (payload, in_tree, out_tree)

Manifest entry::

    {"file": "<key>.bin", "sha256": "…", "fingerprint": "…",
     "jax": "0.4.37", "platform": "cpu", "device_kind": "…",
     "n_devices": 1, "written_at": 1700000000.0}

Telemetry: ``serve/warm_hits_total`` (deserialized loads),
``serve/manifest_stale_total`` (entries rejected — the fallback path),
``serve/executables_saved_total``.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from typing import Any, Dict, Optional

from gansformer_tpu.obs import registry as telemetry
from gansformer_tpu.obs.registry import atomic_write_text

MANIFEST = "manifest.json"
# 2: serve_synth takes per-row noise tags (replica-count-independent
# noise; ISSUE 20) and the fingerprint carries serve_precision +
# device_ordinal — protocol-1 manifests deserialize fine but would hand
# back executables with the OLD call signature, so they must read as
# stale, not as warm hits.
PROTOCOL = 2


def backend_signature() -> Dict[str, Any]:
    """What an executable's bytes are pinned to: the exact runtime."""
    import jax

    devs = jax.devices()
    return {
        "jax": jax.__version__,
        "platform": devs[0].platform,
        "device_kind": devs[0].device_kind,
        "n_devices": len(devs),
        "protocol": PROTOCOL,
    }


def fingerprint(model_cfg_json: str, kind: str, bucket: int,
                serve_precision: str = "f32",
                device_ordinal: int = 0) -> str:
    """Content hash of everything that determines the compiled program:
    the model architecture (full ModelConfig JSON — resolution, dtype,
    attention flavor, attention_backend AND conv_backend, …), the
    program kind, the batch bucket, the serving precision
    (f32|bf16|int8w — an int8w executable takes a quantized params
    signature a f32 service cannot feed), the device ordinal the
    replica's programs are pinned to (ISSUE 20: executables carry their
    device placement through serialization), and the backend signature.
    Two processes agree on the fingerprint iff the serialized
    executable is valid for both — in particular a manifest written
    under ``conv_backend='pallas'`` can never warm-start an xla-conv
    service (or vice versa): mixed-kernel executables are rejected as
    stale, never silently served (ISSUE 14; pinned by
    tests/test_pallas_conv)."""
    payload = json.dumps({"model": json.loads(model_cfg_json),
                          "kind": kind, "bucket": bucket,
                          "serve_precision": serve_precision,
                          "device_ordinal": int(device_ordinal),
                          **backend_signature()}, sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()


def _load_manifest(manifest_dir: str) -> Dict[str, Any]:
    path = os.path.join(manifest_dir, MANIFEST)
    if not os.path.exists(path):
        return {"version": 1, "entries": {}}
    try:
        with open(path) as f:
            data = json.load(f)
        if data.get("version") != 1 or not isinstance(
                data.get("entries"), dict):
            raise ValueError("bad manifest shape")
        return data
    except (ValueError, OSError):
        # torn/corrupt manifest: start over — the .bin files it pointed
        # at are re-validated by checksum on every load anyway
        telemetry.counter("serve/manifest_stale_total").inc()
        return {"version": 1, "entries": {}}


def _sha256(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()


def save_executable(manifest_dir: str, key: str, compiled: Any,
                    fp: str) -> bool:
    """Serialize ``compiled`` under ``key`` and record it in the
    manifest (atomic read-modify-replace).  Returns False — and leaves
    the manifest untouched — when the runtime can't serialize
    executables OR the serialized blob fails to load back (e.g. the
    executable was an XLA disk-cache hit, whose blob references symbols
    of the writing runtime — "Symbols not found" at deserialize);
    serving continues, only warm start is lost.  The verify pass means
    the manifest NEVER records bytes the writing process itself cannot
    load — a corrupted warm start is caught at pre-bake time, not on
    the serving floor (counted in ``serve/save_verify_failed_total``)."""
    try:
        from jax.experimental import serialize_executable as se

        payload, in_tree, out_tree = se.serialize(compiled)
        blob = pickle.dumps((payload, in_tree, out_tree))
        se.deserialize_and_load(*pickle.loads(blob))
    except Exception:
        telemetry.counter("serve/save_verify_failed_total").inc()
        return False
    os.makedirs(manifest_dir, exist_ok=True)
    fname = f"{key}.bin"
    tmp = os.path.join(manifest_dir, f".{fname}.tmp{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, os.path.join(manifest_dir, fname))
    manifest = _load_manifest(manifest_dir)
    manifest["entries"][key] = {
        "file": fname, "sha256": _sha256(blob), "fingerprint": fp,
        **backend_signature(), "written_at": time.time()}
    atomic_write_text(os.path.join(manifest_dir, MANIFEST),
                      json.dumps(manifest, indent=1, sort_keys=True))
    telemetry.counter("serve/executables_saved_total").inc()
    return True


def load_executable(manifest_dir: str, key: str, fp: str) -> Optional[Any]:
    """Deserialize the executable recorded under ``key`` iff its
    manifest entry matches ``fp`` and its bytes match the recorded
    checksum.  EVERY failure mode — missing entry, stale fingerprint,
    checksum drift, unpickle/deserialize error — returns None (counted
    in ``serve/manifest_stale_total`` when an entry existed but was
    unusable): the caller recompiles and overwrites."""
    entry = _load_manifest(manifest_dir)["entries"].get(key)
    if entry is None:
        return None
    try:
        if entry.get("fingerprint") != fp:
            raise ValueError("stale fingerprint")
        path = os.path.join(manifest_dir, entry["file"])
        with open(path, "rb") as f:
            blob = f.read()
        if _sha256(blob) != entry.get("sha256"):
            raise ValueError("checksum mismatch")
        from jax.experimental import serialize_executable as se

        payload, in_tree, out_tree = pickle.loads(blob)
        compiled = se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception:
        telemetry.counter("serve/manifest_stale_total").inc()
        return None
    telemetry.counter("serve/warm_hits_total").inc()
    return compiled


def default_manifest_dir(repo_root: Optional[str] = None) -> str:
    """Rides next to the persistent XLA compile cache — the two layers
    of the same warm-start story share a parent dir."""
    from gansformer_tpu.utils.hostenv import compile_cache_env

    env = compile_cache_env(repo_root)
    return os.path.join(env["JAX_COMPILATION_CACHE_DIR"], "serve")
