"""Replica-per-device serving: placement, routing, autoscaled dispatch.

The single-dispatcher ``GenerationService`` saturates exactly one
accelerator; every additional chip on the host idles.  This module
scales the serving floor *across* local devices the way the TPU serving
fleets do — one full replica per chip, not one sharded model:

* **Placement** — each ``Replica`` owns a ``ServePrograms`` pinned to
  one ``jax.local_devices()`` entry: the params bundle is ``device_put``
  onto that device and every AOT executable is compiled against
  ``SingleDeviceSharding`` abstract args, so dispatch never migrates
  data through device 0.  Warm-start manifests are fingerprinted with
  the device ordinal (serve/warmstart.py), so replica 3's serialized
  executables can never warm-start replica 0.
* **Routing** — ``submit`` assigns each request to the least-loaded
  *accepting* replica (queued + in-flight tickets, ``service.load()``).
  A replica whose breaker tripped or that is draining stops accepting
  and the router walks past it; its queue-compaction/quarantine
  machinery is untouched — per-replica failure containment composes
  with fleet routing instead of replacing it.
* **Autoscaling** — an optional controller thread samples fleet
  saturation every tick and scales OUT on sustained queue pressure
  (before any breaker trips — saturation is a leading indicator,
  breaker trips a trailing one) and IN on batch-fill collapse with an
  empty queue, under hysteresis (consecutive-tick counts + cooldown)
  and ``min_replicas``/``max_replicas`` bounds.  Deactivated replicas
  drain cleanly; their compiled ``ServePrograms`` stay cached so
  reactivation pays zero compiles.

Determinism contract: replica placement NEVER enters the rng path.
``serve_synth`` derives per-row noise from the request seed (the tags
row), the w rows are pure functions of the seed, so the same request
stream produces bit-identical images through 1 or N replicas (pinned by
tests/test_serve_replicas.py).

Telemetry (fleet level — members export ``serve/replica<i>/...``):
``serve/replicas`` (active count), ``serve/health_state`` /
``serve/dispatcher_alive`` (any-alive) / ``serve/queue_depth_now`` (sum)
/ ``serve/queue_bound`` (sum), counters ``serve/scale_out_total`` /
``serve/scale_in_total``, and router-side ``serve/replica<i>/requests_total``
(dispatch share).  Scale/breaker events carry timestamps in
``ReplicaSet.events`` so the chaos drill can assert scale-out fired
*before* the first breaker trip.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from gansformer_tpu.obs import registry as telemetry
from gansformer_tpu.serve.programs import (
    DEFAULT_BUCKETS, SERVE_PRECISIONS, ServePrograms)
from gansformer_tpu.serve.service import (
    HEALTH_CLOSED, HEALTH_UNHEALTHY, _HEALTH_NAMES,
    GenerationService, ServiceClosed, ServiceUnhealthy, Ticket)


class Replica:
    """One device-pinned serving member: ordinal + device + programs +
    (possibly recreated) service.  ``programs`` survives deactivation —
    the compiled executables are the expensive part."""

    def __init__(self, ordinal: int, device: Any,
                 programs: ServePrograms) -> None:
        self.ordinal = int(ordinal)
        self.device = device
        self.programs = programs
        self.service: Optional[GenerationService] = None

    @property
    def active(self) -> bool:
        return self.service is not None


class ReplicaSet:
    """The fleet: replica-per-device placement + least-loaded routing +
    optional autoscaler.  Drop-in supersedes a bare GenerationService
    for the serving entry points (same ``submit``/``health``/``close``
    verbs; ``cli/serve.py`` and ``scripts/loadtest_serve.py`` ride it).
    """

    def __init__(self, bundle: Any,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 manifest_dir: Optional[str] = None,
                 warm_start: bool = True,
                 serve_precision: str = "f32",
                 replicas: Optional[int] = None,
                 min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 autoscale: bool = False,
                 autoscale_interval_s: float = 0.25,
                 scale_out_saturation: float = 0.8,
                 scale_out_ticks: int = 3,
                 scale_in_fill: float = 0.25,
                 scale_in_ticks: int = 8,
                 cooldown_s: float = 2.0,
                 service_kwargs: Optional[Dict[str, Any]] = None) -> None:
        import jax

        if serve_precision not in SERVE_PRECISIONS:
            raise ValueError(f"serve_precision must be one of "
                             f"{SERVE_PRECISIONS}, got {serve_precision!r}")
        self._devices = list(jax.local_devices())
        n_dev = len(self._devices)
        self.max_replicas = min(int(max_replicas or n_dev), n_dev)
        self.min_replicas = max(1, min(int(min_replicas),
                                       self.max_replicas))
        start = int(replicas) if replicas is not None else self.min_replicas
        if not (1 <= start <= self.max_replicas):
            raise ValueError(
                f"replicas={start} out of range [1, {self.max_replicas}] "
                f"({n_dev} local device(s))")
        self._bundle = bundle
        self._mk_programs = lambda dev: ServePrograms(
            bundle, buckets=buckets, manifest_dir=manifest_dir,
            warm_start=warm_start, serve_precision=serve_precision,
            device=dev)
        self.serve_precision = serve_precision
        self._service_kwargs = dict(service_kwargs or {})
        self._lock = threading.RLock()
        self._replicas: List[Replica] = []
        self._closed = False
        # timestamped scale/breaker event log — the chaos drill's
        # ordering evidence (monotonic clock: compare t's, never walls)
        self.events: List[Dict[str, Any]] = []
        self._tripped_seen: set = set()
        # autoscaler hysteresis state
        self._sat_ticks = 0
        self._idle_ticks = 0
        self._last_scale_t = -float("inf")
        self._fill_marks: Dict[int, tuple] = {}
        self._autoscale_cfg = {
            "interval_s": float(autoscale_interval_s),
            "out_saturation": float(scale_out_saturation),
            "out_ticks": int(scale_out_ticks),
            "in_fill": float(scale_in_fill),
            "in_ticks": int(scale_in_ticks),
            "cooldown_s": float(cooldown_s),
        }
        for name in ("serve/scale_out_total", "serve/scale_in_total"):
            telemetry.counter(name)
        for _ in range(start):
            self._activate_one(record_event=False)
        self._update_fleet_gauges()
        self._scaler: Optional[threading.Thread] = None
        self._scaler_stop = threading.Event()
        if autoscale:
            self._scaler = threading.Thread(
                target=self._autoscale_loop, name="serve-autoscaler",
                daemon=True)
            self._scaler.start()

    # -- membership ----------------------------------------------------------

    @property
    def active_replicas(self) -> List[Replica]:
        with self._lock:
            return [r for r in self._replicas if r.active]

    @property
    def n_active(self) -> int:
        return len(self.active_replicas)

    def _activate_one(self, record_event: bool = True) -> Optional[Replica]:
        """Bring up the lowest inactive ordinal (creating the Replica —
        and its device-pinned programs — on first activation; later
        activations reuse the cached programs: zero compiles)."""
        with self._lock:
            if self._closed:
                return None
            target = next((r for r in self._replicas if not r.active), None)
            if target is None:
                if len(self._replicas) >= self.max_replicas:
                    return None
                ordinal = len(self._replicas)
                target = Replica(ordinal, self._devices[ordinal],
                                 self._mk_programs(self._devices[ordinal]))
                self._replicas.append(target)
            target.service = GenerationService(
                target.programs, replica_id=target.ordinal,
                **self._service_kwargs)
            # router-side dispatch-share counter, explicit zero up front
            telemetry.counter(
                f"serve/replica{target.ordinal}/requests_total")
            if record_event:
                telemetry.counter("serve/scale_out_total").inc()
                self.events.append({"kind": "scale_out",
                                    "replica": target.ordinal,
                                    "n_active": self.n_active,
                                    "t": time.monotonic()})
            self._update_fleet_gauges()
            return target

    def _deactivate_one(self, timeout: float = 30.0) -> Optional[int]:
        """Drain + retire the highest-ordinal active replica (programs
        stay cached for reactivation)."""
        with self._lock:
            candidates = [r for r in self._replicas if r.active]
            if len(candidates) <= self.min_replicas:
                return None
            target = candidates[-1]
            svc, target.service = target.service, None
            telemetry.counter("serve/scale_in_total").inc()
            self.events.append({"kind": "scale_in",
                                "replica": target.ordinal,
                                "n_active": self.n_active,
                                "t": time.monotonic()})
            self._update_fleet_gauges()
        svc.close(timeout=timeout)
        return target.ordinal

    scale_out = _activate_one
    scale_in = _deactivate_one

    def warm_start(self) -> Dict[str, Any]:
        """Warm-start every ACTIVE replica's programs from its
        per-ordinal manifest (merged {loaded, compiled, seconds}).
        Replicas the autoscaler activates later warm lazily — their
        cold compiles ride the dispatch watchdog's startup grace."""
        out = {"loaded": 0, "compiled": 0, "seconds": 0.0}
        for r in self.active_replicas:
            stats = r.programs.warm_start()
            out["loaded"] += stats["loaded"]
            out["compiled"] += stats["compiled"]
            out["seconds"] += stats["seconds"]
        return out

    # -- routing -------------------------------------------------------------

    def submit(self, seed: int, psi: float = 0.7, label=None,
               deadline_s: Optional[float] = None) -> Ticket:
        """Route one request to the least-loaded accepting replica.  A
        replica that refuses (sheds / trips between the load sample and
        the submit) is skipped and the next-least-loaded one tried; the
        LAST refusal propagates typed when every replica refused."""
        with self._lock:
            if self._closed:
                raise ServiceClosed("replica set is closed")
            ranked = sorted(
                (r for r in self._replicas
                 if r.active and r.service.accepting()),
                key=lambda r: r.service.load())
        if not ranked:
            raise ServiceUnhealthy(
                "no accepting replica (all tripped, draining, or closed)")
        last_err: Optional[Exception] = None
        for r in ranked:
            try:
                t = r.service.submit(seed, psi, label, deadline_s)
            except Exception as e:          # typed serve errors only
                last_err = e
                continue
            telemetry.counter(
                f"serve/replica{r.ordinal}/requests_total").inc()
            self._update_fleet_gauges()
            return t
        raise last_err

    # -- fleet health --------------------------------------------------------

    def _update_fleet_gauges(self) -> None:
        with self._lock:
            active = [r for r in self._replicas if r.active]
            telemetry.gauge("serve/replicas").set(len(active))
            if not active:
                telemetry.gauge("serve/dispatcher_alive").set(0)
                telemetry.gauge("serve/queue_depth_now").set(0)
                telemetry.gauge("serve/health_state").set(
                    HEALTH_CLOSED if self._closed else HEALTH_UNHEALTHY)
                return
            depth = bound = 0
            any_alive = False
            for r in active:
                svc = r.service
                with svc._cv:
                    depth += len(svc._pending)
                bound += svc._max_queue_depth
                any_alive = any_alive or svc._worker.alive
            telemetry.gauge("serve/dispatcher_alive").set(
                1 if any_alive else 0)
            telemetry.gauge("serve/queue_depth_now").set(depth)
            telemetry.gauge("serve/queue_bound").set(bound)

    def health(self) -> dict:
        """Fleet snapshot: healthiest-member state (the fleet serves as
        long as SOME replica can), per-replica sub-reports, and the
        scale-event tail.  Sets the fleet gauges as a side effect —
        mirrors ``GenerationService.health``."""
        with self._lock:
            members = list(self._replicas)
            closed = self._closed
        reports = []
        for r in members:
            if r.active:
                reports.append(r.service.health())
            else:
                reports.append({"state": "inactive", "state_code": None,
                                "replica_id": r.ordinal, "reasons": [],
                                "queue_depth": 0})
        codes = [rep["state_code"] for rep in reports
                 if rep["state_code"] is not None]
        state = min(codes) if codes else (
            HEALTH_CLOSED if closed else HEALTH_UNHEALTHY)
        reasons: List[str] = []
        for rep in reports:
            for why in rep.get("reasons", []):
                reasons.append(f"replica {rep['replica_id']}: {why}")
        self._update_fleet_gauges()
        telemetry.gauge("serve/health_state").set(state)
        return {"state": _HEALTH_NAMES[state], "state_code": state,
                "replicas": reports, "n_active": self.n_active,
                "n_devices": len(self._devices),
                "reasons": reasons,
                "scale_events": list(self.events[-16:])}

    # -- autoscaler ----------------------------------------------------------

    def _autoscale_tick(self, now: Optional[float] = None) -> Optional[str]:
        """One controller step (exposed for the drill tests — the
        thread just loops this).  Returns 'out'/'in' when it scaled."""
        cfg = self._autoscale_cfg
        now = time.monotonic() if now is None else now
        with self._lock:
            active = [r for r in self._replicas if r.active]
            depth = bound = 0
            batches = 0
            fills: List[float] = []
            for r in active:
                svc = r.service
                if svc._tripped and r.ordinal not in self._tripped_seen:
                    # trailing failure signal, logged for the drill's
                    # scale-out-before-breaker ordering check
                    self._tripped_seen.add(r.ordinal)
                    self.events.append({"kind": "breaker_trip",
                                        "replica": r.ordinal,
                                        "n_active": len(active),
                                        "t": now})
                with svc._cv:
                    depth += len(svc._pending)
                bound += svc._max_queue_depth
                h = telemetry.histogram(
                    svc._g("serve/batch_fill"))
                prev_n, prev_sum = self._fill_marks.get(r.ordinal, (0, 0.0))
                dn, ds = h.count - prev_n, h.sum - prev_sum
                self._fill_marks[r.ordinal] = (h.count, h.sum)
                batches += dn
                if dn > 0:
                    fills.append(ds / dn)
        saturation = (depth / bound) if bound else 0.0
        recent_fill = (sum(fills) / len(fills)) if fills else None
        # -- scale OUT: sustained saturation, a LEADING indicator — it
        # fires ticks before retries/hangs could trip any breaker
        if saturation >= cfg["out_saturation"]:
            self._sat_ticks += 1
        else:
            self._sat_ticks = 0
        # -- scale IN: batch-fill collapse (dispatches running mostly
        # padding) or full idleness, with an empty queue
        collapsed = (depth == 0
                     and (batches == 0
                          or (recent_fill is not None
                              and recent_fill < cfg["in_fill"])))
        self._idle_ticks = self._idle_ticks + 1 if collapsed else 0
        in_cooldown = (now - self._last_scale_t) < cfg["cooldown_s"]
        if (self._sat_ticks >= cfg["out_ticks"] and not in_cooldown
                and self.n_active < self.max_replicas):
            if self._activate_one() is not None:
                self._sat_ticks = 0
                self._last_scale_t = now
                return "out"
        if (self._idle_ticks >= cfg["in_ticks"] and not in_cooldown
                and self.n_active > self.min_replicas):
            if self._deactivate_one() is not None:
                self._idle_ticks = 0
                self._last_scale_t = now
                return "in"
        return None

    def _autoscale_loop(self) -> None:
        interval = self._autoscale_cfg["interval_s"]
        while not self._scaler_stop.wait(interval):
            try:
                self._autoscale_tick()
            except Exception:
                # the controller must never take the serving floor down;
                # a bad tick is dropped and the next one resamples
                pass

    # -- lifecycle -----------------------------------------------------------

    def install_signal_drain(self, grace_s: float = 30.0) -> bool:
        import signal

        if threading.current_thread() is not threading.main_thread():
            return False

        def _on_term(signum, frame):
            threading.Thread(target=self.close,
                             kwargs={"timeout": grace_s},
                             name="serve-fleet-sigterm-drain",
                             daemon=True).start()

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            return False
        return True

    def close(self, timeout: float = 30.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            members = [r for r in self._replicas if r.active]
        self._scaler_stop.set()
        if self._scaler is not None:
            self._scaler.join(timeout=max(1.0, timeout))
        for r in members:
            svc, r.service = r.service, None
            svc.close(timeout=timeout)
        with self._lock:
            self._update_fleet_gauges()
            telemetry.gauge("serve/health_state").set(HEALTH_CLOSED)

    def __enter__(self) -> "ReplicaSet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
