"""Deterministic fault injection — the supervisor's test harness.

Every recovery path the supervisor promises (crash → resume, torn
checkpoint → walk-back, data-thread hang → kill-and-restart, SIGTERM →
graceful preemption checkpoint) must be *exercised*, not trusted.  This
module arms named faults at named code points so a test (or the
battery's ``train_ticks`` stage) can script an exact failure sequence
into a real training run:

    GANSFORMER_TPU_FAULTS="sigkill@ckpt_mid_write:step=2000"
    GANSFORMER_TPU_FAULT_LEDGER=<run_dir>/faults_fired.jsonl

Spec grammar (comma-separated list): ``<action>@<point>[:k=v[,k=v…]]``
where every condition is read as ``coordinate >= value`` (coordinates
are monotonic: step, tick, batch), so a fault fires at the first
crossing.  Each spec fires ONCE — recorded in the ledger *before* the
action executes, so a restarted process (same env) does not re-fire it;
without a ledger, once per process.

Actions:
  ``sigkill``  SIGKILL self — the unannounced crash (mid-checkpoint
               when armed at ``ckpt_mid_write``).
  ``sigterm``  SIGTERM self — the preemption notice; the loop's handler
               turns it into a graceful final checkpoint.
  ``hang``     block the calling thread indefinitely — a wedged data
               thread / writer; only the supervisor's staleness probe
               ends it.
  ``torn``     truncate the file named by the fire-site's ``path``
               context — a torn ``state.npz`` the next restore must
               walk back from.
  ``raise``    raise ``FaultInjected`` — an in-process crash for tests
               that cannot take a SIGKILL.

Fire points wired today: ``ckpt_mid_write`` / ``ckpt_after_write``
(train/checkpoint.py, step=), ``tick`` (train/loop.py, tick=/step=),
``data_thread`` (data/dataset.py prefetch producer, batch=); the
DATA-PLANE points (data/dataset.py TFRecord read path, ISSUE 15; coord:
monotonic ``n``): ``data_read_error`` / ``data_slow_read`` (before every
record read — ``raise`` exercises the bounded-backoff IO retry and
``data/read_retries_total``; ``hang`` the stall watchdog → typed
``DataStalled``), ``data_corrupt_record`` (before every proto parse —
``raise`` exercises quarantine + the corruption budget); and the
SERVING path (serve/service.py, ISSUE 13; coords: monotonic ``batch``
plus ``n``): ``serve_dispatch`` (top of each dispatch iteration),
``serve_map`` (before the mapping dispatch), ``serve_fetch`` (inside
the sanctioned fetch span), ``serve_fulfill`` (before tickets resolve)
— ``raise`` exercises dispatcher restart/breaker, ``hang`` the hang
watchdog.  A point with no armed spec costs one tuple-check per call.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import time
from typing import Dict, List, Optional, Tuple

ENV_SPEC = "GANSFORMER_TPU_FAULTS"
ENV_LEDGER = "GANSFORMER_TPU_FAULT_LEDGER"

ACTIONS = ("sigkill", "sigterm", "hang", "torn", "raise")


class FaultInjected(RuntimeError):
    """The ``raise`` action's exception."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    action: str
    point: str
    cond: Tuple[Tuple[str, float], ...] = ()

    @property
    def key(self) -> str:
        tail = ",".join(f"{k}={v:g}" for k, v in self.cond)
        return f"{self.action}@{self.point}" + (f":{tail}" if tail else "")

    def matches(self, coords: Dict[str, object]) -> bool:
        for k, v in self.cond:
            have = coords.get(k)
            if have is None:
                return False
            try:
                if float(have) < v:
                    return False
            except (TypeError, ValueError):
                return False
        return True


def parse_spec(s: str) -> FaultSpec:
    s = s.strip()
    action, sep, rest = s.partition("@")
    if not sep or not rest:
        raise ValueError(f"fault spec {s!r}: expected <action>@<point>"
                         f"[:k=v,...]")
    if action not in ACTIONS:
        raise ValueError(f"fault spec {s!r}: unknown action {action!r} "
                         f"(have {ACTIONS})")
    point, _, condstr = rest.partition(":")
    cond: List[Tuple[str, float]] = []
    if condstr:
        for kv in condstr.split(","):
            k, sep, v = kv.partition("=")
            if not sep:
                raise ValueError(f"fault spec {s!r}: condition {kv!r} is "
                                 f"not k=v")
            cond.append((k.strip(), float(v)))
    return FaultSpec(action=action, point=point, cond=tuple(cond))


def parse_specs(s: str) -> List[FaultSpec]:
    return [parse_spec(p) for p in _split_specs(s)]


def _split_specs(s: str) -> List[str]:
    """Split a comma-separated spec list — but a comma may also separate
    conditions inside one spec, so split only before ``action@`` heads."""
    parts, cur = [], ""
    for tok in s.split(","):
        if "@" in tok and cur:
            parts.append(cur)
            cur = tok
        else:
            cur = f"{cur},{tok}" if cur else tok
    if cur:
        parts.append(cur)
    return [p for p in (x.strip() for x in parts) if p]


# --- armed state -------------------------------------------------------------

# None = not yet initialized (first fire() reads the env); [] = armed
# with nothing (the cheap common case).
_ARMED: Optional[List[FaultSpec]] = None
_LEDGER: Optional[str] = None
_FIRED: set = set()


def arm(specs: List[FaultSpec], ledger_path: Optional[str] = None) -> None:
    global _ARMED, _LEDGER, _FIRED
    _ARMED = list(specs)
    _LEDGER = ledger_path
    _FIRED = set(_read_ledger(ledger_path))


def disarm() -> None:
    arm([], None)


def install_from_env(environ=None) -> None:
    env = os.environ if environ is None else environ
    spec = env.get(ENV_SPEC, "")
    arm(parse_specs(spec) if spec else [], env.get(ENV_LEDGER))


def _read_ledger(path: Optional[str]) -> List[str]:
    if not path or not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "key" in rec:
                out.append(rec["key"])
    return out


def _record_fired(spec: FaultSpec, coords: Dict[str, object]) -> None:
    """Ledger line BEFORE the action runs (fsync'd: the action may be a
    SIGKILL) — the one-shot guarantee across process restarts."""
    _FIRED.add(spec.key)
    if not _LEDGER:
        return
    rec = {"key": spec.key, "point": spec.point, "time": time.time(),
           "pid": os.getpid(),
           "coords": {k: v for k, v in coords.items()
                      if isinstance(v, (int, float))}}
    with open(_LEDGER, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _execute(spec: FaultSpec, coords: Dict[str, object]) -> None:
    if spec.action == "sigkill":
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(30)  # SIGKILL is not synchronous; never proceed past it
    elif spec.action == "sigterm":
        os.kill(os.getpid(), signal.SIGTERM)
    elif spec.action == "hang":
        while True:    # only SIGKILL (the supervisor's) ends this thread
            time.sleep(1.0)
    elif spec.action == "torn":
        path = coords.get("path")
        if isinstance(path, str) and os.path.exists(path):
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(max(1, int(size * 0.6)))
    elif spec.action == "raise":
        raise FaultInjected(f"injected fault {spec.key} at {coords}")


def fire(point: str, **coords) -> None:
    """Fire any armed, not-yet-fired spec matching this point+coords.
    Called from production code at named boundaries; must stay O(armed
    specs) and allocation-free when nothing is armed."""
    global _ARMED
    if _ARMED is None:
        install_from_env()
    if not _ARMED:
        return
    for spec in _ARMED:
        if spec.point != point or spec.key in _FIRED:
            continue
        if not spec.matches(coords):
            continue
        _record_fired(spec, coords)
        _execute(spec, coords)
