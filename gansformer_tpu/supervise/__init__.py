"""Preemption-tolerant run supervision (ROADMAP item 5).

Four small modules:

* ``events``     — the ``supervisor_events.jsonl`` ledger + the
                   preemption exit contract (``EXIT_PREEMPTED``,
                   ``PreemptionExit``) + availability derivation.
* ``faults``     — deterministic fault injection at named code points
                   (``GANSFORMER_TPU_FAULTS``), so every recovery path
                   is exercised by tests rather than trusted.
* ``elastic``    — validate/rewrite a resumed run's mesh config for the
                   devices actually visible.
* ``supervisor`` — the child-process supervisor itself (imported on
                   demand by ``cli/supervise.py``; NOT here, so that
                   importing ``supervise.faults`` from hot paths stays
                   free of subprocess machinery).

Nothing in this package imports jax at module level: the supervisor
parent must never claim the accelerator its child needs.
"""

from gansformer_tpu.supervise import events, faults  # noqa: F401
from gansformer_tpu.supervise.events import (  # noqa: F401
    EXIT_PREEMPTED, PreemptionExit)
