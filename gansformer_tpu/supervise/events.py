"""Supervisor event ledger — the availability record of a training run.

``supervisor_events.jsonl`` is the append-only ledger the run supervisor
(``supervise/supervisor.py``) and the train loop both write: one JSON
line per lifecycle event (start, exit, resume, elastic re-mesh,
give-up, complete).  It supersedes the bare ``resumes.jsonl`` schema
(utils/logging.append_resume_record — kept for back-compat): where a
resume line only said "a restart happened at step S", an exit event
carries the *cause* (clean / crash / preemption / hang), the uptime it
ended, and the exit code, and a start event carries the downtime paid
before it — which is exactly what the doctor's availability section
grades (``gansformer-telemetry doctor``).

This module is deliberately dependency-free (stdlib only): the
supervisor parent process must never import jax (it would claim the TPU
devices its child needs), and the ledger readers (doctor, schema lint)
run in analysis contexts.

Also home to the preemption contract shared by the loop and the
supervisor: ``EXIT_PREEMPTED`` is the distinct exit code the train CLI
uses after a graceful SIGTERM checkpoint, and ``PreemptionExit`` is the
exception the loop raises to reach it.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

EVENTS_FILE = "supervisor_events.jsonl"
SCHEMA_VERSION = 1

# Exit code of a gracefully preempted training process (SIGTERM → final
# synchronous checkpoint → exit).  75 is EX_TEMPFAIL: "try again later",
# which is literally the supervisor's reading of it.
EXIT_PREEMPTED = 75

# Typed data-plane exits (ISSUE 15).  EX_DATAERR (65): the corruption
# budget is exhausted — a STATIC defect of the data on disk, so the
# supervisor treats it as non-retryable instead of crash-looping on it.
# EX_IOERR (74): the input pipeline stalled past its watchdog — possibly
# a transient filesystem wedge, so it stays retryable but classified.
EXIT_DATA_CORRUPT = 65
EXIT_DATA_STALLED = 74

# The exit-cause vocabulary the supervisor classifies into; anything
# else in the ledger is an "unclassified exit" the doctor WARNs on.
CAUSES = ("clean", "crash", "preemption", "hang", "data-corrupt",
          "data-stall")

# Causes a restart cannot fix: the supervisor gives up immediately
# WITHOUT consuming restart budget (the budget exists for transient
# failures; burning it on a static defect is the crash loop ISSUE 15
# closes).
NON_RETRYABLE_CAUSES = ("data-corrupt",)

# Event kinds the ledger schema lint accepts (telemetry_schema.py).
KINDS = ("supervisor_start", "start", "exit", "resume", "elastic",
         "give_up", "complete", "supervisor_preempted")


class PreemptionExit(RuntimeError):
    """Raised by the train loop after a graceful preemption checkpoint;
    the train CLI converts it into ``SystemExit(EXIT_PREEMPTED)``."""

    def __init__(self, step: int):
        super().__init__(f"preemption checkpoint complete at step {step}")
        self.step = int(step)


def events_path(run_dir: str) -> str:
    return os.path.join(run_dir, EVENTS_FILE)


def append_event(run_dir: str, kind: str, **fields) -> dict:
    """Append one ledger line (fsync'd: the very next thing after some
    of these events is a SIGKILL, and the record must survive it)."""
    rec = {"schema": SCHEMA_VERSION, "kind": kind, "time": time.time(),
           "pid": os.getpid(), **fields}
    os.makedirs(run_dir, exist_ok=True)
    with open(events_path(run_dir), "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return rec


def read_events(run_dir: str) -> List[dict]:
    """Ledger lines, torn-line-tolerant (a SIGKILL mid-append is the
    normal ending for exactly the runs this ledger describes)."""
    path = events_path(run_dir)
    out: List[dict] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def availability(events: List[dict],
                 now: Optional[float] = None) -> Dict[str, object]:
    """Availability summary over a ledger — THE derivation the doctor's
    availability check and the supervisor's own telemetry both use.

    * ``uptime_s`` / ``downtime_s`` — summed from exit/start events.
    * ``ratio`` — uptime / (uptime + downtime), or None before any
      exit landed.
    * ``restarts`` — supervisor re-arms (start events with
      restart_index > 0) plus train-side ``resume`` events (the
      unsupervised ``--resume`` path mirrors its record here).
    * ``restarts_last_hour`` — the restart-storm signal.
    * ``causes`` — exit-cause counts; ``unclassified`` lists causes
      outside the vocabulary.
    * ``gave_up`` / ``completed`` — terminal verdicts, if any.
    """
    now = time.time() if now is None else now
    uptime = sum(float(e.get("uptime_s", 0.0)) for e in events
                 if e.get("kind") == "exit")
    downtime = sum(float(e.get("downtime_s", 0.0)) for e in events
                   if e.get("kind") in ("start", "resume"))
    restart_events = [e for e in events
                      if (e.get("kind") == "start"
                          and e.get("restart_index", 0))
                      or e.get("kind") == "resume"]
    causes: Dict[str, int] = {}
    for e in events:
        if e.get("kind") == "exit":
            c = str(e.get("cause", "?"))
            causes[c] = causes.get(c, 0) + 1
    total = uptime + downtime
    return {
        "uptime_s": uptime,
        "downtime_s": downtime,
        "ratio": (uptime / total) if total > 0 else None,
        "restarts": len(restart_events),
        "restarts_last_hour": sum(
            1 for e in restart_events
            if float(e.get("time", 0.0)) >= now - 3600.0),
        "causes": causes,
        "unclassified": sorted(c for c in causes if c not in CAUSES),
        "gave_up": any(e.get("kind") == "give_up" for e in events),
        "completed": any(e.get("kind") == "complete" for e in events),
        "last_step": max((int(e.get("step", 0)) for e in events
                          if "step" in e), default=0),
    }
