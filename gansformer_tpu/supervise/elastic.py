"""Elastic restart rules — re-mesh a resumed run onto the devices it has.

Preemptible capacity does not come back the same size: a run
checkpointed on a 2-chip claim may resume on 1 chip (or 4).  Everything
below the config already tolerates that — ``restore()`` returns
layout-agnostic default-device arrays, the loop re-places them through
``state_shardings`` (FSDP leaves re-shard via the per-leaf ``fsdp_spec``
rule on the NEW mesh), and batches re-shard per ``MeshEnv.batch()``.
The one thing that crashed was the *saved mesh config*: a pinned
``mesh.data`` that no longer fits raises in ``make_mesh``, and a
derived data axis that stops dividing the batch raises in the loop.

``resolve_elastic_mesh`` is the missing validation/rewrite step the
train CLI runs on every ``--resume``:

* a pinned ``data`` axis that fits and divides the batch is respected;
* a pinned axis that no longer fits is rewritten to ``-1`` (use all
  devices) so a later restart on MORE devices grows back automatically;
* a derived axis that does not divide the global batch is pinned to
  the largest divisor that fits (batch size is part of the training
  run's identity; the mesh bends, the batch does not);
* FSDP is kept where expressible — on a derived data=1 mesh the
  per-leaf rule degrades to replicated placement by construction; a
  rewrite that must PIN data=1 disables it (with a note) until a wider
  claim returns;
* combos the sharding contracts cannot express are REFUSED with words:
  the model axis (sequence-parallel activation sharding) never
  re-sizes, and multi-host process groups are out of elastic scope.

Every rewrite is reported as a note (logged + appended to the
supervisor ledger as an ``elastic`` event by the caller).
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

from gansformer_tpu.core.config import ExperimentConfig


class ElasticMeshError(ValueError):
    """The visible devices cannot express the run's sharding contract."""


def largest_dividing(batch: int, cap: int) -> int:
    """Largest d in [1, cap] with batch % d == 0 (d=1 always works)."""
    for d in range(max(1, cap), 0, -1):
        if batch % d == 0:
            return d
    return 1


def resolve_elastic_mesh(cfg: ExperimentConfig, n_devices: int
                         ) -> Tuple[ExperimentConfig, List[str]]:
    """Validate/rewrite ``cfg.mesh`` for ``n_devices`` visible devices.
    Returns ``(cfg', notes)`` — notes empty when nothing changed; raises
    ``ElasticMeshError`` for combos restarting cannot fix."""
    mesh, batch = cfg.mesh, cfg.train.batch_size
    notes: List[str] = []
    if mesh.coordinator_address is not None or (mesh.num_processes or 1) > 1:
        # Multi-host elasticity needs a process-group re-form, not a
        # config rewrite; validate-only so a fitting pod still resumes.
        if mesh.data > 0 and mesh.data * mesh.model > n_devices:
            raise ElasticMeshError(
                f"resume: multi-host mesh {mesh.data}x{mesh.model} needs "
                f"{mesh.data * mesh.model} devices, {n_devices} visible — "
                f"elastic re-mesh is single-host only; re-launch with a "
                f"matching process set")
        return cfg, notes
    if mesh.model > n_devices:
        raise ElasticMeshError(
            f"resume: mesh.model={mesh.model} (sequence-parallel "
            f"activation sharding) cannot shrink onto {n_devices} visible "
            f"device(s) — the model axis is part of the compiled programs' "
            f"contract; restore this run on ≥{mesh.model} devices or "
            f"retrain with a smaller model axis")
    avail_rows = max(1, n_devices // mesh.model)
    data = mesh.data
    if data > 0 and data <= avail_rows and batch % data == 0:
        return cfg, notes          # pinned and still expressible: respect it
    if data > 0:
        # Pinned but no longer expressible: -1 ("all devices") both fits
        # now and grows back when the bigger claim returns.
        if batch % avail_rows == 0:
            notes.append(
                f"elastic: mesh.data={data} does not fit {n_devices} "
                f"visible device(s); re-meshed to data=-1 "
                f"({avail_rows} row(s) now)")
            data = -1
        else:
            d = largest_dividing(batch, avail_rows)
            notes.append(
                f"elastic: mesh.data={data} does not fit {n_devices} "
                f"visible device(s) and batch {batch} is not divisible "
                f"by {avail_rows}; re-meshed to data={d}")
            data = d
    else:  # data == -1: derived axis — only the divisibility can break
        if batch % avail_rows != 0:
            d = largest_dividing(batch, avail_rows)
            notes.append(
                f"elastic: derived data axis {avail_rows} does not divide "
                f"batch {batch}; pinned data={d}")
            data = d
    if not notes:
        return cfg, notes
    fsdp = mesh.fsdp
    if fsdp and data == 1:
        # validate() refuses a literal data=1 with fsdp (nothing to shard
        # over); a data=-1 that *derives* to 1 is fine — the per-leaf
        # rule degrades to replicated placement.
        notes.append("elastic: fsdp disabled — the re-meshed data axis "
                     "is 1, so optimizer state is replicated until a "
                     "wider claim returns")
        fsdp = False
    cfg = dataclasses.replace(
        cfg, mesh=dataclasses.replace(mesh, data=data, fsdp=fsdp))
    return cfg.validate(), notes
