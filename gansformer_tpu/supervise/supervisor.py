"""Run supervisor — keep a training run alive on preemptible capacity.

The battery already proved the shape (scripts/battery.py: re-arm until
the ledger says complete); this generalizes it to a LIVE child process:

* run training as a supervised subprocess;
* classify every exit — ``clean`` (rc 0), ``preemption`` (the distinct
  ``EXIT_PREEMPTED`` code from the loop's graceful SIGTERM path, or a
  raw SIGTERM death), ``crash`` (everything else), ``hang`` (no fresh
  heartbeat within the staleness budget, or step skew beyond bounds —
  the supervisor SIGTERMs, waits a grace, SIGKILLs);
* auto-resume through the existing ``--resume`` path under bounded
  exponential backoff (progress resets the exponent — only
  back-to-back no-progress failures escalate) with a restart budget;
* append every lifecycle event to ``supervisor_events.jsonl``
  (supervise/events.py) and export ``supervise/*`` telemetry to
  ``supervisor.prom`` — the doctor's availability section grades both.

The supervisor process NEVER imports jax: importing it would claim the
accelerator its child needs.  Liveness comes from the out-of-band
heartbeat files the loop already writes (obs/heartbeat.py), which is
exactly what they were built for.

If the supervisor itself receives SIGTERM/SIGINT (the whole allocation
is going away), it forwards SIGTERM to the child — giving it the
graceful-checkpoint window — records the exit, and stops WITHOUT
restarting, exiting ``EXIT_PREEMPTED`` so an outer re-armer (the
battery's probe loop) knows to re-fire later.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional

from gansformer_tpu.obs.heartbeat import read_heartbeats
from gansformer_tpu.obs.registry import Registry, atomic_write_text
from gansformer_tpu.supervise import events

SUPERVISOR_PROM = "supervisor.prom"


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Knobs for one supervised run (CLI flags map 1:1)."""

    max_restarts: int = 8
    backoff_base_s: float = 2.0
    backoff_max_s: float = 120.0
    poll_interval_s: float = 2.0
    # Hang detection: a child that HAS beaten must beat again within
    # heartbeat_max_age_s; one that has NEVER beaten gets startup_grace_s
    # (compiles happen before the first beat).  hang_kill_grace_s is the
    # SIGTERM→SIGKILL window once a hang verdict lands.
    heartbeat_max_age_s: float = 300.0
    startup_grace_s: float = 1800.0
    hang_kill_grace_s: float = 15.0
    # Grace the child is allowed for its preemption checkpoint when the
    # supervisor forwards a SIGTERM (exported to the child's env so the
    # loop bounds its shutdown to the same window).
    preempt_grace_s: float = 30.0
    max_step_skew: Optional[int] = None


def classify_exit(returncode: int, killed_for_hang: bool = False) -> str:
    """Exit-cause classification — the supervisor's one source of truth
    (and the unit-testable core of it)."""
    if killed_for_hang:
        return "hang"
    if returncode == 0:
        return "clean"
    if returncode == events.EXIT_PREEMPTED:
        return "preemption"        # graceful: checkpoint already on disk
    if returncode == events.EXIT_DATA_CORRUPT:
        return "data-corrupt"      # static data defect: non-retryable
    if returncode == events.EXIT_DATA_STALLED:
        return "data-stall"        # input pipeline stall: retry classified
    if returncode < 0 and -returncode == signal.SIGTERM:
        return "preemption"        # raw SIGTERM death: no final checkpoint
    return "crash"


def probe_hang(run_dir: str, child_start: float,
               cfg: SupervisorConfig,
               now: Optional[float] = None) -> Optional[str]:
    """Liveness verdict for a running child, or None while healthy.

    Only beats written SINCE this child started count — a stale file
    from the previous attempt must not convict the fresh one.  Until
    the first beat lands, ``startup_grace_s`` applies (compile time);
    after it, ``heartbeat_max_age_s`` — EXCEPT while the newest beat
    carries ``phase="setup"`` (written BEFORE the first-dispatch
    compiles) or ``phase="finalize"`` (written before the final
    snapshot + synchronous checkpoint): both windows legitimately go
    beat-less for longer than a tick, so they stay under the startup
    grace — or a cold-cache flagship compile / a slow final save would
    be killed as a hang.  With several fresh beats and
    ``max_step_skew`` set, a straggler process is also a hang verdict
    (the survivors are wedged in a collective against it)."""
    now = time.time() if now is None else now
    beats = read_heartbeats(run_dir)
    fresh = {i: r for i, r in beats.items()
             if float(r.get("time", 0.0)) >= child_start}
    if not fresh:
        if now - child_start > cfg.startup_grace_s:
            return (f"no heartbeat within the startup grace "
                    f"({cfg.startup_grace_s:.0f}s)")
        return None
    newest_rec = max(fresh.values(), key=lambda r: float(r["time"]))
    newest = float(newest_rec["time"])
    phase = newest_rec.get("phase")
    graced = phase in ("setup", "finalize")
    budget = (max(cfg.heartbeat_max_age_s, cfg.startup_grace_s)
              if graced else cfg.heartbeat_max_age_s)
    if now - newest > budget:
        return (f"heartbeat stale: last beat {now - newest:.0f}s ago "
                f"(budget {budget:.0f}s"
                + (f", {phase} phase" if graced else "") + ")")
    if cfg.max_step_skew is not None and len(fresh) > 1:
        steps = [int(r.get("step", 0)) for r in fresh.values()]
        skew = max(steps) - min(steps)
        if skew > cfg.max_step_skew:
            return (f"step skew {skew} > {cfg.max_step_skew} — a process "
                    f"is straggling the collectives")
    return None


def last_heartbeat_step(run_dir: str) -> int:
    beats = read_heartbeats(run_dir)
    return max((int(r.get("step", 0)) for r in beats.values()), default=0)


class _Telemetry:
    """supervise/* instruments on a PRIVATE registry (the supervisor may
    run in-process in tests — it must not fight the loop's process-global
    registry resets), exported to ``<run_dir>/supervisor.prom``."""

    def __init__(self, run_dir: str, cfg: SupervisorConfig):
        self.path = os.path.join(run_dir, SUPERVISOR_PROM)
        self.reg = Registry()
        # Materialize the whole family up front: the schema lint's
        # explicit-marker discipline — absence must mean "wiring rotted",
        # never "nothing happened yet".
        for c in ("restarts_total", "exits_total", "clean_exits_total",
                  "crashes_total", "preemptions_total", "hangs_total",
                  "data_corrupt_exits_total", "data_stall_exits_total"):
            self.reg.counter(f"supervise/{c}")
        self.reg.gauge("supervise/restart_budget_remaining").set(
            cfg.max_restarts)
        for g in ("availability_ratio", "uptime_s_total",
                  "downtime_s_total", "last_exit_code", "last_step"):
            self.reg.gauge(f"supervise/{g}")
        self.flush()

    def record_exit(self, cause: str, rc: int, step: int,
                    run_dir: str) -> None:
        self.reg.counter("supervise/exits_total").inc()
        name = {"clean": "clean_exits_total", "crash": "crashes_total",
                "preemption": "preemptions_total",
                "hang": "hangs_total",
                "data-corrupt": "data_corrupt_exits_total",
                "data-stall": "data_stall_exits_total"}[cause]
        self.reg.counter(f"supervise/{name}").inc()
        self.reg.gauge("supervise/last_exit_code").set(float(rc))
        self.reg.gauge("supervise/last_step").set(float(step))
        avail = events.availability(events.read_events(run_dir))
        self.reg.gauge("supervise/uptime_s_total").set(avail["uptime_s"])
        self.reg.gauge("supervise/downtime_s_total").set(
            avail["downtime_s"])
        if avail["ratio"] is not None:
            self.reg.gauge("supervise/availability_ratio").set(
                avail["ratio"])
        self.flush()

    def record_restart(self, budget_remaining: int) -> None:
        self.reg.counter("supervise/restarts_total").inc()
        self.reg.gauge("supervise/restart_budget_remaining").set(
            budget_remaining)
        self.flush()

    def flush(self) -> None:
        atomic_write_text(self.path, self.reg.export_text())


def supervise(build_argv: Callable[[bool, int], List[str]],
              run_dir: str,
              cfg: SupervisorConfig = SupervisorConfig(),
              child_env: Optional[Dict[str, str]] = None,
              log: Optional[Callable[[str], None]] = None) -> dict:
    """Supervise ``build_argv(resume, restart_index)`` until it exits
    clean, the restart budget runs out, or the supervisor itself is
    preempted.  Returns ``{ok, cause, restarts, exit_code, step}`` —
    ``exit_code`` is what the CLI should exit with."""
    log = log or (lambda m: print(f"[supervise] {m}", flush=True))
    os.makedirs(run_dir, exist_ok=True)
    tele = _Telemetry(run_dir, cfg)
    env = {**os.environ, **(child_env or {}),
           "GANSFORMER_TPU_SUPERVISED": "1",
           "GANSFORMER_TPU_PREEMPT_GRACE_S": str(cfg.preempt_grace_s)}

    shutdown = {"sig": None}
    proc_box: List[Optional[subprocess.Popen]] = [None]

    def _on_preempt(signum, frame):
        shutdown["sig"] = signum
        p = proc_box[0]
        if p is not None and p.poll() is None:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass

    old_handlers = {}
    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                old_handlers[sig] = signal.signal(sig, _on_preempt)
            except (ValueError, OSError):
                pass

    events.append_event(run_dir, "supervisor_start",
                        max_restarts=cfg.max_restarts)
    restarts = 0
    no_progress = 0
    prev_step = -1
    prev_exit_time: Optional[float] = None
    try:
        while True:
            if shutdown["sig"] is not None:
                # Preempted between children (backoff sleep): never
                # spawn into a dying allocation.
                log("supervisor preempted during backoff — not "
                    "restarting")
                events.append_event(run_dir, "supervisor_preempted",
                                    restarts=restarts,
                                    step=last_heartbeat_step(run_dir))
                return {"ok": False, "cause": "supervisor_preempted",
                        "restarts": restarts,
                        "step": last_heartbeat_step(run_dir),
                        "exit_code": events.EXIT_PREEMPTED}
            resume = os.path.isdir(os.path.join(run_dir, "checkpoints"))
            argv = build_argv(resume, restarts)
            t0 = time.time()
            downtime = (t0 - prev_exit_time) if prev_exit_time else 0.0
            events.append_event(run_dir, "start", restart_index=restarts,
                                resume=resume,
                                downtime_s=round(downtime, 3), argv=argv)
            log(f"start #{restarts}{' (resume)' if resume else ''}: "
                f"{' '.join(argv)}")
            proc = subprocess.Popen(argv, env=env)
            proc_box[0] = proc
            killed_for_hang = False
            hang_reason = None
            while True:
                rc = proc.poll()
                if rc is not None:
                    break
                if shutdown["sig"] is not None:
                    # Forward SIGTERM here too — the handler only
                    # reaches the child that was alive when the signal
                    # landed; a child spawned in the race window would
                    # otherwise never get its preemption notice.  Then
                    # give it the checkpoint grace, then insist.
                    try:
                        proc.send_signal(signal.SIGTERM)
                    except OSError:
                        pass
                    try:
                        proc.wait(cfg.preempt_grace_s
                                  + cfg.hang_kill_grace_s)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()
                    rc = proc.returncode
                    break
                hang_reason = probe_hang(run_dir, t0, cfg)
                if hang_reason:
                    killed_for_hang = True
                    log(f"hang: {hang_reason}; SIGTERM, then SIGKILL "
                        f"after {cfg.hang_kill_grace_s:.0f}s")
                    proc.terminate()
                    try:
                        proc.wait(cfg.hang_kill_grace_s)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()
                    rc = proc.returncode
                    break
                time.sleep(cfg.poll_interval_s)
            uptime = time.time() - t0
            cause = classify_exit(rc, killed_for_hang=killed_for_hang)
            step = last_heartbeat_step(run_dir)
            events.append_event(
                run_dir, "exit", cause=cause, exit_code=rc,
                uptime_s=round(uptime, 3), step=step,
                restart_index=restarts,
                **({"hang_reason": hang_reason} if hang_reason else {}))
            tele.record_exit(cause, rc, step, run_dir)
            log(f"exit rc={rc} cause={cause} after {uptime:.1f}s "
                f"(step {step})")
            prev_exit_time = time.time()

            if cause == "clean":
                events.append_event(run_dir, "complete",
                                    restarts=restarts, step=step)
                return {"ok": True, "cause": "clean",
                        "restarts": restarts, "step": step,
                        "exit_code": 0}
            if shutdown["sig"] is not None:
                log("supervisor preempted — not restarting")
                events.append_event(run_dir, "supervisor_preempted",
                                    restarts=restarts, step=step)
                return {"ok": False, "cause": "supervisor_preempted",
                        "restarts": restarts, "step": step,
                        "exit_code": events.EXIT_PREEMPTED}
            if cause in events.NON_RETRYABLE_CAUSES:
                # A restart cannot fix a static data defect: give up NOW
                # with the cause classified and the restart budget
                # untouched — the crash→restart loop on an unrecoverable
                # cause is exactly what ISSUE 15 closes.
                events.append_event(run_dir, "give_up",
                                    restarts=restarts, cause=cause,
                                    step=step, non_retryable=True)
                log(f"non-retryable exit cause {cause!r}; giving up "
                    f"without consuming the restart budget "
                    f"({restarts} restart(s) used)")
                return {"ok": False, "cause": cause,
                        "restarts": restarts, "step": step,
                        "exit_code": 1}
            if restarts >= cfg.max_restarts:
                events.append_event(run_dir, "give_up",
                                    restarts=restarts, cause=cause,
                                    step=step)
                log(f"restart budget exhausted "
                    f"({cfg.max_restarts}); giving up after {cause}")
                return {"ok": False, "cause": cause,
                        "restarts": restarts, "step": step,
                        "exit_code": 1}
            # Progress resets the backoff exponent: a run that advances
            # between preemptions restarts eagerly forever; only
            # back-to-back no-progress failures escalate.
            no_progress = 0 if step > prev_step else no_progress + 1
            prev_step = step
            delay = min(cfg.backoff_max_s,
                        cfg.backoff_base_s * (2 ** max(0,
                                                       no_progress - 1)))
            restarts += 1
            tele.record_restart(cfg.max_restarts - restarts)
            log(f"restart #{restarts}/{cfg.max_restarts} in "
                f"{delay:.1f}s (cause {cause})")
            # Sliced sleep: a preemption notice landing mid-backoff must
            # be honored within a poll interval, not after the full
            # (up to backoff_max_s) delay — the loop-top check then
            # stops the supervisor before it spawns anything.
            deadline = time.time() + delay
            while shutdown["sig"] is None:
                left = deadline - time.time()
                if left <= 0:
                    break
                time.sleep(min(cfg.poll_interval_s, left))
    finally:
        for sig, h in old_handlers.items():
            try:
                signal.signal(sig, h)
            except (ValueError, OSError):
                pass
