"""GAN losses + lazy regularizers.

Capability parity with the reference's ``src/training/loss.py`` (SURVEY.md
§2.2): non-saturating logistic G loss (``G_logistic_ns``), logistic D loss
(``D_logistic``), lazy **R1 gradient penalty** on D, and lazy **path-length
regularization** on G — the exact trio named by the driver's north star
(BASELINE.json:5 "two-timescale G/D loop with R1 and path-length
regularization").

TPU-first notes
---------------
* R1 is a gradient-of-gradient: we take ``jax.grad`` of the discriminator
  score w.r.t. the *images* inside a function that is itself differentiated
  w.r.t. D's params.  All ops on the D path are plain jnp composites
  (SURVEY.md §7.3 item 1), so second-order autodiff Just Works — no
  hand-written double-backward kernels like the reference's
  ``fused_bias_act.cu``.
* Path length uses a ``jvp``-free formulation: grad of ``sum(img * noise)``
  w.r.t. the per-layer latents ``ws`` — one extra VJP through synthesis,
  identical math to the reference.
* Everything returns per-replica scalars; gradient averaging across the data
  mesh axis happens in the train step via jit's automatic ``psum`` — there is
  no loss-side collective code.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def g_nonsaturating_loss(fake_logits: jax.Array) -> jax.Array:
    """-log sigmoid(D(G(z))) — reference ``G_logistic_ns``."""
    return jnp.mean(jax.nn.softplus(-fake_logits))


def d_logistic_loss(real_logits: jax.Array, fake_logits: jax.Array) -> jax.Array:
    """softplus(D(fake)) + softplus(-D(real)) — reference ``D_logistic``."""
    return jnp.mean(jax.nn.softplus(fake_logits)) + jnp.mean(
        jax.nn.softplus(-real_logits))


def r1_penalty(d_score: Callable[[jax.Array], jax.Array],
               reals: jax.Array) -> jax.Array:
    """R1 = E[ ||∇_x D(x)||² ] on real images.

    ``d_score`` maps images → per-sample logits [N] (or [N,1]); the caller
    closes D's params over it so this whole expression stays differentiable
    w.r.t. those params (the lazy-reg D step differentiates through here).
    """
    def scalar_score(x):
        return jnp.sum(d_score(x))

    grads = jax.grad(scalar_score)(reals.astype(jnp.float32))
    # sum over all non-batch dims, mean over batch
    per_sample = jnp.sum(jnp.square(grads), axis=tuple(range(1, grads.ndim)))
    return jnp.mean(per_sample)


def r1_slice(reals: jax.Array, batch_shrink: int) -> jax.Array:
    """The R1 batch slice of the ``r1_batch_shrink`` MFU lever (ISSUE 5).

    Returns the first ``N // batch_shrink`` reals — the subset the penalty
    is computed on when the lever is armed.  Statistical contract: the
    reals arrive in dataset-shuffle order, so a prefix slice is an
    exchangeable subsample and ``mean over slice`` is an unbiased
    estimator of ``mean over batch`` — the lazy-reg weight
    ((γ/2)·d_reg_interval) therefore stays UNCHANGED; the lever trades
    estimator variance for the double-backward's batch dimension.
    ``batch_shrink`` must divide N (enforced by config.validate()); the
    caller slices any conditioning label identically.
    """
    assert batch_shrink >= 1
    if batch_shrink == 1:
        return reals
    n = reals.shape[0]
    assert n % batch_shrink == 0, (n, batch_shrink)
    return reals[: n // batch_shrink]


def path_length_penalty(
    synthesize: Callable[[jax.Array], jax.Array],
    ws: jax.Array,
    pl_mean: jax.Array,
    rng: jax.Array,
    pl_decay: float = 0.01,
) -> Tuple[jax.Array, jax.Array]:
    """Path-length regularizer (reference lazy G reg; SURVEY.md §2.3).

    ``synthesize``: ws [N, num_ws, D] → images [N, H, W, C] with G's params
    closed over (so the penalty is differentiable w.r.t. them).

    Returns ``(penalty, new_pl_mean)``; ``new_pl_mean`` is the updated EMA of
    observed path lengths (tracked as train-state, exactly like the
    reference's ``pl_mean_var``).  The EMA update is stop-gradiented.
    """
    def proj(w):
        img = synthesize(w)
        h, w_ = img.shape[1], img.shape[2]
        noise = jax.random.normal(rng, img.shape, dtype=img.dtype)
        noise = noise / jnp.sqrt(jnp.asarray(h * w_, dtype=img.dtype))
        return jnp.sum(img.astype(jnp.float32) * noise.astype(jnp.float32))

    pl_grads = jax.grad(proj)(ws)
    # [N, num_ws, D] → per-sample length: sqrt(mean over ws of sum over D)
    # The sqrt backward divides by the path length, which is zero only
    # when every projected gradient is exactly zero (a dead generator);
    # the reference formulation is unguarded and we keep its numerics.
    pl_lengths = jnp.sqrt(  # graftlint: disable=unstable-primitive
        jnp.mean(jnp.sum(jnp.square(pl_grads.astype(jnp.float32)), axis=2), axis=1))
    new_pl_mean = pl_mean + pl_decay * (
        jnp.mean(jax.lax.stop_gradient(pl_lengths)) - pl_mean)
    penalty = jnp.mean(jnp.square(pl_lengths - jax.lax.stop_gradient(new_pl_mean)))
    return penalty, new_pl_mean
