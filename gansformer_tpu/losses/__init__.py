from gansformer_tpu.losses.gan import (
    g_nonsaturating_loss,
    d_logistic_loss,
    r1_penalty,
    path_length_penalty,
)
