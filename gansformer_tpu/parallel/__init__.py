from gansformer_tpu.parallel.mesh import (
    MeshEnv,
    make_mesh,
    batch_sharding,
    replicated,
    init_distributed,
    local_batch_size,
)
from gansformer_tpu.parallel.contracts import (  # noqa: F401
    Contract,
    ENTRY_CONTRACTS,
    MESH_MATRIX,
    ROLE_SPECS,
    contract_for,
    simulated_mesh,
)
