from gansformer_tpu.parallel.mesh import (
    MeshEnv,
    make_mesh,
    batch_sharding,
    replicated,
    init_distributed,
    local_batch_size,
)
