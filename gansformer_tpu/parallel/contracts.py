"""Sharding contracts — the INTENDED PartitionSpec per logical arg role.

``parallel/mesh.py`` hands out shardings; this module declares which
sharding each entry point's inputs and outputs are *supposed* to
resolve to, so the graftcomms analysis layer (``analysis/trace/
partition_contract.py`` / ``collective_flow.py``) can prove the
compiled SPMD programs are partitioned as designed — before a rare TPU
window burns minutes discovering an accidental all-gather.

The contract is deliberately small: a role vocabulary (params,
opt-state, batch, rng, …), one intended ``PartitionSpec`` per role, and
a per-entry-point table mapping positional args (and output leaves) to
roles.  Today every role except the batch family is replicated — the
repo's layout is pure data parallelism — so the value of writing it
down is that a future FSDP/tensor-parallel axis changes ONE table here
and the whole analysis stack starts asserting the new intent on every
step program (ROADMAP item 2).

Roles:
  ``params``      G/D/EMA parameter leaves — replicated (DP today; the
                  FSDP hook is flipping this spec to shard over a mesh
                  axis).
  ``opt_state``   optax moment leaves — wherever params go, these go.
  ``stat``        small replicated scalars/vectors (step, w_avg,
                  pl_mean, aux metrics).
  ``batch``       per-example arrays, leading axis over ``data``.
  ``batch_stack`` [K, B, ...] fused-cycle input stacks: axis 1 over
                  ``data`` (``MeshEnv.batch_stack``).
  ``rng``         PRNG keys — replicated (every device folds the same
                  stream; per-device divergence would break the fused/
                  unfused parity contract in tests/test_train.py).
  ``scalar``      python scalars at the jit boundary (no sharding).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P

from gansformer_tpu.parallel.mesh import DATA_AXIS, MeshEnv, make_mesh

# The simulated mesh matrix the contract/collective analyses compile
# against (CPU devices via --xla_force_host_platform_device_count).
# 1 catches degenerate-axis lowering breaks, 2 is the cheap default,
# and the 4-device member is a 2×2 data×model grid: the tiny trace
# batch (2) bounds the data axis, and the reserved model axis is
# exactly the hook a future FSDP/TP layout flips — compiling with it
# non-trivial proves the programs tolerate an idle second axis.
MESH_MATRIX: Tuple[int, ...] = (1, 2, 4)
_MESH_SHAPES: Dict[int, Tuple[int, int]] = {1: (1, 1), 2: (2, 1),
                                            4: (2, 2)}

ROLE_SPECS: Dict[str, Optional[P]] = {
    "params": P(),
    "opt_state": P(),
    "stat": P(),
    "rng": P(),
    "batch": P(DATA_AXIS),
    "batch_stack": P(None, DATA_AXIS),
    "scalar": None,
}

# Sentinel role-spec value: "shard this role's leaves per-leaf with
# ``fsdp_spec``" — a single PartitionSpec cannot express FSDP because
# the shardable axis depends on each leaf's shape.  Used as a
# ``role_specs`` override value (see ``entry_contracts(fsdp=True)``).
FSDP = "fsdp"


def fsdp_spec(shape: Tuple[int, ...], data_size: int) -> P:
    """THE per-leaf FSDP placement rule: shard the largest axis the
    ``data`` mesh axis divides evenly; replicate leaves with no such
    axis (scalars, odd-length vectors, Adam ``count``).  Ties pick the
    LAST such axis (output channels — the contiguous-major dim on
    typical conv/dense kernels).  Deterministic and shape-only, so the
    runtime placement (``state_shardings``), the analysis contracts,
    and the checked expectations all derive the same spec."""
    if data_size <= 1:
        return P()
    best = None
    for i, d in enumerate(shape):
        if d > 0 and d % data_size == 0:
            if best is None or d >= shape[best]:
                best = i
    if best is None:
        return P()
    return P(*([None] * best), DATA_AXIS)


@dataclasses.dataclass(frozen=True)
class Contract:
    """Intended placement for one entry point.

    ``args``: one role per positional arg; the special role ``"state"``
    expands per-leaf via ``state_leaf_role`` (the TrainState pytree
    mixes params/opt-state/stat leaves).  ``outs``: role assignment for
    the flattened outputs — ``"state"`` consumes the donated state's
    leaves (same treedef: the steps return ``state.replace(...)``), and
    the LAST entry soaks up every remaining leaf.  ``role_specs``
    overrides ``ROLE_SPECS`` per entry (the FSDP pilot / fixture hook).
    """

    args: Tuple[str, ...]
    outs: Tuple[str, ...]
    role_specs: Optional[Mapping[str, Any]] = None

    def spec_for(self, role: str, shape: Optional[Tuple[int, ...]] = None,
                 data_size: Optional[int] = None) -> Optional[P]:
        """Intended spec for one leaf.  The ``FSDP`` sentinel resolves
        per-leaf via ``fsdp_spec`` — callers that know the leaf pass
        ``shape``/``data_size``; without them the sentinel resolves to
        None (no expectation), so shape-blind consumers (role-byte
        accounting) stay correct."""
        if self.role_specs is not None and role in self.role_specs:
            val = self.role_specs[role]
        else:
            if role not in ROLE_SPECS:
                raise KeyError(f"unknown contract role {role!r}; "
                               f"have {sorted(ROLE_SPECS)}")
            val = ROLE_SPECS[role]
        if isinstance(val, str) and val == FSDP:
            if shape is None or data_size is None:
                return None
            return fsdp_spec(tuple(shape), data_size)
        return val


_TRAIN_STEP = Contract(args=("state", "batch", "rng"),
                       outs=("state", "stat"))
_G_STEP = Contract(args=("state", "rng"), outs=("state", "stat"))

# One entry per jitted program in analysis/trace/entry_points.py —
# keyed by the short name ("steps.<short>[config]").  A new entry point
# without a contract is a loud skip-note in the analysis, not a silent
# pass (the pre-graftcomms audit silently exempted spec-less entries).
ENTRY_CONTRACTS: Dict[str, Contract] = {
    "d_step": _TRAIN_STEP,
    "d_step_r1": _TRAIN_STEP,
    "g_step": _G_STEP,
    "g_step_pl": _G_STEP,
    "cycle": Contract(args=("state", "batch_stack", "rng", "scalar"),
                      outs=("state", "stat")),
    # Inference programs the serving path (ROADMAP item 3) will reuse:
    # sample(ema_params, w_avg, z, rng) and ppl_pairs(params, z0, z1,
    # t, rng) — params replicated, per-example arrays on ``data``.
    "sample": Contract(args=("params", "stat", "batch", "rng"),
                       outs=("batch",)),
    "ppl_pairs": Contract(args=("params", "batch", "batch", "batch",
                                "rng"),
                          outs=("batch",)),
    # The serving split (ISSUE 10, serve/programs.py): params always
    # replicated (weight-agnostic executables), per-request rows on
    # ``data``.  serve_map_seeds(params, seeds[B]) / serve_map_z(params,
    # z) → ws[B,…]; serve_synth(params, w_avg, ws, psi[B], rng,
    # tags[B]) → imgs — tags are the per-row noise identities (ISSUE
    # 20), request data like psi.  The precision variants (ISSUE 20:
    # serve_precision=bf16|int8w) share the exact signature — int8w
    # swaps the params TREE (QuantizedWeight leaves) but not the
    # argument roles, so one contract shape covers all three and the
    # partition-contract/collective-flow audits gate each compiled
    # variant separately.
    "serve_map_seeds": Contract(args=("params", "batch"),
                                outs=("batch",)),
    "serve_map_z": Contract(args=("params", "batch"), outs=("batch",)),
    "serve_synth": Contract(args=("params", "stat", "batch", "batch",
                                  "rng", "batch"),
                            outs=("batch",)),
    "serve_synth_bf16": Contract(args=("params", "stat", "batch", "batch",
                                       "rng", "batch"),
                                 outs=("batch",)),
    "serve_synth_int8w": Contract(args=("params", "stat", "batch", "batch",
                                        "rng", "batch"),
                                  outs=("batch",)),
}


# The FSDP role override (MeshConfig.fsdp / cli --fsdp): optimizer
# moments shard per-leaf over ``data`` (ZeRO-1 — the biggest replicated
# deadweight, ~2× params per optimizer); compute params and the EMA
# tree stay replicated, so forward/backward never pays a param gather
# and eval samples from a whole tree.  The step programs then pay
# per-leaf all-gathers of the UPDATES at apply time — each far below
# the full-param-gather threshold — which is the declared ZeRO-1 cost
# the collective-flow table prices.
FSDP_ROLE_SPECS: Dict[str, Any] = {"opt_state": FSDP}


def entry_contracts(fsdp: bool = False) -> Dict[str, Contract]:
    """The contract table for one layout mode.  ``fsdp=False`` is THE
    base table (same dict object — tests monkeypatch it); ``fsdp=True``
    overlays ``FSDP_ROLE_SPECS`` on every entry so the partition-
    contract / collective-flow rules gate the sharded-opt-state intent
    instead of the replicated one."""
    if not fsdp:
        return ENTRY_CONTRACTS
    return {name: dataclasses.replace(
                c, role_specs={**(c.role_specs or {}), **FSDP_ROLE_SPECS})
            for name, c in ENTRY_CONTRACTS.items()}


def short_entry_name(name: str) -> str:
    """"steps.d_step[tiny-f32]" → "d_step" (fixture names pass through
    unchanged when they don't follow the catalog convention)."""
    tail = name.split(".", 1)[1] if "." in name else name
    return tail.split("[", 1)[0]


def contract_for(name: str, fsdp: bool = False) -> Optional[Contract]:
    return entry_contracts(fsdp).get(short_entry_name(name))


def key_str(entry: Any) -> str:
    """One pytree path entry (GetAttrKey/DictKey/SequenceKey) → its
    name — THE key-rendering helper (state_leaf_role and the output
    labels both go through it, so a new key type is a one-line fix)."""
    return str(getattr(entry, "name", getattr(entry, "key",
                                              getattr(entry, "idx",
                                                      entry))))


def state_leaf_role(path: Sequence[Any]) -> str:
    """TrainState leaf path → role, keyed on the dataclass field name
    (train/state.py: g_params/d_params/ema_params are parameter trees,
    g_opt/d_opt optimizer moments, the rest replicated stats)."""
    head = key_str(path[0]) if path else ""
    if head in ("g_params", "d_params", "ema_params"):
        return "params"
    if head in ("g_opt", "d_opt"):
        return "opt_state"
    return "stat"


def _flatten_with_paths(tree):
    import jax

    return jax.tree_util.tree_flatten_with_path(tree)[0]


def arg_leaf_contracts(contract: Contract, abstract_args: Tuple[Any, ...],
                       data_size: Optional[int] = None
                       ) -> List[Tuple[int, Tuple, str, Optional[P]]]:
    """Flattened input-leaf view of the contract, aligned with
    ``jax.tree_util.tree_flatten(abstract_args)`` order: one
    ``(arg_index, path, role, intended_spec)`` per leaf.  ``data_size``
    lets FSDP-sentinel roles resolve their per-leaf spec; without it
    those specs are None (no expectation)."""
    if len(contract.args) != len(abstract_args):
        raise ValueError(
            f"contract declares {len(contract.args)} args but the entry "
            f"point has {len(abstract_args)}")
    out: List[Tuple[int, Tuple, str, Optional[P]]] = []
    for i, (role, arg) in enumerate(zip(contract.args, abstract_args)):
        for path, leaf in _flatten_with_paths(arg):
            leaf_role = state_leaf_role(path) if role == "state" else role
            spec = (None if not hasattr(leaf, "shape")
                    else contract.spec_for(leaf_role, leaf.shape,
                                           data_size))
            out.append((i, tuple(path), leaf_role, spec))
    return out


def out_leaf_contracts(contract: Contract, abstract_args: Tuple[Any, ...],
                       n_out_leaves: int,
                       data_size: Optional[int] = None
                       ) -> List[Tuple[str, str, Optional[P]]]:
    """Role + intended spec per flattened OUTPUT leaf: ``"state"`` in
    ``outs`` consumes the arg-0 state's leaves (donated; same treedef —
    the steps return ``state.replace(...)`` first), then the final role
    covers every remaining leaf (the aux/metric tail)."""
    out: List[Tuple[str, str, Optional[P]]] = []
    if contract.outs and contract.outs[0] == "state":
        for path, leaf in _flatten_with_paths(abstract_args[0]):
            leaf_role = state_leaf_role(path)
            label = "/".join(key_str(p) for p in path)
            out.append((f"state:{label}", leaf_role,
                        contract.spec_for(leaf_role,
                                          getattr(leaf, "shape", None),
                                          data_size)))
    tail_role = contract.outs[-1]
    if tail_role == "state":        # outs == ("state",): no aux tail
        tail_role = "stat"
    while len(out) < n_out_leaves:
        out.append((f"out[{len(out)}]", tail_role,
                    contract.spec_for(tail_role)))
    return out[:n_out_leaves]


def sharded_abstract_args(contract: Contract,
                          abstract_args: Tuple[Any, ...],
                          env: MeshEnv) -> Tuple[Any, ...]:
    """``abstract_args`` re-annotated with the CONTRACT's intended
    shardings on ``env``'s mesh — what the analysis hands to
    ``fn.lower`` so GSPMD resolves from declared intent."""
    import jax
    from jax.sharding import NamedSharding

    if len(contract.args) != len(abstract_args):
        raise ValueError(
            f"contract declares {len(contract.args)} args but the entry "
            f"point has {len(abstract_args)}")

    def annotate(leaf, spec):
        if leaf is None or not hasattr(leaf, "shape") or spec is None:
            return leaf
        return jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype,
            sharding=NamedSharding(env.mesh, spec))

    out = []
    for role, arg in zip(contract.args, abstract_args):
        if role == "state":
            out.append(jax.tree_util.tree_map_with_path(
                lambda p, l: annotate(
                    l, contract.spec_for(state_leaf_role(p),
                                         getattr(l, "shape", None),
                                         env.data_size)), arg))
        elif isinstance(arg, (int, float)) and not hasattr(arg, "shape"):
            out.append(arg)
        else:
            out.append(jax.tree_util.tree_map(
                lambda l: annotate(
                    l, contract.spec_for(role, getattr(l, "shape", None),
                                         env.data_size)), arg))
    return tuple(out)


def state_shardings(state: Any, env: MeshEnv, fsdp: bool = False):
    """Per-leaf ``NamedSharding`` tree for a TrainState — THE runtime
    placement (train/loop.py ``device_put``s onto it) derived from the
    same role/spec logic the analysis contracts assert, so the loop and
    the partition-contract rule can never drift apart.  ``fsdp=False``
    is everything-replicated (the historical layout); ``fsdp=True``
    shards optimizer moments per-leaf (``fsdp_spec``), params/EMA/stats
    replicated."""
    import jax
    from jax.sharding import NamedSharding

    def sharding_of(path, leaf):
        role = state_leaf_role(path)
        if fsdp and role == "opt_state":
            spec = fsdp_spec(tuple(getattr(leaf, "shape", ())),
                             env.data_size)
        else:
            spec = P()
        return NamedSharding(env.mesh, spec)

    return jax.tree_util.tree_map_with_path(sharding_of, state)


def simulated_mesh(n_devices: int, devices=None) -> MeshEnv:
    """A mesh over the first ``n_devices`` local devices — the
    fake-mesh machinery the audits compile against (tests/CLI force
    CPU virtual devices).  The data×model factorization comes from
    ``_MESH_SHAPES`` (n×1 for counts outside the matrix)."""
    import jax

    from gansformer_tpu.core.config import MeshConfig

    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n_devices:
        raise ValueError(
            f"simulated mesh needs {n_devices} devices, have "
            f"{len(devices)} (run under XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n_devices})")
    data, model = _MESH_SHAPES.get(n_devices, (n_devices, 1))
    return make_mesh(MeshConfig(data=data, model=model),
                     devices=devices[:n_devices])
