"""Device mesh & sharding layer — the framework's entire "comm backend".

The reference's distributed story is single-host in-graph GPU towers with an
NCCL gradient all-reduce buried in ``src/dnnlib/tflib/optimizer.py``
(SURVEY.md §2.4, T1 BASELINE.json:5).  On TPU that whole subsystem collapses
into this module: build a ``jax.sharding.Mesh``, hand out ``NamedSharding``\\ s,
and let XLA insert ``psum``/``all_gather`` collectives over ICI (intra-slice)
and DCN (cross-slice).  ``jit`` over sharded inputs *is* data parallelism;
there is no replica loop and no hand-written all-reduce anywhere in the
framework.

Axes:
  ``data``  — batch axis (the only axis GANsformer needs; O(n·k) attention and
              ≤~30M-param models make TP/PP unnecessary — SURVEY.md §2.4).
  ``model`` — reserved hook, size 1 by default, so that tensor-parallel
              shardings can be introduced without touching call sites.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gansformer_tpu.core.config import MeshConfig

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class MeshEnv:
    """A constructed mesh plus the shardings the training engine needs."""

    mesh: Mesh

    @property
    def data_size(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    @property
    def model_size(self) -> int:
        return self.mesh.shape[MODEL_AXIS]

    def batch(self) -> NamedSharding:
        """Shard leading (batch) axis over the data axis; replicate the rest."""
        return NamedSharding(self.mesh, P(DATA_AXIS))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_stack(self) -> NamedSharding:
        """[K, B, ...] stacked-iteration batches (the fused lazy-reg
        cycle's input): axis 0 is the iteration index, axis 1 the batch —
        shard the batch axis over data, replicate the stack axis."""
        return NamedSharding(self.mesh, P(None, DATA_AXIS))

    def shard_batch(self, tree):
        """Device-put a host-local batch tree onto the data axis."""
        sh = self.batch()
        return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)

    @property
    def local_data_rows(self) -> int:
        """Data-axis rows whose devices live on THIS process (the unit of
        per-process batch divisibility for ``make_array_from_process_local_
        data``)."""
        pid = jax.process_index()
        mine = sum(1 for d in self.mesh.devices.flat
                   if d.process_index == pid)
        return max(1, mine // self.model_size)

    def put_global(self, arr):
        """Host array with IDENTICAL content on every process → global array
        sharded on the data axis.

        Single-process this is a plain ``device_put``; multi-process a
        ``device_put`` cannot address remote shards, so the global array is
        assembled per-device from the full host copy
        (``make_array_from_callback``).  Used by the metric sweep, whose
        z/t/label draws are seeded identically on every host."""
        sh = self.batch()
        if jax.process_count() == 1:
            return jax.device_put(arr, sh)
        arr = np.asarray(arr)
        return jax.make_array_from_callback(arr.shape, sh,
                                            lambda idx: arr[idx])

    def activate(self):
        """Context manager installing this mesh as the ambient mesh, so
        bare-``PartitionSpec`` sharding constraints (the sequence-parallel
        grid sharding in ``models/attention.py``) resolve inside ``jit``.

        jax ≥ 0.6 exposes this as ``jax.sharding.set_mesh``; on older jax
        (0.4.x, this container) ``Mesh`` itself is the ambient-mesh context
        manager — same semantics for the bare-spec constraints used here."""
        try:
            from jax.sharding import set_mesh
        except ImportError:
            return self.mesh
        return set_mesh(self.mesh)


def ambient_mesh():
    """The ambient mesh (``set_mesh`` on jax ≥ 0.6, ``with Mesh:`` on
    0.4/0.5 — the two forms ``MeshEnv.activate`` installs), or None.

    Same resolution order as the sequence-parallel constraint in
    ``models/attention.py``: prefer the abstract mesh, but an empty one
    must fall through to the thread-resources physical mesh — on the
    jax-0.5.x window ``with Mesh:`` populates only the latter."""
    mesh = None
    try:
        from jax.sharding import get_abstract_mesh

        mesh = get_abstract_mesh()
    except ImportError:
        pass
    if mesh is None or mesh.empty:
        try:
            from jax._src.mesh import thread_resources
        except ImportError:     # private symbol gone: treat as no mesh
            return None
        mesh = thread_resources.env.physical_mesh
    return None if mesh.empty else mesh


def ambient_data_size() -> int:
    """Size of the ambient mesh's ``data`` axis (1 when no ambient mesh
    or no data axis) — the trace-time question the in-step batch
    constraints ask before pinning a spec."""
    mesh = ambient_mesh()
    if mesh is None or DATA_AXIS not in mesh.axis_names:
        return 1
    return mesh.shape[DATA_AXIS]


def constrain_data_axis(x, axis: int = 0):
    """Pin a per-example array's batch axis onto the ``data`` mesh axis.

    THE batch-parallelism hook for arrays *created inside* a jitted
    step (latent/noise draws): without it a replicated RNG key makes
    the whole downstream compute replicated — N chips each doing the
    full batch — and the compiled program shows zero collectives (the
    graftcomms finding that motivated ISSUE 7).  With it, GSPMD shards
    synthesis over ``data`` and inserts the gradient all-reduce.

    No-op when no ambient mesh (or no data axis, or a batch the axis
    doesn't divide — e.g. the path-length probe at batch//pl_shrink):
    the value is IDENTICAL either way (a sharding constraint is a
    layout annotation, not math), so mesh data=1 runs are bit-identical
    to the unconstrained program."""
    size = ambient_data_size()
    if size <= 1 or x.shape[axis] % size != 0:
        return x
    spec = P(*([None] * axis), DATA_AXIS)
    return jax.lax.with_sharding_constraint(x, spec)


def init_distributed(cfg: MeshConfig) -> None:
    """Form the multi-host process group (no-op for single-process runs).

    Replaces the reference's "one process drives all GPUs" model: each host
    runs one process, ``jax.distributed.initialize`` forms the group, and the
    global mesh spans every chip in the slice.
    """
    if cfg.coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )


def make_mesh(cfg: MeshConfig = MeshConfig(),
              devices: Optional[Sequence[jax.Device]] = None) -> MeshEnv:
    devices = list(devices if devices is not None else jax.devices())
    data, model = cfg.axis_sizes(len(devices))
    if data * model > len(devices):
        raise ValueError(
            f"mesh {data}x{model} needs {data * model} devices, have {len(devices)}")
    grid = np.asarray(devices[: data * model]).reshape(data, model)
    return MeshEnv(mesh=Mesh(grid, (DATA_AXIS, MODEL_AXIS)))


def batch_sharding(env: MeshEnv) -> NamedSharding:
    return env.batch()


def replicated(env: MeshEnv) -> NamedSharding:
    return env.replicated()


def local_batch_size(global_batch: int, env: MeshEnv) -> int:
    """Per-process share of the global batch (multi-host input pipeline).

    Each data-axis row holds one batch shard (replicated across the model
    axis), so the local share is per-row batch × the number of data rows
    whose devices live on this process.
    """
    if global_batch % env.data_size != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by data axis {env.data_size}")
    per_row = global_batch // env.data_size
    pid = jax.process_index()
    local_mesh_devices = sum(
        1 for d in env.mesh.devices.flat if d.process_index == pid)
    local_rows = local_mesh_devices // env.model_size
    if local_rows == 0:
        raise ValueError(
            f"process {pid} contributes no devices to the mesh "
            f"{dict(zip(env.mesh.axis_names, env.mesh.devices.shape))}; "
            f"shrink the process set or grow the mesh")
    return per_row * local_rows
