"""Physics/consistency validation for throughput measurements.

The round-3 bench recorded 1021.9 img/s/chip on a v5e — ~3× the chip's
bf16 peak — and nothing in the harness noticed (VERDICT r3 weak #1).
These are the pure checks ``bench.py`` runs over its own timings before
presenting them as measurements; they live here, separate from the
measurement loop, so the validation itself is unit-tested
(``tests/test_benchcheck.py``).

All FLOPs are PER-DEVICE (XLA cost analysis on the partitioned module —
see ``flops_of``), paired with per-device phase times.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


def flops_of(compiled) -> Optional[float]:
    """PER-DEVICE FLOPs of a compiled program from XLA cost analysis.

    Under SPMD, cost analysis runs on the partitioned per-device module —
    verified empirically: a 4-way-sharded einsum reports total/4 — so these
    numbers pair directly with per-chip phase times for MFU (no further
    division by device count).  Returns None when the backend reports no
    usable figure."""
    f = _cost_metric(compiled, "flops")
    return f if f else None


def _cost_metric(compiled, key: str) -> Optional[float]:
    """ONE metric from ``cost_analysis()`` (list-wrapped on some
    backends), or None when the backend reports no usable figure — the
    single extraction every cost reader goes through."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        v = float(ca.get(key, 0.0))
        return v if v > 0 else None
    except Exception:
        return None


def bytes_accessed_of(compiled) -> Optional[float]:
    """PER-DEVICE bytes accessed from XLA cost analysis (raw, like
    ``flops_of``), or None when the backend reports no usable figure."""
    return _cost_metric(compiled, "bytes accessed")


def cost_summary(compiled) -> Dict[str, Optional[float]]:
    """``{'gflops', 'gbytes'}`` from XLA cost analysis (None when the
    backend reports no usable figure) — the shared extraction for the
    satellite benches' JSON lines (``flops_of`` stays the raw-FLOPs API
    the MFU math uses)."""
    fl = _cost_metric(compiled, "flops")
    by = _cost_metric(compiled, "bytes accessed")
    return {"gflops": round(fl / 1e9, 3) if fl else None,
            "gbytes": round(by / 1e9, 4) if by else None}


def temp_workspace_gbytes(compiled) -> Optional[float]:
    """Temp-workspace GB from ``memory_analysis()`` (None when absent) —
    the §2 readiness quantity, shared by the satellite benches."""
    try:
        ma = compiled.memory_analysis()
        v = float(getattr(ma, "temp_size_in_bytes", 0.0))
        return round(v / 1e9, 4) if v > 0 else None
    except Exception:
        return None


# bf16 peak TFLOP/s per chip by device_kind substring (public TPU specs).
# Order matters: 'v5 lite' must win over 'v5'.
BF16_PEAK_TFLOPS: List[Tuple[str, float]] = [
    ("v6e", 918.0), ("v6 lite", 918.0), ("v6", 918.0),
    ("v5e", 197.0), ("v5 lite", 197.0), ("v5litepod", 197.0),
    ("v5p", 459.0), ("v5", 459.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 46.0),
]

# HBM bandwidth GB/s per chip by device_kind substring (public TPU
# specs) — the memory roof of the roofline classification below.
HBM_PEAK_GBPS: List[Tuple[str, float]] = [
    ("v6e", 1640.0), ("v6 lite", 1640.0), ("v6", 1640.0),
    ("v5e", 819.0), ("v5 lite", 819.0), ("v5litepod", 819.0),
    ("v5p", 2765.0), ("v5", 2765.0),
    ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0),
]


def _lookup(table: List[Tuple[str, float]],
            device_kind: str) -> Optional[float]:
    dk = device_kind.lower()
    for key, val in table:
        if key in dk:
            return val
    return None


def peak_tflops(device_kind: str) -> Optional[float]:
    return _lookup(BF16_PEAK_TFLOPS, device_kind)


def peak_hbm_gbps(device_kind: str) -> Optional[float]:
    return _lookup(HBM_PEAK_GBPS, device_kind)


def roofline(flops: Optional[float], bytes_accessed: Optional[float],
             peak_tflops_per_chip: Optional[float],
             hbm_gbps: Optional[float],
             ms: Optional[float] = None) -> dict:
    """Roofline classification of one program from XLA cost analysis
    (ISSUE 14 satellite): arithmetic intensity (FLOPs/byte) against the
    machine-balance ridge point decides whether the compute or the
    memory roof binds; with a measured ``ms``, ``pct_of_roof`` is the
    achieved fraction of the BINDING ceiling — the attributable number
    (a memory-bound op at 90% of its bandwidth roof is done; the same
    MFU on a compute-bound op is the optimization target).

    Pure and unit-tested (tests/test_benchcheck.py); returns {} when the
    inputs can't support the classification.
    """
    if not flops or not bytes_accessed or not peak_tflops_per_chip \
            or not hbm_gbps:
        return {}
    intensity = flops / bytes_accessed                     # FLOP/byte
    ridge = peak_tflops_per_chip * 1e12 / (hbm_gbps * 1e9)
    bound = "compute" if intensity >= ridge else "memory"
    roof_flops_s = min(peak_tflops_per_chip * 1e12,
                       intensity * hbm_gbps * 1e9)
    out = {"intensity_flops_per_byte": round(intensity, 2),
           "ridge_flops_per_byte": round(ridge, 2),
           "bound": bound,
           "roof_ms": round(flops / roof_flops_s * 1e3, 4)}
    if ms:
        out["pct_of_roof"] = round((flops / (ms * 1e-3)) / roof_flops_s, 4)
    return out


def cadence_weighted(vals: Dict[str, float], d_reg_interval: int,
                     g_reg_interval: int) -> float:
    """Steady-state per-iteration cost at the lazy-reg cadence (SURVEY
    §3.1 hot loop).  With only (d, g) present, reg phases are approximated
    by the plain ones."""
    d0, g0 = vals["d"], vals["g"]
    dr = vals.get("d_r1", d0)
    gp = vals.get("g_pl", g0)
    return (d0 * (1 - 1 / d_reg_interval) + dr / d_reg_interval
            + g0 * (1 - 1 / g_reg_interval) + gp / g_reg_interval)


def mfu(flops_per_it: float, seconds_per_it: float,
        peak_tflops_per_chip: float) -> float:
    """Model FLOPs utilization: achieved per-chip FLOP/s over bf16 peak."""
    return flops_per_it / seconds_per_it / (peak_tflops_per_chip * 1e12)


def trace_suspect(busy_s: float, wall_s: float, iters: int,
                  per_it_s: float) -> Optional[str]:
    """The xplane device-time witness check (pure; bench.py wires it).

    ``busy_s`` is what the device plane says it executed during a traced
    window the host claims lasted ``wall_s`` (and whose per-iteration
    claim is ``per_it_s`` × ``iters``).  Device busy far above both claims
    means the wall clock stopped before the chip did."""
    if busy_s <= 0:
        return None
    claim = max(wall_s, iters * per_it_s)
    if busy_s > 1.5 * claim + 0.1:
        return (f"trace: device busy {busy_s:.3f}s in a window claimed to "
                f"last {claim:.3f}s — wall clock is not covering device "
                f"execution")
    return None


def find_suspects(
    timings: Dict[str, float],          # per-iteration seconds, per phase
    flops: Dict[str, float],            # per-device FLOPs, per phase
    *,
    d_reg_interval: int,
    g_reg_interval: int,
    peak: Optional[float] = None,       # bf16 TFLOP/s per chip
    device_kind: str = "?",
    iters: int = 1,
    fetch_tails: Optional[Dict[str, float]] = None,   # post-block sync, s
    linearity: Optional[Dict[str, Tuple[float, float]]] = None,
    flops_ratio_tol: float = 0.35,
    linearity_band: Tuple[float, float] = (0.7, 1.5),
) -> List[str]:
    """Reasons this measurement cannot be trusted; empty = no objection.

    Checks (VERDICT r3 item 1a):
    * implied MFU ≥ 1.0 — faster than the device's physics;
    * t(d_r1)/t(d) inconsistent with the phases' FLOPs ratio — the timer
      is not scaling with compute;
    * per-iteration time shifts at doubled iteration count — wall clock
      not proportional to work;
    * a ``device_get`` sync tail comparable to the timed loop — the
      block clock stopped before the device finished (early relay acks).
    """
    out: List[str] = []
    if peak and all(k in flops for k in timings):
        m = mfu(cadence_weighted(flops, d_reg_interval, g_reg_interval),
                cadence_weighted(timings, d_reg_interval, g_reg_interval),
                peak)
        if m >= 1.0:
            out.append(
                f"mfu {m:.2f} >= 1.0 — implied throughput exceeds "
                f"{device_kind} bf16 peak ({peak} TFLOP/s); the timer is "
                f"not measuring the device")
    if "d_r1" in timings and flops.get("d") and flops.get("d_r1"):
        tr = timings["d_r1"] / timings["d"]
        fr = flops["d_r1"] / flops["d"]
        if abs(tr - fr) / fr > flops_ratio_tol:
            out.append(
                f"t(d_r1)/t(d) = {tr:.2f} but FLOPs ratio = {fr:.2f} "
                f"— phase times do not scale with compute")
    for name, (t1, t2) in (linearity or {}).items():
        ratio = t2 / t1 if t1 > 0 else 0.0
        lo, hi = linearity_band
        if not (lo <= ratio <= hi):
            out.append(
                f"linearity({name}): per-it time at 2N iters is "
                f"{ratio:.2f}x the N-iter time (expect ~1.0) — "
                f"wall clock not proportional to work done")
    for name, tail in (fetch_tails or {}).items():
        # An honest block_until_ready leaves only ~1 RTT of sync tail; a
        # tail comparable to the whole timed loop means the work was
        # still running when the clock stopped.
        loop_total = timings[name] * iters
        if tail > 0.3 * loop_total + 1.0:
            out.append(
                f"{name}: device_get sync tail {tail:.2f}s after a "
                f"{loop_total:.2f}s timed loop — block_until_ready "
                f"returned before the device finished (early acks)")
    return out


def single_timer_suspects(
    name: str,
    per_it_s: float,
    tail_s: float,
    iters: int,
    per_it_2n_s: Optional[float] = None,
    linearity_band: Tuple[float, float] = (0.7, 1.5),
) -> List[str]:
    """``find_suspects``'s early-ack defenses for ONE timed program (no
    phase structure): the satellite benches (bench_pallas_attention)
    route their loops through ``bench.steady_state_time`` and this check
    so their numbers inherit the r3-retraction discipline.  Empty list =
    no objection."""
    out: List[str] = []
    loop_total = per_it_s * iters
    if tail_s > 0.3 * loop_total + 1.0:
        out.append(
            f"{name}: device_get sync tail {tail_s:.2f}s after a "
            f"{loop_total:.2f}s timed loop — block_until_ready returned "
            f"before the device finished (early acks)")
    if per_it_2n_s is not None and per_it_s > 0:
        ratio = per_it_2n_s / per_it_s
        lo, hi = linearity_band
        if not (lo <= ratio <= hi):
            out.append(
                f"linearity({name}): per-it time at 2N iters is "
                f"{ratio:.2f}x the N-iter time (expect ~1.0) — wall "
                f"clock not proportional to work done")
    return out


def lower_phase(cfg, phase: str, batch_size: Optional[int] = None):
    """AOT-compile ONE real step phase with abstract args — the shared
    lowering every measurement surface uses (bench_components'
    share-of-step denominator, ab_levers' per-variant cost pass,
    readiness_ffhq1024's memory_analysis, the lever acceptance tests).

    Handles the conditional-label arg (a labeled config's D head raises
    at trace time without it) in exactly one place.  Imports lazily so
    this module's pure validation half stays importable without jax.
    Returns the compiled executable (cost_analysis / memory_analysis /
    direct calls all hang off it).
    """
    import jax
    import numpy as np

    from gansformer_tpu.train.state import create_train_state
    from gansformer_tpu.train.steps import make_train_steps

    b = batch_size if batch_size is not None else cfg.train.batch_size
    fns = make_train_steps(cfg, batch_size=b)
    fn = {"d": fns.d_step, "d_r1": fns.d_step_r1,
          "g": fns.g_step, "g_pl": fns.g_step_pl}[phase]
    key_s = jax.ShapeDtypeStruct((2,), np.uint32)
    state_s = jax.eval_shape(lambda k: create_train_state(cfg, k), key_s)
    imgs_s = jax.ShapeDtypeStruct(
        (b, cfg.model.resolution, cfg.model.resolution,
         cfg.model.img_channels), np.uint8)
    lbl_s = (jax.ShapeDtypeStruct((b, cfg.model.label_dim), np.float32)
             if cfg.model.label_dim else None)
    args = ((state_s, imgs_s, key_s, lbl_s) if phase.startswith("d")
            else (state_s, key_s, lbl_s))
    return fn.lower(*args).compile()
