"""Image grid saving — the reference's ``misc.save_image_grid`` (SURVEY.md
§2.2 "Misc/vis utils"): every tick the loop writes ``fakes<kimg>.png`` so a
human can eyeball training health (the reference's primary "test" — §4)."""

from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np


def to_uint8(images: np.ndarray, drange: Tuple[float, float] = (-1, 1)) -> np.ndarray:
    """float [N,H,W,C] in drange → uint8."""
    lo, hi = drange
    img = (np.asarray(images, dtype=np.float32) - lo) * (255.0 / (hi - lo))
    return np.clip(np.rint(img), 0, 255).astype(np.uint8)


def make_grid(images: np.ndarray, grid: Optional[Tuple[int, int]] = None) -> np.ndarray:
    """[N,H,W,C] uint8 → one [GH*H, GW*W, C] tile image."""
    n, h, w, c = images.shape
    if grid is None:
        gw = max(1, int(math.sqrt(n)))
        gh = (n + gw - 1) // gw
    else:
        gw, gh = grid
    canvas = np.zeros((gh * h, gw * w, c), dtype=np.uint8)
    for i in range(min(n, gw * gh)):
        r, col = divmod(i, gw)
        canvas[r * h:(r + 1) * h, col * w:(col + 1) * w] = images[i]
    return canvas


def save_image_grid(images, path: str, drange: Tuple[float, float] = (-1, 1),
                    grid: Optional[Tuple[int, int]] = None) -> None:
    from PIL import Image

    arr = make_grid(to_uint8(np.asarray(images), drange), grid)
    if arr.shape[-1] == 1:
        arr = arr[..., 0]
    Image.fromarray(arr).save(path)


# Distinct colors for up to 32 latent components (k ≤ 32 in every config).
_COMPONENT_COLORS = np.array(
    [[230, 25, 75], [60, 180, 75], [255, 225, 25], [0, 130, 200],
     [245, 130, 48], [145, 30, 180], [70, 240, 240], [240, 50, 230],
     [210, 245, 60], [250, 190, 212], [0, 128, 128], [220, 190, 255],
     [170, 110, 40], [255, 250, 200], [128, 0, 0], [170, 255, 195],
     [128, 128, 0], [255, 215, 180], [0, 0, 128], [128, 128, 128],
     [255, 255, 255], [0, 0, 0], [233, 109, 109], [109, 233, 168],
     [109, 150, 233], [233, 208, 109], [176, 109, 233], [109, 233, 233],
     [233, 109, 187], [150, 150, 80], [80, 150, 150], [150, 80, 150]],
    dtype=np.float32)


def attention_overlay(images: np.ndarray, probs: np.ndarray,
                      alpha: float = 0.55) -> np.ndarray:
    """Blend latent→region assignment maps over the generated images — the
    GANsformer paper's attention visualization.

    images: [N,H,W,3] float in [-1,1]; probs: [N,h,w,k] row-stochastic over
    k (any attention resolution — nearest-upsampled to the image size).
    Returns uint8 [N,H,W,3]: grayscale image under a per-component color
    segmentation weighted by assignment confidence."""
    imgs = to_uint8(images).astype(np.float32)
    n, H, W, _ = imgs.shape
    k = probs.shape[-1]
    # nearest-neighbour upsample the maps to the image resolution
    ph, pw = probs.shape[1:3]
    probs = np.asarray(probs, np.float32)
    probs = probs[:, np.repeat(np.arange(ph), H // ph), :, :][
        :, :, np.repeat(np.arange(pw), W // pw), :]
    # palette tiles past 32 components (colors repeat rather than crash)
    colors = _COMPONENT_COLORS[np.arange(k) % len(_COMPONENT_COLORS)]
    seg = probs @ colors                                # [N,H,W,3]
    gray = imgs.mean(axis=-1, keepdims=True)
    out = (1 - alpha) * np.broadcast_to(gray, imgs.shape) + alpha * seg
    return np.clip(np.rint(out), 0, 255).astype(np.uint8)


def save_attention_grid(images, probs, path: str,
                        grid: Optional[Tuple[int, int]] = None) -> None:
    """Attention-overlay grid PNG (cli/generate.py --save-attention)."""
    from PIL import Image

    arr = make_grid(attention_overlay(np.asarray(images), np.asarray(probs)),
                    grid)
    Image.fromarray(arr).save(path)
