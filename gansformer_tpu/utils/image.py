"""Image grid saving — the reference's ``misc.save_image_grid`` (SURVEY.md
§2.2 "Misc/vis utils"): every tick the loop writes ``fakes<kimg>.png`` so a
human can eyeball training health (the reference's primary "test" — §4)."""

from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np


def to_uint8(images: np.ndarray, drange: Tuple[float, float] = (-1, 1)) -> np.ndarray:
    """float [N,H,W,C] in drange → uint8."""
    lo, hi = drange
    img = (np.asarray(images, dtype=np.float32) - lo) * (255.0 / (hi - lo))
    return np.clip(np.rint(img), 0, 255).astype(np.uint8)


def make_grid(images: np.ndarray, grid: Optional[Tuple[int, int]] = None) -> np.ndarray:
    """[N,H,W,C] uint8 → one [GH*H, GW*W, C] tile image."""
    n, h, w, c = images.shape
    if grid is None:
        gw = max(1, int(math.sqrt(n)))
        gh = (n + gw - 1) // gw
    else:
        gw, gh = grid
    canvas = np.zeros((gh * h, gw * w, c), dtype=np.uint8)
    for i in range(min(n, gw * gh)):
        r, col = divmod(i, gw)
        canvas[r * h:(r + 1) * h, col * w:(col + 1) * w] = images[i]
    return canvas


def save_image_grid(images, path: str, drange: Tuple[float, float] = (-1, 1),
                    grid: Optional[Tuple[int, int]] = None) -> None:
    from PIL import Image

    arr = make_grid(to_uint8(np.asarray(images), drange), grid)
    if arr.shape[-1] == 1:
        arr = arr[..., 0]
    Image.fromarray(arr).save(path)
