"""Host-environment sanitation for CPU-only JAX child processes.

This build container injects a TPU PJRT plugin through a ``PYTHONPATH``
sitecustomize that claims a single-session TPU tunnel at interpreter start
and can hang every later interpreter — even under ``JAX_PLATFORMS=cpu``.
Anything that needs a deterministic CPU (or virtual multi-device CPU)
backend therefore re-execs in a child with this sanitized environment.
Used by ``bench.py`` (CPU fallback) and ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

import os
from typing import Dict, Optional


def sanitized_cpu_env(n_devices: int = 1,
                      extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """A copy of ``os.environ`` forced onto an n-device virtual CPU backend:
    TPU-plugin sitecustomize dropped, platform pinned, host devices forced."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env.pop("JAX_PLATFORM_NAME", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    if extra:
        env.update(extra)
    return env


def enable_compile_cache(repo_root: Optional[str] = None) -> None:
    """Turn on the persistent XLA compile cache for this process.

    The jax.config form of ``compile_cache_env`` — call before the first
    compile.  Entry points (CLI train/evaluate/generate/experiment, bench)
    share one cache dir, so a TPU training run warm-starts from the bench's
    compiles and vice versa; without this every CLI invocation cold-compiles
    the second-order-grad step variants (~minutes on the TPU tunnel).
    """
    import jax

    env = compile_cache_env(repo_root)
    jax.config.update("jax_compilation_cache_dir",
                      env["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                      int(env["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"]))


def compile_cache_env(repo_root: Optional[str] = None) -> Dict[str, str]:
    """The persistent-XLA-compile-cache env trio, defined once.

    Shared by tests/conftest.py, the dryrun child (__graft_entry__), and any
    other entry point that wants warm second-order-grad compiles.  One
    definition — a drifted copy silently gives that entry point a cold or
    separate cache.
    """
    root = repo_root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return {
        "JAX_COMPILATION_CACHE_DIR": os.path.join(root, ".jax_compile_cache"),
        # 1 s (not jax's default 1 s-vs-2 s ambiguity): over the TPU tunnel
        # even small programs cost real latency to re-lower, and the cache
        # exists precisely for tunnel-window thrift (ADVICE r4).
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1",
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "-1",
    }
