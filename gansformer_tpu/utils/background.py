"""Single-slot background writers — the host-side half of the overlap layer.

The train loop's throughput discipline (ISSUE 2 / PERF.md §1b) forbids
host work on the loop thread between dispatches.  Checkpoint writes and
image-grid snapshots are exactly that kind of work: serialize + encode +
fsync can cost hundreds of ms while the device idles.  ``SingleSlotWriter``
moves them to a background thread with deliberately *bounded* buffering:

* **single slot** — at most ONE job in flight.  Submitting while busy
  first joins the previous job, so a slow disk backpressures the loop
  instead of queueing an unbounded pile of multi-GB host pytrees.
* **sticky failures** — a job exception is stored and re-raised (wrapped
  in ``BackgroundWriteError``) at the next ``poll()`` / ``submit()`` /
  ``wait()``; the train loop polls at every tick boundary, so a writer
  crash surfaces within one tick instead of being silently swallowed.
* **joinable** — ``wait()`` blocks until the slot is empty; the loop's
  ``finally`` joins with ``reraise=False`` so a writer failure never
  masks the training exception that is already unwinding.

Telemetry (obs/registry), per writer ``prefix``:
``<prefix>_inflight`` gauge (0/1), ``<prefix>_total`` /
``<prefix>_errors_total`` counters, ``<prefix>_write_ms`` histogram, and
``<prefix>_writer_heartbeat`` gauge (unix time of the writer thread's
last activity — a stuck write is visible from telemetry.prom while the
loop is still running).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class BackgroundWriteError(RuntimeError):
    """A background writer job failed; ``__cause__`` is the original."""


class LoopWorker:
    """A long-lived background loop thread with the writer discipline.

    ``SingleSlotWriter`` owns one-shot jobs; this owns a CONTINUOUS
    loop (the serving dispatch loop, ISSUEs 10 + 13) under the same
    failure contract: the target runs once on its own thread (the
    target body is the ``while``), an escaped exception is stored
    STICKY — readable un-wrapped via ``error`` (how the serving
    supervisor classifies a death before restarting a replacement
    worker, serve/service.py) or re-raised wrapped in
    ``BackgroundWriteError`` at EVERY later ``poll()``.  Unlike
    ``SingleSlotWriter`` (one-shot jobs, error delivered once then
    cleared), a dead continuous loop never becomes healthy again —
    one ``LoopWorker`` is one dispatcher lifetime; recovery means a
    NEW worker, never a cleared error.  Telemetry, per ``prefix``:
    ``<prefix>_heartbeat`` gauge (last liveness touch — call
    ``beat()`` from inside the loop), ``<prefix>_errors_total``.
    """

    def __init__(self, target: Callable[[], None], prefix: str):
        self.prefix = prefix
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, args=(target,), name=f"{prefix}-loop",
            daemon=True)

    def _inst(self, kind: str, suffix: str):
        from gansformer_tpu.obs import registry as telemetry

        return getattr(telemetry, kind)(f"{self.prefix}{suffix}")

    def start(self) -> "LoopWorker":
        self._inst("gauge", "_heartbeat").set(time.time())
        self._thread.start()
        return self

    def beat(self) -> None:
        """Liveness touch — the loop body calls this per iteration so a
        wedged dispatch is visible from telemetry.prom."""
        self._inst("gauge", "_heartbeat").set(time.time())

    def poll(self) -> None:
        """Re-raise a loop crash — sticky forever: the loop is dead, so
        every caller from now on must see it, not just the first."""
        with self._lock:
            err = self._error
        if err is not None:
            raise BackgroundWriteError(
                f"{self.prefix} background loop died: "
                f"{type(err).__name__}: {err}") from err

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    def wait(self, reraise: bool = True,
             timeout: Optional[float] = None) -> bool:
        """Bounded join + optional error delivery.  Returns False when
        the loop thread is still running after ``timeout`` — a wedged
        dispatch must not block a preemption shutdown past its grace
        window (the thread is a daemon; abandoning it is safe)."""
        self._thread.join(timeout)
        if reraise:
            self.poll()
        return not self._thread.is_alive()

    def close(self, timeout: Optional[float] = None) -> bool:
        """Shutdown-path join: never raises (the sticky error stays for
        ``poll``), just reports whether the thread ended in time."""
        return self.wait(reraise=False, timeout=timeout)

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    @property
    def error(self) -> Optional[BaseException]:
        """The stored loop crash, un-raised and un-wrapped — for a
        SUPERVISOR deciding restart-vs-trip (serve/service.py), where
        ``poll()``'s raise-on-read contract is the wrong shape."""
        with self._lock:
            return self._error

    def _run(self, target: Callable[[], None]) -> None:
        try:
            target()
        except BaseException as e:  # noqa: BLE001 — re-raised via poll()
            with self._lock:
                self._error = e
            self._inst("counter", "_errors_total").inc()
        finally:
            self._inst("gauge", "_heartbeat").set(time.time())


class SingleSlotWriter:
    """Bounded (depth-1) background executor for writeback jobs."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._error_job: Optional[str] = None
        self._lock = threading.Lock()

    def _inst(self, kind: str, suffix: str):
        # Instruments are resolved PER CALL, not cached at construction:
        # writers outlive a single train() run (checkpoint.py keys them by
        # directory), and the loop resets the registry at run start — a
        # cached Gauge would silently update an orphaned instrument.
        from gansformer_tpu.obs import registry as telemetry

        return getattr(telemetry, kind)(f"{self.prefix}{suffix}")

    # -- consumer-side API (loop thread) ------------------------------------

    def submit(self, job: Callable[[], None], label: str = "") -> None:
        """Run ``job()`` on the writer thread.  Joins any in-flight job
        first (single slot = bounded backpressure) and raises a prior
        failure rather than burying it under new work."""
        self.wait()                     # join + re-raise sticky error
        with self._lock:
            self._inst("gauge", "_inflight").set(1)
            self._inst("gauge", "_writer_heartbeat").set(time.time())
            self._thread = threading.Thread(
                target=self._run, args=(job, label),
                name=f"{self.prefix}-writer", daemon=True)
            self._thread.start()

    def poll(self) -> None:
        """Re-raise a failed job's exception (tick-boundary check).
        Non-blocking; a still-running job is not an error.  The error is
        delivered ONCE and then cleared — a ``--resume`` reusing the same
        writer (checkpoint.py keys writers by directory) starts clean
        instead of tripping over the crash it is recovering from."""
        with self._lock:
            err, job = self._error, self._error_job
            self._error = self._error_job = None
        if err is not None:
            raise BackgroundWriteError(
                f"{self.prefix} background write"
                f"{f' ({job})' if job else ''} failed: "
                f"{type(err).__name__}: {err}") from err

    def wait(self, reraise: bool = True,
             timeout: Optional[float] = None) -> bool:
        """Join the in-flight job (if any); optionally re-raise failures.
        ``reraise=False`` is for ``finally`` blocks where a writer error
        must not mask the exception already unwinding.  ``timeout``
        bounds the join (preemption shutdown: a wedged writer must not
        eat the grace window); returns False when the job is still
        running after it — the sticky-error contract is untouched (an
        already-stored failure is still delivered when ``reraise``, and
        a failure that lands later surfaces at the next poll/wait)."""
        t = self._thread
        joined = True
        if t is not None:
            t.join(timeout)
            joined = not t.is_alive()
        if reraise:
            self.poll()
        return joined

    def close(self, timeout: Optional[float] = None) -> bool:
        """Shutdown-path join: never raises; reports whether the writer
        drained in time (daemon thread — abandoning it is safe)."""
        return self.wait(reraise=False, timeout=timeout)

    def clear_error(self) -> None:
        """Drop an undelivered sticky error WITHOUT raising it.  For run
        starts only: a writer cached across train() runs (checkpoint.py
        keys them by directory) may hold a failure from a previous run
        that aborted before its tick-boundary poll — the new run must
        not crash on it (the error was that run's secondary diagnostics;
        its ``_errors_total`` count remains)."""
        with self._lock:
            self._error = self._error_job = None

    @property
    def busy(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # -- writer thread -------------------------------------------------------

    def _run(self, job: Callable[[], None], label: str) -> None:
        t0 = time.perf_counter()
        try:
            job()
            self._inst("counter", "_total").inc()
        except BaseException as e:  # noqa: BLE001 — re-raised via poll()
            with self._lock:
                self._error = e
                self._error_job = label
            self._inst("counter", "_errors_total").inc()
        finally:
            self._inst("histogram", "_write_ms").observe(
                (time.perf_counter() - t0) * 1000.0)
            self._inst("gauge", "_writer_heartbeat").set(time.time())
            self._inst("gauge", "_inflight").set(0)
