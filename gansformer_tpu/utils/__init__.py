from gansformer_tpu.utils.image import save_image_grid, to_uint8
from gansformer_tpu.utils.logging import RunLogger
