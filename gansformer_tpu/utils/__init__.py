from gansformer_tpu.utils.image import (
    save_image_grid, to_uint8, attention_overlay, save_attention_grid)
from gansformer_tpu.utils.logging import RunLogger
