"""NaN/Inf debugging — the XLA analog of the reference's (nonexistent)
sanitizer story (SURVEY.md §5 "Race detection / sanitizers": TF1 serializes
everything; under JAX the equivalent debug switch is ``jax_debug_nans``).

Two layers:
* ``enable_nan_debug()`` flips ``jax_debug_nans`` — every jitted function
  re-runs op-by-op when a NaN appears and raises at the producing op.
  Costly (de-optimizes dispatch), so it's a flag, not a default.
* ``check_finite_stats()`` — cheap always-available tick-boundary guard:
  raises ``FloatingPointError`` naming the first non-finite scalar, so a
  diverging run dies loudly at the next tick instead of training on NaNs
  for hours.
"""

from __future__ import annotations

import math
from typing import Dict


def enable_nan_debug() -> None:
    import jax

    jax.config.update("jax_debug_nans", True)


def check_finite_stats(stats: Dict[str, float], where: str = "") -> None:
    """Raise FloatingPointError on the first non-finite scalar in a
    fetched tick-stats dict."""
    for k, v in stats.items():
        if isinstance(v, (int, float)) and not math.isfinite(v):
            raise FloatingPointError(
                f"non-finite training statistic {k!r} = {v}"
                + (f" at {where}" if where else "")
                + "; re-run with --debug-nans to locate the producing op")
