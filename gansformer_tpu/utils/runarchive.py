"""Packed run-dir archives — the reference's pretrained-model loading
surface (SURVEY.md §2.2 "Generate/eval CLI": ``loader.py`` /
``pretrained_networks.py`` load a snapshot from a local path OR a URL).

The reference distributes models as single pickle files; this framework's
checkpoint is an Orbax *directory* plus ``config.json``, so the
distributable unit is a tarball of the run dir.  ``pack_run`` creates one
(config + latest checkpoint only — not the image grids / logs);
``resolve_run_dir`` accepts a plain run dir, a local ``.tar.gz``, or an
``http(s)://`` URL of one (downloaded through ``data/download.py``'s
resumable cache) and hands back a usable run dir either way.
"""

from __future__ import annotations

import hashlib
import os
import tarfile
from typing import Optional


def pack_run(run_dir: str, out_path: Optional[str] = None,
             step: Optional[int] = None) -> str:
    """Pack ``config.json`` + one checkpoint step into a ``.tar.gz``.

    Default: the latest checkpoint (the reference ships one snapshot per
    pickle; same granularity here).
    """
    from gansformer_tpu.train import checkpoint as ckpt

    cfg = os.path.join(run_dir, "config.json")
    if not os.path.exists(cfg):
        raise FileNotFoundError(f"no config.json under {run_dir}")
    ckpt_root = os.path.join(run_dir, "checkpoints")
    if step is None:
        step = ckpt.latest_step(ckpt_root)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {ckpt_root}")
    step_dir = os.path.join(ckpt_root, str(step))
    if not os.path.isdir(step_dir):
        raise FileNotFoundError(f"no checkpoint step {step} in {ckpt_root}")
    out_path = out_path or os.path.join(
        os.path.dirname(os.path.normpath(run_dir)) or ".",
        f"{os.path.basename(os.path.normpath(run_dir))}-step{step}.tar.gz")
    with tarfile.open(out_path, "w:gz") as t:
        t.add(cfg, arcname="run/config.json")
        t.add(step_dir, arcname=f"run/checkpoints/{step}")
    return out_path


def resolve_run_dir(spec: str, cache_dir: Optional[str] = None) -> str:
    """Plain dir → itself; ``.tar(.gz)`` path or ``http(s)://`` URL → a
    cached extraction of the packed run."""
    if os.path.isdir(spec):
        return spec
    cache_dir = cache_dir or os.path.join(
        os.path.expanduser("~"), ".cache", "gansformer_tpu", "runs")
    if spec.startswith(("http://", "https://")):
        from gansformer_tpu.data.download import download

        url_key = hashlib.sha256(spec.encode()).hexdigest()[:16]
        archive = os.path.join(cache_dir, url_key,
                               os.path.basename(spec) or "run.tar.gz")
        download(spec, archive)
    elif os.path.isfile(spec):
        archive = spec
    else:
        raise FileNotFoundError(
            f"{spec!r} is neither a run dir, an archive, nor a URL")
    # Extraction key includes the archive's size+mtime: re-packing to the
    # same path must invalidate the cached extraction, or metrics would
    # silently run against the stale checkpoint.
    st = os.stat(archive)
    key = hashlib.sha256(
        f"{os.path.abspath(archive)}:{st.st_size}:{st.st_mtime_ns}"
        .encode()).hexdigest()[:16]
    out = os.path.join(cache_dir, key, "extracted")
    marker = os.path.join(out, ".extracted")
    if not os.path.exists(marker):
        os.makedirs(out, exist_ok=True)
        with tarfile.open(archive) as t:
            t.extractall(out, filter="data")
        with open(marker, "w") as f:
            f.write("ok\n")
    run = os.path.join(out, "run")
    if not os.path.exists(os.path.join(run, "config.json")):
        raise FileNotFoundError(
            f"archive {spec!r} holds no run/config.json — not a pack_run "
            f"archive")
    return run
