"""Run-dir logging & observability.

Replaces the reference's trio of ``autosummary`` → TensorBoard events,
``log.txt`` stdout tee, and per-tick console lines (SURVEY.md §5
"Metrics / logging").  Design: one structured per-tick dict goes to
(1) the console in the reference's one-line format, (2) ``stats.jsonl``
(machine-readable), (3) a real TensorBoard event file under
``<run_dir>/tensorboard/`` (dependency-free writer,
``utils/tensorboard.py``), and (4) scalar names kept
reference-compatible (``Loss/G``, ``Progress/kimg``,
``timing/img_per_sec_per_chip``) so dashboards translate 1:1.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, Optional


def append_metric_line(run_dir: str, name: str, value: float,
                       kimg: float) -> None:
    """The one place that knows the metric-<name>.txt line format
    (reference convention, SURVEY.md §3.3)."""
    with open(os.path.join(run_dir, f"metric-{name}.txt"), "a") as f:
        f.write(f"kimg {kimg:<10.1f} {name} {value:.6f}\n")


def write_flag(run_dir: str, name: str, value) -> None:
    """Boolean/enum run FLAGS (e.g. the metric sweep's ``calibrated``
    regime) are state, not series: one ``flag-<name>.txt`` overwritten in
    place — never a ``metric-<name>.txt`` pseudo-metric whose every line
    repeats the same 0.000000 (VERDICT r5 weak #4 / item 7)."""
    v = int(value) if isinstance(value, (bool, int, float)) else value
    with open(os.path.join(run_dir, f"flag-{name}.txt"), "w") as f:
        f.write(f"{name} {v}\n")


def append_resume_record(run_dir: str, step: int) -> None:
    """One JSON line per ``--resume`` restart → ``resumes.jsonl``.  The
    run doctor counts these as the restart/availability evidence (ISSUE
    8 / ROADMAP item 5): a run dir with N lines survived N preemptions
    or crashes, and the last line says where it picked back up.

    The richer ``supervisor_events.jsonl`` schema (supervise/events.py)
    supersedes this file; it is kept for back-compat readers.  An
    UNSUPERVISED ``--resume`` also mirrors its record into the
    supervisor ledger (kind ``resume``) so the doctor's availability
    section sees manual re-arms too; under ``gansformer-supervise`` the
    supervisor owns the ledger and the mirror is skipped (it would
    double-count the restart the supervisor already logged)."""
    rec = {"time": time.time(), "step": int(step), "pid": os.getpid()}
    with open(os.path.join(run_dir, "resumes.jsonl"), "a") as f:
        f.write(json.dumps(rec) + "\n")
    if not os.environ.get("GANSFORMER_TPU_SUPERVISED"):
        from gansformer_tpu.supervise import events

        events.append_event(run_dir, "resume", step=int(step),
                            source="train")


def read_resume_records(run_dir: str):
    """Resume records, torn-line-tolerant (a SIGKILL mid-append is the
    normal ending for exactly the runs the doctor inspects)."""
    path = os.path.join(run_dir, "resumes.jsonl")
    out = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


class RunLogger:
    """Run-dir writer.  ``active=False`` (non-zero process index in a
    multi-host run) turns every write into a no-op so only one host owns
    the run dir's files."""

    def __init__(self, run_dir: str, active: bool = True):
        self.run_dir = run_dir
        self.active = active
        self.tb = None
        self._closed = False
        if active:
            os.makedirs(run_dir, exist_ok=True)
            self.jsonl = open(os.path.join(run_dir, "stats.jsonl"), "a")
            self.log_file = open(os.path.join(run_dir, "log.txt"), "a")
            from gansformer_tpu.utils.tensorboard import EventWriter

            self.tb = EventWriter(os.path.join(run_dir, "tensorboard"))
        self.t0 = time.time()

    def log_tick(self, stats: Dict[str, float],
                 telemetry: Optional[dict] = None) -> None:
        """One tick record.  ``stats`` holds flat scalars (including the
        tracer's ``timing/phase/*`` breakdown, which therefore reaches
        TensorBoard for free); ``telemetry`` is the registry snapshot
        (counters/gauges/histograms), embedded as a nested section of
        the jsonl record only — TensorBoard gets scalars, the machine
        record gets everything."""
        if not self.active:
            return
        rec = {"time": round(time.time() - self.t0, 2), **{
            k: (round(float(v), 6) if isinstance(v, (int, float)) else v)
            for k, v in stats.items()}}
        if telemetry is not None:
            rec["telemetry"] = telemetry
        self.jsonl.write(json.dumps(rec) + "\n")
        self.jsonl.flush()
        if self.tb is not None:
            # global step = images seen (the lineage's x-axis convention)
            self.tb.scalars(stats,
                            step=int(stats.get("Progress/kimg", 0.0) * 1000))
        line = ("tick {tick:<5d} kimg {kimg:<8.1f} "
                "time {time:<8.1f} sec/tick {sec_tick:<7.1f} "
                "img/s {imgs:<8.1f} G {g:<6.3f} D {d:<6.3f}").format(
            tick=int(stats.get("Progress/tick", 0)),
            kimg=stats.get("Progress/kimg", 0.0),
            time=rec["time"],
            sec_tick=stats.get("timing/sec_per_tick", 0.0),
            imgs=stats.get("timing/img_per_sec", 0.0),
            g=stats.get("Loss/G", float("nan")),
            d=stats.get("Loss/D", float("nan")))
        self.write(line)

    def write(self, msg: str) -> None:
        if not self.active:
            return
        print(msg)
        sys.stdout.flush()
        if self._closed:
            # post-close writes (the CLI's preemption farewell runs after
            # train()'s context manager released the files) still reach
            # the console; writing to the closed file would raise and
            # turn a clean preemption exit into a crash code.
            return
        self.log_file.write(msg + "\n")
        self.log_file.flush()

    def metric(self, name: str, value: float, kimg: float) -> None:
        if not self.active:
            return
        append_metric_line(self.run_dir, name, value, kimg)
        if self.tb is not None:
            self.tb.scalars({f"Metrics/{name}": value},
                            step=int(kimg * 1000))

    def flag(self, name: str, value) -> None:
        """Run flags → flag-<name>.txt (state file, not a metric series)."""
        if not self.active:
            return
        write_flag(self.run_dir, name, value)

    def close(self) -> None:
        """Idempotent — the context-manager exit and an explicit caller
        close may both run (train() owns the logger's lifetime even when
        the caller constructed it)."""
        if self.active and not self._closed:
            self._closed = True
            self.jsonl.close()
            self.log_file.close()
            if self.tb is not None:
                self.tb.close()

    def __enter__(self) -> "RunLogger":
        return self

    def __exit__(self, *exc) -> None:
        # never swallow the training exception; just release the files
        self.close()


def list_run_dirs(results_root: str):
    """Numbered run dirs under results_root, sorted by run id."""
    if not os.path.isdir(results_root):
        return []
    return sorted(
        os.path.join(results_root, d) for d in os.listdir(results_root)
        if os.path.isdir(os.path.join(results_root, d))
        and d.split("-")[0].isdigit())


def next_run_id(results_root: str) -> int:
    existing = [int(os.path.basename(d).split("-")[0])
                for d in list_run_dirs(results_root)]
    return max(existing, default=-1) + 1


def create_run_dir(results_root: str, desc: str,
                   run_id: Optional[int] = None, create: bool = True) -> str:
    """Numbered run dirs — reference ``results/00012-<desc>/`` convention
    (SURVEY.md §2.2 "Submit/run framework").  Multi-host runs pass an
    explicit ``run_id`` (agreed via broadcast) and ``create=False`` on
    non-zero processes so only one host touches the filesystem."""
    if run_id is None:
        os.makedirs(results_root, exist_ok=True)
        run_id = next_run_id(results_root)
    run_dir = os.path.join(results_root, f"{run_id:05d}-{desc}")
    if create:
        os.makedirs(run_dir, exist_ok=True)
    return run_dir
