"""Parse ``jax.profiler`` traces for DEVICE time (VERDICT r3 item 1b,
ISSUE 8 tentpole a).

A wall clock around ``block_until_ready`` can lie on a relayed backend (the
retracted r3 measurement); the profiler's trace records what the device
itself executed.  Two parsers feed one event model:

* **xplane** — ``*.xplane.pb`` via the protobuf that ships inside
  tensorflow.  Dense (per-op device events, per-core lines), the
  preferred source when the proto is importable.
* **chrome-trace** — ``*.trace.json.gz`` (the profiler always writes it
  next to the xplane).  No dependency beyond the stdlib: process-name
  metadata events map pids to plane names, ``"ph": "X"`` events carry
  µs ``ts``/``dur``.  This is the no-TensorFlow fallback that keeps
  device-time attribution alive in containers without the proto.

Every entry point degrades instead of raising — trace parsing is a
witness, never a dependency.  ``device_time_report`` is the rich form:
it returns an explicit ``{"status": "unavailable", "reason": ...}``
sentinel when neither parser can run, and on success attributes busy
time to named jitted programs (``PjitFunction(d_step)`` host events /
``jit_d_step`` device-plane module events), which is what the loop's
periodic sampler folds into the ``device/phase_ms/*`` gauges.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Dict, List, Optional, Tuple

# plane -> [(event name, start_ps, duration_ps), ...]
Events = Dict[str, List[Tuple[str, int, int]]]


def _latest(trace_dir: str, pattern: str) -> Optional[str]:
    paths = glob.glob(os.path.join(trace_dir, "**", pattern),
                      recursive=True)
    return max(paths, key=os.path.getmtime) if paths else None


def _merge_busy(intervals: List[Tuple[int, int]]) -> int:
    """Total covered picoseconds of possibly-overlapping intervals."""
    busy = 0
    end = -1
    for s, t in sorted(intervals):
        if s > end:
            busy += t - s
            end = t
        elif t > end:
            busy += t - end
            end = t
    return busy


# --- parsers ----------------------------------------------------------------


# The profiler's PYTHON tracer emits "$file.py:123 fn" frame events whose
# start is the frame's TRUE entry time — a frame entered minutes before
# start_trace (the train loop itself) spans far outside the trace window
# and inflates busy past wall.  They are host python frames, not executor
# work, so every consumer here drops them.
def _keep(name: str) -> bool:
    return not name.startswith("$")


def _xplane_events(trace_dir: str) -> Optional[Events]:
    """Named events from the newest ``*.xplane.pb``; None when the proto
    isn't importable or no file exists (the caller falls back)."""
    path = _latest(trace_dir, "*.xplane.pb")
    if path is None:
        return None
    from tensorflow.tsl.profiler.protobuf import xplane_pb2  # deferred

    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    out: Events = {}
    for p in xs.planes:
        # XEvent.offset_ps is relative to ITS LINE's timestamp_ns — events
        # from different lines (threads/cores) must be rebased to a common
        # clock before merging, or busy/span mix incompatible time bases.
        names = {m_id: m.name for m_id, m in p.event_metadata.items()}
        evs = []
        for line in p.lines:
            base = line.timestamp_ns * 1000          # ns → ps
            for e in line.events:
                name = names.get(e.metadata_id, "")
                if not _keep(name):
                    continue
                s = base + e.offset_ps
                evs.append((name, s, e.duration_ps))
        if evs:
            out[p.name] = evs
    return out or None


def _chrome_events(trace_dir: str) -> Optional[Events]:
    """Named events from the newest ``*.trace.json[.gz]`` (Chrome trace
    format).  ``process_name`` metadata events name the planes; complete
    events carry µs ts/dur (converted to ps to share the xplane model)."""
    path = _latest(trace_dir, "*.trace.json.gz") \
        or _latest(trace_dir, "*.trace.json")
    if path is None:
        return None
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    plane_of: Dict[int, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            plane_of[ev.get("pid", 0)] = ev.get("args", {}).get(
                "name", f"pid{ev.get('pid', 0)}")
    out: Events = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = str(ev.get("name", ""))
        if not _keep(name):
            continue
        pid = ev.get("pid", 0)
        plane = plane_of.get(pid, f"pid{pid}")
        out.setdefault(plane, []).append(
            (name,
             int(ev.get("ts", 0) * 1e6),            # µs → ps
             int(ev.get("dur", 0) * 1e6)))
    return out or None


def parse_trace_events(trace_dir: str):
    """``(events, source)`` from the best available parser, or
    ``(None, reason)``.  xplane is preferred (denser; real device planes
    on TPU); an unimportable proto or a missing ``.pb`` falls through to
    the Chrome trace instead of failing."""
    xplane_err = None
    try:
        evs = _xplane_events(trace_dir)
        if evs:
            return evs, "xplane"
    except Exception as e:            # ImportError, parse error, torn file
        xplane_err = f"{type(e).__name__}: {e}"
    try:
        evs = _chrome_events(trace_dir)
        if evs:
            return evs, "chrome-trace"
    except Exception as e:
        return None, (f"chrome-trace parse failed ({type(e).__name__}: "
                      f"{e})" + (f"; xplane: {xplane_err}"
                                 if xplane_err else ""))
    reason = f"no parseable trace under {trace_dir}"
    if xplane_err:
        reason += f" (xplane: {xplane_err})"
    return None, reason


# --- summaries --------------------------------------------------------------


def _summarize(events: Events) -> Dict[str, Dict[str, float]]:
    out: Dict[str, Dict[str, float]] = {}
    for plane, evs in events.items():
        iv = [(s, s + d) for _, s, d in evs]
        if not iv:
            continue
        lo = min(s for s, _ in iv)
        hi = max(t for _, t in iv)
        out[plane] = {
            "busy_s": _merge_busy(iv) / 1e12,
            "span_s": (hi - lo) / 1e12,
            "events": float(len(iv)),
        }
    return out


def parse_planes(trace_dir: str) -> Optional[Dict[str, Dict[str, float]]]:
    """{plane name: {busy_s, span_s, events}} from the best parser."""
    events, _ = parse_trace_events(trace_dir)
    return _summarize(events) if events else None


def _pick_plane(planes: Dict[str, Dict[str, float]]) -> Optional[str]:
    """Preference: a TPU device plane; else any ``/device:`` plane; else
    the host CPU plane (the only executor plane a CPU-backend trace
    has)."""
    for want in ("/device:TPU", "/device:", "/host:CPU"):
        cands = {n: v for n, v in planes.items() if n.startswith(want)}
        if cands:
            return max(cands, key=lambda n: cands[n]["busy_s"])
    return None


def device_busy_span(trace_dir: str) -> Optional[Tuple[float, float, str]]:
    """(busy_s, span_s, plane) for the best device plane in the trace.
    ``busy_s`` is interval-merged across the plane's lines, so overlapping
    per-core lines don't double-count."""
    planes = parse_planes(trace_dir)
    if not planes:
        return None
    name = _pick_plane(planes)
    if name is None:
        return None
    return planes[name]["busy_s"], planes[name]["span_s"], name


# --- program (phase) attribution --------------------------------------------

_PJIT_RE = re.compile(r"^PjitFunction\((.+)\)$")
_JIT_RE = re.compile(r"^jit_+(.+?)(?:[.(].*)?$")


def program_name(event_name: str) -> Optional[str]:
    """Extract a jitted-program name from a trace event name, sanitized
    for the telemetry registry namespace (lowercase ``[a-z0-9_]``).

    Matches the host dispatch events (``PjitFunction(d_step)``) and the
    device-plane XLA module events (``jit_d_step`` / ``jit_d_step.42``).
    Everything else (per-op fusions, executor internals) returns None.
    """
    m = _PJIT_RE.match(event_name) or _JIT_RE.match(event_name)
    if not m:
        return None
    n = re.sub(r"[^a-z0-9_]+", "_", m.group(1).strip().lower()).strip("_")
    return n or None


def attribute_programs(events: Events) -> Dict[str, float]:
    """{program name: merged busy seconds} over the trace's named jitted
    programs.  Device planes win when any of them carries program events
    (the TPU xplane's "XLA Modules" line — true device time); otherwise
    every plane contributes (the CPU backend's host-side dispatch events,
    which bound execution from above under synchronous blocking)."""
    per_plane: Dict[str, Dict[str, List[Tuple[int, int]]]] = {}
    for plane, evs in events.items():
        progs: Dict[str, List[Tuple[int, int]]] = {}
        for name, s, d in evs:
            prog = program_name(name)
            if prog:
                progs.setdefault(prog, []).append((s, s + d))
        if progs:
            per_plane[plane] = progs
    if not per_plane:
        return {}
    device_planes = {p: v for p, v in per_plane.items()
                     if p.startswith("/device:")}
    chosen = device_planes or per_plane
    merged: Dict[str, List[Tuple[int, int]]] = {}
    for progs in chosen.values():
        for prog, iv in progs.items():
            merged.setdefault(prog, []).extend(iv)
    return {prog: _merge_busy(iv) / 1e12 for prog, iv in merged.items()}


def device_time_report(trace_dir: str) -> dict:
    """One-call device-truth summary of a profiler trace dir.

    ``{"status": "ok", "source", "plane", "busy_s", "span_s", "events",
    "program_busy_s": {name: s}}`` on success;
    ``{"status": "unavailable", "reason": ...}`` when neither parser can
    produce events — an explicit sentinel, never an exception, so the
    loop's periodic sampler and the bench witness can fold the outcome
    into telemetry either way."""
    events, source = parse_trace_events(trace_dir)
    if not events:
        return {"status": "unavailable", "reason": source}
    planes = _summarize(events)
    plane = _pick_plane(planes)
    if plane is None:
        return {"status": "unavailable",
                "reason": "no executor plane in trace"}
    return {
        "status": "ok",
        "source": source,
        "plane": plane,
        "busy_s": planes[plane]["busy_s"],
        "span_s": planes[plane]["span_s"],
        "events": int(planes[plane]["events"]),
        "program_busy_s": attribute_programs(events),
    }
