"""Parse ``jax.profiler`` traces for DEVICE time (VERDICT r3 item 1b).

A wall clock around ``block_until_ready`` can lie on a relayed backend (the
retracted r3 measurement); the profiler's xplane trace records what the
device itself executed.  ``device_busy_span`` returns (busy seconds, span
seconds, plane name) for the trace's device plane so the bench can check
its wall-clock claim against device reality.

The xplane proto ships inside tensorflow (CPU wheel, present in this
image); the import is deferred and every entry point degrades to ``None``
rather than raising — trace validation is an extra witness, never a
dependency.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional, Tuple


def _latest_xplane(trace_dir: str) -> Optional[str]:
    pbs = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                    recursive=True)
    return max(pbs, key=os.path.getmtime) if pbs else None


def _merge_busy(intervals: List[Tuple[int, int]]) -> int:
    """Total covered picoseconds of possibly-overlapping intervals."""
    busy = 0
    end = -1
    for s, t in sorted(intervals):
        if s > end:
            busy += t - s
            end = t
        elif t > end:
            busy += t - end
            end = t
    return busy


def parse_planes(trace_dir: str) -> Optional[Dict[str, Dict[str, float]]]:
    """{plane name: {busy_s, span_s, events}} from the newest xplane.pb."""
    path = _latest_xplane(trace_dir)
    if path is None:
        return None
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError:
        return None
    xs = xplane_pb2.XSpace()
    try:
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
    except Exception:
        return None
    out: Dict[str, Dict[str, float]] = {}
    for p in xs.planes:
        # XEvent.offset_ps is relative to ITS LINE's timestamp_ns — events
        # from different lines (threads/cores) must be rebased to a common
        # clock before merging, or busy/span mix incompatible time bases.
        iv = []
        for line in p.lines:
            base = line.timestamp_ns * 1000          # ns → ps
            for e in line.events:
                s = base + e.offset_ps
                iv.append((s, s + e.duration_ps))
        if not iv:
            continue
        lo = min(s for s, _ in iv)
        hi = max(t for _, t in iv)
        out[p.name] = {
            "busy_s": _merge_busy(iv) / 1e12,
            "span_s": (hi - lo) / 1e12,
            "events": float(len(iv)),
        }
    return out


def device_busy_span(trace_dir: str) -> Optional[Tuple[float, float, str]]:
    """(busy_s, span_s, plane) for the best device plane in the trace.

    Preference: a TPU device plane; else any ``/device:`` plane; else the
    host CPU plane (the only executor plane a CPU-backend trace has).
    ``busy_s`` is interval-merged across the plane's lines, so overlapping
    per-core lines don't double-count.
    """
    planes = parse_planes(trace_dir)
    if not planes:
        return None
    for want in ("/device:TPU", "/device:", "/host:CPU"):
        cands = {n: v for n, v in planes.items() if n.startswith(want)}
        if cands:
            name = max(cands, key=lambda n: cands[n]["busy_s"])
            return cands[name]["busy_s"], cands[name]["span_s"], name
    return None
