"""Dependency-free TensorBoard scalar event writer.

The reference's only observability mechanism is ``autosummary`` moving
averages flushed to TensorBoard event files once per tick
(SURVEY.md §2.2 autosummary row, §5 metrics/logging row).  This module
completes that surface without importing TensorFlow: TensorBoard's event
files are ordinary TFRecord-framed ``tensorflow.Event`` protos, and this
framework already owns both halves — the masked-CRC TFRecord framing and
the hand-rolled proto emitters live in ``data/tfrecord_writer.py``.

Wire format (only the fields TensorBoard's scalar dashboard reads):

  Event:   wall_time double=1, step int64=2, file_version string=3,
           summary Summary=5
  Summary: repeated Value value=1
  Value:   tag string=1, simple_value float=2

Verified against TensorFlow's own ``summary_iterator`` in
``tests/test_cli.py::test_tensorboard_event_file`` when TF is available.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Dict, Optional

from gansformer_tpu.data.tfrecord_writer import (
    _len_delim, _varint, write_record)


def _double_field(field: int, value: float) -> bytes:
    return _varint(field << 3 | 1) + struct.pack("<d", value)


def _float_field(field: int, value: float) -> bytes:
    return _varint(field << 3 | 5) + struct.pack("<f", value)


def _int_field(field: int, value: int) -> bytes:
    return _varint(field << 3 | 0) + _varint(value)


def _scalar_value(tag: str, value: float) -> bytes:
    body = _len_delim(1, tag.encode("utf-8")) + _float_field(2, float(value))
    return _len_delim(1, body)            # Summary.value = 1


def encode_event(wall_time: float, step: Optional[int] = None,
                 scalars: Optional[Dict[str, float]] = None,
                 file_version: Optional[str] = None) -> bytes:
    ev = _double_field(1, wall_time)
    if step is not None:
        ev += _int_field(2, int(step))
    if file_version is not None:
        ev += _len_delim(3, file_version.encode("utf-8"))
    if scalars:
        summary = b"".join(_scalar_value(t, v) for t, v in scalars.items())
        ev += _len_delim(5, summary)
    return ev


class EventWriter:
    """Append-only scalar event file, TensorBoard-readable.

    One instance per run dir; ``scalars({'Loss/G': …}, step)`` per tick —
    the same names the reference's autosummary emits, so existing
    TensorBoard habits (regex ``Loss/.*``) carry over.
    """

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}")
        self.path = os.path.join(logdir, fname)
        self._f = open(self.path, "ab")
        # TensorBoard ignores files without the version preamble.
        write_record(self._f, encode_event(time.time(),
                                           file_version="brain.Event:2"))
        self._f.flush()

    def scalars(self, values: Dict[str, float], step: int) -> None:
        clean = {k: float(v) for k, v in values.items()
                 if isinstance(v, (int, float))}
        if not clean:
            return
        write_record(self._f, encode_event(time.time(), step=step,
                                           scalars=clean))
        self._f.flush()

    def close(self) -> None:
        self._f.close()
