"""Checkpoint / resume — Orbax-backed full-state snapshots.

The reference checkpoints by pickling ``Network`` objects (code + weights)
as ``network-snapshot-<kimg>.pkl`` and does NOT save optimizer state —
Adam moments silently reset on resume (SURVEY.md §5 "Checkpoint / resume").
Here the whole ``TrainState`` pytree (params, both Adam states, EMA params,
w_avg, pl_mean, step) round-trips atomically, plus the resolved config JSON
so a checkpoint is self-describing.  ``--resume`` auto-picks the latest step.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from gansformer_tpu.core.config import ExperimentConfig
from gansformer_tpu.obs import registry as telemetry
from gansformer_tpu.obs.spans import span
from gansformer_tpu.train.state import TrainState


_MANAGERS: dict = {}


def _manager(ckpt_dir: str, max_to_keep: int = 5):
    """One CheckpointManager per directory — construction spins up worker
    threads and directory scans, so save/latest_step/restore share it."""
    import orbax.checkpoint as ocp

    key = os.path.abspath(ckpt_dir)
    if key not in _MANAGERS:
        _MANAGERS[key] = ocp.CheckpointManager(
            key,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True),
        )
    return _MANAGERS[key]


def save(ckpt_dir: str, state: TrainState, cfg: Optional[ExperimentConfig] = None,
         max_to_keep: int = 5, block: bool = True) -> None:
    """``block=False`` → async save (SURVEY.md §5: Orbax async
    checkpointing): device buffers are staged and the write happens on
    Orbax's background threads, so the train loop's tick stall is the
    staging cost only.  Orbax serializes with any still-pending previous
    save internally.  Call ``wait(ckpt_dir)`` (or a blocking save) before
    reading ``latest_step`` for dedupe/shutdown."""
    import orbax.checkpoint as ocp

    mgr = _manager(ckpt_dir, max_to_keep)
    step = int(jax.device_get(state.step))
    # ckpt/write_ms measures what the TRAIN LOOP paid: staging cost for an
    # async save, full serialize+write for a blocking one.
    with span("ckpt/save") as sp:
        mgr.save(step, args=ocp.args.StandardSave(state))
        if block:
            mgr.wait_until_finished()
    telemetry.gauge("ckpt/write_ms").set(sp.duration_s * 1000.0)
    telemetry.counter("ckpt/save_total").inc()
    if cfg is not None:
        cfg_path = os.path.join(ckpt_dir, "config.json")
        if not os.path.exists(cfg_path):
            with open(cfg_path, "w") as f:
                f.write(cfg.to_json())


def wait(ckpt_dir: str) -> None:
    """Block until any in-flight async save for this directory completes."""
    key = os.path.abspath(ckpt_dir)
    if key in _MANAGERS:
        _MANAGERS[key].wait_until_finished()


def latest_step(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    mgr = _manager(ckpt_dir)
    return mgr.latest_step()


def restore(ckpt_dir: str, template: TrainState,
            step: Optional[int] = None) -> TrainState:
    """Restore into the structure of ``template`` (shapes/dtypes/shardings
    come from the template — works under any mesh)."""
    import orbax.checkpoint as ocp

    mgr = _manager(ckpt_dir)
    step = step if step is not None else mgr.latest_step()
    if step is None:
        raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    with span("ckpt/restore") as sp:
        out = mgr.restore(step, args=ocp.args.StandardRestore(template))
    telemetry.gauge("ckpt/restore_ms").set(sp.duration_s * 1000.0)
    return out
